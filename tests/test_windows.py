"""Window operator semantics — modeled on the reference's window test suites
(internal/topo/topotest/window_rule_test.go, 5.9k LoC). Drives WindowNode /
FusedWindowAggNode directly with the mock clock and asserts emitted windows.
"""
import time

import pytest

from ekuiper_tpu.data.rows import Tuple, WindowTuples
from ekuiper_tpu.runtime.events import Watermark
from ekuiper_tpu.runtime.nodes_window import WatermarkNode, WindowNode
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.utils import timex


def window_of(sql):
    return parse_select(sql).window


class Harness:
    """Synchronous window-node driver: calls handlers inline, collects emits."""

    def __init__(self, node):
        self.node = node
        self.emitted = []
        node.broadcast = self._capture
        # triggers enqueue into inq; drain them inline for determinism
        node.inq.put = self._on_put
        node.on_open()

    def _capture(self, item):
        if isinstance(item, WindowTuples):
            self.emitted.append(item)

    def _on_put(self, item):
        from ekuiper_tpu.runtime.events import Trigger

        if isinstance(item, Trigger):
            self.node.on_trigger(item)

    def feed(self, message, ts=None):
        t = Tuple(emitter="s", message=message,
                  timestamp=ts if ts is not None else timex.now_ms())
        self.node.process(t)

    def watermark(self, ts):
        self.node.on_watermark(Watermark(ts=ts))

    def windows(self):
        return [[r.message for r in w.rows()] for w in self.emitted]

    def ranges(self):
        return [(w.window_range.window_start, w.window_range.window_end)
                for w in self.emitted]


class TestTumblingProcessing:
    def test_basic(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY TUMBLINGWINDOW(ss, 10)"))
        h = Harness(node)
        h.feed({"v": 1})
        mock_clock.advance(5000)
        h.feed({"v": 2})
        mock_clock.advance(5000)  # t=10000: fire
        assert h.windows() == [[{"v": 1}, {"v": 2}]]
        assert h.ranges() == [(0, 10_000)]
        h.feed({"v": 3})
        mock_clock.advance(10_000)
        assert h.windows()[1] == [{"v": 3}]

    def test_empty_window_emits_empty(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY TUMBLINGWINDOW(ss, 10)"))
        h = Harness(node)
        mock_clock.advance(10_000)
        # reference emits nothing downstream for empty windows (no rows)
        assert h.windows() == [[]]


class TestHoppingProcessing:
    def test_overlap(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY HOPPINGWINDOW(ss, 10, 5)"))
        h = Harness(node)
        h.feed({"v": 1})          # t=0
        mock_clock.advance(4000)
        h.feed({"v": 2})          # t=4000
        mock_clock.advance(1000)  # t=5000: window (-5000, 5000]
        mock_clock.advance(2000)
        h.feed({"v": 3})          # t=7000
        mock_clock.advance(3000)  # t=10000: window (0, 10000]
        ws = h.windows()
        assert ws[0] == [{"v": 1}, {"v": 2}]
        assert ws[1] == [{"v": 1}, {"v": 2}, {"v": 3}]
        mock_clock.advance(5000)  # t=15000: window (5000,15000] -> only v3
        assert h.windows()[2] == [{"v": 3}]


class TestSlidingProcessing:
    def test_per_event_trigger(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY SLIDINGWINDOW(ss, 10)"))
        h = Harness(node)
        h.feed({"v": 1})
        mock_clock.advance(5000)
        h.feed({"v": 2})  # window (t-10s, t] includes v1
        assert h.windows() == [[{"v": 1}], [{"v": 1}, {"v": 2}]]
        mock_clock.advance(11_000)
        h.feed({"v": 3})  # v1, v2 expired
        assert h.windows()[2] == [{"v": 3}]

    def test_trigger_condition(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY SLIDINGWINDOW(ss, 10) OVER (WHEN v > 5)"))
        h = Harness(node)
        h.feed({"v": 1})
        assert h.windows() == []  # condition false: no trigger
        h.feed({"v": 9})
        assert h.windows() == [[{"v": 1}, {"v": 9}]]

    def test_delay(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY SLIDINGWINDOW(ss, 10, 2)"))
        h = Harness(node)
        h.feed({"v": 1})
        assert h.windows() == []  # delayed
        mock_clock.advance(1000)
        h.feed({"v": 2})  # lands inside the delay extension
        mock_clock.advance(1000)  # delay expires for v1's trigger
        assert h.windows() == [[{"v": 1}, {"v": 2}]]


class TestSessionProcessing:
    def test_gap_timeout(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY SESSIONWINDOW(ss, 100, 5)"))
        h = Harness(node)
        h.feed({"v": 1})
        mock_clock.advance(3000)
        h.feed({"v": 2})
        mock_clock.advance(5000)  # gap 5s elapses: session closes
        assert h.windows() == [[{"v": 1}, {"v": 2}]]
        h.feed({"v": 3})
        mock_clock.advance(5000)
        assert h.windows()[1] == [{"v": 3}]


class TestCountWindow:
    def test_simple(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY COUNTWINDOW(3)"))
        h = Harness(node)
        for i in range(7):
            h.feed({"v": i})
        ws = h.windows()
        assert ws[0] == [{"v": 0}, {"v": 1}, {"v": 2}]
        assert ws[1] == [{"v": 3}, {"v": 4}, {"v": 5}]

    def test_overlapping(self, mock_clock):
        # COUNTWINDOW(3, 1): every event, last 3 rows
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY COUNTWINDOW(3, 1)"))
        h = Harness(node)
        for i in range(4):
            h.feed({"v": i})
        ws = h.windows()
        assert ws[0] == [{"v": 0}]
        assert ws[2] == [{"v": 0}, {"v": 1}, {"v": 2}]
        assert ws[3] == [{"v": 1}, {"v": 2}, {"v": 3}]


class TestStateWindow:
    def test_begin_emit(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY STATEWINDOW(st = 'on', st = 'off')"))
        h = Harness(node)
        h.feed({"st": "idle"})  # before begin: ignored
        h.feed({"st": "on"})
        h.feed({"st": "run"})
        h.feed({"st": "off"})  # emit
        h.feed({"st": "stray"})
        assert h.windows() == [[{"st": "on"}, {"st": "run"}, {"st": "off"}]]


class TestEventTime:
    def test_tumbling_watermark(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY TUMBLINGWINDOW(ss, 10)"),
            is_event_time=True)
        h = Harness(node)
        h.feed({"v": 1}, ts=1000)
        h.feed({"v": 2}, ts=9000)
        h.feed({"v": 3}, ts=12_000)
        h.watermark(9500)
        assert h.windows() == []  # window (0,10000] not complete yet
        h.watermark(10_500)
        assert h.windows() == [[{"v": 1}, {"v": 2}]]
        h.watermark(20_500)
        assert h.windows()[1] == [{"v": 3}]

    def test_session_event_time(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT * FROM s GROUP BY SESSIONWINDOW(ss, 100, 5)"),
            is_event_time=True)
        h = Harness(node)
        h.feed({"v": 1}, ts=1000)
        h.feed({"v": 2}, ts=3000)
        h.feed({"v": 3}, ts=20_000)  # new session (gap > 5s)
        h.watermark(30_000)
        ws = h.windows()
        assert ws[0] == [{"v": 1}, {"v": 2}]
        assert ws[1] == [{"v": 3}]

    def test_watermark_node_drops_late(self, mock_clock):
        wm_node = WatermarkNode("wm", late_tolerance_ms=1000)
        out = []
        wm_node.broadcast = lambda item: out.append(item)
        wm_node.emit = lambda item, count=1: out.append(item)
        wm_node.process(Tuple(message={"v": 1}, timestamp=10_000))
        wm_node.process(Tuple(message={"v": 2}, timestamp=5_000))  # late
        rows = [x for x in out if isinstance(x, Tuple)]
        assert [r.message["v"] for r in rows] == [1]
        wms = [x for x in out if isinstance(x, Watermark)]
        assert wms[-1].ts == 9_000


class TestFusedHopping:
    def test_hopping_device_path(self, mock_clock):
        """Fused hopping window through the e2e rule surface."""
        from ekuiper_tpu.io import memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        mem.reset()
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (k STRING, v FLOAT) WITH (DATASOURCE="t", TYPE="memory")'
        )
        topo = plan_rule(RuleDef(id="hop", sql=(
            "SELECT k, sum(v) AS s FROM demo GROUP BY k, HOPPINGWINDOW(ss, 10, 5)"
        ), actions=[{"memory": {"topic": "hop_res"}}]), store)
        assert any(n.name == "window_agg" for n in topo.ops)
        got = []
        mem.subscribe("hop_res", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("t", {"k": "a", "v": 1.0})
            mock_clock.advance(20)
            time.sleep(0.4)
            mock_clock.advance(4980)  # t=5000: first hop fires
            deadline = time.time() + 5
            while len(got) < 1 and time.time() < deadline:
                time.sleep(0.02)
            mem.publish("t", {"k": "a", "v": 2.0})
            mock_clock.advance(20)
            time.sleep(0.4)
            mock_clock.advance(4980)  # t=10000: window covers both
            deadline = time.time() + 5
            while len(got) < 2 and time.time() < deadline:
                time.sleep(0.02)
            first = got[0] if isinstance(got[0], dict) else got[0][0]
            second = got[1] if isinstance(got[1], dict) else got[1][0]
            assert first == {"k": "a", "s": 1.0}
            assert second == {"k": "a", "s": 3.0}  # both panes merged
            # t=15000 and t=20000: v1 pane expires, then v2 pane expires
            time.sleep(0.1)
            mock_clock.advance(5000)
            deadline = time.time() + 5
            while len(got) < 3 and time.time() < deadline:
                time.sleep(0.02)
            third = got[2] if isinstance(got[2], dict) else got[2][0]
            assert third == {"k": "a", "s": 2.0}
        finally:
            topo.close()
            mem.reset()


class TestColumnarBuffer:
    """Tumbling/hopping windows keep ColumnBatches whole until emit
    (columnar spine through the host window path)."""

    def _batch(self, vals, ts0=1000):
        import numpy as np
        from ekuiper_tpu.data.batch import ColumnBatch

        n = len(vals)
        return ColumnBatch(
            n=n,
            columns={"v": np.asarray(vals, dtype=np.float32)},
            timestamps=np.arange(ts0, ts0 + n, dtype=np.int64),
            emitter="s")

    def test_batches_stay_columnar_until_trigger(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT v FROM s GROUP BY TUMBLINGWINDOW(ss, 10)"))
        h = Harness(node)
        node.process(self._batch([1, 2, 3]))
        node.process(self._batch([4, 5], ts0=2000))
        assert node._use_bbuf and len(node.bbuf) == 2
        assert node.buffer == []  # nothing exploded at ingest
        mock_clock.advance(10_000)
        assert [r.message["v"] for r in h.emitted[0].rows()] == \
            [1, 2, 3, 4, 5]
        assert node.bbuf == []

    def test_vectorized_window_filter(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT v FROM s GROUP BY TUMBLINGWINDOW(ss, 10) "
            "FILTER (WHERE v > 2)"))
        assert node._use_bbuf and node._vfilter is not None
        h = Harness(node)
        node.process(self._batch([1, 2, 3, 4]))
        mock_clock.advance(10_000)
        assert [r.message["v"] for r in h.emitted[0].rows()] == [3, 4]

    def test_hopping_columnar_selection(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT v FROM s GROUP BY HOPPINGWINDOW(ss, 10, 5)"))
        assert node._use_bbuf
        h = Harness(node)
        node.process(self._batch([1, 2], ts0=1000))
        mock_clock.advance(5_000)   # first hop
        mock_clock.advance(5_000)   # second hop: rows still in [0,10s)
        assert len(h.emitted) >= 2
        assert [r.message["v"] for r in h.emitted[1].rows()] == [1, 2]

    def test_mixed_rows_and_batches_merge(self, mock_clock):
        node = WindowNode("w", window_of(
            "SELECT v FROM s GROUP BY TUMBLINGWINDOW(ss, 10)"))
        h = Harness(node)
        node.process(self._batch([1]))
        h.feed({"v": 99}, ts=2000)  # single row -> row buffer
        assert len(node.bbuf) == 1 and len(node.buffer) == 1
        mock_clock.advance(10_000)
        assert sorted(r.message["v"] for r in h.emitted[0].rows()) == [1, 99]
