"""IO connector & converter tests."""
import json
import os
import threading
import time
import urllib.request

import pytest

from ekuiper_tpu.io import memory as mem
from ekuiper_tpu.io import registry as io_registry
from ekuiper_tpu.io.converters import get_converter
from ekuiper_tpu.io.file import FileSink, FileSource
from ekuiper_tpu.io.http import HttpPushSource, RestSink, get_data_server


class TestConverters:
    def test_json_roundtrip(self):
        c = get_converter("json")
        assert c.decode(b'{"a": 1}') == {"a": 1}
        assert c.decode(b'[{"a": 1}, {"a": 2}]') == [{"a": 1}, {"a": 2}]
        assert json.loads(c.encode({"a": 1})) == {"a": 1}
        with pytest.raises(Exception):
            c.decode(b'"scalar"')

    def test_binary(self):
        c = get_converter("binary")
        assert c.decode(b"\x01\x02") == {"self": b"\x01\x02"}
        assert c.encode({"self": b"xy"}) == b"xy"

    def test_delimited(self):
        c = get_converter("delimited", delimiter=",", fields=["a", "b", "c"])
        assert c.decode(b"1,true,hi") == {"a": 1, "b": True, "c": "hi"}
        assert c.encode({"a": 1, "b": True, "c": "hi"}) == b"1,True,hi"

    def test_urlencoded(self):
        c = get_converter("urlencoded")
        assert c.decode(b"a=1&b=x") == {"a": 1, "b": "x"}
        assert c.encode({"a": 1}) == b"a=1"

    def test_unknown_format(self):
        with pytest.raises(Exception):
            get_converter("bogus")


class TestMemoryPubSub:
    def setup_method(self):
        mem.reset()

    def teardown_method(self):
        mem.reset()

    def test_wildcards(self):
        got = []
        mem.subscribe("a/+/c", lambda t, p: got.append(("plus", t)))
        mem.subscribe("a/#", lambda t, p: got.append(("hash", t)))
        mem.publish("a/b/c", {})
        mem.publish("a/x", {})
        mem.publish("z/b/c", {})
        assert ("plus", "a/b/c") in got
        assert ("hash", "a/b/c") in got and ("hash", "a/x") in got
        assert not any(t == "z/b/c" for _, t in got)

    def test_unsubscribe(self):
        got = []
        unsub = mem.subscribe("t", lambda t, p: got.append(p))
        mem.publish("t", 1)
        unsub()
        mem.publish("t", 2)
        assert got == [1]


class TestFileIO:
    def test_json_file_source(self, tmp_path):
        path = tmp_path / "data.json"
        path.write_text(json.dumps([{"a": 1}, {"a": 2}]))
        src = FileSource()
        src.configure(str(path), {"fileType": "json"})
        got = []
        done = threading.Event()

        def ingest(payload, meta=None):
            got.append(payload)
            done.set()

        src.open(ingest)
        assert done.wait(3)
        src.close()
        assert got[0] == [{"a": 1}, {"a": 2}]

    def test_csv_file_source(self, tmp_path):
        path = tmp_path / "d.csv"
        path.write_text("a,b\n1,x\n2,y\n")
        src = FileSource()
        src.configure(str(path), {"fileType": "csv"})
        got = []
        src.open(lambda p, meta=None: got.append(p))
        deadline = time.time() + 3
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        src.close()
        assert got == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]

    def test_file_sink_lines(self, tmp_path):
        path = tmp_path / "out.log"
        sink = FileSink()
        sink.configure({"path": str(path)})
        sink.connect()
        sink.collect({"x": 1})
        sink.collect([{"x": 2}])
        sink.close()
        lines = path.read_text().strip().split("\n")
        assert json.loads(lines[0]) == {"x": 1}
        assert json.loads(lines[1]) == [{"x": 2}]

    def test_file_sink_rolling(self, tmp_path):
        path = tmp_path / "roll.log"
        sink = FileSink()
        sink.configure({"path": str(path), "rollingSize": 10})
        sink.connect()
        for i in range(5):
            sink.collect({"i": i})
        sink.close()
        rolled = [f for f in os.listdir(tmp_path) if f.startswith("roll.log.")]
        assert rolled  # at least one roll happened


class TestHttpIO:
    def test_httppush_roundtrip(self):
        src = HttpPushSource()
        src.configure("/push_test", {"server_port": 0})
        got = []
        src.open(lambda p, meta=None: got.append(p))
        port = get_data_server().port
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/push_test",
                data=json.dumps({"v": 7}).encode(), method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 200
            deadline = time.time() + 3
            while not got and time.time() < deadline:
                time.sleep(0.01)
            assert got == [{"v": 7}]
            # unknown path -> 404
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{port}/nope", data=b"{}", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req2, timeout=5)
        finally:
            src.close()

    def test_rest_sink(self):
        received = []
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            sink = RestSink()
            sink.configure({"url": f"http://127.0.0.1:{server.server_address[1]}/hook"})
            sink.collect({"r": 1})
            assert received == [{"r": 1}]
        finally:
            server.shutdown()


class TestRegistry:
    def test_builtin_types(self):
        srcs = io_registry.source_types()
        sinks = io_registry.sink_types()
        for s in ("memory", "simulator", "file", "httppull", "httppush"):
            assert s in srcs
        for s in ("memory", "log", "nop", "file", "rest"):
            assert s in sinks

    def test_unknown(self):
        with pytest.raises(ValueError):
            io_registry.create_source("bogus")


import urllib.error  # noqa: E402  (used in TestHttpIO)


class TestFileRewind:
    def test_offset_and_rewind(self, tmp_path):
        import json as _json
        import time as _time

        from ekuiper_tpu.io import registry as ior

        p = tmp_path / "d.lines"
        p.write_text("\n".join(_json.dumps({"i": i}) for i in range(5)))
        src = ior.create_source("file")
        src.configure(str(p), {"fileType": "lines", "interval": 0})
        src.rewind(2)  # resume mid-file, as a checkpoint restore would
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        deadline = _time.time() + 5
        while _time.time() < deadline and len(got) < 3:
            _time.sleep(0.02)
        while _time.time() < deadline and src.get_offset() < 5:
            _time.sleep(0.02)
        src.close()
        assert [g["i"] for g in got] == [2, 3, 4]
        assert src.get_offset() == 5
        # offsets ride SourceNode checkpoints (Rewindable contract)
        from ekuiper_tpu.runtime.nodes_source import SourceNode

        node = SourceNode("f", src)
        snap = node.snapshot_state()
        assert snap == {"offset": 5}
        src2 = ior.create_source("file")
        src2.configure(str(p), {"fileType": "lines"})
        node2 = SourceNode("f2", src2)
        node2.restore_state(snap)
        assert src2.get_offset() == 5
