"""Checkpoint correctness: fan-in barrier tracking/alignment
(reference internal/topo/checkpoint/barrier_handler.go:23-88) and
crash-replay recovery (reference topotest/checkpoint_test.go)."""
import time

import numpy as np

from ekuiper_tpu.planner.planner import RuleDef, plan_rule
from ekuiper_tpu.runtime.events import Barrier
from ekuiper_tpu.runtime.node import Node
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


class _Recorder(Node):
    """Fan-in node recording processed items and snapshots."""

    def __init__(self):
        super().__init__("rec")
        self.items = []
        self.snapshots = 0

    def process(self, item):
        self.items.append(item)
        self.emit(item)

    def snapshot_state(self):
        self.snapshots += 1
        return {"n": self.snapshots}


class _Acks:
    def __init__(self):
        self.acks = []

    def checkpoint_ack(self, name, barrier, state):
        self.acks.append((name, barrier.checkpoint_id))

    def drain_error(self, err, origin=""):
        raise err


class _Sink(Node):
    def __init__(self):
        super().__init__("cap")
        self.got = []

    def process(self, item):
        self.got.append(item)

    def on_barrier(self, barrier):
        self.got.append(barrier)


def _fanin_setup():
    a, b = Node("a"), Node("b")
    rec = _Recorder()
    sink = _Sink()
    a.connect(rec)
    b.connect(rec)
    rec.connect(sink)
    acks = _Acks()
    rec._topo = acks
    return a, b, rec, sink, acks


class TestBarrierTracker:
    def test_fanin_snapshots_once_forwards_once(self):
        a, b, rec, sink, acks = _fanin_setup()
        bar = Barrier(checkpoint_id=1, qos=1)
        rec._dispatch(bar, "a")
        rec._dispatch(bar, "b")
        assert rec.snapshots == 1  # first barrier snapshots
        assert acks.acks == [("rec", 1)]
        barriers = [x for x in sink.inq.queue]
        assert len(barriers) == 1  # forwarded exactly once

    def test_ids_tracked_independently(self):
        a, b, rec, sink, acks = _fanin_setup()
        rec._dispatch(Barrier(checkpoint_id=1, qos=1), "a")
        rec._dispatch(Barrier(checkpoint_id=2, qos=1), "a")
        rec._dispatch(Barrier(checkpoint_id=1, qos=1), "b")
        rec._dispatch(Barrier(checkpoint_id=2, qos=1), "b")
        assert rec.snapshots == 2
        assert [c for _, c in acks.acks] == [1, 2]


class TestSnapshotFailure:
    def test_failed_snapshot_skips_ack_forwards_barrier(self):
        """A snapshot_state exception (e.g. bounded async-emit drain timing
        out on a wedged device fetch) fails the CHECKPOINT, not the rule:
        no ack (so it never commits), barrier still forwarded, no raise
        out of the barrier path (which would kill the worker thread)."""
        a, b, rec, sink, acks = _fanin_setup()
        rec.snapshot_state = lambda: (_ for _ in ()).throw(
            RuntimeError("drain timed out"))
        rec._dispatch(Barrier(checkpoint_id=3, qos=1), "a")
        rec._dispatch(Barrier(checkpoint_id=3, qos=1), "b")
        assert acks.acks == []  # checkpoint 3 never completes
        assert len([x for x in sink.inq.queue]) == 1  # barrier forwarded
        # the node is still alive for the next checkpoint
        del rec.snapshot_state  # restore the class implementation
        rec._dispatch(Barrier(checkpoint_id=4, qos=1), "a")
        rec._dispatch(Barrier(checkpoint_id=4, qos=1), "b")
        assert acks.acks == [("rec", 4)]


class TestBarrierAligner:
    def test_exactly_once_holds_back_barriered_edge(self):
        a, b, rec, sink, acks = _fanin_setup()
        bar = Barrier(checkpoint_id=7, qos=2)
        rec._dispatch(bar, "a")
        assert rec.snapshots == 0  # waiting for b's barrier
        rec._dispatch("post-barrier-from-a", "a")  # must be held back
        rec._dispatch("pre-barrier-from-b", "b")  # must flow through
        assert rec.items == ["pre-barrier-from-b"]
        rec._dispatch(bar, "b")  # alignment complete
        assert rec.snapshots == 1  # consistent cut: only pre-barrier data
        # held-back item replayed after the snapshot
        assert rec.items == ["pre-barrier-from-b", "post-barrier-from-a"]

    def test_single_input_aligns_immediately(self):
        a, rec = Node("a"), _Recorder()
        a.connect(rec)
        rec._topo = _Acks()
        rec._dispatch(Barrier(checkpoint_id=1, qos=2), "a")
        assert rec.snapshots == 1


class TestSharedFoldRestore:
    def test_kill_restore_through_shared_fold(self):
        """Kill a shared pane fold mid-window and restore it into a fresh
        store (pane partials + per-rule emit cursors): replaying the
        post-snapshot rows must yield windows byte-identical to the
        uninterrupted run, for every member rule."""
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.ops.panestore import union_plan
        from ekuiper_tpu.runtime.events import Trigger
        from ekuiper_tpu.runtime.nodes_sharedfold import (
            MemberSpec, SharedEmitNode, SharedFoldNode)
        from ekuiper_tpu.sql.parser import parse_select

        sqls = [
            "SELECT deviceId, avg(temperature) AS a, count(*) AS c FROM "
            "demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "SELECT deviceId, max(temperature) AS mx FROM demo "
            "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)",
        ]
        stmts = [parse_select(s) for s in sqls]
        plans = [extract_kernel_plan(s) for s in stmts]
        union, _ = union_plan(plans)

        def mk_store(key):
            st = SharedFoldNode(key, "sf", union, 5_000, 6,
                                subtopo_ref=None, capacity=64,
                                micro_batch=128)
            st._cur_bucket = 0
            entries = []
            for i, (stmt, plan) in enumerate(zip(stmts, plans)):
                w = stmt.window
                spec = MemberSpec(
                    rule_id=f"r{i}", length_ms=w.length_ms(),
                    interval_ms=w.interval_ms() or w.length_ms(),
                    plan=plan,
                    direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                    dims=["deviceId"])
                e = SharedEmitNode(f"{key}_r{i}")
                st.attach_rule(spec, e, None)
                entries.append(e)
            return st, entries

        def batches(seed, n_batches):
            rng = np.random.default_rng(seed)
            out = []
            for _ in range(n_batches):
                ids = np.array([f"d{rng.integers(0, 6)}"
                                for _ in range(50)], dtype=np.object_)
                temp = np.rint(rng.normal(20, 5, 50)).astype(np.float32)
                out.append(ColumnBatch(
                    n=50, columns={"deviceId": ids, "temperature": temp},
                    timestamps=np.zeros(50, dtype=np.int64),
                    emitter="demo"))
            return out

        def drain(entry):
            got = []
            while not entry.inq.empty():
                item = entry.inq.get_nowait()
                if isinstance(item, ColumnBatch):
                    got.append(item)
            return got

        pre, post = batches(1, 3), batches(2, 3)
        # uninterrupted reference run
        ref, ref_entries = mk_store("ref")
        for b in pre:
            ref.process(b)
        ref.on_trigger(Trigger(ts=5_000))
        for b in post:
            ref.process(b)
        ref.on_trigger(Trigger(ts=10_000))
        ref_out = [drain(e) for e in ref_entries]

        # crash run: snapshot mid-window (after the 5s pane boundary),
        # kill, restore into a FRESH store, replay post-snapshot rows
        live, live_entries = mk_store("live")
        for b in pre:
            live.process(b)
        live.on_trigger(Trigger(ts=5_000))
        for e in live_entries:
            drain(e)  # already-delivered windows don't replay
        snap = live.snapshot_state()
        assert snap["cursors"]  # per-rule emit cursors persisted

        restored, new_entries = mk_store("restored")
        restored.restore_state(snap)
        for rid, m in restored._members.items():
            assert m.last_end_ms == snap["cursors"].get(rid, m.last_end_ms)
        for b in post:
            restored.process(b)
        restored.on_trigger(Trigger(ts=10_000))
        got = [drain(e) for e in new_entries]
        for i in range(len(stmts)):
            # the reference's post-snapshot windows (hopping emitted one at
            # 5s already — only compare what the restored run re-emits)
            ref_tail = ref_out[i][-len(got[i]):] if got[i] else []
            assert got[i] and len(got[i]) == len(ref_tail)
            for a, b in zip(got[i], ref_tail):
                assert set(a.columns) == set(b.columns)
                for c in a.columns:
                    assert a.columns[c].dtype == b.columns[c].dtype
                    assert np.array_equal(a.columns[c], b.columns[c]), \
                        (i, c)


class TestCrashReplay:
    def test_no_loss_no_dup_across_crash(self, mock_clock):
        """Kill a qos=1 rule mid-window, restore, replay post-checkpoint
        rows (at-least-once source contract): the window result must equal
        an uninterrupted run — pre-checkpoint rows exactly once."""
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="t/ckpt", TYPE="memory", FORMAT="JSON")'
        )

        def make_topo():
            return plan_rule(RuleDef(
                id="ck", sql=(
                    "SELECT deviceId, count(*) AS c, avg(temperature) AS a "
                    "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
                actions=[{"memory": {"topic": "ckpt/out"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000},
            ), store)

        topo = make_topo()
        assert topo.sources, "qos>0 rule must have a private source"
        topo.open()
        pre = [("a", 10.0), ("a", 20.0), ("b", 30.0)]
        post = [("a", 30.0), ("b", 10.0)]
        for d, t in pre:
            mem.publish("t/ckpt", {"deviceId": d, "temperature": t})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        from conftest import wait_for_checkpoint

        cid = topo.trigger_checkpoint()
        wait_for_checkpoint(store, "ck", cid)
        # post-checkpoint rows arrive, then the process dies un-gracefully
        for d, t in post:
            mem.publish("t/ckpt", {"deviceId": d, "temperature": t})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        topo.close()  # crash: no save_state_now

        # recovery: fresh topo restores the checkpoint, source replays
        # everything after the checkpoint (at-least-once), window fires
        topo2 = make_topo()
        topo2.open()
        for d, t in post:
            mem.publish("t/ckpt", {"deviceId": d, "temperature": t})
        mock_clock.advance(20)
        assert topo2.wait_idle(10)
        from conftest import collect_window_result

        msgs = collect_window_result(mem, "ckpt/out", mock_clock)
        topo2.close()
        res = {m["deviceId"]: (m["c"], round(m["a"], 4)) for m in msgs}
        # uninterrupted expectation: a -> 3 rows avg 20; b -> 2 rows avg 20
        assert res == {"a": (3, 20.0), "b": (2, 20.0)}, res


class TestTieredRestore:
    """ISSUE 13: kill/restore through tiered key state — keys demoted at
    checkpoint time restore correctly in BOTH tiers (hot-tier holes +
    cold-tier rows), cross-impl with slidingImpl=daba and through the
    shared pane fold (docs/TIERED_STATE.md)."""

    def test_kill_restore_through_tiered_shared_fold(self):
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.panestore import PaneStore
        from ekuiper_tpu.sql.parser import parse_select

        plan = extract_kernel_plan(parse_select(
            "SELECT deviceId, sum(temperature) AS s, count(*) AS c FROM "
            "demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 4)"))

        def mk():
            return PaneStore(plan, 1000, 4, capacity=64, micro_batch=128,
                             tier_budget_mb=0.001)

        store = mk()
        assert store.tier is not None and store.gb.track_touch
        ids = np.array(["a", "b", "c"], dtype=np.object_)
        slots, _ = store.kt.encode_column(ids)
        store.fold({"temperature": np.array([1.0, 2.0, 3.0])}, {},
                   slots, 0)
        # pane 0 expires -> every key quiescent; demote a and b
        store.reset_pane(0)
        store.tier._plan = [0, 1]
        store.reset_pane(1)  # boundary hook applies the plan
        assert store.tier.demoted_total == 2
        assert store.kt.free_slots() == [0, 1]

        snap = store.snapshot()
        assert None in snap["keys"]  # hot-tier holes persist
        restored = mk()
        restored.restore(snap)
        assert restored.kt.free_slots() == [0, 1]
        assert restored.kt.decode(2) == "c"
        # a demoted-at-kill key comes back queryable: it re-encodes into
        # a recycled slot and folds/combines exactly
        s2, grew = restored.kt.encode_column(
            np.array(["a"], dtype=np.object_))
        assert not grew and s2[0] in (0, 1)
        restored.fold({"temperature": np.array([7.0])}, {}, s2, 2)
        outs, act = restored.combine([2], restored.kt.n_keys)
        alive = np.nonzero(act > 0)[0]
        assert [restored.kt.decode(i) for i in alive.tolist()] == ["a"]
        assert outs[0][alive][0] == 7.0 and outs[1][alive][0] == 1

    def test_kill_restore_daba_tiered_cross_impl(self, mock_clock):
        """A tiered DABA sliding rule killed with a key demoted restores
        into a REFOLD-impl node (cross-impl, pane layout shared): the
        demoted key's slot hole survives, the ring rebuilds from the
        panes, and post-restore triggers emit exactly the untiered
        reference's windows."""
        from ekuiper_tpu.data.batch import ColumnBatch
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
        from ekuiper_tpu.sql.parser import parse_select

        sql = ("SELECT deviceId, count(*) AS c, sum(temp) AS s FROM s "
               "GROUP BY deviceId, SLIDINGWINDOW(ss, 2) "
               "OVER (WHEN temp > 90)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)

        def mk(impl, tier_mb):
            node = FusedWindowAggNode(
                f"tsl_{impl}", stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions],
                capacity=64, micro_batch=128,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                sliding_impl=impl, tier_budget_mb=tier_mb)
            node.state = node.gb.init_state()
            got = []
            node.broadcast = lambda item: got.append(item)
            return node, got

        def batch(ids, temps, tss):
            ids = np.array(ids, dtype=np.object_)
            return ColumnBatch(
                n=len(ids),
                columns={"deviceId": ids,
                         "temp": np.asarray(temps, np.float64)},
                timestamps=np.asarray(tss, np.int64), emitter="s")

        tiered, out_t = mk("daba", 0.001)
        ref, out_r = mk("refold", 0.0)
        assert tiered.tier is not None and tiered.tier.quiescent_only
        # d_old folds once, then the stream moves on long past the ring
        # retention — d_old becomes quiescent
        for n in (tiered, ref):
            n.process(batch(["d_old", "d1"], [10.0, 20.0], [100, 100]))
        for t in range(1, 40):
            ts = t * 250
            for n in (tiered, ref):
                n.process(batch(["d1"], [30.0], [ts]))
        slot_old = tiered.kt._ids["d_old"]
        tiered.tier._plan = [slot_old]
        tiered._tier_boundary()
        tiered._drain_async_emits()
        assert tiered.tier.demoted_total == 1
        assert tiered.kt.decode(slot_old) is None
        assert tiered._rg_dirty  # ring invalidated, panes stay truth

        snap = tiered.snapshot_state()
        assert None in snap["keys"]
        restored, out_c = mk("refold", 0.001)  # CROSS impl, tier on
        restored.restore_state(snap)
        assert restored.kt.free_slots() == tiered.kt.free_slots()
        # post-restore: d_old returns, a trigger row fires the window —
        # both the restored and the uninterrupted reference must emit
        # identical windows
        tail_ts = 40 * 250
        for n, sink in ((restored, out_c), (ref, out_r)):
            sink.clear()
            n.process(batch(["d_old", "d1"], [50.0, 95.0],
                            [tail_ts, tail_ts]))
            n._drain_async_emits()

        def flat(items):
            rows = {}
            for m in items:
                for r in (m if isinstance(m, list) else [m]):
                    k = tuple(sorted(r.items()))
                    rows[k] = rows.get(k, 0) + 1
            return rows

        assert flat(out_c) == flat(out_r)
        assert flat(out_c), "trigger emitted nothing"


class TestAnalyticRestore:
    """ISSUE 19 satellite: __analytic_* state must survive kill/restore —
    both the evaluator/segscan carry (lag's per-partition history) and
    the cal-col overlays on rows buffered inside a window."""

    def test_analytic_snapshot_is_frozen_copy(self):
        # snapshot_state must hand out a deep copy: post-barrier rows
        # advancing the evaluator must not mutate the taken checkpoint
        from ekuiper_tpu.planner.planner import _analytic_calls
        from ekuiper_tpu.runtime.nodes_ops import AnalyticNode
        from ekuiper_tpu.sql.parser import parse_select
        from ekuiper_tpu.data.rows import Tuple
        import json

        calls = _analytic_calls(parse_select(
            "SELECT lag(temperature) OVER (PARTITION BY deviceId) AS lt "
            "FROM demo"))
        node = AnalyticNode("an", calls)
        node.emit = lambda item: None
        node.process(Tuple(emitter="demo", timestamp=0,
                           message={"deviceId": "a", "temperature": 1.0}))
        snap = node.snapshot_state()
        frozen = json.dumps(snap, sort_keys=True)
        node.process(Tuple(emitter="demo", timestamp=1,
                           message={"deviceId": "a", "temperature": 2.0}))
        assert json.dumps(snap, sort_keys=True) == frozen

    def _lag_roundtrip(self, impl, mock_clock, tag):
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            f'CREATE STREAM an{tag} (deviceId STRING, temperature FLOAT) '
            f'WITH (DATASOURCE="an/{tag}", TYPE="memory", FORMAT="JSON")')

        def make_topo():
            return plan_rule(RuleDef(
                id=f"an{tag}", sql=(
                    f"SELECT deviceId, temperature, lag(temperature) "
                    f"OVER (PARTITION BY deviceId) AS lt FROM an{tag}"),
                actions=[{"memory": {"topic": f"an{tag}/out"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000,
                         "analyticImpl": impl}), store)

        got = []
        mem.subscribe(f"an{tag}/out", lambda t, p: got.append(p))
        topo = make_topo()
        topo.open()
        for d, t in [("a", 1.0), ("b", 5.0), ("a", 2.0)]:
            mem.publish(f"an/{tag}", {"deviceId": d, "temperature": t})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        from conftest import wait_for_checkpoint

        cid = topo.trigger_checkpoint()
        wait_for_checkpoint(store, f"an{tag}", cid)
        topo.close()  # crash

        topo2 = make_topo()
        topo2.open()
        try:
            # post-restore rows: lag must continue each partition where
            # the checkpoint left it (a: last 2.0; b: last 5.0)
            mem.publish(f"an/{tag}", {"deviceId": "a", "temperature": 9.0})
            mem.publish(f"an/{tag}", {"deviceId": "b", "temperature": 8.0})
            mock_clock.advance(20)
            assert topo2.wait_idle(10)
            import time as _time

            deadline = _time.time() + 6
            while _time.time() < deadline and len(got) < 5:
                _time.sleep(0.02)
        finally:
            topo2.close()
        flat = []
        for p in got:
            flat.extend(p if isinstance(p, list) else [p])
        post = {m["deviceId"]: m["lt"] for m in flat
                if m["temperature"] in (9.0, 8.0)}
        assert post == {"a": 2.0, "b": 5.0}, flat

    def test_lag_state_survives_restore_device(self, mock_clock):
        self._lag_roundtrip("device", mock_clock, "dv")

    def test_lag_state_survives_restore_host(self, mock_clock):
        self._lag_roundtrip("host", mock_clock, "ho")

    def test_window_buffer_keeps_analytic_overlays(self, mock_clock):
        """Rows checkpointed inside a window buffer carry their
        __analytic_* cal-cols through restore — losing them would
        re-run the analytic post-restore and double-advance its state."""
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM anw (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="an/w", TYPE="memory", FORMAT="JSON")')

        def make_topo():
            return plan_rule(RuleDef(
                id="anw", sql=(
                    "SELECT deviceId, temperature, lag(temperature) "
                    "OVER (PARTITION BY deviceId) AS lt FROM anw "
                    "GROUP BY TUMBLINGWINDOW(ss, 10)"),
                actions=[{"memory": {"topic": "anw/out"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000}),
                store)

        got = []
        mem.subscribe("anw/out", lambda t, p: got.append(p))
        topo = make_topo()
        topo.open()
        for d, t in [("a", 1.0), ("a", 2.0)]:
            mem.publish("an/w", {"deviceId": d, "temperature": t})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        from conftest import wait_for_checkpoint

        cid = topo.trigger_checkpoint()  # mid-window: rows in buffer
        wait_for_checkpoint(store, "anw", cid)
        topo.close()  # crash

        topo2 = make_topo()
        topo2.open()
        try:
            mem.publish("an/w", {"deviceId": "a", "temperature": 3.0})
            mock_clock.advance(20)
            assert topo2.wait_idle(10)
            from conftest import collect_window_result

            msgs = collect_window_result(mem, "anw/out", mock_clock)
        finally:
            topo2.close()
        lags = sorted((m["temperature"], m["lt"]) for m in msgs)
        # uninterrupted expectation: 1.0->None, 2.0->1.0, 3.0->2.0
        assert lags == [(1.0, None), (2.0, 1.0), (3.0, 2.0)], msgs
