"""RuleRegistry.recover() crash-recovery under churn (ISSUE 9 satellite):
kill a registry whose rules sit in every FSM state, recover over the
same store, and assert started rules resume and no ghost sharing
declarations survive a mid-churn delete."""
import time

import pytest

from ekuiper_tpu.planner import sharing
from ekuiper_tpu.runtime.rule import RunState
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.server.rule_manager import RuleRegistry
from ekuiper_tpu.store import kv


def _mk_stream(store, name="recv", topic="recv/t"):
    StreamProcessor(store).exec_stmt(
        f'CREATE STREAM {name} (deviceId STRING, v FLOAT) '
        f'WITH (DATASOURCE="{topic}", TYPE="memory", FORMAT="JSON")')


def _rule_json(rid, window=True, extra=None):
    sql = ("SELECT deviceId, avg(v) AS a FROM recv "
           "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)") if window \
        else "SELECT deviceId, v FROM recv"
    return {"id": rid, "sql": sql, "actions": [{"nop": {}}],
            "options": dict(extra or {})}


def _wait_state(reg, rid, state, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rs = reg.state(rid)
        if rs is not None and rs.state == state:
            return rs
        time.sleep(0.02)
    rs = reg.state(rid)
    raise AssertionError(
        f"{rid} never reached {state}; at "
        f"{rs.state if rs else None}")


def _hard_kill(reg):
    """Crash-shape teardown: node close only, no graceful state save, no
    run-table writes — what a SIGKILL leaves behind."""
    for entry in reg.list():
        rs = reg.state(entry["id"])
        if rs is None:
            continue
        rs._stop_supervision.set()
        if rs.topo is not None:
            rs.topo.close()
            with rs._lock:
                rs.topo = None
                rs.state = RunState.STOPPED


class TestRecoverAfterChurnKill:
    def test_every_fsm_state_recovers_correctly(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        reg = RuleRegistry(store)
        # running
        reg.create(_rule_json("run1"))
        _wait_state(reg, "run1", RunState.RUNNING)
        # stopped by the user (run table records False)
        reg.create(_rule_json("stop1"))
        _wait_state(reg, "stop1", RunState.RUNNING)
        reg.stop("stop1")
        _wait_state(reg, "stop1", RunState.STOPPED)
        # scheduled (cron between firings — ACTIVE, must resume)
        reg.create(_rule_json(
            "cron1", extra={"cron": "0 0 * * *", "duration": "10s"}))
        _wait_state(reg, "cron1", RunState.SCHEDULED)
        # stopped_by_error (a crashed rule marked started in the run
        # table: boot recovery retries it)
        reg.create(_rule_json("err1"))
        rs_err = _wait_state(reg, "err1", RunState.RUNNING)
        with rs_err._lock:
            rs_err._set_state(RunState.STOPPED_BY_ERR, reason="induced")
        # churn: one rule created AND deleted before the kill — its
        # sharing declaration must not survive as a ghost peer
        reg.create(_rule_json("ghost1"))
        _wait_state(reg, "ghost1", RunState.RUNNING)
        reg.delete("ghost1")

        _hard_kill(reg)

        reg2 = RuleRegistry(store)
        reg2.recover()
        # started rules resume
        _wait_state(reg2, "run1", RunState.RUNNING)
        _wait_state(reg2, "err1", RunState.RUNNING)
        _wait_state(reg2, "cron1", RunState.SCHEDULED)
        # user-stopped stays stopped
        time.sleep(0.2)
        assert reg2.state("stop1").state == RunState.STOPPED
        # no ghost sharing declarations: every declared rule id still
        # exists in the definition store
        live = set(reg2.processor.list())
        declared = {rid for decls in sharing._declared.values()
                    for rid in decls}
        assert declared <= live, f"ghost declarations: {declared - live}"
        assert "ghost1" not in declared
        reg2.stop_all()

    def test_queued_rule_survives_restart(self, mock_clock, monkeypatch):
        """A queue-admitted rule must not be stranded by a restart: the
        persisted admission_queue slot re-enqueues it with the new
        controller, and it starts when pressure clears."""
        from ekuiper_tpu.runtime import control

        store = kv.get_store()
        _mk_stream(store, "recv3", "recv3/t")
        reg = RuleRegistry(store)
        box = {"x": {"state": "breaching"}}
        ctl = control.install(lambda: [], start_fn=reg.start, start=False)
        ctl._verdicts_fn = lambda: dict(box)
        monkeypatch.setenv("KUIPER_ADMISSION_DEFER_BREACHING", "1")
        reg.create({"id": "qr1", "sql": "SELECT deviceId FROM recv3",
                    "actions": [{"nop": {}}]})
        assert ctl.queued("qr1") is not None
        assert store.kv("admission_queue").get_ok("qr1")[1]

        _hard_kill(reg)
        # "restart": fresh registry + fresh controller (the in-memory
        # queue died with the process)
        reg2 = RuleRegistry(store)
        ctl2 = control.install(lambda: [], start_fn=reg2.start,
                               start=False)
        ctl2._verdicts_fn = lambda: dict(box)
        reg2.recover()
        assert ctl2.queued("qr1") is not None  # re-enqueued, not stranded
        rs = reg2.state("qr1")
        assert rs is None or rs.topo is None  # still deferred
        box.clear()
        monkeypatch.delenv("KUIPER_ADMISSION_DEFER_BREACHING")
        ctl2.tick()
        _wait_state(reg2, "qr1", RunState.RUNNING)
        assert not store.kv("admission_queue").get_ok("qr1")[1]
        reg2.stop_all()

    def test_recover_resumes_checkpointed_state(self, mock_clock):
        """qos=1 rule killed between checkpoints resumes from the LAST
        completed checkpoint (not the stop-time save — a hard kill never
        ran one)."""
        import ekuiper_tpu.io.memory as mem
        from tests.conftest import wait_for_checkpoint

        store = kv.get_store()
        _mk_stream(store, "recv2", "recv2/t")
        reg = RuleRegistry(store)
        reg.create({
            "id": "ck1",
            "sql": ("SELECT deviceId, count(*) AS c FROM recv2 "
                    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            "actions": [{"memory": {"topic": "recv2/out"}}],
            "options": {"qos": 1}})
        rs = _wait_state(reg, "ck1", RunState.RUNNING)
        mem.publish("recv2/t", {"deviceId": "a", "v": 1.0})
        mock_clock.advance(20)
        rs.topo.wait_idle(5.0)
        cid = rs.topo.trigger_checkpoint()
        wait_for_checkpoint(store, "ck1", cid)
        _hard_kill(reg)
        reg2 = RuleRegistry(store)
        reg2.recover()
        rs2 = _wait_state(reg2, "ck1", RunState.RUNNING)
        snap, ok = store.kv("checkpoint:ck1").get_ok("latest")
        assert ok and snap["checkpoint_id"] == cid
        # the restored topo carries on: a window fires with both the
        # checkpointed and the fresh row
        got = []
        mem.subscribe("recv2/out", lambda t, p: got.append(p))
        mem.publish("recv2/t", {"deviceId": "a", "v": 2.0})
        mock_clock.advance(10_000)
        deadline = time.time() + 8
        while time.time() < deadline and not got:
            time.sleep(0.02)
        assert got, "recovered rule never emitted a window"
        reg2.stop_all()
