"""jitcert (observability/jitcert.py + tools/jitcert): compile-contract
certificates — derivation math, the runtime observed-vs-certified diff,
registry lifetime, the sketch pad ladder, and the CLI gates. CPU jax,
tier-1."""
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from ekuiper_tpu.observability import devwatch, jitcert
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy, slot_dtype
from ekuiper_tpu.sql.parser import parse_select

REPO = Path(__file__).resolve().parent.parent


def _plan(sql="SELECT deviceId, avg(v) AS a, count(*) AS c FROM s "
              "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)"):
    plan = extract_kernel_plan(parse_select(sql))
    assert plan is not None
    return plan


def _gb(capacity=32, n_panes=1, micro_batch=16, sql=None):
    return DeviceGroupBy(_plan(sql) if sql else _plan(),
                         capacity=capacity, n_panes=n_panes,
                         micro_batch=micro_batch)


def _cert(gb, op):
    certs = {c.op: c for c in jitcert.certificates_for(gb)}
    return certs[op]


# ------------------------------------------------------------- derivations
class TestDerivations:
    def test_deterministic_and_machine_checkable(self):
        gb = _gb()
        a = jitcert.certificates_for(gb)
        b = jitcert.certificates_for(gb)
        assert [c.op for c in a] == [c.op for c in b]
        for ca, cb in zip(a, b):
            assert ca.signatures == cb.signatures
            assert ca.params == cb.params
            assert not ca.truncated
            assert ca.signatures  # never empty
            assert ca.derivation  # carries its reasoning

    def test_capacity_ladder_spans_growth(self):
        gb = _gb(capacity=32)
        fold = _cert(gb, "groupby.fold")
        caps = {32 << i for i in range(jitcert.MAX_GROWS + 1)}
        seen = set()
        for sig in fold.signatures:
            for leaf in sig.split("|"):
                if leaf.startswith("float32[1,") and leaf.count(",") == 1:
                    seen.add(int(leaf[len("float32[1,"):-1]))
        assert seen == caps

    def test_slot_dtype_boundary(self):
        """Certified slots carry BOTH wire dtypes (cached uint16 arrays
        outlive a grow; int32 appears past 65,535) — and the boundary
        function itself is what the derivation mirrors."""
        assert slot_dtype(65535) is np.uint16
        assert slot_dtype(65536) is np.int32
        gb = _gb(micro_batch=16)
        fold = _cert(gb, "groupby.fold")
        assert any("uint16[16]" in s for s in fold.signatures)
        assert any("int32[16]" in s for s in fold.signatures)

    def test_mask_subsets_and_pane_forms(self):
        gb = _gb(micro_batch=16)
        fold = _cert(gb, "groupby.fold")
        # event-time per-row pane vector and scalar pane both certified
        assert any(s.endswith("uint8[16]") for s in fold.signatures)
        assert any(s.endswith("int32[]") for s in fold.signatures)
        # the avg(v) plan has one column: signatures with and without
        # its validity mask must both be legal
        assert any("bool[16]" in s for s in fold.signatures)
        assert any("bool[16]" not in s for s in fold.signatures)

    def test_boundary_tails(self):
        gb = _gb(n_panes=4)
        fin = _cert(gb, "groupby.finalize")
        assert all(s.endswith("True|True|True|True")
                   for s in fin.signatures)
        dyn = _cert(gb, "groupby.finalize_dyn")
        assert all(s.endswith("bool[4]") for s in dyn.signatures)
        reset = _cert(gb, "groupby.reset_pane")
        assert all(s.endswith("int32[]") for s in reset.signatures)

    def test_hh_plan_certifies_hh_finalize(self):
        gb = _gb(sql="SELECT deviceId, heavy_hitters(tag, 2) AS h FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")
        ops = {c.op for c in jitcert.certificates_for(gb)}
        assert "groupby.hh_finalize" in ops

    def test_unknown_kernel_raises(self):
        with pytest.raises(ValueError, match="no jitcert derivation"):
            jitcert.certificates_for(object())

    def test_estimate_plan_signatures(self):
        plan = _plan()
        n = jitcert.estimate_plan_signatures(plan, 1, 4096, 16384)
        assert n > 0
        # hopping panes widen the surface, never shrink it
        n4 = jitcert.estimate_plan_signatures(plan, 4, 4096, 16384)
        assert n4 >= n
        # deterministic
        assert n == jitcert.estimate_plan_signatures(plan, 1, 4096, 16384)

    WIDE_SQL = ("SELECT deviceId, "
                + ", ".join(f"avg(c{i}) AS a{i}" for i in range(7))
                + " FROM s GROUP BY deviceId, TUMBLINGWINDOW(ss, 1)")

    def test_wide_rule_prices_its_true_surface(self):
        """Review regression: a 7-column rule's mask-subset enumeration
        truncates (2^7 > MASK_SUBSET_CAP), but admission must price the
        TRUE product-formula surface — otherwise the signature budget
        inverts, admitting the compile-heaviest rules while rejecting
        narrower honest ones."""
        wide = jitcert.estimate_plan_signatures(
            _plan(self.WIDE_SQL), 1, 4096, 16384)
        narrow = jitcert.estimate_plan_signatures(_plan(), 1, 4096, 16384)
        assert wide >= (1 << 7)  # at least the 2^7 mask subsets
        assert wide > narrow
        fold = _cert(_gb(sql=self.WIDE_SQL), "groupby.fold")
        assert fold.truncated
        assert fold.full_count > len(fold.signatures)

    def test_truncated_site_surfaces_as_open_not_silent(self):
        """Review regression: a truncated certificate cannot be
        enforced — the diff must SAY so (sites_open + open_sites), not
        silently skip the site while reporting full coverage."""
        gb = _gb(sql=self.WIDE_SQL)
        state = gb.init_state()
        cols = {f"c{i}": np.arange(10, dtype=np.float64)
                for i in range(7)}
        state = gb.fold(state, cols, np.arange(10, dtype=np.int32) % 4)
        d = jitcert.diff_live()
        assert d["sites_open"] >= 1
        assert any(o["op"] == "groupby.fold" and "truncated"
                   in o["reason"] for o in d["open_sites"])


# ------------------------------------------------------------ runtime diff
class TestRuntimeDiff:
    def _drive(self, gb, n_keys=4):
        state = gb.init_state()
        cols = {"v": np.arange(10, dtype=np.float64)}
        slots = np.arange(10, dtype=np.int32) % n_keys
        state = gb.fold(state, cols, slots)
        gb.finalize(state, n_keys)
        return state

    def test_clean_on_certified_workload(self):
        gb = _gb()
        self._drive(gb)
        d = jitcert.diff_live()
        assert d["clean"]
        assert d["sites_observed"] >= 2
        assert d["observed_signatures"] >= 2
        assert d["certified_signatures"] > 0
        assert d["uncertified"] == []

    def test_growth_respecialization_stays_certified(self):
        gb = _gb(capacity=32)
        state = self._drive(gb)
        state = gb.grow(state, 64)
        cols = {"v": np.arange(10, dtype=np.float64)}
        state = gb.fold(state, cols, np.arange(10, dtype=np.int32) % 4)
        gb.finalize(state, 4)
        assert jitcert.diff_live()["clean"]

    def test_observed_outside_certificate_is_reported(self):
        """The report IS the signature: drive a pane-mask combination
        the static-tuple certificate does not admit (all-True only) and
        the diff must name the op, rule, and offending signature."""
        gb = _gb(n_panes=2)
        state = gb.init_state()
        # direct static-route call with a SUBSET mask — every engine
        # caller routes subsets through the traced-mask twin, so this
        # is exactly an uncertified executable
        gb._finalize(state, (True, False))
        d = jitcert.diff_live()
        assert not d["clean"]
        bad = [u for u in d["uncertified"]
               if u["op"] == "groupby.finalize"]
        assert bad and bad[0]["signature"].endswith("True|False")
        assert "outside the certified set" in bad[0]["reason"]

    def test_uncovered_site_is_reported(self):
        gb = _gb()
        self._drive(gb)
        jitcert.reset()  # certificates gone, observations remain
        d = jitcert.diff_live()
        assert not d["clean"]
        assert d["sites_uncovered"] >= 1
        assert any("no certificate registered" in u["reason"]
                   for u in d["uncertified"])

    def test_registry_weakref_lifetime(self):
        import gc

        gb = _gb()
        assert any(op == "groupby.fold"
                   for (op, _r) in jitcert.live_certificates())
        del gb
        gc.collect()
        assert not jitcert.live_certificates()

    def test_rule_attribution_fallback_pools_by_op(self):
        """An OpWatch whose rule tag drifted from the registration
        (restart) still diffs against the op's pooled certificates."""
        gb = _gb()
        self._drive(gb)
        for w in devwatch.registry().watches():
            w.rule = "restarted_rule"
        assert jitcert.diff_live()["clean"]


# ------------------------------------------------------------ sketch ladder
class TestSketchPadLadder:
    def test_counts_unaffected_by_padding(self):
        from ekuiper_tpu.ops.sketches import CountMinSketch

        sk = CountMinSketch(depth=2, width=128, max_candidates=64)
        sk.update(np.array([1.0] * 5 + [2.0] * 3, dtype=np.float32))
        hh = dict(sk.heavy_hitters(2))
        assert hh[1.0] >= 5 and hh[2.0] >= 3
        # zero-weight pad rows must not inflate any estimate
        assert hh[1.0] < 5 + 8  # cm overestimates, but not by the pad
        # review regression: the 0.0 pad filler must never become a
        # phantom CANDIDATE (it would burn a max_candidates slot and
        # could surface with a nonzero collided estimate)
        assert 0.0 not in sk.candidates

    def test_update_signatures_ride_pow2_ladder(self):
        from ekuiper_tpu.ops.sketches import CountMinSketch, _pad_pow2

        assert _pad_pow2(1) == 256
        assert _pad_pow2(256) == 256
        assert _pad_pow2(257) == 512
        sk = CountMinSketch(depth=2, width=64)
        for n in (3, 200, 300, 600):
            sk.update(np.arange(n, dtype=np.float32))
        d = jitcert.diff_live()
        assert d["clean"]
        obs = [w for w in devwatch.registry().watches()
               if w.op == "sketch.update"]
        sigs = set().union(*(w.signature_dump() for w in obs))
        # 3+200 share the 256 bucket; 300 and 600 take 512 and 1024
        assert len(sigs) == 3


# -------------------------------------------------------------------- CLI
class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.jitcert", *args],
            capture_output=True, text=True, timeout=300, cwd=str(REPO))

    def test_certify_gate(self):
        proc = self._run("certify", "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["ok"]
        assert report["total_signatures"] > 0
        # every non-sharded derivation is exercised by the battery
        assert set(report["ops_certified"]) >= {
            op for op in jitcert.SITE_DERIVATIONS
            if not op.startswith("sharded.")}

    def test_diff_gate(self):
        proc = self._run("diff", "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["clean"]
        assert report["observed_signatures"] > 0
