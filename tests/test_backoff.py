"""Jittered exponential backoff (utils/backoff.py) + its connector
wiring. The schedule is a pure function of (attempt, rng), so every
assertion here is deterministic with a seeded RNG and nothing sleeps
(connector waits run against stop events the tests pre-set)."""
import random
import threading

from ekuiper_tpu.utils.backoff import Backoff, backoff_delay_s


class TestBackoffDelay:
    def test_exponential_growth_and_cap(self):
        rng = random.Random(7)
        raws = [backoff_delay_s(a, base_s=1.0, cap_s=30.0, rng=rng)
                for a in range(1, 10)]
        # every delay sits in [raw/2, raw] of its attempt's raw value
        for a, d in enumerate(raws, start=1):
            raw = min(1.0 * 2 ** (a - 1), 30.0)
            assert raw / 2 <= d <= raw
        # cap: attempts far out never exceed cap_s
        assert backoff_delay_s(50, base_s=1.0, cap_s=30.0,
                               rng=random.Random(1)) <= 30.0

    def test_jitter_spreads_concurrent_retriers(self):
        # two clients at the SAME attempt must (almost surely) pick
        # different delays — the whole point vs fixed sleeps
        d1 = backoff_delay_s(4, rng=random.Random(1))
        d2 = backoff_delay_s(4, rng=random.Random(2))
        assert d1 != d2

    def test_floor_never_zero(self):
        # equal jitter keeps >= raw/2: full jitter could return ~0 and
        # hot-spin a dead broker
        for seed in range(20):
            assert backoff_delay_s(1, base_s=0.1,
                                   rng=random.Random(seed)) >= 0.05


class TestBackoffObject:
    def test_schedule_advances_and_resets(self):
        bo = Backoff(base_s=1.0, cap_s=30.0, rng=random.Random(3))
        first = bo.next_s()
        second = bo.next_s()
        assert first <= 1.0 and second <= 2.0 and second > 0.5
        assert bo.attempt == 2
        bo.reset()
        assert bo.attempt == 0
        assert bo.next_s() <= 1.0

    def test_wait_interrupted_by_stop(self):
        bo = Backoff(base_s=60.0, rng=random.Random(0))
        stop = threading.Event()
        stop.set()
        # a set stop event returns True immediately — close() must be
        # able to interrupt a capped 60s backoff
        assert bo.wait(stop) is True


class TestConnectorWiring:
    def test_kafka_retry_deadline_is_jittered(self):
        """_note_failure's per-partition deadline must land inside the
        jittered window of the attempt's raw exponential delay."""
        import time

        from ekuiper_tpu.io.kafka_io import KafkaSource

        src = KafkaSource()
        src.topic = "t"
        fails, retry_at = {}, {}
        t0 = time.monotonic()
        src._note_failure(fails, retry_at, 0, 42, RuntimeError("x"))
        src._note_failure(fails, retry_at, 0, 42, RuntimeError("x"))
        assert fails[0] == 2
        # attempt 2: raw = 2s -> deadline within (t0+1.0, t0+2.0+eps)
        delta = retry_at[0] - t0
        assert 1.0 <= delta <= 2.1

    def test_zmq_sub_uses_backoff(self):
        import inspect

        from ekuiper_tpu.io import zmq_native

        src = inspect.getsource(zmq_native.SubClient._run)
        assert "Backoff" in src and "backoff.wait" in src

    def test_mqtt_reconnect_uses_backoff(self):
        import inspect

        from ekuiper_tpu.io import mqtt_native

        src = inspect.getsource(mqtt_native.NativeMqttClient._reconnect) \
            if hasattr(mqtt_native, "NativeMqttClient") else ""
        if not src:  # class name may differ — find the method on any class
            for name in dir(mqtt_native):
                obj = getattr(mqtt_native, name)
                if isinstance(obj, type) and hasattr(obj, "_reconnect"):
                    src = inspect.getsource(obj._reconnect)
                    break
        assert "Backoff" in src and "bo.wait" in src
