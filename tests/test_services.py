"""External services: descriptor JSON → SQL functions over REST/gRPC/
msgpack-rpc (reference internal/service/manager.go, executors.go)."""
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ekuiper_tpu.services.manager import ServiceManager
from ekuiper_tpu.services.schema import ProtoServiceSchema
from ekuiper_tpu.functions import registry as fn_registry
from ekuiper_tpu.store import kv

PROTO = """
syntax = "proto3";
package sample;

message Req { string text = 1; int32 times = 2; }
message Resp { string out = 1; }

service Helper {
  rpc EchoTimes(Req) returns (Resp);
}
"""


@pytest.fixture
def rest_stub():
    calls = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            calls.append((self.path, body))
            if self.path.endswith("/EchoTimes"):
                out = {"out": body.get("text", "") * int(body.get("times", 1))}
            elif self.path.endswith("/double"):
                out = {"value": body * 2 if isinstance(body, (int, float))
                       else [v * 2 for v in body]}
            else:
                out = {"echo": body}
            data = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}", calls
    srv.shutdown()


class TestProtoServiceSchema:
    def test_method_index_and_marshal(self):
        s = ProtoServiceSchema(PROTO)
        full, in_cls, out_cls = s.method("EchoTimes")
        assert full == "sample.Helper"
        msg = s.build_request("EchoTimes", ["ab", 3])
        assert msg.text == "ab" and msg.times == 3
        msg2 = s.build_request("EchoTimes", [{"text": "x", "times": 2}])
        assert msg2.text == "x"
        resp = out_cls(out="zz")
        assert s.result_to_value("EchoTimes", resp) == "zz"  # single field unwraps


class TestRestService:
    def test_schemaless_function_call(self, rest_stub):
        addr, calls = rest_stub
        mgr = ServiceManager(kv.get_store())
        mgr.create("mysvc", {"interfaces": {"calc": {
            "address": addr, "protocol": "rest",
            "functions": [{"name": "sv_echo", "serviceName": "echoit"}],
        }}})
        fd = fn_registry.lookup("sv_echo")
        assert fd is not None
        out = fd.exec([{"a": 1}], None)
        assert out == {"echo": {"a": 1}}
        assert calls[-1][0] == "/echoit"

    def test_protobuf_rest(self, rest_stub):
        addr, calls = rest_stub
        mgr = ServiceManager(kv.get_store())
        mgr.create("psvc", {"interfaces": {"helper": {
            "address": addr, "protocol": "rest",
            "schemaType": "protobuf", "schemaContent": PROTO,
        }}})
        # no explicit mapping -> every proto method is a function
        fd = fn_registry.lookup("echotimes")
        assert fd is not None
        assert fd.exec(["ab", 2], None) == "abab"
        assert calls[-1][1] == {"text": "ab", "times": 2}

    def test_sql_rule_calls_external_function(self, rest_stub, mock_clock):
        addr, _ = rest_stub
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        import ekuiper_tpu.io.memory as mem

        store = kv.get_store()
        mgr = ServiceManager(store)
        mgr.create("s1", {"interfaces": {"helper": {
            "address": addr, "protocol": "rest",
            "schemaType": "protobuf", "schemaContent": PROTO,
            "functions": [{"name": "rep", "serviceName": "EchoTimes"}],
        }}})
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (word STRING, n BIGINT) '
            'WITH (DATASOURCE="svc/demo", TYPE="memory", FORMAT="JSON")')
        topo = plan_rule(RuleDef(
            id="svcr", sql="SELECT rep(word, n) AS out FROM demo",
            actions=[{"memory": {"topic": "svc/out"}}], options={}), store)
        got = []
        mem.subscribe("svc/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("svc/demo", {"word": "hi", "n": 3})
            mock_clock.advance(20)
            deadline = time.time() + 6
            while time.time() < deadline and not got:
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = []
        for p in got:
            msgs.extend(p if isinstance(p, list) else [p])
        assert msgs and msgs[0]["out"] == "hihihi"


class TestGrpcService:
    def test_grpc_roundtrip(self):
        import grpc
        from concurrent import futures

        schema = ProtoServiceSchema(PROTO)
        _, in_cls, out_cls = schema.method("EchoTimes")

        def repeat(request, context):
            return out_cls(out=request.text * request.times)

        server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        handler = grpc.method_handlers_generic_handler("sample.Helper", {
            "EchoTimes": grpc.unary_unary_rpc_method_handler(
                repeat, request_deserializer=in_cls.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })
        server.add_generic_rpc_handlers((handler,))
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            mgr = ServiceManager(kv.get_store())
            mgr.create("gsvc", {"interfaces": {"helper": {
                "address": f"127.0.0.1:{port}", "protocol": "grpc",
                "schemaType": "protobuf", "schemaContent": PROTO,
                "functions": [{"name": "grepeat", "serviceName": "EchoTimes"}],
            }}})
            fd = fn_registry.lookup("grepeat")
            assert fd.exec(["xy", 2], None) == "xyxy"
        finally:
            server.stop(0)


class TestMsgpackService:
    def test_msgpack_rpc_roundtrip(self):
        import msgpack

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def serve():
            conn, _ = srv.accept()
            unp = msgpack.Unpacker(raw=False)
            while True:
                data = conn.recv(65536)
                if not data:
                    return
                unp.feed(data)
                for frame in unp:
                    typ, msgid, method, params = frame
                    result = sum(params) if method == "add" else None
                    conn.sendall(msgpack.packb([1, msgid, None, result]))

        threading.Thread(target=serve, daemon=True).start()
        mgr = ServiceManager(kv.get_store())
        mgr.create("msvc", {"interfaces": {"m": {
            "address": f"tcp://127.0.0.1:{port}", "protocol": "msgpack-rpc",
            "functions": [{"name": "madd", "serviceName": "add"}],
        }}})
        fd = fn_registry.lookup("madd")
        assert fd.exec([1, 2, 3], None) == 6
        srv.close()


class TestManagerCrud:
    def test_crud_and_persistence(self, rest_stub):
        addr, _ = rest_stub
        store = kv.get_store()
        mgr = ServiceManager(store)
        desc = {"interfaces": {"i": {
            "address": addr, "protocol": "rest",
            "functions": [{"name": "pfn", "serviceName": "echoit"}]}}}
        mgr.create("crudsvc", desc)
        assert "crudsvc" in mgr.list()
        assert mgr.describe("crudsvc") == desc
        assert any(f["name"] == "pfn" for f in mgr.list_functions())
        # restore from the store into a FRESH manager (boot path)
        mgr2 = ServiceManager(store)
        assert "crudsvc" in mgr2.list()
        assert fn_registry.lookup("pfn") is not None
        mgr2.delete("crudsvc")
        assert "crudsvc" not in mgr2.list()
        assert fn_registry.lookup("pfn") is None

    def test_builtin_clash_rejected(self, rest_stub):
        addr, _ = rest_stub
        mgr = ServiceManager(kv.get_store())
        with pytest.raises(Exception, match="already exists"):
            mgr.create("clash", {"interfaces": {"i": {
                "address": addr, "protocol": "rest",
                "functions": [{"name": "abs", "serviceName": "x"}]}}})
