"""Device-path SLIDING windows (round 3): exact row-triggered semantics via
time panes + edge-bucket refolds from the host ring, checked for parity
against the host WindowNode path on identical timestamped rows.

Reference semantics: internal/topo/node/window_op.go:741 (sliding trigger
per row, OVER(WHEN ...) gating, optional delay).
"""
import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch, from_tuples
from ekuiper_tpu.data.rows import Tuple
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.planner.planner import device_path_eligible
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.utils.config import RuleOptionConfig

SQL = ("SELECT deviceId, count(*) AS c, avg(temp) AS a, min(temp) AS mn, "
       "max(temp) AS mx FROM s GROUP BY deviceId, "
       "SLIDINGWINDOW(ss, 2) OVER (WHEN temp > 90)")

SQL_PCT = ("SELECT deviceId, percentile_approx(temp, 0.5) AS p50 FROM s "
           "GROUP BY deviceId, SLIDINGWINDOW(ss, 2) OVER (WHEN temp > 90)")


def mkbatches(rng, n_batches=8, rows=64, keys=5, t0=10_000, step=100):
    """Batches with monotone timestamps; ~1/15 rows trigger (temp>90)."""
    out = []
    t = t0
    for _ in range(n_batches):
        ids = np.array([f"d{i}" for i in rng.integers(0, keys, rows)],
                       dtype=np.object_)
        temp = rng.uniform(0, 95, rows).astype(np.float32)
        ts = t + np.sort(rng.integers(0, step, rows)).astype(np.int64)
        out.append(ColumnBatch(
            n=rows, columns={"deviceId": ids, "temp": temp},
            timestamps=ts, emitter="s"))
        t += step
    return out


def run_device(sql, batches):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    node = FusedWindowAggNode(
        "sd", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=128,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item: got.append(item)
    for b in batches:
        node.process(b)
    node._drain_async_emits()  # trigger emissions deliver via the worker
    return got, node


def run_host_expected(sql, batches):
    """Ground truth computed directly from the row data: for each trigger
    row t, window rows are (t-L, t+delay]."""
    stmt = parse_select(sql)
    L = stmt.window.length_ms()
    delay = stmt.window.delay_ms()
    rows = []
    for b in batches:
        for i in range(b.n):
            rows.append((int(b.timestamps[i]), b.columns["deviceId"][i],
                         float(b.columns["temp"][i])))
    out = []
    for t, _, temp in rows:
        if temp <= 90:
            continue
        sel = [(k, v) for (ts, k, v) in rows if t - L < ts <= t + delay]
        per = {}
        for k, v in sel:
            per.setdefault(k, []).append(v)
        out.append((t, per))
    return out


def flat(items):
    msgs = []
    for item in items:
        if isinstance(item, ColumnBatch):
            msgs.extend(item.to_messages())
        elif isinstance(item, list):
            msgs.extend(item)
        else:
            msgs.append(item.message if hasattr(item, "message") else item)
    return msgs


def per_trigger(items):
    """One dict {deviceId: msg} per emission (device emits per trigger)."""
    out = []
    for item in items:
        msgs = flat([item])
        out.append({m["deviceId"]: m for m in msgs})
    return out


class TestSlidingDeviceParity:
    def test_eligibility(self):
        stmt = parse_select(SQL)
        assert device_path_eligible(stmt, RuleOptionConfig()) is not None
        # no trigger condition -> host path
        stmt2 = parse_select(
            "SELECT deviceId, count(*) AS c FROM s "
            "GROUP BY deviceId, SLIDINGWINDOW(ss, 2)")
        assert device_path_eligible(stmt2, RuleOptionConfig()) is None
        # event-time sliding -> host path
        assert device_path_eligible(
            stmt, RuleOptionConfig(is_event_time=True)) is None

    def test_parity_counts_avg_min_max(self):
        rng = np.random.default_rng(7)
        batches = mkbatches(rng)
        got, node = run_device(SQL, batches)
        expected = run_host_expected(SQL, batches)
        triggers = per_trigger(got)
        assert len(triggers) == len(expected) >= 1
        for trig, (t, per) in zip(triggers, expected):
            assert set(trig) == set(per)
            for k, vals in per.items():
                m = trig[k]
                assert m["c"] == len(vals)
                np.testing.assert_allclose(m["a"], np.mean(vals), rtol=1e-5)
                np.testing.assert_allclose(m["mn"], min(vals), rtol=1e-6)
                np.testing.assert_allclose(m["mx"], max(vals), rtol=1e-6)

    def test_parity_window_spans_many_buckets(self):
        """Window length >> bucket: full panes + both edge refolds used."""
        rng = np.random.default_rng(11)
        batches = mkbatches(rng, n_batches=30, rows=32, step=80)
        got, node = run_device(SQL, batches)
        assert node.bucket_ms < node.length_ms  # pane decomposition active
        expected = run_host_expected(SQL, batches)
        triggers = per_trigger(got)
        assert len(triggers) == len(expected)
        for trig, (t, per) in zip(triggers, expected):
            assert {k: m["c"] for k, m in trig.items()} == {
                k: len(v) for k, v in per.items()}

    def test_percentile_sliding(self):
        rng = np.random.default_rng(3)
        batches = mkbatches(rng, n_batches=10, rows=48)
        got, _ = run_device(SQL_PCT, batches)
        expected = run_host_expected(SQL_PCT, batches)
        triggers = per_trigger(got)
        assert len(triggers) == len(expected)
        for trig, (t, per) in zip(triggers, expected):
            assert set(trig) == set(per)
            for k, vals in per.items():
                # the sketch quantile is inverted-CDF (smallest value whose
                # cumulative count reaches q*n) with ~2-3% log-bin error —
                # compare against the same definition, not the interpolated
                # np.median
                emed = float(np.quantile(vals, 0.5, method="inverted_cdf"))
                assert abs(trig[k]["p50"] - emed) <= max(abs(emed) * 0.05, 0.5)

    def test_checkpoint_roundtrip(self):
        rng = np.random.default_rng(5)
        batches = mkbatches(rng, n_batches=6)
        stmt = parse_select(SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "s1", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        node.broadcast = lambda item: None
        for b in batches[:4]:
            node.process(b)
        snap = node.snapshot_state()
        import json

        snap = json.loads(json.dumps(snap))  # checkpoint serialization
        node2 = FusedWindowAggNode(
            "s2", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        got2 = []
        node2.broadcast = lambda item: got2.append(item)
        node2.restore_state(snap)
        for b in batches[4:]:
            node2.process(b)
        node2._drain_async_emits()
        # ground truth over ALL rows (windows straddle the checkpoint)
        expected = run_host_expected(SQL, batches)
        t_cut = int(batches[3].timestamps[-1])
        exp_after = [e for e in expected if e[0] > t_cut]
        triggers = per_trigger(got2)
        assert len(triggers) == len(exp_after)
        for trig, (t, per) in zip(triggers, exp_after):
            assert {k: m["c"] for k, m in trig.items()} == {
                k: len(v) for k, v in per.items()}


class TestSlidingRobustness:
    def test_late_rows_dropped_not_corrupting(self):
        """A late row is dropped (counted) ONLY when its pane has been
        recycled past its bucket; an ancient row landing in an unused pane
        is accepted harmlessly and never pollutes emitted windows."""
        stmt = parse_select(SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "lr", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=64,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)

        def b(ts_list, temps):
            k = len(ts_list)
            return ColumnBatch(
                n=k,
                columns={"deviceId": np.array(["d0"] * k, dtype=np.object_),
                         "temp": np.asarray(temps, dtype=np.float32)},
                timestamps=np.asarray(ts_list, dtype=np.int64), emitter="s")

        node.process(b([100_000, 100_200, 100_400], [50.0, 50.0, 50.0]))
        # ancient row: its pane was never assigned -> accepted, no drop
        before = node.stats.dropped.get("pane_recycle", 0)
        node.process(b([1_000], [50.0]))
        assert node.stats.dropped.get("pane_recycle", 0) == before
        # trigger: the emitted window must NOT include the ancient row
        node.process(b([100_500], [95.0]))
        node._drain_async_emits()
        msgs = flat(got)
        assert len(msgs) == 1 and msgs[0]["c"] == 4
        # row whose bucket ALIASES the pane of a live newer bucket -> drop
        head_bucket = 100_500 // node.bucket_ms
        conflict_ts = (head_bucket - node.n_ring_panes) * node.bucket_ms + 1
        node.process(b([conflict_ts], [50.0]))
        # taxonomy, not exceptions: a retention drop is by-design data loss
        assert node.stats.dropped.get("pane_recycle", 0) == before + 1
        assert node.stats.exceptions == 0

    def test_missing_trigger_column_is_no_trigger(self):
        stmt = parse_select(SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "mt", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=64,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        nobatch = ColumnBatch(
            n=2, columns={"deviceId": np.array(["a", "b"], dtype=np.object_)},
            timestamps=np.array([10_000, 10_001], dtype=np.int64),
            emitter="s")
        node.process(nobatch)  # no temp column: no triggers, no exception
        assert got == []

    def test_delayed_trigger_survives_restore(self, mock_clock):
        """SLIDINGWINDOW(ss,2,1): a pending delayed emission checkpointed
        before its fire time re-arms after restore and emits the window."""
        import json

        from ekuiper_tpu.utils import timex

        sql_d = ("SELECT deviceId, count(*) AS c FROM s GROUP BY deviceId, "
                 "SLIDINGWINDOW(ss, 2, 1) OVER (WHEN temp > 90)")
        stmt = parse_select(sql_d)
        plan = extract_kernel_plan(stmt)

        def mknode(name):
            n = FusedWindowAggNode(
                name, stmt.window, plan,
                dims=[d.expr for d in stmt.dimensions], capacity=64,
                micro_batch=64,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]))
            n.state = n.gb.init_state()
            got = []
            n.broadcast = lambda item: got.append(item)
            return n, got

        clock = timex.get_clock()
        clock.set(10_000)
        node, got = mknode("a")
        b = ColumnBatch(
            n=3, columns={"deviceId": np.array(["x", "x", "y"], dtype=np.object_),
                          "temp": np.array([10.0, 95.0, 20.0], dtype=np.float32)},
            timestamps=np.array([10_000, 10_050, 10_060], dtype=np.int64),
            emitter="s")
        node.process(b)
        assert node._pending_slides  # delayed emission armed, not fired
        snap = json.loads(json.dumps(node.snapshot_state()))

        node2, got2 = mknode("b")
        node2.restore_state(snap)
        assert got2 == []
        clock.advance(1_200)  # past fire time (10_050 + 1000)
        # the re-armed timer enqueues the Trigger; deliver it manually
        # (no worker thread in this direct-drive test)
        trig = node2.inq.get(timeout=1)
        node2.on_trigger(trig)
        node2._drain_async_emits()
        msgs = flat(got2)
        by = {m["deviceId"]: m["c"] for m in msgs}
        # window (8050, 11050]: all three rows
        assert by == {"x": 2, "y": 1}


class TestSlidingBurst:
    def test_batch_spanning_pane_budget_stays_exact(self):
        """A replay burst whose single batch spans more buckets than the
        pane ring must fold in alias-free chunks — the emitted window stays
        exact (review finding r3: two aliased buckets corrupted one pane)."""
        stmt = parse_select(SQL)
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan as _ekp
        from ekuiper_tpu.ops.emit import build_direct_emit as _bde
        plan = _ekp(stmt)
        node = FusedWindowAggNode(
            "burst", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=512,
            direct_emit=_bde(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        n = 200
        span_ms = (node.n_ring_panes + 5) * node.bucket_ms
        ts = np.sort(np.random.default_rng(3).integers(
            10_000, 10_000 + span_ms, n)).astype(np.int64)
        temp = np.full(n, 50.0, dtype=np.float32)
        temp[-1] = 95.0  # single trigger row at the end
        batch = ColumnBatch(
            n=n, columns={"deviceId": np.array(["d0"] * n, dtype=np.object_),
                          "temp": temp},
            timestamps=ts, emitter="s")
        node.process(batch)
        node._drain_async_emits()
        msgs = flat(got)
        assert len(msgs) == 1
        t = int(ts[-1])
        exact = int(np.sum((ts > t - stmt.window.length_ms()) & (ts <= t)))
        assert msgs[0]["c"] == exact

    def test_mildly_late_rows_still_fold(self):
        """Rows a few buckets out of order are NOT dropped when their pane
        still holds their bucket (review finding r3: over-aggressive late
        guard)."""
        stmt = parse_select(SQL)
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan as _ekp
        from ekuiper_tpu.ops.emit import build_direct_emit as _bde
        plan = _ekp(stmt)
        node = FusedWindowAggNode(
            "late", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=512,
            direct_emit=_bde(stmt, plan, ["deviceId"]))
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)

        def b(ts_list, temps):
            k = len(ts_list)
            return ColumnBatch(
                n=k,
                columns={"deviceId": np.array(["d0"] * k, dtype=np.object_),
                         "temp": np.asarray(temps, dtype=np.float32)},
                timestamps=np.asarray(ts_list, dtype=np.int64), emitter="s")

        node.process(b([10_000, 10_400], [50.0, 50.0]))
        # 8 buckets (200ms) behind the stream head, pane not recycled
        node.process(b([10_200], [50.0]))
        # trigger: window (8410-2000, 8410+0] ... covers all four rows
        node.process(b([10_410], [95.0]))
        node._drain_async_emits()
        msgs = flat(got)
        assert len(msgs) == 1
        assert msgs[0]["c"] == 4  # the late row counted


class TestDevRingBudget:
    """HBM budget on the refold impl's device-input cache (_dev_ring):
    past the cap the oldest entries drop to None and refolds take the
    exact host path — output parity must hold at ANY budget. Pinned to
    slidingImpl=refold: the DABA default keeps no batch cache at all
    (tests/test_sliding_ring.py covers its budget fallback)."""

    def _run_with_budget(self, budget_bytes):
        stmt = parse_select(SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "sb", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            sliding_impl="refold")
        if budget_bytes is not None:
            node.dev_ring_budget_bytes = budget_bytes
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        rng = np.random.default_rng(21)
        for b in mkbatches(rng, n_batches=10, rows=64):
            node.process(b)
        node._drain_async_emits()
        return got, node

    def test_zero_budget_evicts_everything_and_stays_exact(self):
        ref, _ = self._run_with_budget(None)
        got, node = self._run_with_budget(0)
        # cache fully evicted: nothing pinned, accounting balanced
        assert node._dev_ring_bytes == 0
        assert all(e is None for lst in node._dev_ring.values() for e in lst)
        # parity: host-path refolds produce the same windows
        assert per_trigger(got) == per_trigger(ref)

    def test_default_budget_caches_and_accounts(self):
        got, node = self._run_with_budget(None)
        cached = [e for lst in node._dev_ring.values() for e in lst
                  if e is not None]
        assert cached  # 64-row batches pass the mb//4 guard
        assert node._dev_ring_bytes > 0
        assert node._dev_ring_bytes <= node.dev_ring_budget_bytes

    def test_tiny_budget_keeps_only_newest(self):
        _, ref_node = self._run_with_budget(None)
        one_entry = ref_node._dev_entry_nbytes(
            next(e for lst in ref_node._dev_ring.values() for e in lst
                 if e is not None))
        got, node = self._run_with_budget(one_entry)
        cached = sum(1 for lst in node._dev_ring.values() for e in lst
                     if e is not None)
        assert cached <= 1
        assert node._dev_ring_bytes <= node.dev_ring_budget_bytes


class TestWarmupForce:
    def test_warmup_upload_bypasses_small_batch_guard(self):
        """The 1-row warmup batch must compile fold_masked: without force
        the mb//4 guard rejects it and the first real trigger pays the jit
        stall the warmup promises to avoid (ADVICE r5 medium)."""
        stmt = parse_select(SQL)
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "sw", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
            capacity=64, micro_batch=128,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            sliding_impl="refold")
        node.state = node.gb.init_state()
        cols = {n: np.zeros(1, dtype=np.float32) for n in plan.columns}
        slots = np.zeros(1, dtype=np.int32)
        assert node._upload_sliding_inputs(cols, {}, slots) is None
        dev = node._upload_sliding_inputs(cols, {}, slots, force=True)
        assert dev is not None
        # the forced upload is mb-padded: exactly what fold_masked takes
        assert int(dev[2].shape[0]) == node.gb.micro_batch
        # and _warmup itself goes through without error, compiling the
        # mask-only refold executable
        node._warmup()
