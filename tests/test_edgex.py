"""EdgeX message-bus connector (io/edgex_io.py): value-type mapping parity
with the reference (internal/io/edgex/source.go getValue, sink.go
getValueType), envelope round-trip over the in-repo redis bus, and an
edgex-format reading driven through a real rule to a sink."""
import base64
import json
import time

import numpy as np
import pytest

from ekuiper_tpu.io import registry as io_registry
from ekuiper_tpu.io.edgex_io import (
    EdgexSink, EdgexSource, decode_reading_value, infer_value_type)

from test_io_connectors import FakeRedis, fake_redis  # noqa: F401


class TestValueTypes:
    def test_simple_round_trip(self):
        cases = [
            (True, "Bool"), (False, "Bool"), (7, "Int64"), (-3, "Int64"),
            (2.5, "Float64"), ("hi", "String"),
            (b"\x01\x02", "Binary"), ({"a": 1}, "Object"),
            ([True, False], "BoolArray"), ([1, 2, 3], "Int64Array"),
            ([1.5, 2.0], "Float64Array"), (["x", "y"], "StringArray"),
        ]
        for v, want_vt in cases:
            vt, formatted = infer_value_type(v)
            assert vt == want_vt, (v, vt)
            reading = {"resourceName": "r", "valueType": vt}
            if vt == "Binary":
                reading["binaryValue"] = base64.b64encode(formatted).decode()
            elif vt == "Object":
                reading["objectValue"] = formatted
            else:
                reading["value"] = formatted
            back = decode_reading_value(reading)
            if isinstance(v, tuple):
                v = list(v)
            assert back == v, (v, back)

    def test_reference_source_forms(self):
        # string-encoded numerics and float-string arrays, as the reference
        # parses them (source.go:203-301)
        assert decode_reading_value(
            {"valueType": "Uint64", "value": "18446744073709551615"}) == \
            18446744073709551615
        assert decode_reading_value(
            {"valueType": "Float32", "value": "1.5"}) == 1.5
        assert decode_reading_value(
            {"valueType": "Float64Array", "value": '["1.1", "2.2"]'}) == \
            [1.1, 2.2]
        assert decode_reading_value(
            {"valueType": "Int32Array", "value": "[1, 2]"}) == [1, 2]
        # unsupported type degrades to string (warn-and-continue)
        assert decode_reading_value(
            {"valueType": "Exotic", "value": "raw"}) == "raw"
        with pytest.raises(ValueError):
            decode_reading_value({"valueType": "Bool", "value": "maybe"})
        with pytest.raises(ValueError):
            infer_value_type(None)


class TestBusRoundTrip:
    def test_sink_to_source_over_redis(self, fake_redis):  # noqa: F811
        sink = io_registry.create_sink("edgex")
        sink.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                        "protocol": "redis", "topic": "app/events",
                        "deviceName": "dev7", "sourceName": "ruleX"})
        sink.connect()
        src = io_registry.create_source("edgex")
        src.configure("app/events",
                      {"addr": f"127.0.0.1:{fake_redis.port}",
                       "protocol": "redis"})
        got = []
        src.open(lambda payload, meta=None: got.append((payload, meta)))
        deadline = time.time() + 5
        while time.time() < deadline and not fake_redis.subs:
            time.sleep(0.01)
        try:
            sink.collect({"temperature": 21.5, "count": 3, "ok": True,
                          "label": "warm"})
            deadline = time.time() + 5
            while time.time() < deadline and not got:
                time.sleep(0.01)
            assert got, "no event delivered over the bus"
            payload, meta = got[0]
            assert payload == {"temperature": 21.5, "count": 3, "ok": True,
                               "label": "warm"}
            assert meta["deviceName"] == "dev7"
            assert meta["sourceName"] == "ruleX"
            assert meta["temperature"]["valueType"] == "Float64"
            assert meta["count"]["valueType"] == "Int64"
        finally:
            src.close()
            sink.close()

    def test_request_message_type_and_bare_event(self, fake_redis):  # noqa: F811
        sink = EdgexSink()
        sink.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                        "protocol": "redis", "topic": "req/t",
                        "messageType": "request",
                        "contentType": "application/json"})
        sink.connect()
        src = EdgexSource()
        src.configure("req/t", {"addr": f"127.0.0.1:{fake_redis.port}",
                                "protocol": "redis",
                                "messageType": "request"})
        got = []
        src.open(lambda payload, meta=None: got.append(payload))
        deadline = time.time() + 5
        while time.time() < deadline and not fake_redis.subs:
            time.sleep(0.01)
        try:
            sink.collect([{"a": 1}, {"b": "x"}])  # rows merge into ONE event
            deadline = time.time() + 5
            while time.time() < deadline and not got:
                time.sleep(0.01)
            assert got[0] == {"a": 1, "b": "x"}
            # bare (non-enveloped) event JSON is also accepted
            from ekuiper_tpu.io.redis_io import RespClient

            ev = {"deviceName": "d", "readings": [
                {"resourceName": "x", "valueType": "Int64", "value": "9"}]}
            pub = RespClient("127.0.0.1", fake_redis.port)
            pub.connect()
            pub.command("PUBLISH", "req.t", json.dumps({"event": ev}))
            pub.close()
            deadline = time.time() + 5
            while time.time() < deadline and len(got) < 2:
                time.sleep(0.01)
            assert got[1] == {"x": 9}
        finally:
            src.close()
            sink.close()

    def test_topic_prefix_and_metadata_override(self, fake_redis):  # noqa: F811
        sink = EdgexSink()
        sink.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                        "protocol": "redis", "topicPrefix": "edgex/rules",
                        "metadata": "md"})
        sink.connect()
        # capture the published channel via a raw subscriber
        from ekuiper_tpu.io.redis_io import RespClient

        cli = RespClient("127.0.0.1", fake_redis.port)
        cli.connect()
        cli._sock.settimeout(5)
        cli.send("SUBSCRIBE", "edgex.rules.profZ.devZ.srcZ")
        cli.read_reply()  # subscribe ack
        try:
            sink.collect({"v": 1.0, "md": {
                "deviceName": "devZ", "profileName": "profZ",
                "sourceName": "srcZ",
                "v": {"valueType": "Float64", "origin": 123}}})
            reply = cli.read_reply()
            assert reply[0] in (b"message", "message")
            env = json.loads(reply[2])
            ev = json.loads(base64.b64decode(env["payload"]))
            assert ev["deviceName"] == "devZ" and ev["sourceName"] == "srcZ"
            r = ev["readings"][0]
            assert r["resourceName"] == "v" and r["origin"] == 123
            assert "md" not in [x["resourceName"] for x in ev["readings"]]
        finally:
            cli.close()
            sink.close()


class TestEdgexRuleE2E:
    def test_reading_through_rule_to_sink(self, fake_redis, mock_clock):  # noqa: F811
        """BASELINE config #3 shape: an edgex-format reading stream drives
        a windowed rule; results land in a sink (VERDICT r3 item 5)."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        store = kv.get_store()
        # conf_key profile carries the bus address (ref yaml_config_ops)
        store.kv("source_conf").set("edgex:default", {
            "addr": f"127.0.0.1:{fake_redis.port}", "protocol": "redis"})
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM edgexdemo (temperature FLOAT, humidity FLOAT) '
            'WITH (DATASOURCE="rules-events", TYPE="edgex", '
            'CONF_KEY="default", FORMAT="JSON")')
        topo = plan_rule(RuleDef(id="ex1", sql=(
            "SELECT avg(temperature) AS a, count(*) AS c FROM edgexdemo "
            "WHERE temperature > 20 GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "out/ex1"}}], options={}), store)
        sink = topo.sinks[0]
        topo.open()
        try:
            deadline = time.time() + 5
            while time.time() < deadline and not fake_redis.subs:
                time.sleep(0.01)
            # publish edgex readings through the sink side of the connector
            pub = EdgexSink()
            pub.configure({"addr": f"127.0.0.1:{fake_redis.port}",
                           "protocol": "redis", "topic": "rules-events"})
            pub.connect()
            for t_ in (18.0, 22.0, 30.0):
                pub.collect({"temperature": t_, "humidity": 40.0})
            pub.close()
            time.sleep(0.3)
            mock_clock.advance(50)   # linger flush
            time.sleep(0.3)
            mock_clock.advance(10_000)  # window closes
            deadline = time.time() + 8
            while time.time() < deadline and not sink.results:
                time.sleep(0.02)
            assert sink.results, "no window emitted from edgex stream"
            row = sink.results[0]
            row = row[0] if isinstance(row, list) else row
            assert row["c"] == 2 and row["a"] == pytest.approx(26.0)
        finally:
            topo.close()
