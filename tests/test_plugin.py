"""Portable plugin system tests — modeled on the reference's portable FVT
(fvt/portable_test.go) and the plugin mock server
(tools/plugin_server/plugin_test_server.go)."""
import json
import os
import threading
import time

import pytest

from ekuiper_tpu.plugin import ipc
from ekuiper_tpu.plugin.manager import PluginMeta, PortableManager
from ekuiper_tpu.plugin.portable import PortableSink, PortableSource

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "sample_plugin.py")


# ------------------------------------------------------------------ ipc layer
@pytest.mark.parametrize("force_pure", [False, True])
def test_ipc_pair_roundtrip(force_pure, monkeypatch, tmp_path):
    sock_cls = ipc._PySocket if force_pure else None
    mk = (lambda p: ipc._PySocket(p)) if force_pure else ipc.Socket
    url = f"ipc://{tmp_path}/pair.ipc"
    host = mk(ipc.PAIR)
    host.listen(url)
    results = []

    def worker():
        w = mk(ipc.PAIR)
        w.dial(url, 2000)
        w.send(b"ping")
        results.append(w.recv(2000))
        w.close()

    t = threading.Thread(target=worker)
    t.start()
    assert host.recv(2000) == b"ping"
    host.send(b"pong")
    t.join(timeout=5)
    host.close()
    assert results == [b"pong"]


def test_ipc_pull_fan_in(tmp_path):
    url = f"ipc://{tmp_path}/pull.ipc"
    pull = ipc.Socket(ipc.PULL)
    pull.listen(url)

    def pusher(i):
        p = ipc.Socket(ipc.PUSH)
        p.dial(url, 2000)
        for j in range(5):
            p.send(f"{i}:{j}".encode())
        time.sleep(0.2)
        p.close()

    ts = [threading.Thread(target=pusher, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    got = {pull.recv(3000).decode() for _ in range(15)}
    for t in ts:
        t.join(timeout=5)
    pull.close()
    assert got == {f"{i}:{j}" for i in range(3) for j in range(5)}


def test_ipc_recv_timeout(tmp_path):
    s = ipc.Socket(ipc.PAIR)
    s.listen(f"ipc://{tmp_path}/t.ipc")
    with pytest.raises(ipc.IpcTimeout):
        s.recv(100)
    s.close()


# ---------------------------------------------------------------- full plugin
@pytest.fixture
def manager():
    mgr = PortableManager()
    PortableManager.set_global(mgr)
    mgr.register(PluginMeta(
        name="sample", executable=FIXTURE,
        sources=["pycount"], sinks=["pyfile"], functions=["prev", "padd"],
    ))
    yield mgr
    mgr.kill_all()


def test_portable_function_exec(manager):
    from ekuiper_tpu.functions import registry as freg

    fd = freg.lookup("prev")
    assert fd is not None
    assert fd.exec(["hello"], {}) == "olleh"
    assert freg.lookup("padd").exec([3, 4], {}) == 7


def test_portable_function_worker_restart(manager):
    from ekuiper_tpu.functions import registry as freg

    assert freg.lookup("prev").exec(["ab"], {}) == "ba"
    # kill the worker behind its back; next call must respawn it
    ins = manager.get_or_start("sample")
    ins.proc.kill()
    ins.proc.wait(timeout=5)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            assert freg.lookup("prev").exec(["cd"], {}) == "dc"
            break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("worker did not restart")


def test_portable_source_ingest(manager):
    src = PortableSource(manager, "sample", "pycount")
    src.configure("", {"count": 8, "interval": 0.005})
    got = []
    src.open(lambda payload, meta=None: got.append(payload))
    deadline = time.monotonic() + 10
    while len(got) < 8 and time.monotonic() < deadline:
        time.sleep(0.05)
    src.close()
    assert [t["seq"] for t in got[:8]] == list(range(8))


def test_portable_sink_collect(manager, tmp_path):
    out = tmp_path / "sink.jsonl"
    sink = PortableSink(manager, "sample", "pyfile")
    sink.configure({"path": str(out)})
    sink.connect()
    for i in range(4):
        sink.collect({"i": i})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if out.exists() and len(out.read_text().splitlines()) >= 4:
            break
        time.sleep(0.05)
    sink.close()
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert [r["i"] for r in rows] == [0, 1, 2, 3]


def test_delete_unbinds_symbols(manager):
    from ekuiper_tpu.functions import registry as freg
    from ekuiper_tpu.io import registry as ioreg

    assert freg.lookup("prev") is not None
    assert "pycount" in ioreg.source_types()
    manager.delete("sample")
    assert freg.lookup("prev") is None
    assert "pycount" not in ioreg.source_types()


def test_manager_registry_persistence(tmp_path):
    from ekuiper_tpu.store.kv import Store

    store = Store("memory", str(tmp_path))
    mgr = PortableManager(store)
    mgr.register(PluginMeta(name="p1", executable=FIXTURE, functions=["prev"]))
    assert mgr.list() == ["p1"]
    # new manager over same store restores the registry
    mgr2 = PortableManager(store)
    assert mgr2.list() == ["p1"]
    assert mgr2.get("p1").functions == ["prev"]
    mgr2.delete("p1")
    assert mgr2.list() == []
