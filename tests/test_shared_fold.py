"""Shared pane-fold subsystem (planner/sharing.py + ops/panestore.py +
runtime/nodes_sharedfold.py): correlated rules over one stream fold once
into a shared pane store; per-rule emitted windows must be bit-for-bit
what the unshared plan produces, across tumbling/hopping and
processing/event time, including attach/detach mid-stream.

Parity inputs use integer-valued float32 measurements so pane-sum
association is exact (docs/SHARING.md "exactness" section): count/min/max
are order-independent, and integer-valued sums are exactly representable,
so shared-vs-private comparison is byte-identical, not approximate.
"""
import logging
import time

import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import (
    _call_key as spec_call_key_, extract_kernel_plan,
)
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.ops.panestore import pane_gcd, spec_map_into, union_plan
from ekuiper_tpu.planner import sharing
from ekuiper_tpu.planner.planner import RuleDef, explain, plan_rule
from ekuiper_tpu.runtime import nodes_sharedfold as sf
from ekuiper_tpu.runtime import subtopo
from ekuiper_tpu.runtime.events import Trigger, Watermark
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.runtime.nodes_sharedfold import (
    MemberSpec, SharedEmitNode, SharedFoldNode)
from ekuiper_tpu.data.rows import WindowRange
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.sql import ast
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.infra import logger
import ekuiper_tpu.io.memory as mem

SQLS = [
    "SELECT deviceId, avg(temperature) AS a, count(*) AS c FROM demo "
    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
    "SELECT deviceId, min(temperature) AS mn, max(temperature) AS mx "
    "FROM demo GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)",
    "SELECT deviceId, sum(temperature) AS s, count(*) AS c FROM demo "
    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 20)",
]


def _plans(sqls=SQLS):
    stmts = [parse_select(s) for s in sqls]
    return stmts, [extract_kernel_plan(s) for s in stmts]


def _member(i, stmt, plan, emit_columnar=True):
    w = stmt.window
    length = w.length_ms()
    iv = w.interval_ms() or length
    return MemberSpec(
        rule_id=f"r{i}", length_ms=length, interval_ms=iv, plan=plan,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        dims=["deviceId"], emit_columnar=emit_columnar)


def _private(stmt, plan, **kw):
    node = FusedWindowAggNode(
        "priv", stmt.window, plan, dims=[d.expr for d in stmt.dimensions],
        capacity=64, micro_batch=128,
        direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
        emit_columnar=True, prefinalize_lead_ms=0, **kw)
    node.state = node.gb.init_state()
    got = []
    node.broadcast = lambda item, g=got: g.append(item)
    return node, got


def _int_batch(rng, n, t0=0, span_ms=1):
    """Integer-valued measurements: pane-sum association is exact."""
    ids = np.array([f"d{rng.integers(0, 8)}" for _ in range(n)],
                   dtype=np.object_)
    temp = np.rint(rng.normal(20, 5, n)).astype(np.float32)
    ts = np.sort(rng.integers(t0, t0 + span_ms, n)).astype(np.int64)
    return ColumnBatch(n=n, columns={"deviceId": ids, "temperature": temp},
                       timestamps=ts, emitter="demo")


def _copy(b):
    return ColumnBatch(n=b.n, columns=b.columns, valid=b.valid,
                       timestamps=b.timestamps, emitter=b.emitter)


def _drain_cbs(entry):
    out = []
    while not entry.inq.empty():
        item = entry.inq.get_nowait()
        if isinstance(item, ColumnBatch):
            out.append(item)
    return out


def _assert_cb_equal(a, b, ctx=""):
    assert set(a.columns) == set(b.columns), ctx
    for c in a.columns:
        assert a.columns[c].dtype == b.columns[c].dtype, (ctx, c)
        assert np.array_equal(a.columns[c], b.columns[c]), (ctx, c)


def _private_boundary(p, end):
    iv = p.interval_ms or p.length_ms
    if end % iv:
        return
    p._emit(WindowRange(end - p.length_ms, end))
    if p.wt == ast.WindowType.TUMBLING_WINDOW:
        p.state = p.gb.reset_pane(p.state, 0)
    else:
        p.cur_pane = (p.cur_pane + 1) % p.n_panes
        p.state = p.gb.reset_pane(p.state, p.cur_pane)


class TestUnionPlan:
    def test_dedup_and_maps(self):
        stmts, plans = _plans([
            SQLS[0],
            "SELECT deviceId, count(*) AS c, avg(temperature) AS a, "
            "sum(temperature) AS s FROM demo "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        ])
        union, maps = union_plan(plans)
        # avg + count shared; sum added once
        assert [s.kind for s in union.specs] == ["avg", "count", "sum"]
        assert maps == [[0, 1], [1, 0, 2]]
        assert spec_map_into(union, plans[0]) == [0, 1]
        with pytest.raises(KeyError):
            spec_map_into(plans[0], plans[1])  # sum not covered

    def test_pane_gcd(self):
        assert pane_gcd([10_000, 5_000, 20_000]) == 5_000
        assert pane_gcd([10_000, 15_000]) == 5_000
        assert pane_gcd([]) == 1


class TestParityProcessingTime:
    def test_tumbling_hopping_byte_identical(self):
        stmts, plans = _plans()
        union, _ = union_plan(plans)
        pane = pane_gcd([10_000, 5_000, 20_000])
        store = SharedFoldNode("k", "sf", union, pane, 6, subtopo_ref=None,
                               capacity=64, micro_batch=128)
        store._cur_bucket = 0
        entries = []
        for i, (stmt, plan) in enumerate(zip(stmts, plans)):
            e = SharedEmitNode(f"r{i}_emit")
            assert store.attach_rule(_member(i, stmt, plan), e, None)
            entries.append(e)
        privs = [_private(stmt, plan) for stmt, plan in zip(stmts, plans)]
        rng = np.random.default_rng(3)
        for end in (5_000, 10_000, 15_000, 20_000, 25_000, 30_000):
            for _ in range(2):
                b = _int_batch(rng, 100)
                store.process(b)
                for p, _g in privs:
                    p.process(_copy(b))
            store.on_trigger(Trigger(ts=end))
            for p, _g in privs:
                _private_boundary(p, end)
        for i, e in enumerate(entries):
            shared = _drain_cbs(e)
            priv = [x for x in privs[i][1] if isinstance(x, ColumnBatch)]
            assert shared and len(shared) == len(priv), (i, len(shared),
                                                         len(priv))
            for s, p in zip(shared, priv):
                _assert_cb_equal(s, p, ctx=f"rule {i}")
        # dedup accounting: one fold per batch for 3 members
        assert store.folds_did == 12
        assert store.fold_dedup_ratio() == pytest.approx(2 / 3)

    def test_emissions_carry_ingest_provenance(self):
        """Shared-fold window emissions must stamp ingest_ms (the PR 3
        e2e SLO layer) exactly like the private node's emit() would —
        send_to alone doesn't stamp."""
        stmts, plans = _plans(SQLS[:1])
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 10_000, 3,
                               subtopo_ref=None, capacity=64,
                               micro_batch=128)
        store._cur_bucket = 0
        e = SharedEmitNode("r0_emit")
        store.attach_rule(_member(0, stmts[0], plans[0]), e, None)
        rng = np.random.default_rng(6)
        b = _int_batch(rng, 40)
        b.ingest_ms = 1234  # what a source node would stamp
        store._cur_ingest_ms = 1234  # node fabric sets this per dispatch
        store.process(b)
        store.on_trigger(Trigger(ts=10_000))
        got = _drain_cbs(e)
        assert got and got[0].ingest_ms == 1234

    def test_tick_trigger_carries_scheduled_boundary(self, monkeypatch):
        """The real clock invokes timer callbacks with the ACTUAL
        (sleep-overshot) fire time; the tick must enqueue the SCHEDULED
        pane boundary or every member's `end % interval == 0` emission
        gate fails forever in production."""
        from ekuiper_tpu.utils import timex as timex_mod

        stmts, plans = _plans(SQLS[:1])
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 5_000, 4, subtopo_ref=None,
                               capacity=64, micro_batch=128)
        captured = {}

        def fake_after(ms, cb=None):
            captured["cb"] = cb

            class T:
                def stop(self):
                    pass

            return T()

        monkeypatch.setattr(timex_mod, "after", fake_after)
        store._schedule_tick()
        expected = timex_mod.align_to_window(timex_mod.now_ms() + 1, 5_000)
        captured["cb"](expected + 3)  # simulate sleep overshoot
        trig = store.inq.get_nowait()
        assert trig.ts == expected  # aligned, NOT the late fire time

    def test_attach_midstream_warms_from_live_panes(self):
        stmts, plans = _plans()
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 5_000, 6, subtopo_ref=None,
                               capacity=64, micro_batch=128)
        store._cur_bucket = 0
        e0 = SharedEmitNode("r0_emit")
        store.attach_rule(_member(0, stmts[0], plans[0]), e0, None)
        rng = np.random.default_rng(4)
        store.process(_int_batch(rng, 60))
        store.on_trigger(Trigger(ts=5_000))
        # late joiner: attaches mid-window, without restarting the peer
        e1 = SharedEmitNode("r1_emit")
        store.attach_rule(_member(1, stmts[1], plans[1]), e1, None)
        assert store.member_count() == 2
        store.process(_int_batch(rng, 60))
        store.on_trigger(Trigger(ts=10_000))
        # the late joiner's first window covers the LIVE panes — including
        # rows folded before it attached (warm-attach semantics)
        got = _drain_cbs(e1)
        assert got and int(got[0].columns["mx"].shape[0]) > 0
        assert _drain_cbs(e0)  # peer kept emitting
        # detach mid-stream: peer unaffected, store survives
        store.detach_rule("r1")
        assert store.member_count() == 1
        store.process(_int_batch(rng, 60))
        store.on_trigger(Trigger(ts=15_000))
        store.on_trigger(Trigger(ts=20_000))
        assert _drain_cbs(e0)
        store.detach_rule("r0")  # last detach closes the store


class TestEventTimeRecycleGuard:
    def test_stale_rows_drop_instead_of_corrupting_newer_pane(self):
        """A row whose pane a NEWER bucket already claimed must DROP
        (counted), never fold into the newer bucket's window."""
        stmts, plans = _plans(SQLS[:1])
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 1_000, 4, subtopo_ref=None,
                               capacity=64, micro_batch=128,
                               is_event_time=True)
        e = SharedEmitNode("r0_emit")
        store.attach_rule(MemberSpec(
            rule_id="r0", length_ms=1_000, interval_ms=1_000,
            plan=plans[0],
            direct_emit=build_direct_emit(stmts[0], plans[0], ["deviceId"]),
            dims=["deviceId"]), e, None)

        def at(bucket, n):
            ids = np.array(["d0"] * n, dtype=np.object_)
            return ColumnBatch(
                n=n, columns={"deviceId": ids,
                              "temperature": np.full(n, 10.0, np.float32)},
                timestamps=np.full(n, bucket * 1_000 + 5, dtype=np.int64),
                emitter="demo")

        store.process(at(0, 3))
        store.process(at(10, 4))  # bucket 10 claims pane 10 % 4 = 2
        drop_before = store.stats.snapshot()["dropped_total"].get(
            "pane_recycle", 0)
        store.process(at(2, 5))   # pane 2 % 4 = 2 held by NEWER bucket 10
        assert store.stats.snapshot()["dropped_total"].get(
            "pane_recycle", 0) > drop_before
        store.on_watermark(Watermark(ts=11_000))
        got = _drain_cbs(e)
        # bucket 10's window counts exactly its own 4 rows — the 5 stale
        # rows were dropped, not folded into pane 2
        counts = {int(cb.columns["c"][0]) for cb in got}
        assert 4 in counts and 9 not in counts, counts

    def test_recycled_pane_never_leaks_future_rows_into_old_window(self):
        """A pane recycled to a newer bucket must be EXCLUDED from an old
        window's combine — its loss was counted at recycle time; merging
        it would fold future rows into the old window (corruption)."""
        stmts, plans = _plans(SQLS[:1])  # tumbling, but length overridden
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 1_000, 6, subtopo_ref=None,
                               capacity=64, micro_batch=128,
                               is_event_time=True)
        e = SharedEmitNode("r0_emit")
        store.attach_rule(MemberSpec(
            rule_id="r0", length_ms=4_000, interval_ms=4_000,
            plan=plans[0],
            direct_emit=build_direct_emit(stmts[0], plans[0], ["deviceId"]),
            dims=["deviceId"]), e, None)

        def at(bucket, n):
            ids = np.array(["d0"] * n, dtype=np.object_)
            return ColumnBatch(
                n=n, columns={"deviceId": ids,
                              "temperature": np.full(n, 5.0, np.float32)},
                timestamps=np.full(n, bucket * 1_000 + 5, dtype=np.int64),
                emitter="demo")

        for b in range(4):  # buckets 0..3 (the [0,4000) window)
            store.process(at(b, 2))
        store.process(at(6, 7))  # bucket 6 recycles pane 0 (6 % 6)
        store.on_watermark(Watermark(ts=4_000))
        got = _drain_cbs(e)
        assert got, "window [0,4000) must still emit from buckets 1-3"
        # bucket 0's 2 rows were lost (counted); bucket 6's 7 rows must
        # NOT appear: count is exactly buckets 1-3 = 6 rows
        assert int(got[0].columns["c"][0]) == 6, got[0].columns["c"]

    def test_wide_batch_spread_drops_aliasing_rows(self):
        """One batch spanning >= n_panes buckets would alias two buckets
        onto one pane WITHIN one fold — older rows drop (counted)."""
        stmts, plans = _plans(SQLS[:1])
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 1_000, 4, subtopo_ref=None,
                               capacity=64, micro_batch=128,
                               is_event_time=True)
        e = SharedEmitNode("r0_emit")
        store.attach_rule(MemberSpec(
            rule_id="r0", length_ms=1_000, interval_ms=1_000,
            plan=plans[0],
            direct_emit=build_direct_emit(stmts[0], plans[0], ["deviceId"]),
            dims=["deviceId"]), e, None)
        n = 10
        ids = np.array(["d0"] * n, dtype=np.object_)
        ts = np.array([b * 1_000 + 5 for b in range(n)], dtype=np.int64)
        store.process(ColumnBatch(
            n=n, columns={"deviceId": ids,
                          "temperature": np.full(n, 1.0, np.float32)},
            timestamps=ts, emitter="demo"))
        # buckets 0..5 aliased (spread 10 >= 4 panes): dropped + counted
        assert store.stats.snapshot()["dropped_total"].get(
            "pane_recycle", 0) >= 1
        store.on_watermark(Watermark(ts=20_000))
        got = _drain_cbs(e)
        assert all(int(cb.columns["c"][0]) == 1 for cb in got)
        assert len(got) == 4  # only the surviving newest buckets emitted


class TestParityEventTime:
    def test_event_time_byte_identical(self):
        stmts, plans = _plans(SQLS[:2])
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 5_000, 6, subtopo_ref=None,
                               capacity=64, micro_batch=128,
                               is_event_time=True)
        entries = []
        for i, (stmt, plan) in enumerate(zip(stmts, plans)):
            e = SharedEmitNode(f"r{i}_emit")
            store.attach_rule(_member(i, stmt, plan), e, None)
            entries.append(e)
        privs = [_private(stmt, plan, is_event_time=True,
                          late_tolerance_ms=0)
                 for stmt, plan in zip(stmts, plans)]
        rng = np.random.default_rng(9)
        for k in range(10):
            b = _int_batch(rng, 80, t0=12_000 + k * 3_000, span_ms=3_000)
            store.process(b)
            for p, _g in privs:
                p.process(_copy(b))
            wm_ts = 12_000 + k * 3_000
            store.on_watermark(Watermark(ts=wm_ts))
            for p, _g in privs:
                p.on_watermark(Watermark(ts=wm_ts))
        for i, e in enumerate(entries):
            shared = _drain_cbs(e)
            priv = [x for x in privs[i][1] if isinstance(x, ColumnBatch)]
            assert shared and len(shared) == len(priv)
            for s, p in zip(shared, priv):
                _assert_cb_equal(s, p, ctx=f"evt rule {i}")


def _mk_stream(store, topic="t/sf"):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        f'WITH (DATASOURCE="{topic}", TYPE="memory", FORMAT="JSON")')


def _rule(rid, sql, **opts):
    return RuleDef(id=rid, sql=sql,
                   actions=[{"memory": {"topic": f"out/{rid}"}}],
                   options=opts)


def _flat(msgs):
    out = []
    for p in msgs:
        out.extend(p if isinstance(p, list) else [p])
    return sorted(out, key=str)


class TestPlannerIntegration:
    def test_correlated_rules_share_and_match_private_plan(self, mock_clock):
        store = kv.get_store()
        _mk_stream(store)
        r1 = _rule("r1", SQLS[0])
        r2 = _rule("r2", SQLS[1])
        rp = _rule("rp", SQLS[0], sharedFold=False)  # private reference
        # first plan of a lone rule stays private but DECLARES candidacy;
        # planning the peer then replanning r1 converges both onto the
        # shared fold (create-order independence via declarations)
        t_first = plan_rule(r1, store)
        # lone rule: shared SOURCE (subtopo) but a private fused fold
        assert any(isinstance(n, FusedWindowAggNode) for n in t_first.ops)
        assert not any(isinstance(ref, sf.SharedFoldRef)
                       for ref, _ in t_first.shared)
        t2 = plan_rule(r2, store)  # sees r1's declaration -> shared
        t1 = plan_rule(r1, store)  # replan joins the fleet
        tp = plan_rule(rp, store)
        # shared plan: no private source, no private fused node
        assert not t1.sources
        assert not any(isinstance(n, FusedWindowAggNode) for n in t1.ops)
        assert any(isinstance(n, FusedWindowAggNode) for n in tp.ops)
        t1.open(); t2.open(); tp.open()
        try:
            assert sf.pool_size() == 1 and subtopo.pool_size() == 1
            st = sf.live_stores()[0]
            assert st.member_count() == 2
            assert st.pane_ms == 5_000  # GCD of 10s tumbling + 10s/5s hop
            got = {r: [] for r in ("r1", "rp")}
            for r in got:
                mem.subscribe(f"out/{r}", lambda t, p, r=r: got[r].append(p))
            rng = np.random.default_rng(5)
            for _ in range(60):
                mem.publish("t/sf", {
                    "deviceId": f"d{rng.integers(0, 8)}",
                    "temperature": float(np.rint(rng.normal(20, 5)))})
            mock_clock.advance(20)  # linger flush
            deadline = time.time() + 8
            while time.time() < deadline and not (
                    t1.wait_idle(2) and tp.wait_idle(2)):
                time.sleep(0.02)
            mock_clock.advance(10_000 - 20)  # tumbling boundary
            deadline = time.time() + 8
            while time.time() < deadline and not (got["r1"] and got["rp"]):
                time.sleep(0.02)
            assert _flat(got["r1"]) == _flat(got["rp"]) != []
            # one fold served both rules
            assert st.folds_did >= 1 and st.fold_dedup_ratio() > 0
        finally:
            t1.close()
            assert sf.live_stores() and \
                sf.live_stores()[0].member_count() == 1
            t2.close(); tp.close()
        assert sf.pool_size() == 0 and subtopo.pool_size() == 0

    def test_explain_shows_sharing_decision(self):
        store = kv.get_store()
        _mk_stream(store)
        # no peers yet: private, but the reason says it is a candidate
        out = explain(_rule("rx", SQLS[0]), store)
        assert out["path"] == "device-fused"
        assert out["sharing"]["decision"] == "private"
        assert "peer" in out["sharing"]["reason"]
        # a declared correlated peer flips the decision to shared
        plan_rule(_rule("peer", SQLS[1]), store)
        out = explain(_rule("rx", SQLS[0]), store)
        assert out["path"] == "device-fused-shared"
        assert out["sharing"]["decision"] == "shared"
        est = out["sharing"]["estimates"]
        assert est["saved_fold_us_per_s"] > est["emit_overhead_us_per_s"]
        # declined rule explains the reason too
        out = explain(_rule("ry", SQLS[0], sharedFold=False), store)
        assert out["path"] == "device-fused"
        assert out["sharing"]["decision"] == "private"
        assert "sharedFold" in out["sharing"]["reason"]

    def test_qos_rule_gets_logged_private_fallback(self):
        """ISSUE satellite: a qos>0 rule requesting a shared fold must get
        an explicit, LOGGED planner fallback — not silent convention."""
        store = kv.get_store()
        _mk_stream(store)
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        h = Capture(level=logging.INFO)
        logger.addHandler(h)
        old_level = logger.level
        logger.setLevel(logging.INFO)
        try:
            t = plan_rule(_rule("rq", SQLS[0], qos=1, sharedFold=True),
                          store)
        finally:
            logger.setLevel(old_level)
            logger.removeHandler(h)
        # private plan: own source, private fused node, no shared fold
        assert t.sources and not t.shared
        assert any(isinstance(n, FusedWindowAggNode) for n in t.ops)
        assert sf.pool_size() == 0
        msgs = [r.getMessage() for r in records]
        assert any("qos" in m and ("declined" in m or "private" in m)
                   for m in msgs), msgs

    def test_cost_model_declines_wide_span(self):
        """A window spanning more shared panes than the cap keeps its
        private fold, with the reason visible in the decision."""
        store = kv.get_store()
        _mk_stream(store)
        # declare a 1s-tumbling peer, then probe a 600s window: span 600
        plan_rule(_rule("rs", SQLS[0].replace(
            "TUMBLINGWINDOW(ss, 10)", "TUMBLINGWINDOW(ss, 1)")), store)
        out = explain(_rule("rw", SQLS[0].replace(
            "TUMBLINGWINDOW(ss, 10)", "TUMBLINGWINDOW(ss, 600)")), store)
        assert out["sharing"]["decision"] == "private"
        assert "panes" in out["sharing"]["reason"]

    def test_mixed_where_shares_one_store_via_predicate_lift(self):
        """Rules that differ ONLY in WHERE share one pooled fold: each
        member's predicate lifts into per-spec device FILTER masks + a
        private activity spec (ops/aggspec.py lift_predicate), so the
        store key no longer includes the WHERE expression."""
        store = kv.get_store()
        _mk_stream(store)
        def mk(rid, thresh):
            return _rule(rid, "SELECT deviceId, count(*) AS c FROM demo "
                         f"WHERE temperature > {thresh} GROUP BY deviceId, "
                         "TUMBLINGWINDOW(ss, 10)")

        # two pairs of WHEREs: identical-WHERE specs dedup outright,
        # different-WHERE specs coexist as masked specs in ONE store
        for r in (mk("ra0", 5), mk("rb0", 50)):
            plan_rule(r, store)  # declare candidates
        ta, tb = plan_rule(mk("ra1", 5), store), plan_rule(mk("rb1", 50),
                                                           store)
        assert not ta.sources and not tb.sources  # both planned shared
        ta.open(); tb.open()
        try:
            assert sf.pool_size() == 1  # ONE store across both WHEREs
            st = sf.live_stores()[0]
            assert st.member_count() == 2
            # the union plan carries each predicate's lifted specs:
            # count(*) FILTER(t>5), act(t>5), count(*) FILTER(t>50),
            # act(t>50) — the t>5 pair dedups with ra0's declaration
            keys = {spec_call_key_(s.call) for s in st.plan.specs}
            assert len(keys) == len(st.plan.specs)  # all distinct
            assert any("5" in k and "f:" in k for k in keys)
            assert any("50" in k and "f:" in k for k in keys)
            # per-member activity: each attached member reads its own
            # lifted act spec, not the store-global act
            for m in st._members.values():
                assert m.spec.act_idx is not None
        finally:
            ta.close(); tb.close()

    def test_validate_probe_leaves_no_ghost_candidacy(self):
        """POST /rules/validate plans (and declares) but creates nothing —
        the phantom must not count as a peer for later lone rules."""
        from ekuiper_tpu.server.rule_manager import RuleRegistry

        store = kv.get_store()
        _mk_stream(store)
        rr = RuleRegistry(store)
        out = rr.validate({"id": "phantom", "sql": SQLS[0],
                           "actions": [{"nop": {}}]})
        assert out["valid"] is True
        assert not sharing._declared
        assert explain(_rule("lone", SQLS[0]),
                       store)["sharing"]["decision"] == "private"
        # probing a REGISTERED rule's id with a DIFFERENT window must not
        # overwrite its live declaration (pane GCD of future stores)
        rr.create({"id": "real", "sql": SQLS[0], "actions": [{"nop": {}}],
                   "options": {"triggered": False}})
        before = sharing.snapshot_declarations()
        rr.validate({"id": "real", "sql": SQLS[0].replace(
            "TUMBLINGWINDOW(ss, 10)", "TUMBLINGWINDOW(ss, 7)"),
            "actions": [{"nop": {}}]})
        assert sharing.snapshot_declarations() == before
        rr.delete("real")

    def test_delete_forgets_sharing_candidacy(self):
        """A deleted rule must stop counting as a peer — ghost
        declarations would make a later lone rule 'share' with nobody."""
        from ekuiper_tpu.server.rule_manager import RuleRegistry

        store = kv.get_store()
        _mk_stream(store)
        rr = RuleRegistry(store)
        rr.create({"id": "ghost", "sql": SQLS[0],
                   "actions": [{"nop": {}}],
                   "options": {"triggered": False}})
        assert sharing._declared  # candidacy declared at validation plan
        rr.delete("ghost")
        assert not sharing._declared
        # with the ghost gone, a new lone rule stays private
        out = explain(_rule("lone", SQLS[0]), store)
        assert out["sharing"]["decision"] == "private"

    def test_store_builder_clamps_pane_to_span_cap(self):
        """A fine-grained declaration landing between a peer's decide()
        and the store build must not blow the peer's span past the pane
        cap (decide-time vs build-time GCD race): the builder drops the
        finest declarations until every surviving span fits."""
        from ekuiper_tpu.planner.sharing import (
            MAX_SPAN_PANES, _store_builder, declare)

        stmts, plans = _plans(SQLS[:1])
        key = "k|fold|test"
        declare(key, "long", 64_000, 64_000, plans[0])
        declare(key, "fine", 70, 70, plans[0])  # gcd would become 10ms
        opts_obj = type("O", (), {"key_slots": 64, "micro_batch_rows": 128,
                                  "buffer_length": 16})()
        fallback = {"length_ms": 64_000, "interval_ms": 64_000,
                    "plan": plans[0]}
        build = _store_builder(key, "subkey", lambda: [], "sf", opts_obj,
                               False, 0, fallback_decl=fallback)
        node = build()
        node._subtopo_ref = None  # standalone: no real source pipeline
        assert node.n_panes <= 255
        # empty-declarations race (concurrent delete between plan and
        # open): the builder falls back to the resolver's own declaration
        sharing.reset()
        node2 = _store_builder(key, "subkey", lambda: [], "sf", opts_obj,
                               False, 0, fallback_decl=fallback)()
        assert node2.pane_ms == 64_000 and node2.plan.specs
        assert 64_000 // node.pane_ms <= MAX_SPAN_PANES
        # the long rule attaches; the dropped fine rule is rejected and
        # its restart replans against the live store (private fallback)
        e = SharedEmitNode("long_emit")
        assert node.attach_rule(
            MemberSpec(rule_id="long", length_ms=64_000,
                       interval_ms=64_000, plan=plans[0], direct_emit=None,
                       dims=["deviceId"]), e, None)
        with pytest.raises(RuntimeError, match="not a multiple"):
            node.attach_rule(
                MemberSpec(rule_id="fine", length_ms=70, interval_ms=70,
                           plan=plans[0], direct_emit=None,
                           dims=["deviceId"]),
                SharedEmitNode("fine_emit"), None)


class TestProbeSharing:
    def test_probe_smoke(self):
        """tools/probe_sharing.py prints the decision table for the demo
        rule set and exits 0 (tier-1 smoke, like check_metrics)."""
        import os
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(root, "tools",
                                          "probe_sharing.py")],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr
        assert "shared" in r.stdout
        assert "saved" in r.stdout or "us/s" in r.stdout
