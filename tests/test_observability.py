"""Prometheus exposition, span tracing, metrics dump."""
import json
import time
import urllib.request

import pytest

from ekuiper_tpu.observability import prometheus
from ekuiper_tpu.observability.tracer import Tracer
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.server.rest import RestApi, serve
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


@pytest.fixture
def fresh_tracer():
    old = Tracer._instance
    Tracer._instance = Tracer()
    yield Tracer._instance
    Tracer._instance = old


@pytest.fixture
def api_server(mock_clock):
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="obs/demo", TYPE="memory", FORMAT="JSON")')
    api = RestApi(store)
    srv = serve(api, "127.0.0.1", 0)
    port = srv.server_address[1]

    def req(method, path, body=None, raw=False):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=5) as resp:
            payload = resp.read()
            return payload.decode() if raw else json.loads(payload or b"null")

    yield api, req
    api.rules.stop_all()
    srv.shutdown()


class TestPrometheus:
    def test_metrics_endpoint(self, api_server, mock_clock, fresh_tracer):
        api, req = api_server
        req("POST", "/rules", {
            "id": "obs1",
            "sql": "SELECT deviceId, temperature FROM demo",
            "actions": [{"memory": {"topic": "obs/out"}}]})
        api.rules.start("obs1")
        time.sleep(0.3)
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 1.0})
        mock_clock.advance(20)
        time.sleep(0.3)
        text = req("GET", "/metrics", raw=True)
        assert "# TYPE kuiper_rule_status gauge" in text
        assert 'kuiper_rule_status{rule="obs1"} 1' in text
        assert 'kuiper_op_records_in_total{rule="obs1"' in text
        assert "kuiper_uptime_seconds" in text
        # shared-source subtopo nodes are scraped too
        assert 'op="demo"' in text

    def test_dump(self, api_server):
        api, req = api_server
        req("POST", "/rules", {
            "id": "obs2", "sql": "SELECT deviceId FROM demo",
            "actions": [{"log": {}}]})
        out = req("GET", "/metrics/dump")
        assert out["rules"] >= 1
        with open(out["file"]) as f:
            lines = [json.loads(ln) for ln in f.read().splitlines()]
        assert any(ln["rule"] == "obs2" for ln in lines)


class TestTracing:
    def test_trace_rule_spans(self, api_server, mock_clock, fresh_tracer):
        api, req = api_server
        req("POST", "/rules", {
            "id": "tr1",
            "sql": "SELECT deviceId, temperature FROM demo "
                   "WHERE temperature > 0",
            "actions": [{"memory": {"topic": "tr/out"}}]})
        api.rules.start("tr1")
        time.sleep(0.3)
        assert req("POST", "/rules/tr1/trace/start") == \
            "Tracing enabled for rule tr1."
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 5.0})
        mock_clock.advance(20)
        deadline = time.time() + 5
        while time.time() < deadline and not fresh_tracer.rule_traces("tr1"):
            time.sleep(0.05)
        traces = req("GET", "/trace/rule/tr1")
        assert traces
        # the trace follows the ColumnBatch through the rule chain (sink
        # items are plain lists — not taggable — and start their own trace)
        by_trace = {t: req("GET", f"/trace/{t}") for t in traces}
        chain = next(
            (spans for spans in by_trace.values()
             if {"filter", "project"} <= {s["op"] for s in spans}), None)
        assert chain is not None, {
            t: [s["op"] for s in spans] for t, spans in by_trace.items()}
        assert len({s["traceId"] for s in chain}) == 1
        assert all(s["rule"] == "tr1" for s in chain)
        assert all(s["rows"] == 1 for s in chain)
        assert req("POST", "/rules/tr1/trace/stop") == \
            "Tracing disabled for rule tr1."
        assert not fresh_tracer.is_enabled("tr1")

    def test_disabled_rules_record_nothing(self, fresh_tracer):
        fresh_tracer.enable("other")
        fresh_tracer.record("other", "op1", 1, 10, "Tuple", 1)
        assert fresh_tracer.rule_spans("other")
        assert fresh_tracer.rule_spans("not_enabled") == []
