"""Prometheus exposition, span tracing, metrics dump."""
import json
import time
import urllib.request

import pytest

from ekuiper_tpu.observability import prometheus
from ekuiper_tpu.observability.tracer import Tracer
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.server.rest import RestApi, serve
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


@pytest.fixture
def fresh_tracer():
    old = Tracer._instance
    Tracer._instance = Tracer()
    yield Tracer._instance
    Tracer._instance = old


@pytest.fixture
def api_server(mock_clock):
    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM demo (deviceId STRING, temperature FLOAT) '
        'WITH (DATASOURCE="obs/demo", TYPE="memory", FORMAT="JSON")')
    api = RestApi(store)
    srv = serve(api, "127.0.0.1", 0)
    port = srv.server_address[1]

    def req(method, path, body=None, raw=False):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=5) as resp:
            payload = resp.read()
            return payload.decode() if raw else json.loads(payload or b"null")

    yield api, req
    api.rules.stop_all()
    srv.shutdown()


class TestPrometheus:
    def test_metrics_endpoint(self, api_server, mock_clock, fresh_tracer):
        api, req = api_server
        req("POST", "/rules", {
            "id": "obs1",
            "sql": "SELECT deviceId, temperature FROM demo",
            "actions": [{"memory": {"topic": "obs/out"}}]})
        api.rules.start("obs1")
        time.sleep(0.3)
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 1.0})
        mock_clock.advance(20)
        time.sleep(0.3)
        text = req("GET", "/metrics", raw=True)
        assert "# TYPE kuiper_rule_status gauge" in text
        assert 'kuiper_rule_status{rule="obs1"} 1' in text
        assert 'kuiper_op_records_in_total{rule="obs1"' in text
        assert "kuiper_uptime_seconds" in text
        # shared-source subtopo nodes are scraped too
        assert 'op="demo"' in text

    def test_dump(self, api_server):
        api, req = api_server
        req("POST", "/rules", {
            "id": "obs2", "sql": "SELECT deviceId FROM demo",
            "actions": [{"log": {}}]})
        out = req("GET", "/metrics/dump")
        assert out["rules"] >= 1
        with open(out["file"]) as f:
            lines = [json.loads(ln) for ln in f.read().splitlines()]
        assert any(ln["rule"] == "obs2" for ln in lines)


class TestTracing:
    def test_trace_rule_spans(self, api_server, mock_clock, fresh_tracer):
        api, req = api_server
        req("POST", "/rules", {
            "id": "tr1",
            "sql": "SELECT deviceId, temperature FROM demo "
                   "WHERE temperature > 0",
            "actions": [{"memory": {"topic": "tr/out"}}]})
        api.rules.start("tr1")
        time.sleep(0.3)
        assert req("POST", "/rules/tr1/trace/start") == \
            "Tracing enabled for rule tr1."
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 5.0})
        mock_clock.advance(20)
        deadline = time.time() + 5
        while time.time() < deadline and not fresh_tracer.rule_traces("tr1"):
            time.sleep(0.05)
        traces = req("GET", "/trace/rule/tr1")
        assert traces
        # the trace follows the ColumnBatch through the rule chain (plain
        # list/dict items ride the tracer's bounded fallback map since the
        # non-weakref-able fix, so the sink hop keeps the trace too)
        by_trace = {t: req("GET", f"/trace/{t}") for t in traces}
        chain = next(
            (spans for spans in by_trace.values()
             if {"filter", "project"} <= {s["op"] for s in spans}), None)
        assert chain is not None, {
            t: [s["op"] for s in spans] for t, spans in by_trace.items()}
        assert len({s["traceId"] for s in chain}) == 1
        assert all(s["rule"] == "tr1" for s in chain)
        assert all(s["rows"] == 1 for s in chain)
        assert req("POST", "/rules/tr1/trace/stop") == \
            "Tracing disabled for rule tr1."
        assert not fresh_tracer.is_enabled("tr1")

    def test_disabled_rules_record_nothing(self, fresh_tracer):
        fresh_tracer.enable("other")
        fresh_tracer.record("other", "op1", 1, 10, "Tuple", 1)
        assert fresh_tracer.rule_spans("other")
        assert fresh_tracer.rule_spans("not_enabled") == []

    def test_non_weakrefable_items_keep_trace(self, fresh_tracer):
        """Regression: plain lists/dicts (multi-row project output) used to
        silently drop trace propagation at the queue hop — they now ride
        the bounded fallback map."""
        t = fresh_tracer
        t.enable("r")
        tid = t.new_trace()
        item = {"deviceId": "a", "temperature": 1.0}
        t.tag(item)
        rows = [1, 2, 3]
        t.tag(rows)
        t.set_current(None)  # the receiving node's worker: fresh context
        assert t.lookup(item) == tid
        assert t.lookup(rows) == tid

    def test_fallback_map_bounded_eviction(self, fresh_tracer):
        t = fresh_tracer
        t.enable("r")
        t.new_trace()
        first = {"k": 0}
        t.tag(first)
        keep_alive = [{"k": i} for i in range(t.FALLBACK_CAP)]
        for d in keep_alive:
            t.tag(d)
        assert len(t._fallback_traces) <= t.FALLBACK_CAP
        assert t.lookup(first) is None  # oldest evicted, newest retained
        assert t.lookup(keep_alive[-1]) is not None

    def test_span_attributes_surface_in_dict_and_otlp(self, fresh_tracer):
        from ekuiper_tpu.observability.otlp import encode_span

        t = fresh_tracer
        t.enable("r")
        t.record("r", "sink", 5, 100, "list", 2, attrs={"e2e_ms": 17})
        span = [s for s in t.rule_spans("r") if s["op"] == "sink"][0]
        assert span["attributes"] == {"e2e_ms": 17}
        plain = t.rule_spans("r")
        # attribute-less spans omit the key (legacy dict/bytes unchanged)
        t.record("r", "op", 5, 100, "Tuple", 1)
        plain = [s for s in t.rule_spans("r") if s["op"] == "op"][0]
        assert "attributes" not in plain

        class S:  # minimal span shape for the encoder
            trace_id, span_id, parent_id = "t1", "s1", ""
            rule_id, op, start_ms, duration_us = "r", "sink", 5, 100
            kind, rows = "list", 2
            attrs = None

        base = encode_span(S())
        S.attrs = {"e2e_ms": 17}
        with_attr = encode_span(S())
        assert len(with_attr) > len(base)  # extra KeyValue appended
        assert b"e2e_ms" in with_attr and b"e2e_ms" not in base


class TestE2ELatency:
    """The tentpole: ingest→emit latency measured at the sink under the
    deterministic mock clock, exported through status JSON and the
    Prometheus histogram."""

    @staticmethod
    def _wait_topo(api, rid, timeout=10.0):
        """Poll until the rule's topo is live (start is async; a fixed
        sleep flakes on cold-compile runs)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            rs = api.rules.state(rid)
            if rs is not None and rs.topo is not None:
                return rs.topo
            time.sleep(0.05)
        raise AssertionError(f"rule {rid} topo never came up")

    def _make_rule(self, api, req, rid="sle1"):
        req("POST", "/rules", {
            "id": rid,
            "sql": "SELECT deviceId, temperature FROM demo",
            "actions": [{"memory": {"topic": f"{rid}/out"}}]})
        api.rules.start(rid)
        return self._wait_topo(api, rid)

    def _wait_count(self, topo, n=1, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline and topo.e2e_hist.count < n:
            time.sleep(0.05)
        return topo.e2e_hist.count

    def test_mock_clock_rule_reports_sane_p99(self, api_server, mock_clock,
                                              fresh_tracer):
        api, req = api_server
        topo = self._make_rule(api, req)
        for i in range(5):
            mem.publish("obs/demo", {"deviceId": f"d{i}", "temperature": 1.0})
        mock_clock.advance(20)  # one linger flush covers every row
        assert self._wait_count(topo, n=1) >= 1
        snap = topo.e2e_hist.snapshot()
        # every row ingested at mock t=0, linger-flushed at t=10, delivered
        # with the clock parked at t=20: samples are deterministically
        # 0..20ms — a sane p99 under the mock clock
        assert 0 <= snap["p50"] <= 20
        assert 0 <= snap["p99"] <= 20
        assert snap["max"] <= 20
        # rule status JSON carries the SLO summary
        status = req("GET", "/rules/sle1/status")
        assert status["e2e_latency_ms"]["count"] >= 1
        assert 0 <= status["e2e_latency_ms"]["p99"] <= 20
        # per-op histogram summaries ride the same status payload
        hist_keys = [k for k in status if k.endswith("process_latency_us_hist")]
        assert hist_keys and all(
            set(status[k]) == {"count", "p50", "p90", "p99", "max"}
            for k in hist_keys)
        # fleet-wide SLO view (sibling of /rules/usage/cpu)
        usage = req("GET", "/rules/usage/latency")
        assert usage["sle1"]["count"] >= 1
        assert 0 <= usage["sle1"]["p99"] <= 20

    def test_windowed_rule_records_e2e_at_boundary(self, api_server,
                                                   mock_clock, fresh_tracer):
        """The fused window path: emission happens on a TRIGGER dispatch
        (not the data dispatch), so the stamp must survive through the
        node's last-seen provenance. Under the mock clock the single batch
        is 10s old at the boundary — the sample is its true dwell."""
        api, req = api_server
        req("POST", "/rules", {
            "id": "slw1",
            "sql": "SELECT deviceId, avg(temperature) AS a FROM demo "
                   "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
            "actions": [{"memory": {"topic": "slw1/out"}}]})
        api.rules.start("slw1")
        topo = self._wait_topo(api, "slw1")
        for i in range(8):
            mem.publish("obs/demo", {"deviceId": f"d{i % 2}",
                                     "temperature": float(i)})
        mock_clock.advance(50)  # linger flush into the fused fold
        time.sleep(0.3)
        mock_clock.advance(10_000)  # boundary fires, window emits
        assert self._wait_count(topo, n=1, timeout=8.0) >= 1
        snap = topo.e2e_hist.snapshot()
        assert 10_000 <= snap["p99"] <= 11_000, snap  # dwell, ≤6.25% bucket

    def test_metrics_exposes_e2e_histogram(self, api_server, mock_clock,
                                           fresh_tracer):
        api, req = api_server
        topo = self._make_rule(api, req, rid="sle2")
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 2.0})
        mock_clock.advance(20)
        assert self._wait_count(topo, n=1) >= 1
        text = req("GET", "/metrics", raw=True)
        assert "# TYPE kuiper_rule_e2e_latency_ms histogram" in text
        assert "# HELP kuiper_rule_e2e_latency_ms" in text
        bucket_lines = [ln for ln in text.splitlines()
                        if ln.startswith("kuiper_rule_e2e_latency_ms_bucket"
                                         '{rule="sle2"')]
        les = [ln.rsplit('le="', 1)[1].split('"')[0] for ln in bucket_lines]
        assert les[-1] == "+Inf"
        nums = [float(x) for x in les[:-1]]
        assert nums == sorted(nums)
        counts = [int(ln.split()[-1]) for ln in bucket_lines]
        assert counts == sorted(counts)  # cumulative
        count_line = [ln for ln in text.splitlines()
                      if ln.startswith("kuiper_rule_e2e_latency_ms_count"
                                       '{rule="sle2"')][0]
        assert int(count_line.split()[-1]) == counts[-1]
        assert f'kuiper_rule_e2e_latency_ms_sum{{rule="sle2"}}' in text
        # per-op latency quantiles render too
        assert 'kuiper_op_process_latency_quantile_us{' in text
        assert 'q="0.99"' in text
        assert 'kuiper_op_queue_wait_quantile_us{' in text

    def test_shared_subtopo_metrics_emitted_once(self, api_server,
                                                 mock_clock, fresh_tracer):
        """Regression: nodes reached via a shared subtopo were emitted once
        per referencing rule, double-counting records_*_total in any PromQL
        sum — they now render exactly once, under rule="__shared__"."""
        api, req = api_server
        self._make_rule(api, req, rid="shd1")
        self._make_rule(api, req, rid="shd2")
        mem.publish("obs/demo", {"deviceId": "a", "temperature": 1.0})
        mock_clock.advance(20)
        time.sleep(0.3)
        text = req("GET", "/metrics", raw=True)
        demo_in = [ln for ln in text.splitlines()
                   if ln.startswith("kuiper_op_records_in_total")
                   and 'op="demo"' in ln]
        assert len(demo_in) == 1, demo_in
        assert 'rule="__shared__"' in demo_in[0]
        # both rules' OWN nodes still render per rule
        for rid in ("shd1", "shd2"):
            assert any(f'rule="{rid}"' in ln for ln in text.splitlines()
                       if ln.startswith("kuiper_op_records_in_total"))
