"""Scripted in-memory Kafka broker for connector tests.

Serves exactly the legacy RPC versions the connector speaks (ApiVersions
v0, Metadata v1, ListOffsets v1, Produce v2, Fetch v2 — MessageSet magic=1).
Deliberately does NOT import ekuiper_tpu.io.kafka_wire: every struct layout
here is hand-coded from the Kafka protocol spec, so the test cross-validates
the client's encoding against an independent implementation (a shared
encode/decode bug can't cancel itself out).

Knobs: `fail_produces` makes the next N produce requests return
NOT_LEADER_FOR_PARTITION (retry-path tests); `log` records every
(api_key, api_version) served.
"""
from __future__ import annotations

import socket
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple


def _s(v: Optional[str]) -> bytes:
    if v is None:
        return struct.pack(">h", -1)
    b = v.encode()
    return struct.pack(">h", len(b)) + b


def _b(v: Optional[bytes]) -> bytes:
    if v is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(v)) + v


class _Cur:
    def __init__(self, data: bytes) -> None:
        self.d = data
        self.p = 0

    def take(self, n: int) -> bytes:
        out = self.d[self.p:self.p + n]
        self.p += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def s(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.take(n).decode()

    def b(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self.take(n)


class MockBroker:
    """One-node cluster. topics: name -> partition count.
    sasl_users: when set, connections must SaslHandshake(PLAIN) +
    SaslAuthenticate before any other API (independently hand-coded like
    the rest of the broker)."""

    def __init__(self, topics: Dict[str, int],
                 sasl_users: Optional[Dict[str, str]] = None) -> None:
        self.topics = dict(topics)
        self.sasl_users = sasl_users
        # (topic, partition) -> list of (key, value, ts)
        self.data: Dict[Tuple[str, int], List[Tuple[Optional[bytes], bytes, int]]] = {
            (t, p): [] for t, n in self.topics.items() for p in range(n)}
        self.log: List[Tuple[int, int]] = []
        self.fail_produces = 0
        self.node_id = 7
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def append(self, topic: str, partition: int, key: Optional[bytes],
               value: bytes, ts: int = 0) -> int:
        """Seed a record directly (test setup); returns its offset."""
        log = self.data[(topic, partition)]
        log.append((key, value, ts))
        return len(log) - 1

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    # ------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _recv_n(self, conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    _MECHS = ("PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512")

    def _serve(self, conn: socket.socket) -> None:
        authed = self.sasl_users is None
        pending_mech: Optional[str] = None
        scram = None
        try:
            while not self._stop.is_set():
                size = struct.unpack(">i", self._recv_n(conn, 4))[0]
                req = _Cur(self._recv_n(conn, size))
                api_key, api_ver, corr = req.i16(), req.i16(), req.i32()
                req.s()  # client id
                self.log.append((api_key, api_ver))
                if api_key == 17:  # SaslHandshake v1
                    mech = (req.s() or "").upper()
                    if mech in self._MECHS:
                        pending_mech = mech
                        body = struct.pack(">h", 0) \
                            + struct.pack(">i", len(self._MECHS))
                        for m in self._MECHS:
                            body += _s(m)
                    else:
                        body = struct.pack(">h", 33) \
                            + struct.pack(">i", 1) + _s("PLAIN")
                    resp = struct.pack(">i", corr) + body
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
                    continue
                if api_key == 36:  # SaslAuthenticate v0
                    token = req.b() or b""
                    if pending_mech is None:
                        break  # authenticate without handshake: drop
                    ok = False
                    reply_bytes = b""
                    done = False
                    if pending_mech == "PLAIN":
                        parts = token.split(b"\x00")
                        ok = (len(parts) == 3
                              and self.sasl_users is not None
                              and self.sasl_users.get(parts[1].decode())
                              == parts[2].decode())
                        done = True
                    elif pending_mech is not None:  # SCRAM
                        if scram is None:
                            scram = scram_server_exchange(
                                pending_mech, self.sasl_users or {})
                        out = scram(token)
                        if out is None:
                            ok, done = False, True
                        else:
                            reply_bytes = out
                            done = out.startswith(b"v=")
                            ok = done
                    if done and not ok:
                        body = struct.pack(">h", 58) \
                            + _s("Authentication failed") + _b(b"")
                        resp = struct.pack(">i", corr) + body
                        conn.sendall(struct.pack(">i", len(resp)) + resp)
                        break  # real brokers drop unauthenticated conns
                    if done and ok:
                        authed = True
                    body = struct.pack(">h", 0) + _s("") + _b(reply_bytes)
                    resp = struct.pack(">i", corr) + body
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
                    continue
                if not authed:
                    break  # no API before authentication
                handler = {18: self._api_versions, 3: self._metadata,
                           2: self._list_offsets, 0: self._produce,
                           1: self._fetch}.get(api_key)
                if handler is None:
                    break
                body = handler(api_ver, req)
                if body is None:
                    continue  # acks=0 produce: no response
                resp = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _api_versions(self, ver: int, req: _Cur) -> bytes:
        assert ver == 0
        supported = [(0, 0, 2), (1, 0, 2), (2, 0, 1), (3, 0, 1), (18, 0, 0)]
        out = struct.pack(">h", 0) + struct.pack(">i", len(supported))
        for k, lo, hi in supported:
            out += struct.pack(">hhh", k, lo, hi)
        return out

    def _metadata(self, ver: int, req: _Cur) -> bytes:
        assert ver == 1
        n = req.i32()
        names = ([req.s() for _ in range(n)] if n >= 0
                 else list(self.topics))
        out = struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", self.node_id) + _s(self.host) \
            + struct.pack(">i", self.port) + _s(None)
        out += struct.pack(">i", self.node_id)  # controller
        out += struct.pack(">i", len(names))
        for name in names:
            known = name in self.topics
            out += struct.pack(">h", 0 if known else 3)  # UNKNOWN_TOPIC=3
            out += _s(name) + struct.pack(">b", 0)
            parts = range(self.topics.get(name, 0))
            out += struct.pack(">i", len(parts))
            for p in parts:
                out += struct.pack(">hii", 0, p, self.node_id)
                out += struct.pack(">ii", 1, self.node_id)  # replicas [node]
                out += struct.pack(">ii", 1, self.node_id)  # isr [node]
        return out

    def _list_offsets(self, ver: int, req: _Cur) -> bytes:
        assert ver == 1
        req.i32()  # replica id
        out_topics = []
        for _ in range(req.i32()):
            topic = req.s() or ""
            parts = []
            for _ in range(req.i32()):
                p, ts = req.i32(), req.i64()
                log = self.data.get((topic, p))
                if log is None:
                    parts.append(struct.pack(">ihqq", p, 3, -1, -1))
                    continue
                off = 0 if ts == -2 else len(log)
                parts.append(struct.pack(">ihqq", p, 0, -1, off))
            out_topics.append(_s(topic) + struct.pack(">i", len(parts))
                              + b"".join(parts))
        return struct.pack(">i", len(out_topics)) + b"".join(out_topics)

    def _decode_mset(self, data: bytes) -> List[Tuple[Optional[bytes], bytes, int]]:
        out = []
        pos = 0
        while pos + 12 <= len(data):
            _, size = struct.unpack(">qi", data[pos:pos + 12])
            msg = _Cur(data[pos + 12:pos + 12 + size])
            crc = msg.i32() & 0xFFFFFFFF
            body = msg.d[msg.p:]
            assert zlib.crc32(body) & 0xFFFFFFFF == crc, "bad producer CRC"
            magic, attrs = msg.i8(), msg.i8()
            ts = msg.i64() if magic >= 1 else 0
            key = msg.b()
            value = msg.b() or b""
            out.append((key, value, ts))
            pos += 12 + size
        return out

    def _encode_mset(self, entries: List[Tuple[Optional[bytes], bytes, int]],
                     base: int) -> bytes:
        out = b""
        for i, (key, value, ts) in enumerate(entries):
            body = struct.pack(">bb", 1, 0) + struct.pack(">q", ts) \
                + _b(key) + _b(value)
            crc = zlib.crc32(body) & 0xFFFFFFFF
            msg = struct.pack(">I", crc) + body
            out += struct.pack(">qi", base + i, len(msg)) + msg
        return out

    def _produce(self, ver: int, req: _Cur) -> Optional[bytes]:
        assert ver == 2
        acks = req.i16()
        req.i32()  # timeout
        out_topics = []
        for _ in range(req.i32()):
            topic = req.s() or ""
            parts = []
            for _ in range(req.i32()):
                p = req.i32()
                mset = req.b() or b""
                log = self.data.get((topic, p))
                if log is None:
                    parts.append(struct.pack(">ihqq", p, 3, -1, -1))
                    continue
                if self.fail_produces > 0:
                    self.fail_produces -= 1
                    parts.append(struct.pack(">ihqq", p, 6, -1, -1))
                    continue
                base = len(log)
                log.extend(self._decode_mset(mset))
                parts.append(struct.pack(">ihqq", p, 0, base, -1))
            out_topics.append(_s(topic) + struct.pack(">i", len(parts))
                              + b"".join(parts))
        if acks == 0:
            return None
        return (struct.pack(">i", len(out_topics)) + b"".join(out_topics)
                + struct.pack(">i", 0))  # throttle

    def _fetch(self, ver: int, req: _Cur) -> bytes:
        assert ver == 2
        req.i32()  # replica
        req.i32()  # max wait (mock never long-polls)
        req.i32()  # min bytes
        out_topics = []
        for _ in range(req.i32()):
            topic = req.s() or ""
            parts = []
            for _ in range(req.i32()):
                p, off = req.i32(), req.i64()
                pmax = req.i32()  # partition max bytes
                log = self.data.get((topic, p))
                if log is None:
                    parts.append(struct.pack(">ihq", p, 3, -1) + _b(b""))
                    continue
                if off > len(log):
                    parts.append(struct.pack(">ihq", p, 1, len(log)) + _b(b""))
                    continue
                mset = self._encode_mset(log[off:off + 100], off)
                # real brokers truncate the message set at max_bytes (the
                # pre-KIP-74 oversized-first-message case clients must grow
                # past) — honor it so that path is testable
                mset = mset[:pmax]
                parts.append(struct.pack(">ihq", p, 0, len(log)) + _b(mset))
            out_topics.append(_s(topic) + struct.pack(">i", len(parts))
                              + b"".join(parts))
        return (struct.pack(">i", 0)  # throttle
                + struct.pack(">i", len(out_topics)) + b"".join(out_topics))


import base64
import hashlib
import hmac
import os
from typing import Any

from ekuiper_tpu.io.kafka_wire import _scram_hash, _scram_hi

def scram_server_exchange(mech, users):
    """Server half of the RFC 5802 exchange: a stateful callable mapping
    each client message to the server reply (None = authentication
    failed). Test-only — shares just the hash/Hi primitives with the
    client (ekuiper_tpu.io.kafka_wire)."""
    h = _scram_hash(mech)
    state: Dict[str, Any] = {}

    def respond(client_msg: bytes):
        msg = client_msg.decode()
        if "first" not in state:
            state["first"] = True
            bare = msg.split(",", 2)[2]
            state["c_first_bare"] = bare
            user = dict(p.split("=", 1)
                        for p in bare.split(","))["n"]
            pw = users.get(user.replace("=2C", ",").replace("=3D", "="))
            if pw is None:
                return None
            state["salt"] = os.urandom(12)
            state["iters"] = 4096
            c_nonce = dict(p.split("=", 1) for p in bare.split(","))["r"]
            state["nonce"] = c_nonce + base64.b64encode(os.urandom(9)).decode()
            state["salted"] = _scram_hi(mech, pw.encode(), state["salt"],
                                        state["iters"])
            s_first = (f"r={state['nonce']},"
                       f"s={base64.b64encode(state['salt']).decode()},"
                       f"i={state['iters']}")
            state["s_first"] = s_first
            return s_first.encode()
        attrs = dict(p.split("=", 1) for p in msg.split(","))
        if attrs.get("r") != state["nonce"]:
            return None
        c_final_bare = msg.rsplit(",p=", 1)[0]
        auth_msg = (f"{state['c_first_bare']},{state['s_first']},"
                    f"{c_final_bare}").encode()
        client_key_sig = hmac.new(
            h(hmac.new(state["salted"], b"Client Key", h).digest()).digest(),
            auth_msg, h).digest()
        proof = base64.b64decode(attrs["p"])
        client_key = bytes(a ^ b for a, b in zip(proof, client_key_sig))
        if h(client_key).digest() != h(
                hmac.new(state["salted"], b"Client Key", h).digest()).digest():
            return None
        server_sig = hmac.new(
            hmac.new(state["salted"], b"Server Key", h).digest(),
            auth_msg, h).digest()
        return f"v={base64.b64encode(server_sig).decode()}".encode()

    return respond
