"""Long-tail subsystems: meta catalog, sql connectors, redis-backed KV,
gated extensions, confKey REST routes, plugin test server importability."""
import json
import os
import sqlite3
import time
import urllib.request

import pytest

import ekuiper_tpu.meta as meta
from ekuiper_tpu.io import registry as io_registry
from ekuiper_tpu.server.rest import RestApi, serve
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.infra import EngineError


class TestMeta:
    def test_catalog(self):
        assert "mqtt" in meta.list_sources()
        assert "redis" in meta.list_sinks()
        src = meta.describe_source("websocket")
        assert any(p["name"] == "addr" for p in src["properties"])
        snk = meta.describe_sink("redis")
        assert any(p["name"] == "dataType" for p in snk["properties"])
        fns = meta.list_functions()
        assert "avg" in fns["aggregate"] and "abs" in fns["scalar"]
        with pytest.raises(EngineError):
            meta.describe_source("nope")


class TestGatedExtensions:
    def test_extension_connectors_ungated(self):
        # kafka + zmq + video are real connectors now (bundled wire
        # clients / MJPEG-over-HTTP frame puller)
        assert io_registry.create_source("kafka") is not None
        assert io_registry.create_sink("zmq") is not None
        assert io_registry.create_source("video") is not None


class TestSqlIo:
    def test_source_sink_lookup_roundtrip(self, tmp_path):
        db = str(tmp_path / "t.db")
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE readings (id INTEGER, dev TEXT, v REAL)")
        conn.execute("CREATE TABLE outs (dev TEXT, v REAL)")
        conn.executemany("INSERT INTO readings VALUES (?, ?, ?)",
                         [(1, "a", 1.5), (2, "b", 2.5)])
        conn.commit()

        src = io_registry.create_source("sql")
        src.configure("readings", {
            "url": f"sqlite://{db}", "interval": 50, "trackingColumn": "id"})
        got = []
        src.open(lambda rows: got.extend(rows))
        deadline = time.time() + 5
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.02)
        # incremental: new row picked up, old not re-fetched
        conn.execute("INSERT INTO readings VALUES (3, 'c', 3.5)")
        conn.commit()
        while time.time() < deadline and len(got) < 3:
            time.sleep(0.02)
        src.close()
        assert [r["dev"] for r in got] == ["a", "b", "c"]
        assert src.get_offset() == 3

        sink = io_registry.create_sink("sql")
        sink.configure({"url": f"sqlite://{db}", "table": "outs"})
        sink.connect()
        sink.collect([{"dev": "x", "v": 9.0}])
        sink.close()
        assert conn.execute("SELECT dev, v FROM outs").fetchall() == \
            [("x", 9.0)]

        lk = io_registry.create_lookup("sql")
        lk.configure("readings", {"url": f"sqlite://{db}"})
        lk.open()
        assert lk.lookup([], ["dev"], ["b"])[0]["v"] == 2.5
        lk.close()


class TestRedisStore:
    def test_rediskv_contract_with_stub_client(self):
        class StubCli:
            def __init__(self):
                self.h = {}

            def command(self, *args):
                op = args[0]
                if op == "HSET":
                    self.h[args[2]] = args[3]
                    return 1
                if op == "HSETNX":
                    if args[2] in self.h:
                        return 0
                    self.h[args[2]] = args[3]
                    return 1
                if op == "HGET":
                    return self.h.get(args[2])
                if op == "HDEL":
                    return 1 if self.h.pop(args[2], None) is not None else 0
                if op == "HKEYS":
                    return list(self.h.keys())
                if op == "DEL":
                    self.h.clear()
                    return 1

        from ekuiper_tpu.store.kv import RedisKV

        r = RedisKV(StubCli(), "t")
        r.set("a", {"x": 1})
        assert r.get_ok("a") == ({"x": 1}, True)
        assert not r.setnx("a", 2) and r.setnx("b", 2)
        assert r.keys() == ["a", "b"]
        assert r.delete("a") and not r.delete("a")
        r.clean()
        assert r.keys() == []


class TestConfKeysRest:
    def test_confkey_crud_feeds_planner(self, mock_clock):
        store = kv.get_store()
        api = RestApi(store)
        srv = serve(api, "127.0.0.1", 0)
        port = srv.server_address[1]

        def req(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=data, method=method,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(r, timeout=5) as resp:
                return json.loads(resp.read() or b"null")

        try:
            req("PUT", "/metadata/sources/mqtt/confKeys/broker1",
                {"server": "tcp://h:1883", "qos": 2})
            assert req("GET", "/metadata/sources/mqtt/confKeys") == ["broker1"]
            # the planner reads the same table through _source_props
            got, ok = store.kv("source_conf").get_ok("mqtt:broker1")
            assert ok and got["qos"] == 2
            req("DELETE", "/metadata/sources/mqtt/confKeys/broker1")
            assert req("GET", "/metadata/sources/mqtt/confKeys") == []
            # metadata endpoints over REST
            assert "sql" in req("GET", "/metadata/sources")
            assert req("GET", "/metadata/sinks/redis")["name"] == "redis"
        finally:
            srv.shutdown()


class TestPluginTestServer:
    def test_importable_and_help(self):
        from ekuiper_tpu.tools import plugin_test_server

        with pytest.raises(SystemExit):
            plugin_test_server.main(["--help"])


class FakeBroker:
    """Tiny MQTT 3.1.1 broker: CONNACK, SUBACK, qos0/1 PUBLISH routing with
    topic filter matching, PINGRESP."""

    def __init__(self):
        import socket as _s
        import threading as _t

        self.srv = _s.socket(_s.AF_INET, _s.SOCK_STREAM)
        self.srv.setsockopt(_s.SOL_SOCKET, _s.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self.subs = []  # (conn, filter, lock)
        self._stop = False
        _t.Thread(target=self._accept, daemon=True).start()

    def close(self):
        self._stop = True
        self.srv.close()

    def _accept(self):
        import threading as _t

        while not self._stop:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            _t.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        import struct
        import threading as _t

        from ekuiper_tpu.io import mqtt_native as mn

        wlock = _t.Lock()

        def read_exact(n):
            out = b""
            while len(out) < n:
                c = conn.recv(n - len(out))
                if not c:
                    raise ConnectionError
                out += c
            return out

        def read_packet():
            first = read_exact(1)[0]
            mult, length = 1, 0
            while True:
                b = read_exact(1)[0]
                length += (b & 0x7F) * mult
                if not (b & 0x80):
                    break
                mult *= 128
            return first, read_exact(length) if length else b""

        def send(first, body, lk=wlock):
            with lk:
                conn.sendall(bytes([first]) + mn.encode_varint(len(body)) + body)

        try:
            typ, _ = read_packet()
            assert typ & 0xF0 == mn.CONNECT
            send(mn.CONNACK, b"\x00\x00")
            while True:
                typ, body = read_packet()
                kind = typ & 0xF0
                if kind == 0x80:  # SUBSCRIBE
                    mid = body[:2]
                    tlen = struct.unpack(">H", body[2:4])[0]
                    filt = body[4:4 + tlen].decode()
                    self.subs.append((send, filt))
                    send(mn.SUBACK, mid + b"\x00")
                elif kind == mn.PUBLISH:
                    qos = (typ >> 1) & 3
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    pos = 2 + tlen
                    if qos:
                        mid = body[pos:pos + 2]
                        pos += 2
                        send(mn.PUBACK, mid)
                    payload = body[pos:]
                    for sub_send, filt in list(self.subs):
                        if mn.topic_matches(filt, topic):
                            var = mn.encode_str(topic)
                            try:
                                sub_send(mn.PUBLISH, var + payload)
                            except Exception:
                                pass
                elif kind == mn.PINGREQ:
                    send(mn.PINGRESP, b"")
                elif kind == mn.DISCONNECT:
                    return
        except Exception:
            pass


class TestNativeMqtt:
    def test_source_sink_roundtrip(self):
        broker = FakeBroker()
        try:
            src = io_registry.create_source("mqtt")
            src.configure("sensors/+/t", {
                "server": f"tcp://127.0.0.1:{broker.port}", "qos": 1})
            got = []
            src.open(lambda payload, meta=None: got.append((payload, meta)))
            deadline = time.time() + 5
            while time.time() < deadline and not broker.subs:
                time.sleep(0.02)
            sink = io_registry.create_sink("mqtt")
            sink.configure({"server": f"tcp://127.0.0.1:{broker.port}",
                            "topic": "sensors/d1/t", "qos": 0})
            sink.connect()
            sink.collect({"v": 3})
            while time.time() < deadline and not got:
                time.sleep(0.02)
            # the source delivers RAW bytes — decoding (incl. native
            # columnar batch decode) belongs to the SourceNode
            assert got and got[0][0] == b'{"v": 3}'
            assert got[0][1]["topic"] == "sensors/d1/t"
            sink.close()
            src.close()
        finally:
            broker.close()

    def test_topic_matching(self):
        from ekuiper_tpu.io.mqtt_native import topic_matches

        assert topic_matches("a/+/c", "a/b/c")
        assert topic_matches("a/#", "a/b/c/d")
        assert not topic_matches("a/+", "a/b/c")
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/x")


class TestRuleLogFiles:
    def test_per_rule_log_routing(self, tmp_path, mock_clock):
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.utils import rulelog
        from ekuiper_tpu.utils.infra import logger
        import ekuiper_tpu.io.memory as mem

        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM demo (a BIGINT) '
            'WITH (DATASOURCE="rl/demo", TYPE="memory", FORMAT="JSON")')
        rulelog.install(str(tmp_path))
        try:
            topo = plan_rule(RuleDef(
                id="rl-1", sql="SELECT bad_fn(a) AS x FROM demo",
                actions=[{"memory": {"topic": "rl/out"}}], options={}), store)
            topo.open()
            try:
                mem.publish("rl/demo", {"a": 1})
                mock_clock.advance(20)
                assert topo.wait_idle(10)
            finally:
                topo.close()
            logfile = tmp_path / "rl-1.log"
            deadline = time.time() + 5
            while time.time() < deadline and not logfile.exists():
                time.sleep(0.05)
            assert logfile.exists()
            content = logfile.read_text()
            assert "bad_fn" in content  # the unknown-function warning landed
        finally:
            rulelog.uninstall()

    def test_k8s_tool_processes_commands(self, tmp_path):
        from ekuiper_tpu.server.rest import RestApi, serve
        from ekuiper_tpu.tools import kubernetes_tool

        store = kv.get_store()
        api = RestApi(store)
        srv = serve(api, "127.0.0.1", 0)
        endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
        (tmp_path / "init.json").write_text(json.dumps({"commands": [
            {"url": "/streams", "method": "post", "description": "s",
             "data": {"sql": 'CREATE STREAM kst (a BIGINT) WITH '
                             '(DATASOURCE="k/t", TYPE="memory", '
                             'FORMAT="JSON")'}},
        ]}))
        try:
            done = kubernetes_tool.process_dir(str(tmp_path), endpoint)
            assert done == ["init.json"]
            assert "kst" in api.streams.show()
            # unchanged file is not re-processed
            assert kubernetes_tool.process_dir(str(tmp_path), endpoint) == []
        finally:
            srv.shutdown()


class TestMqttFullPipe:
    def test_mqtt_stream_rule_decodes_in_source_node(self, mock_clock):
        """Full pipe: mqtt broker bytes → SourceNode decode (native fast
        path for scalar typed schemas) → rule → sink."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv as kvmod

        broker = FakeBroker()
        mem.reset()
        pub = None
        try:
            store = kvmod.get_store()
            StreamProcessor(store).exec_stmt(
                f'CREATE STREAM mq (deviceId STRING, v FLOAT) WITH '
                f'(DATASOURCE="sensors/t", TYPE="mqtt", FORMAT="JSON", '
                f'CONF_KEY="fb{broker.port}")')
            store.kv("source_conf").set(
                f"mqtt:fb{broker.port}",
                {"server": f"tcp://127.0.0.1:{broker.port}", "qos": 0})
            topo = plan_rule(RuleDef(
                id="mq1", sql="SELECT deviceId, v FROM mq WHERE v > 1",
                actions=[{"memory": {"topic": "mq/out"}}], options={}),
                store)
            sink = topo.sinks[0]
            topo.open()
            src = (topo._live_shared[0][0].source if topo._live_shared
                   else topo.sources[0])
            assert src._fast_spec is not None  # native decode active
            deadline = time.time() + 5
            while time.time() < deadline and not broker.subs:
                time.sleep(0.02)
            pub = io_registry.create_sink("mqtt")
            pub.configure({"server": f"tcp://127.0.0.1:{broker.port}",
                           "topic": "sensors/t", "qos": 0})
            pub.connect()
            pub.collect({"deviceId": "a", "v": 2.5})
            pub.collect({"deviceId": "b", "v": 0.5})
            while time.time() < deadline and src.stats.records_in < 2:
                time.sleep(0.02)
            mock_clock.advance(20)  # linger flush
            while time.time() < deadline and not sink.results:
                time.sleep(0.02)
            topo.close()
            assert sink.results
            msgs = sink.results[0]
            msgs = msgs if isinstance(msgs, list) else [msgs]
            assert msgs == [{"deviceId": "a", "v": 2.5}]
        finally:
            if pub is not None:
                pub.close()
            broker.close()
            mem.reset()
