"""Join rules through the full planner/topology: stream-stream joins over a
window (both sources planned and fed — regression for the missing
join-table sources) and stream-to-lookup-table joins."""
import time

import pytest

from ekuiper_tpu.planner.planner import PlanError, RuleDef, plan_rule
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _streams(store):
    sp = StreamProcessor(store)
    sp.exec_stmt('CREATE STREAM ls (id STRING, v FLOAT) '
                 'WITH (DATASOURCE="j/l", TYPE="memory", FORMAT="JSON")')
    sp.exec_stmt('CREATE STREAM rs (id STRING, w FLOAT) '
                 'WITH (DATASOURCE="j/r", TYPE="memory", FORMAT="JSON")')


def _flat(got):
    out = []
    for p in got:
        out.extend(p if isinstance(p, list) else [p])
    return out


class TestStreamJoin:
    def test_windowed_inner_join(self, mock_clock):
        store = kv.get_store()
        _streams(store)
        topo = plan_rule(RuleDef(
            id="j1", sql=("SELECT ls.id, ls.v, rs.w FROM ls "
                          "INNER JOIN rs ON ls.id = rs.id "
                          "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "j1/out"}}], options={}), store)
        # both streams got an ingest pipeline
        src_names = [n.name for n in topo.sources] + [
            n.name for n in topo.ops if n.name.endswith("_shared")]
        assert any("ls" in n for n in src_names)
        assert any("rs" in n for n in src_names)
        got = []
        mem.subscribe("j1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("j/l", {"id": "a", "v": 1.0})
            mem.publish("j/r", {"id": "a", "w": 2.0})
            mem.publish("j/l", {"id": "only_left", "v": 9.0})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert {m["id"] for m in msgs} == {"a"}  # inner join drops only_left
        assert msgs[0]["v"] == 1.0 and msgs[0]["w"] == 2.0

    def test_join_without_window_rejected(self):
        store = kv.get_store()
        _streams(store)
        with pytest.raises(PlanError, match="JOIN requires a window"):
            plan_rule(RuleDef(
                id="j2", sql=("SELECT ls.id FROM ls "
                              "INNER JOIN rs ON ls.id = rs.id"),
                actions=[{"log": {}}], options={}), store)


class TestMixedJoins:
    def test_lookup_node_only_on_its_stream_chain(self):
        """With a stream join AND a lookup join, the lookup node must sit
        only on the chain its ON clause references — other streams' rows
        must not be filtered through it."""
        store = kv.get_store()
        _streams(store)
        StreamProcessor(store).exec_stmt(
            'CREATE TABLE meta (id STRING, site STRING) '
            'WITH (DATASOURCE="mx/meta", TYPE="memory", FORMAT="JSON", '
            'KEY="id")')
        topo = plan_rule(RuleDef(
            id="mx1", sql=(
                "SELECT ls.id, rs.w, meta.site FROM ls "
                "INNER JOIN rs ON ls.id = rs.id "
                "INNER JOIN meta ON ls.id = meta.id "
                "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "mx1/out"}}], options={}), store)
        lookup = next(n for n in topo.ops if n.name.startswith("lookup_join"))
        # only the ls chain feeds the lookup node
        feeders = [n.name for n in topo.ops + topo.sources
                   if lookup in n.outputs]
        assert feeders == ["ls_shared"], feeders
        return topo

    def test_mixed_join_values(self, mock_clock):
        topo = self.test_lookup_node_only_on_its_stream_chain()
        got = []
        mem.subscribe("mx1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("mx/meta", {"id": "a", "site": "oslo"})
            mem.publish("j/l", {"id": "a", "v": 1.0})
            mem.publish("j/r", {"id": "a", "w": 2.0})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert msgs and msgs[0] == {"id": "a", "w": 2.0, "site": "oslo"}, msgs


class TestLookupJoin:
    def test_stream_to_table_join(self, mock_clock):
        store = kv.get_store()
        sp = StreamProcessor(store)
        sp.exec_stmt('CREATE STREAM ev (dev STRING, val FLOAT) '
                     'WITH (DATASOURCE="lk/ev", TYPE="memory", FORMAT="JSON")')
        sp.exec_stmt('CREATE TABLE meta (dev STRING, site STRING) '
                     'WITH (DATASOURCE="lk/meta", TYPE="memory", '
                     'FORMAT="JSON", KEY="dev")')
        # seed the lookup table BEFORE the rule starts? Memory lookup
        # subscribes at open; publish after open.
        topo = plan_rule(RuleDef(
            id="lk1", sql=("SELECT ev.dev, ev.val, meta.site FROM ev "
                           "INNER JOIN meta ON ev.dev = meta.dev"),
            actions=[{"memory": {"topic": "lk1/out"}}], options={}), store)
        assert any(n.name.startswith("lookup_join") for n in topo.ops)
        got = []
        mem.subscribe("lk1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("lk/meta", {"dev": "d1", "site": "berlin"})
            mem.publish("lk/ev", {"dev": "d1", "val": 7.0})
            mock_clock.advance(20)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert msgs and msgs[0]["site"] == "berlin" and msgs[0]["val"] == 7.0


# --------------------------------------------------------------------------
# Device relational tier (ISSUE 19): the device join ring must emit
# byte-identical results to the host nested loop across join types,
# interval vs window-only bounds, NULL-key rows and late rows — and a
# join rule must survive kill/restore mid-window.
import random

import pytest

from ekuiper_tpu.planner import relational
from ekuiper_tpu.runtime.nodes_join import JoinNode
from ekuiper_tpu.runtime.nodes_relational import DeviceJoinNode
from ekuiper_tpu.data.rows import JoinTuple, Tuple
from ekuiper_tpu.sql.parser import parse_select


def _parity_case(sql, trials=6, seed=0, late=False):
    """Drive host JoinNode and DeviceJoinNode over identical randomized
    windows; emissions must match byte-for-byte (messages AND order)."""
    stmt = parse_select(sql)
    low = relational.lower_join(stmt, stmt.joins)
    host = JoinNode("join", stmt.joins, left_name=stmt.sources[0].ref_name)
    dev = DeviceJoinNode("join", stmt.joins,
                         left_name=stmt.sources[0].ref_name, lowering=low)
    rng = random.Random(seed)
    for trial in range(trials):
        nl, nr = rng.randint(0, 10), rng.randint(0, 10)

        def rows(side, n):
            out = []
            for _ in range(n):
                ts = rng.randint(0, 25)
                if late:  # stragglers far outside the band
                    ts = rng.choice([ts, ts + 10_000])
                msg = {"k": rng.choice(["a", "b", None]), "ts": ts}
                if side == "l":
                    msg["v"] = rng.choice([1.0, 5.0, None])
                else:
                    msg["w"] = rng.choice([0.0, 3.0, None])
                out.append(Tuple(emitter=side, message=msg, timestamp=ts))
            return out

        left = [JoinTuple(tuples=[t]) for t in rows("l", nl)]
        right = rows("r", nr)
        eh = host._join_step(left, right, stmt.joins[0])
        ed = dev._join_step(left, right, stmt.joins[0])
        got_h = [[t.message for t in j.tuples] for j in eh]
        got_d = [[t.message for t in j.tuples] for j in ed]
        assert got_h == got_d, (sql, trial, got_h, got_d)


class TestDeviceJoinParity:
    @pytest.mark.parametrize("jt", ["INNER", "LEFT", "RIGHT", "FULL"])
    def test_interval_join_types(self, jt):
        _parity_case(
            f"SELECT l.v, r.w FROM l {jt} JOIN r ON l.k = r.k "
            "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 "
            "GROUP BY TUMBLINGWINDOW(ss, 1)", seed=hash(jt) % 1000)

    @pytest.mark.parametrize("jt", ["INNER", "LEFT", "RIGHT", "FULL"])
    def test_window_bounds_join_types(self, jt):
        # window-only: no band predicate, every in-window pair is a
        # key-equality candidate
        _parity_case(
            f"SELECT l.v, r.w FROM l {jt} JOIN r ON l.k = r.k "
            "GROUP BY TUMBLINGWINDOW(ss, 1)", seed=31 + hash(jt) % 1000)

    def test_cross_join(self):
        _parity_case("SELECT l.v, r.w FROM l CROSS JOIN r "
                     "GROUP BY TUMBLINGWINDOW(ss, 1)", seed=7)

    def test_interval_join_with_residual(self):
        _parity_case(
            "SELECT l.v, r.w FROM l FULL JOIN r ON l.k = r.k "
            "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 AND l.v > r.w "
            "GROUP BY TUMBLINGWINDOW(ss, 1)", seed=13)

    def test_late_rows(self):
        _parity_case(
            "SELECT l.v, r.w FROM l LEFT JOIN r ON l.k = r.k "
            "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 "
            "GROUP BY TUMBLINGWINDOW(ss, 1)", seed=17, late=True)

    def test_fallback_window_runs_host_loop(self):
        # a non-integer event time in ONE window falls back to the host
        # nested loop for that window only, counted on the ring
        sql = ("SELECT l.v, r.w FROM l INNER JOIN r ON l.k = r.k "
               "AND l.ts - r.ts >= -5 AND l.ts - r.ts <= 5 "
               "GROUP BY TUMBLINGWINDOW(ss, 1)")
        stmt = parse_select(sql)
        low = relational.lower_join(stmt, stmt.joins)
        dev = DeviceJoinNode("join", stmt.joins, left_name="l",
                             lowering=low)
        host = JoinNode("join", stmt.joins, left_name="l")
        left = [JoinTuple(tuples=[Tuple(
            emitter="l", message={"k": "a", "ts": 0.5, "v": 1.0},
            timestamp=0)])]
        right = [Tuple(emitter="r", message={"k": "a", "ts": 1, "w": 2.0},
                       timestamp=1)]
        eh = host._join_step(left, right, stmt.joins[0])
        ed = dev._join_step(left, right, stmt.joins[0])
        assert [[t.message for t in j.tuples] for j in eh] == \
               [[t.message for t in j.tuples] for j in ed]
        assert dev.ring.fallback_windows_total == 1


class TestDeviceJoinE2E:
    def _run(self, impl, mock_clock, tag):
        store = kv.get_store()
        try:
            _streams(store)
        except PlanError:
            pass  # second run in the same test: streams already defined
        topo = plan_rule(RuleDef(
            id=f"dj_{tag}", sql=(
                "SELECT ls.id, ls.v, rs.w FROM ls "
                "LEFT JOIN rs ON ls.id = rs.id "
                "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": f"dj_{tag}/out"}}],
            options={"joinImpl": impl}), store)
        got = []
        mem.subscribe(f"dj_{tag}/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("j/l", {"id": "a", "v": 1.0})
            mem.publish("j/r", {"id": "a", "w": 2.0})
            mem.publish("j/l", {"id": "solo", "v": 9.0})
            mem.publish("j/r", {"id": "a", "w": 4.0})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        return _flat(got)

    def test_device_rule_byte_identical_to_host_rule(self, mock_clock):
        dev = self._run("device", mock_clock, "dev")
        host = self._run("host", mock_clock, "host")
        assert dev == host and dev, (dev, host)
        # the planner actually took the device path (not a silent host)
        store = kv.get_store()
        topo = plan_rule(RuleDef(
            id="dj_probe", sql=(
                "SELECT ls.id FROM ls LEFT JOIN rs ON ls.id = rs.id "
                "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"log": {}}], options={}), store)
        assert any(isinstance(n, DeviceJoinNode) for n in topo.ops)

    def test_kill_restore_mid_window(self, mock_clock):
        store = kv.get_store()
        _streams(store)

        def make_topo():
            return plan_rule(RuleDef(
                id="djc", sql=(
                    "SELECT ls.id, ls.v, rs.w FROM ls "
                    "INNER JOIN rs ON ls.id = rs.id "
                    "GROUP BY TUMBLINGWINDOW(ss, 10)"),
                actions=[{"memory": {"topic": "djc/out"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000}),
                store)

        topo = make_topo()
        topo.open()
        got = []
        mem.subscribe("djc/out", lambda t, p: got.append(p))
        mem.publish("j/l", {"id": "a", "v": 1.0})
        mem.publish("j/r", {"id": "a", "w": 2.0})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        from conftest import wait_for_checkpoint

        cid = topo.trigger_checkpoint()
        wait_for_checkpoint(store, "djc", cid)
        mem.publish("j/l", {"id": "b", "v": 3.0})
        mem.publish("j/r", {"id": "b", "w": 4.0})
        mock_clock.advance(20)
        assert topo.wait_idle(10)
        topo.close()  # crash: no graceful save

        topo2 = make_topo()
        topo2.open()
        try:
            # at-least-once replay of the post-checkpoint rows
            mem.publish("j/l", {"id": "b", "v": 3.0})
            mem.publish("j/r", {"id": "b", "w": 4.0})
            mock_clock.advance(20)
            assert topo2.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo2.close()
        msgs = _flat(got)
        pairs = {(m["id"], m["v"], m["w"]) for m in msgs}
        # uninterrupted expectation: both pairs exactly once
        assert pairs == {("a", 1.0, 2.0), ("b", 3.0, 4.0)}, msgs
        assert len(msgs) == 2, msgs
