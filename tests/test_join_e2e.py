"""Join rules through the full planner/topology: stream-stream joins over a
window (both sources planned and fed — regression for the missing
join-table sources) and stream-to-lookup-table joins."""
import time

import pytest

from ekuiper_tpu.planner.planner import PlanError, RuleDef, plan_rule
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.store import kv
import ekuiper_tpu.io.memory as mem


def _streams(store):
    sp = StreamProcessor(store)
    sp.exec_stmt('CREATE STREAM ls (id STRING, v FLOAT) '
                 'WITH (DATASOURCE="j/l", TYPE="memory", FORMAT="JSON")')
    sp.exec_stmt('CREATE STREAM rs (id STRING, w FLOAT) '
                 'WITH (DATASOURCE="j/r", TYPE="memory", FORMAT="JSON")')


def _flat(got):
    out = []
    for p in got:
        out.extend(p if isinstance(p, list) else [p])
    return out


class TestStreamJoin:
    def test_windowed_inner_join(self, mock_clock):
        store = kv.get_store()
        _streams(store)
        topo = plan_rule(RuleDef(
            id="j1", sql=("SELECT ls.id, ls.v, rs.w FROM ls "
                          "INNER JOIN rs ON ls.id = rs.id "
                          "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "j1/out"}}], options={}), store)
        # both streams got an ingest pipeline
        src_names = [n.name for n in topo.sources] + [
            n.name for n in topo.ops if n.name.endswith("_shared")]
        assert any("ls" in n for n in src_names)
        assert any("rs" in n for n in src_names)
        got = []
        mem.subscribe("j1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("j/l", {"id": "a", "v": 1.0})
            mem.publish("j/r", {"id": "a", "w": 2.0})
            mem.publish("j/l", {"id": "only_left", "v": 9.0})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert {m["id"] for m in msgs} == {"a"}  # inner join drops only_left
        assert msgs[0]["v"] == 1.0 and msgs[0]["w"] == 2.0

    def test_join_without_window_rejected(self):
        store = kv.get_store()
        _streams(store)
        with pytest.raises(PlanError, match="JOIN requires a window"):
            plan_rule(RuleDef(
                id="j2", sql=("SELECT ls.id FROM ls "
                              "INNER JOIN rs ON ls.id = rs.id"),
                actions=[{"log": {}}], options={}), store)


class TestMixedJoins:
    def test_lookup_node_only_on_its_stream_chain(self):
        """With a stream join AND a lookup join, the lookup node must sit
        only on the chain its ON clause references — other streams' rows
        must not be filtered through it."""
        store = kv.get_store()
        _streams(store)
        StreamProcessor(store).exec_stmt(
            'CREATE TABLE meta (id STRING, site STRING) '
            'WITH (DATASOURCE="mx/meta", TYPE="memory", FORMAT="JSON", '
            'KEY="id")')
        topo = plan_rule(RuleDef(
            id="mx1", sql=(
                "SELECT ls.id, rs.w, meta.site FROM ls "
                "INNER JOIN rs ON ls.id = rs.id "
                "INNER JOIN meta ON ls.id = meta.id "
                "GROUP BY TUMBLINGWINDOW(ss, 10)"),
            actions=[{"memory": {"topic": "mx1/out"}}], options={}), store)
        lookup = next(n for n in topo.ops if n.name.startswith("lookup_join"))
        # only the ls chain feeds the lookup node
        feeders = [n.name for n in topo.ops + topo.sources
                   if lookup in n.outputs]
        assert feeders == ["ls_shared"], feeders
        return topo

    def test_mixed_join_values(self, mock_clock):
        topo = self.test_lookup_node_only_on_its_stream_chain()
        got = []
        mem.subscribe("mx1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("mx/meta", {"id": "a", "site": "oslo"})
            mem.publish("j/l", {"id": "a", "v": 1.0})
            mem.publish("j/r", {"id": "a", "w": 2.0})
            mock_clock.advance(20)
            assert topo.wait_idle(10)
            mock_clock.advance(10_000)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert msgs and msgs[0] == {"id": "a", "w": 2.0, "site": "oslo"}, msgs


class TestLookupJoin:
    def test_stream_to_table_join(self, mock_clock):
        store = kv.get_store()
        sp = StreamProcessor(store)
        sp.exec_stmt('CREATE STREAM ev (dev STRING, val FLOAT) '
                     'WITH (DATASOURCE="lk/ev", TYPE="memory", FORMAT="JSON")')
        sp.exec_stmt('CREATE TABLE meta (dev STRING, site STRING) '
                     'WITH (DATASOURCE="lk/meta", TYPE="memory", '
                     'FORMAT="JSON", KEY="dev")')
        # seed the lookup table BEFORE the rule starts? Memory lookup
        # subscribes at open; publish after open.
        topo = plan_rule(RuleDef(
            id="lk1", sql=("SELECT ev.dev, ev.val, meta.site FROM ev "
                           "INNER JOIN meta ON ev.dev = meta.dev"),
            actions=[{"memory": {"topic": "lk1/out"}}], options={}), store)
        assert any(n.name.startswith("lookup_join") for n in topo.ops)
        got = []
        mem.subscribe("lk1/out", lambda t, p: got.append(p))
        topo.open()
        try:
            mem.publish("lk/meta", {"dev": "d1", "site": "berlin"})
            mem.publish("lk/ev", {"dev": "d1", "val": 7.0})
            mock_clock.advance(20)
            deadline = time.time() + 6
            while time.time() < deadline and not _flat(got):
                time.sleep(0.02)
        finally:
            topo.close()
        msgs = _flat(got)
        assert msgs and msgs[0]["site"] == "berlin" and msgs[0]["val"] == 7.0
