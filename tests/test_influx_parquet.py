"""Influx line-protocol sinks (io/influx_io.py) against a fake HTTP
endpoint, and the parquet file format/columnar batch writer
(io/file.py) round-trips."""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.io import registry as io_registry
from ekuiper_tpu.io.influx_io import to_lines
from ekuiper_tpu.utils.infra import EngineError


# ------------------------------------------------------------ line protocol
class TestLineProtocol:
    def test_types_and_escaping(self):
        rows = [{"t": 21.5, "n": 3, "ok": True, "s": 'say "hi"',
                 "skip": None, "arr": [1, 2], "ts": 1_700_000_000_000}]
        out = to_lines(rows, "my m", {"site": "a=b", "dev": "{{.s}}"},
                       "ts", "ms").decode()
        assert out.startswith("my\\ m,")
        assert "site=a\\=b" in out
        assert 'dev=say\\ "hi"' in out
        assert "t=21.5" in out and "n=3i" in out and "ok=true" in out
        assert 's="say \\"hi\\""' in out
        assert "skip" not in out and "arr" not in out
        assert out.endswith(" 1700000000000")

    def test_ts_field_used_verbatim(self):
        # ref getTime: a configured ts field is ALREADY in the precision
        # unit — no conversion (tspoint/transform.go:121-137)
        rows = [{"v": 1.0, "ts": 1_000}]
        assert to_lines(rows, "m", {}, "ts", "s").decode().endswith(" 1000")
        assert to_lines(rows, "m", {}, "ts", "ns").decode().endswith(" 1000")

    def test_now_timestamp_when_no_ts_field(self, mock_clock):
        mock_clock.set(5_000)
        out = to_lines([{"v": 1.0}], "m", {"dev": "{{.missing}}"},
                       "", "ms").decode()
        assert out == "m v=1.0 5000"  # empty tag dropped, now() stamped
        out_s = to_lines([{"v": 1.0}], "m", {}, "", "s").decode()
        assert out_s == "m v=1.0 5"


# ---------------------------------------------------------------- fake http
class _Recorder(BaseHTTPRequestHandler):
    requests: list = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        type(self).requests.append({
            "path": self.path,
            "auth": self.headers.get("Authorization"),
            "body": self.rfile.read(n).decode(),
        })
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture
def http_server():
    _Recorder.requests = []
    srv = HTTPServer(("127.0.0.1", 0), _Recorder)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()


class TestInfluxSinks:
    def test_v1_write(self, http_server):
        sink = io_registry.create_sink("influx")
        sink.configure({"addr": f"http://127.0.0.1:{http_server.server_port}",
                        "database": "mydb", "measurement": "weather",
                        "username": "u", "password": "p",
                        "tags": {"deviceId": "{{.deviceId}}"}})
        sink.connect()
        sink.collect([{"deviceId": "d1", "temperature": 20.5},
                      {"deviceId": "d2", "temperature": 21.0}])
        req = _Recorder.requests[0]
        assert req["path"].startswith("/write?")
        assert "db=mydb" in req["path"] and "precision=ms" in req["path"]
        assert req["auth"].startswith("Basic ")
        lines = req["body"].splitlines()
        # tag-source fields stay fields too (ref: Fields=mm); now() stamps
        assert lines[0].startswith(
            'weather,deviceId=d1 deviceId="d1",temperature=20.5 ')
        assert lines[1].startswith(
            'weather,deviceId=d2 deviceId="d2",temperature=21.0 ')

    def test_v2_write_and_errors(self, http_server):
        sink = io_registry.create_sink("influx2")
        sink.configure({"addr": f"http://127.0.0.1:{http_server.server_port}",
                        "org": "o1", "bucket": "b1", "token": "tk",
                        "measurement": "m"})
        sink.connect()
        sink.collect({"v": 2})
        req = _Recorder.requests[0]
        assert req["path"].startswith("/api/v2/write?")
        assert "org=o1" in req["path"] and "bucket=b1" in req["path"]
        assert req["auth"] == "Token tk"
        assert req["body"].startswith("m v=2i ")
        with pytest.raises(EngineError, match="measurement"):
            io_registry.create_sink("influx").configure(
                {"database": "d"})
        with pytest.raises(EngineError, match="org and bucket"):
            io_registry.create_sink("influx2").configure(
                {"measurement": "m"})


# ------------------------------------------------------------------ parquet
class TestParquet:
    def test_row_round_trip(self, tmp_path):
        path = str(tmp_path / "out.parquet")
        sink = io_registry.create_sink("file")
        sink.configure({"path": path, "fileType": "parquet"})
        sink.connect()
        sink.collect([{"deviceId": "a", "t": 1.5}, {"deviceId": "b", "t": 2.5}])
        sink.collect({"deviceId": "c", "t": 3.5})
        sink.close()
        src = io_registry.create_source("file")
        src.configure(path, {"fileType": "parquet"})
        got = []
        done = threading.Event()
        src.open(lambda payload, meta=None: (got.extend(payload),
                                             done.set()))
        deadline = time.time() + 5
        while time.time() < deadline and len(got) < 3:
            time.sleep(0.01)
        src.close()
        assert [r["deviceId"] for r in got] == ["a", "b", "c"]
        assert [r["t"] for r in got] == [1.5, 2.5, 3.5]

    def test_columnar_batch_write_with_validity(self, tmp_path):
        """ColumnBatch emissions write column-wise (BatchWriterOp analogue):
        validity masks become parquet nulls, no row dicts in between."""
        import pyarrow.parquet as pq

        path = str(tmp_path / "cb.parquet")
        sink = io_registry.create_sink("file")
        sink.configure({"path": path, "fileType": "parquet"})
        assert sink.accepts_batches  # SinkNode takes the columnar fast path
        sink.connect()
        cb = ColumnBatch(
            n=3,
            columns={"deviceId": np.array(["a", "b", "c"], dtype=np.object_),
                     "t": np.array([1.0, 2.0, 3.0], dtype=np.float32)},
            valid={"t": np.array([True, False, True])},
            emitter="s")
        sink.collect(cb)
        sink.close()
        table = pq.read_table(path)
        assert table.column("deviceId").to_pylist() == ["a", "b", "c"]
        assert table.column("t").to_pylist() == [1.0, None, 3.0]

    def test_schema_drift_rolls_file(self, tmp_path):
        path = str(tmp_path / "drift.parquet")
        sink = io_registry.create_sink("file")
        sink.configure({"path": path, "fileType": "parquet"})
        sink.connect()
        sink.collect({"a": 1})
        sink.collect({"b": "x"})  # different schema -> rolls to .1
        sink.close()
        import pyarrow.parquet as pq

        assert pq.read_table(path + ".1").column("a").to_pylist() == [1]
        assert pq.read_table(path).column("b").to_pylist() == ["x"]

    def test_sink_rule_e2e(self, tmp_path, mock_clock):
        """Windowed rule results land in a parquet file via the columnar
        fast path (reference: file sink parquet build tag)."""
        import ekuiper_tpu.io.memory as mem
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv

        path = str(tmp_path / "rule.parquet")
        store = kv.get_store()
        StreamProcessor(store).exec_stmt(
            'CREATE STREAM pqs (deviceId STRING, temperature FLOAT) '
            'WITH (DATASOURCE="t/pq", TYPE="memory", FORMAT="JSON")')
        topo = plan_rule(RuleDef(id="pq1", sql=(
            "SELECT deviceId, avg(temperature) AS a FROM pqs "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"file": {"path": path, "fileType": "parquet"}}],
            options={}), store)
        topo.open()
        try:
            for t_ in (10.0, 20.0):
                mem.publish("t/pq", {"deviceId": "a", "temperature": t_})
            time.sleep(0.2)
            mock_clock.advance(50)
            time.sleep(0.3)
            mock_clock.advance(10_000)
            deadline = time.time() + 8
            import os

            while time.time() < deadline and not os.path.exists(path):
                time.sleep(0.02)
            time.sleep(0.3)
        finally:
            topo.close()
        import pyarrow.parquet as pq

        table = pq.read_table(path)
        rows = table.to_pylist()
        assert any(r["deviceId"] == "a" and r["a"] == 15.0 for r in rows)
