"""QoS control plane (runtime/control.py): shed gate mechanics, the
controller's ladder/hysteresis/autosize loops, admission pricing and
decisions, and the REST/metrics surfaces."""
import os
import threading
import time

import pytest

from ekuiper_tpu.runtime import control
from ekuiper_tpu.runtime.control import (QoSController, SHED_LADDERS,
                                         AdmissionRejected,
                                         parse_qos_class)
from ekuiper_tpu.runtime.events import (EOF, Barrier, PreTrigger, Trigger,
                                        Watermark, recorder)
from ekuiper_tpu.runtime.node import Node
from ekuiper_tpu.store import kv


class Batch:
    """Row-carrying data item (ColumnBatch stand-in)."""

    def __init__(self, n=10):
        self.n = n


# ------------------------------------------------------------- shed gate
class TestShedGate:
    def test_fraction_drops_deterministically(self):
        n = Node("t")
        n.set_shed_fraction(0.5)
        for _ in range(10):
            n.put({"x": 1})
        assert n.stats.dropped.get("shed_qos") == 5
        assert n.inq.qsize() == 5

    def test_rows_counted_not_items(self):
        n = Node("t")
        n.set_shed_fraction(1.0)
        n.put(Batch(n=128))
        assert n.stats.dropped["shed_qos"] == 128
        n.put([1, 2, 3])
        assert n.stats.dropped["shed_qos"] == 131

    def test_control_events_never_shed(self):
        n = Node("t")
        n.set_shed_fraction(1.0)
        for ev in (Barrier(checkpoint_id=1), Watermark(ts=1), EOF(),
                   Trigger(ts=1), PreTrigger(ts=1)):
            n.put(ev)
        assert "shed_qos" not in n.stats.dropped
        assert n.inq.qsize() == 5

    def test_clear_resets_accumulator(self):
        n = Node("t")
        n.set_shed_fraction(0.9)
        n.put({"x": 1})  # acc 0.9, kept
        n.set_shed_fraction(0.0)
        n.set_shed_fraction(0.9)
        n.put({"x": 1})  # acc restarts at 0.9, kept again
        assert "shed_qos" not in n.stats.dropped

    def test_zero_fraction_is_free_path(self):
        n = Node("t")
        for _ in range(5):
            n.put({"x": 1})
        assert n.inq.qsize() == 5


# ------------------------------------------------------------- fake topo
class FakeTopo:
    def __init__(self, pooled_source=None):
        self.entry = Node("entry")
        self.sources = [pooled_source] if pooled_source is not None else []
        self.shared = [(None, self.entry)]

    def entry_nodes(self):
        return [self.entry]

    def set_shed(self, frac):
        self.entry.set_shed_fraction(frac)

    def shed_fraction(self):
        return self.entry._shed_frac

    def shed_rows(self):
        return self.entry.stats.dropped.get("shed_qos", 0)

    def live_shared(self):
        return []


class FakePooledSource:
    """SourceNode stand-in with the resize_ingest contract."""

    def __init__(self, pool=2, ring=2):
        self.name = "src"
        self.decode_pool_size = pool
        self.ring_depth = ring

    def resize_ingest(self, pool_size=None, ring_depth=None):
        if self.decode_pool_size <= 0:
            return None
        if pool_size is not None:
            self.decode_pool_size = max(1, int(pool_size))
        if ring_depth is not None:
            self.ring_depth = max(1, int(ring_depth))
        return {"pool_size": self.decode_pool_size,
                "ring_depth": self.ring_depth}


def make_ctl(topo, options, verdict_box):
    return QoSController(
        lambda: [("r1", topo, options)],
        verdicts_fn=lambda: dict(verdict_box),
        interval_ms=1000)


# ------------------------------------------------------- ladder/hysteresis
class TestShedLadder:
    def test_escalates_after_up_ticks_only(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {"qosClass": "standard"}, box)
        ctl.tick()
        assert topo.shed_fraction() == 0.0  # 1 breaching tick < up_ticks
        ctl.tick()
        assert topo.shed_fraction() == SHED_LADDERS["standard"][0]

    def test_full_ladder_then_recovery(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {"qosClass": "low"}, box)
        for _ in range(8):
            ctl.tick()
        assert topo.shed_fraction() == SHED_LADDERS["low"][3]  # maxed
        box["r1"] = {"state": "healthy"}
        for _ in range(3):
            ctl.tick()
        assert topo.shed_fraction() == SHED_LADDERS["low"][2]  # one step
        for _ in range(12):
            ctl.tick()
        assert topo.shed_fraction() == 0.0

    def test_degraded_holds_level(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {}, box)
        ctl.tick()
        ctl.tick()
        frac = topo.shed_fraction()
        assert frac > 0
        box["r1"] = {"state": "degraded"}
        for _ in range(6):
            ctl.tick()
        assert topo.shed_fraction() == frac

    def test_critical_never_shed(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {"qosClass": "critical"}, box)
        for _ in range(6):
            ctl.tick()
        assert topo.shed_fraction() == 0.0
        assert "shed_qos" not in topo.entry.stats.dropped

    def test_shed_events_carry_severity(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {}, box)
        for _ in range(2):
            ctl.tick()
        box["r1"] = {"state": "healthy"}
        for _ in range(3):
            ctl.tick()
        evs = recorder().events(kind="shed")
        assert [e["severity"] for e in evs] == ["warn", "info"]
        assert evs[0]["level"] == 1 and evs[1]["level"] == 0
        assert evs[0]["qos"] == "standard"

    def test_shed_totals_survive_topo_restart(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        ctl = make_ctl(topo, {}, box)
        ctl.tick()
        ctl.tick()  # level 1 installed
        for _ in range(20):
            topo.entry.put({"x": 1})
        ctl.tick()  # fold drops into totals
        before = ctl.shed_totals()[("r1", "standard")]
        assert before > 0
        # "restart": fresh entry node (counters reset), same rule
        topo.entry = Node("entry")
        ctl.tick()  # re-baselines without negative delta
        assert ctl.shed_totals()[("r1", "standard")] == before
        # and the gate is re-asserted on the new topo's entry
        assert topo.shed_fraction() > 0

    def test_track_grace_over_restart_window(self):
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        holder = [("r1", topo, {})]
        ctl = QoSController(lambda: list(holder),
                            verdicts_fn=lambda: dict(box))
        ctl.tick()
        ctl.tick()
        assert ctl.shed_state()["r1"]["level"] == 1
        holder.clear()  # rule mid-restart: no live topo
        for _ in range(5):
            ctl.tick()
        assert "r1" in ctl.shed_state()  # grace keeps the track
        for _ in range(10):
            ctl.tick()
        assert "r1" not in ctl.shed_state()  # gone for good -> swept


# ---------------------------------------------------------------- autosize
class TestAutosize:
    def _verdict(self, stage, state="degraded"):
        return {"state": state, "bottleneck": {"stage": stage,
                                               "share": 0.8}}

    def test_decode_bottleneck_grows_pool(self):
        src = FakePooledSource(pool=2)
        topo = FakeTopo(pooled_source=src)
        box = {"r1": self._verdict("decode")}
        ctl = make_ctl(topo, {}, box)
        ctl.tick()
        assert src.decode_pool_size == 3
        assert ctl.autosize_events == 1
        evs = recorder().events(kind="autosize")
        assert evs and evs[0]["action"] == "grow_pool"

    def test_cooldown_rate_limits(self):
        src = FakePooledSource(pool=2)
        topo = FakeTopo(pooled_source=src)
        box = {"r1": self._verdict("decode")}
        ctl = make_ctl(topo, {}, box)
        for _ in range(4):
            ctl.tick()
        assert src.decode_pool_size == 3  # one action per cooldown run
        for _ in range(4):
            ctl.tick()
        assert src.decode_pool_size == 4

    def test_upload_bottleneck_grows_ring_and_bound(self, monkeypatch):
        monkeypatch.setenv("KUIPER_AUTOSIZE_MAX_RING", "3")
        src = FakePooledSource(ring=2)
        topo = FakeTopo(pooled_source=src)
        box = {"r1": self._verdict("upload")}
        ctl = make_ctl(topo, {}, box)
        for _ in range(20):
            ctl.tick()
        assert src.ring_depth == 3  # capped at the bound

    def test_sustained_health_shrinks_back(self):
        src = FakePooledSource(pool=2)
        topo = FakeTopo(pooled_source=src)
        box = {"r1": self._verdict("decode")}
        ctl = make_ctl(topo, {}, box)
        ctl.tick()
        assert src.decode_pool_size == 3
        box["r1"] = {"state": "healthy"}
        for _ in range(20):
            ctl.tick()
        assert src.decode_pool_size == 2  # back to the configured size

    def test_inline_source_untouched(self):
        src = FakePooledSource(pool=0)
        topo = FakeTopo(pooled_source=src)
        box = {"r1": self._verdict("decode")}
        ctl = make_ctl(topo, {}, box)
        for _ in range(4):
            ctl.tick()
        assert src.decode_pool_size == 0
        assert ctl.autosize_events == 0


# --------------------------------------------------------------- admission
def _mk_stream(store, name="ctrl", topic="ctrl/t"):
    from ekuiper_tpu.server.processors import StreamProcessor

    StreamProcessor(store).exec_stmt(
        f'CREATE STREAM {name} (deviceId STRING, v FLOAT) '
        f'WITH (DATASOURCE="{topic}", TYPE="memory", FORMAT="JSON")')


def _rule(rid="adm1", sql=None, options=None):
    from ekuiper_tpu.planner.planner import RuleDef

    return RuleDef(
        id=rid,
        sql=sql or ("SELECT deviceId, avg(v) AS a FROM ctrl "
                    "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
        actions=[{"nop": {}}], options=options or {})


class TestAdmission:
    def test_accepts_by_default(self):
        store = kv.get_store()
        _mk_stream(store)
        d = control.admit_rule(_rule(), store)
        assert d["decision"] == "accept"
        assert d["price"]["fold_us_per_s"] > 0
        assert d["price"]["path"] in ("device-private", "device-shared")

    def test_price_degrades_on_unparseable_rule(self):
        store = kv.get_store()
        d = control.admit_rule(_rule(sql="NOT EVEN SQL"), store)
        assert d["decision"] == "accept"  # pricing failure != rejection

    def test_fold_budget_rejects_structured(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        monkeypatch.setenv("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S", "1")
        d = control.admit_rule(_rule(), store)
        assert d["decision"] == "reject"
        assert "budget" in d["reason"]
        assert d["price"]["fold_us_per_s"] > 1

    def test_hbm_budget_rejects(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        from ekuiper_tpu.observability import memwatch

        owner = object.__new__(Node)  # any weakref-able owner
        memwatch.register("test_blob", owner, lambda o: 512 * 1024 * 1024,
                          rule="x")
        monkeypatch.setenv("KUIPER_HBM_BUDGET_MB", "256")
        d = control.admit_rule(_rule(), store)
        assert d["decision"] == "reject"
        assert "HBM" in d["reason"]
        assert d["price"]["hbm_current_bytes"] >= 512 * 1024 * 1024

    def test_kill_switch(self, monkeypatch):
        store = kv.get_store()
        monkeypatch.setenv("KUIPER_ADMISSION", "0")
        monkeypatch.setenv("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S", "1")
        d = control.admit_rule(_rule(), store)
        assert d["decision"] == "accept"

    def test_update_not_double_billed(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        ctl = control.install(lambda: [], start=False)
        d = control.admit_rule(_rule("same"), store)
        ctl.commit("same", d["price"]["fold_us_per_s"])
        # budget covers exactly one copy of the rule: re-admitting the
        # SAME id must subtract its own committed cost first
        monkeypatch.setenv(
            "KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S",
            str(d["price"]["fold_us_per_s"] + 1))
        d2 = control.admit_rule(_rule("same"), store)
        assert d2["decision"] == "accept"
        d3 = control.admit_rule(_rule("other"), store)
        assert d3["decision"] == "reject"

    def test_queue_on_breaching_pressure_then_drain(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        box = {"x": {"state": "breaching"}}
        started = []
        ctl = control.install(lambda: [], start_fn=started.append,
                              start=False)
        ctl._verdicts_fn = lambda: dict(box)
        monkeypatch.setenv("KUIPER_ADMISSION_DEFER_BREACHING", "1")
        d = control.admit_rule(_rule("qd1"), store)
        assert d["decision"] == "queue"
        assert ctl.enqueue("qd1", d)
        ctl.tick()
        assert not started  # pressure still on: held
        assert ctl.queued("qd1")["attempts"] == 1
        box.clear()
        ctl.tick()
        assert started == ["qd1"]
        assert ctl.queued("qd1") is None
        assert ctl.admission_counts()["accept"] >= 1
        evs = recorder().events(kind="admission")
        assert any(e.get("dequeued") for e in evs)

    def test_queue_capacity_bounded(self):
        ctl = control.install(lambda: [], start=False)
        for i in range(control.ADMISSION_QUEUE_CAP):
            assert ctl.enqueue(f"r{i}", {"reason": "x", "price": {}})
        assert not ctl.enqueue("overflow", {"reason": "x", "price": {}})

    def test_rejected_exception_carries_decision(self):
        exc = AdmissionRejected({"decision": "reject", "reason": "why",
                                 "price": {"fold_us_per_s": 9}})
        assert exc.decision["price"]["fold_us_per_s"] == 9
        assert "why" in str(exc)


class TestCertifiedSignaturePricing:
    """ISSUE 10: admission prices a candidate's jitcert-certified
    new-signature count instead of waiting for the live storm edge."""

    def test_private_device_rule_prices_certificate(self):
        store = kv.get_store()
        _mk_stream(store)
        d = control.admit_rule(_rule(), store)
        assert d["price"]["path"] == "device-private"
        n = d["price"]["certified_new_signatures"]
        assert n > 0
        # machine-checkable: re-deriving from the same plan-time
        # declarations reproduces the count admission priced
        from ekuiper_tpu.observability import jitcert
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.planner.planner import merged_options
        from ekuiper_tpu.sql.parser import parse_select

        rule = _rule()
        opts = merged_options(rule)
        plan = extract_kernel_plan(parse_select(rule.sql))
        assert n == jitcert.estimate_plan_signatures(
            plan, 1, opts.micro_batch_rows, opts.key_slots)

    def test_pane_count_does_not_change_executable_count(self):
        """Hopping windows widen signature SHAPES, not the executable
        count admission budgets — a hopping twin prices identically to
        its tumbling sibling (and the estimator is pane-invariant, so
        price_rule passes n_panes=1 without a window inspection)."""
        store = kv.get_store()
        _mk_stream(store)
        tumble = control.admit_rule(_rule(), store)
        hop = control.admit_rule(_rule(
            rid="adm_hop",
            sql=("SELECT deviceId, avg(v) AS a FROM ctrl GROUP BY "
                 "deviceId, HOPPINGWINDOW(ss, 40, 10)")), store)
        assert (hop["price"]["certified_new_signatures"]
                == tumble["price"]["certified_new_signatures"])
        from ekuiper_tpu.observability import jitcert
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.sql.parser import parse_select

        plan = extract_kernel_plan(parse_select(_rule().sql))
        assert (jitcert.estimate_plan_signatures(plan, 1, 512, 1024)
                == jitcert.estimate_plan_signatures(plan, 8, 512, 1024))

    def test_pricing_failure_is_unknown_not_zero(self, monkeypatch):
        """An estimate crash must leave the UNKNOWN sentinel (None):
        failing open to 0 would both disarm the signature budget and
        route a compile-heavy candidate through the storm bypass."""
        store = kv.get_store()
        _mk_stream(store)
        from ekuiper_tpu.observability import jitcert

        def boom(*a, **k):
            raise RuntimeError("no derivation")

        monkeypatch.setattr(jitcert, "estimate_plan_signatures", boom)
        ctl = control.install(lambda: [], start=False)
        ctl._storm_active = True
        d = control.admit_rule(_rule("adm_unknown"), store)
        assert d["price"]["certified_new_signatures"] is None
        assert "certify_error" in d["price"]
        # unknown defers like compile load during a storm
        assert d["decision"] == "queue"
        # ...but does not trip the signature budget (that would 429
        # every unpriceable rule)
        control.reset()
        monkeypatch.setenv("KUIPER_ADMISSION_SIG_BUDGET", "1")
        d = control.admit_rule(_rule("adm_unknown2"), store)
        assert d["decision"] == "accept"

    def test_sig_budget_rejects_structured(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        monkeypatch.setenv("KUIPER_ADMISSION_SIG_BUDGET", "1")
        d = control.admit_rule(_rule(), store)
        assert d["decision"] == "reject"
        assert "signature" in d["reason"]
        assert d["price"]["certified_new_signatures"] > 1

    def test_host_path_rule_prices_zero_signatures(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store)
        monkeypatch.setenv("KUIPER_ADMISSION_SIG_BUDGET", "1")
        d = control.admit_rule(
            _rule(rid="adm_host",
                  sql="SELECT deviceId, v FROM ctrl WHERE v > 1"), store)
        assert d["price"]["certified_new_signatures"] == 0
        assert d["decision"] == "accept"  # no compile surface, no gate

    def test_zero_sig_candidate_bypasses_storm_deferral(self):
        """A storm defers new COMPILE load — a candidate whose
        certificate prices zero new signatures adds none and must be
        admitted straight through."""
        store = kv.get_store()
        _mk_stream(store)
        ctl = control.install(lambda: [], start=False)
        ctl._storm_active = True
        dev = control.admit_rule(_rule("adm_dev"), store)
        assert dev["decision"] == "queue"
        assert "storm" in dev["reason"]
        host = control.admit_rule(
            _rule(rid="adm_host2",
                  sql="SELECT deviceId, v FROM ctrl WHERE v > 1"), store)
        assert host["decision"] == "accept"


# ------------------------------------------------------------ REST surface
class TestRestSurface:
    def _api(self):
        from ekuiper_tpu.server.rest import RestApi

        api = RestApi(kv.get_store())
        # manual ticks only — deterministic
        api.health_evaluator.stop()
        api.qos_controller.stop()
        return api

    def test_create_reject_is_429_structured(self, monkeypatch):
        api = self._api()
        _mk_stream(api.store, "r1s", "r1s/t")
        monkeypatch.setenv("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S", "1")
        code, out = api.dispatch("POST", "/rules", {
            "id": "rj", "sql": ("SELECT deviceId, avg(v) AS a FROM r1s "
                                "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
            "actions": [{"nop": {}}]}, {})
        assert code == 429
        assert out["admission"]["decision"] == "reject"
        assert out["admission"]["price"]["fold_us_per_s"] > 0
        # rolled back: the definition must not linger
        assert all(e["id"] != "rj" for e in api.rules.list())
        from ekuiper_tpu.planner import sharing

        assert not any("rj" in d for d in sharing._declared.values())

    def test_diagnostics_control_shape(self):
        api = self._api()
        code, out = api.dispatch("GET", "/diagnostics/control", None, {})
        assert code == 200
        assert "decisions" in out["admission"]
        assert "shedding" in out and "autosize" in out

    def test_delete_releases_ledger(self, monkeypatch):
        api = self._api()
        _mk_stream(api.store, "r2s", "r2s/t")
        code, _ = api.dispatch("POST", "/rules", {
            "id": "led", "sql": ("SELECT deviceId, avg(v) AS a FROM r2s "
                                 "GROUP BY deviceId, "
                                 "TUMBLINGWINDOW(ss, 10)"),
            "actions": [{"nop": {}}], "options": {"triggered": False}}, {})
        assert code == 201
        ctl = control.controller()
        ctl.commit("led", 123.0)
        api.dispatch("DELETE", "/rules/led", None, {})
        assert ctl.committed_us_per_s() == 0.0

    def test_prometheus_families_render(self):
        api = self._api()
        ctl = control.controller()
        ctl.note_admission("reject")
        ctl._shed_totals[("r", "low")] = 7
        from ekuiper_tpu.observability import prometheus

        text = prometheus.render(api.rules)
        assert 'kuiper_admission_total{decision="reject"} 1' in text
        assert 'kuiper_shed_total{rule="r",qos="low"} 7' in text
        assert "kuiper_autosize_events_total 0" in text


# ---------------------------------------------------------- pool plumbing
class TestDecodePoolResize:
    def _pool(self, size=1, ring=2):
        from ekuiper_tpu.runtime.ingest import DecodePool

        out = []
        pool = DecodePool(size, ring, decode_fn=lambda j: j,
                          emit_fn=out.append, name="t")
        return pool, out

    def test_grow_keeps_order(self):
        pool, out = self._pool(size=1)
        for i in range(5):
            pool.submit(i)
        assert pool.resize(4) == 4
        for i in range(5, 40):
            pool.submit(i)
        assert pool.drain(timeout=10)
        assert out == list(range(40))
        pool.close()

    def test_shrink_retires_and_still_drains(self):
        pool, out = self._pool(size=4)
        assert pool.resize(1) == 1
        for i in range(20):
            pool.submit(i)
        assert pool.drain(timeout=10)
        assert out == list(range(20))
        pool.close()
        # retired workers exit; close joins the rest
        time.sleep(0.1)
        alive = [t for t in pool._threads if t.is_alive()]
        assert not alive

    def test_ring_depth_grow_unblocks_submitter(self):
        from ekuiper_tpu.runtime.ingest import DecodePool

        gate = threading.Event()
        out = []
        pool = DecodePool(1, 1, decode_fn=lambda j: (gate.wait(5), j)[1],
                          emit_fn=out.append, name="t")
        pool.submit(0)
        done = []

        def second():
            pool.submit(1)  # blocks: ring depth 1, job 0 in flight
            done.append(True)

        t = threading.Thread(target=second, daemon=True)
        t.start()
        time.sleep(0.1)
        assert not done
        pool.set_ring_depth(3)
        t.join(timeout=5)
        assert done
        gate.set()
        assert pool.drain(timeout=10)
        assert out == [0, 1]
        pool.close()


class TestReviewRegressions:
    """Fixes from the PR's review pass, each pinned."""

    def test_class_change_to_critical_clears_live_shed(self):
        # a rule UPDATE that flips qosClass to critical while a shed
        # level is live must clamp the level (not IndexError) and the
        # re-assert must clear the installed gate
        topo = FakeTopo()
        box = {"r1": {"state": "breaching"}}
        opts = {"qosClass": "low"}
        ctl = QoSController(lambda: [("r1", topo, opts)],
                            verdicts_fn=lambda: dict(box))
        for _ in range(4):
            ctl.tick()
        assert topo.shed_fraction() > 0
        opts["qosClass"] = "critical"
        ctl.tick()  # must not raise
        assert topo.shed_fraction() == 0.0
        assert ctl.shed_state()["r1"]["level"] == 0
        ctl.diagnostics()  # must not raise either

    def test_queue_drain_regates_budgets(self, monkeypatch):
        # two rules queued during one storm each passed the gates
        # against a ledger excluding the other — at dequeue the gates
        # re-run, so only what fits the budget starts
        started = []
        ctl = control.install(lambda: [], start_fn=started.append,
                              start=False)
        monkeypatch.setenv("KUIPER_ADMISSION_FOLD_BUDGET_US_PER_S",
                           "100")
        price = {"fold_us_per_s": 60.0, "hbm_current_bytes": 0,
                 "hbm_projected_bytes": 0}
        assert ctl.enqueue("a", {"reason": "storm", "price": dict(price)})
        assert ctl.enqueue("b", {"reason": "storm", "price": dict(price)})
        ctl.tick()
        assert started == ["a"]
        assert ctl.queued("b") is None  # rejected at dequeue, not held
        assert ctl.admission_counts()["reject"] == 1
        assert ctl.committed_us_per_s() == 60.0

    def test_update_never_counts_queue(self, monkeypatch):
        store = kv.get_store()
        _mk_stream(store, "upq", "upq/t")
        box = {"x": {"state": "breaching"}}
        ctl = control.install(lambda: [], start=False)
        ctl._verdicts_fn = lambda: dict(box)
        monkeypatch.setenv("KUIPER_ADMISSION_DEFER_BREACHING", "1")
        d = control.admit_rule(_rule("u1"), store, allow_queue=False)
        assert d["decision"] == "accept"
        assert ctl.admission_counts()["queue"] == 0
        assert not recorder().events(kind="admission")

    def test_failed_update_does_not_rebill_ledger(self):
        from ekuiper_tpu.server.rule_manager import RuleRegistry
        from ekuiper_tpu.utils.infra import PlanError

        store = kv.get_store()
        _mk_stream(store, "upl", "upl/t")
        reg = RuleRegistry(store)
        ctl = control.install(lambda: [], start=False)
        ctl.commit("ghost", 10.0)  # stale billing for a vanished rule
        with pytest.raises(PlanError):
            # processor rejects the update (unknown id) AFTER admission
            # priced it — the ledger must keep the pre-update value
            reg.update({"id": "ghost", "sql": "SELECT deviceId FROM upl",
                        "actions": [{"nop": {}}]})
        assert ctl.committed_us_per_s() == 10.0

    def test_claim_pops_and_commits_once(self):
        ctl = control.install(lambda: [], start=False)
        assert ctl.enqueue("c1", {"reason": "x",
                                  "price": {"fold_us_per_s": 7.0}})
        entry = ctl.claim("c1")
        assert entry is not None
        assert ctl.committed_us_per_s() == 7.0
        assert ctl.claim("c1") is None  # second claim is a no-op
        assert ctl.queued("c1") is None


class TestLedgerLifecycle:
    """Round-2 review: the committed ledger must track RUNNING rules
    through every lifecycle path, not just create-triggered ones."""

    def _registry(self):
        from ekuiper_tpu.server.rule_manager import RuleRegistry

        store = kv.get_store()
        _mk_stream(store, "led", "led/t")
        return RuleRegistry(store), store

    def _dev_rule(self, rid, triggered=True):
        return {"id": rid,
                "sql": ("SELECT deviceId, avg(v) AS a FROM led "
                        "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"),
                "actions": [{"nop": {}}],
                "options": {"triggered": triggered}}

    def test_recover_rebuilds_ledger(self):
        reg, store = self._registry()
        ctl = control.install(lambda: [], start=False)
        reg.create(self._dev_rule("lr1"))
        billed = ctl.committed_us_per_s()
        assert billed > 0
        # "restart": fresh controller (empty ledger) + recover
        ctl2 = control.install(lambda: [], start=False)
        assert ctl2.committed_us_per_s() == 0.0
        reg.recover()
        assert ctl2.committed_us_per_s() == pytest.approx(billed)
        reg.stop_all()

    def test_untriggered_start_bills_and_stop_releases(self):
        reg, store = self._registry()
        ctl = control.install(lambda: [], start=False)
        reg.create(self._dev_rule("lu1", triggered=False))
        assert ctl.committed_us_per_s() == 0.0  # defined, not running
        reg.start("lu1")
        assert ctl.committed_us_per_s() > 0  # running -> billed
        reg.stop("lu1")
        assert ctl.committed_us_per_s() == 0.0  # stopped -> released
        reg.stop_all()

    def test_dequeue_regates_live_hbm(self, monkeypatch):
        from ekuiper_tpu.observability import memwatch

        started = []
        unqueued = []
        ctl = control.install(lambda: [], start_fn=started.append,
                              unqueue_fn=unqueued.append, start=False)
        monkeypatch.setenv("KUIPER_HBM_BUDGET_MB", "1")
        # enqueue-time snapshot was UNDER budget...
        ctl.enqueue("hq1", {"reason": "storm", "price": {
            "fold_us_per_s": 0.0, "hbm_current_bytes": 0,
            "hbm_projected_bytes": 0}})
        # ...but HBM grew past it during the queue period
        owner = object.__new__(Node)
        memwatch.register("hb_blob", owner,
                          lambda o: 8 * 1024 * 1024, rule="x")
        ctl.tick()
        assert started == []  # NOT started over budget
        assert ctl.queued("hq1") is None
        assert ctl.admission_counts()["reject"] == 1
        assert unqueued == ["hq1"]  # persisted slot cleanup hook fired

    def test_queue_full_downgrade_counts_reject_not_queue(
            self, monkeypatch):
        from ekuiper_tpu.server.rest import RestApi

        api = RestApi(kv.get_store())
        api.health_evaluator.stop()
        api.qos_controller.stop()
        _mk_stream(api.store, "ledf", "ledf/t")
        ctl = control.controller()
        for i in range(control.ADMISSION_QUEUE_CAP):
            assert ctl.enqueue(f"filler{i}", {"reason": "x", "price": {}})
        queue_count = ctl.admission_counts()["queue"]
        monkeypatch.setenv("KUIPER_ADMISSION_DEFER_BREACHING", "1")
        ctl._verdicts_fn = lambda: {"x": {"state": "breaching"}}
        code, out = api.dispatch("POST", "/rules", {
            "id": "overflowed", "sql": "SELECT deviceId FROM ledf",
            "actions": [{"nop": {}}]}, {})
        assert code == 429
        counts = ctl.admission_counts()
        assert counts["queue"] == queue_count  # NOT counted as queued
        assert counts["reject"] == 1


def test_parse_qos_class():
    assert parse_qos_class(None) == "standard"
    assert parse_qos_class({"qosClass": "LOW"}) == "low"
    assert parse_qos_class({"qos_class": "critical"}) == "critical"
    assert parse_qos_class({"qosClass": "goldplated"}) == "standard"
