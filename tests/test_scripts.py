"""Script UDF tests — modeled on the reference's JS function tests
(internal/plugin/js/function_test.go) and script management
(rpc_script.go)."""
import time

import pytest

from ekuiper_tpu.functions import registry as freg
from ekuiper_tpu.plugin.script import ScriptManager, ScriptOpNode, _compile_script
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.infra import EngineError


@pytest.fixture
def mgr():
    m = ScriptManager(kv.get_store())
    ScriptManager.set_global(m)
    yield m
    for name in list(m.list()):
        m.delete(name)


def test_script_expression_form(mgr):
    mgr.create({"id": "double", "script": "args[0] * 2"})
    assert freg.lookup("double").exec([21], {}) == 42


def test_script_def_form(mgr):
    mgr.create({"id": "area", "script":
                "def exec(args, ctx):\n    return args[0] * args[1]\n"})
    assert freg.lookup("area").exec([6, 7], {}) == 42


def test_script_in_sql_rule(mgr):
    mgr.create({"id": "fahrenheit", "script": "args[0] * 9 / 5 + 32"})
    from ekuiper_tpu.io.memory import publish, subscribe
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.server.rule_manager import RuleRegistry
    from ekuiper_tpu.utils import timex

    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM sc (t float) WITH (TYPE="memory", DATASOURCE="sct")')
    got = []
    unsub = subscribe("scout", lambda t, d: got.append(d))
    timex.use_real_clock()
    rr = RuleRegistry(store)
    rr.create({"id": "rsc", "sql": "SELECT fahrenheit(t) AS f FROM sc",
               "actions": [{"memory": {"topic": "scout"}}]})
    time.sleep(0.3)
    publish("sct", {"t": 100.0})
    time.sleep(1.0)
    rr.stop("rsc")
    rr.delete("rsc")
    unsub()
    rows = [r for g in got for r in (g if isinstance(g, list) else [g])]
    assert rows and rows[0]["f"] == 212.0


def test_script_update_hot_reload(mgr):
    mgr.create({"id": "v", "script": "args[0] + 1"})
    assert freg.lookup("v").exec([1], {}) == 2
    mgr.update({"id": "v", "script": "args[0] + 100"})
    assert freg.lookup("v").exec([1], {}) == 101


def test_script_delete_unregisters(mgr):
    mgr.create({"id": "gone", "script": "args[0]"})
    assert freg.lookup("gone") is not None
    mgr.delete("gone")
    assert freg.lookup("gone") is None


def test_script_persistence_across_managers():
    store = kv.get_store()
    m1 = ScriptManager(store)
    m1.create({"id": "persisted", "script": "args[0] * 3"})
    m2 = ScriptManager(store)
    assert m2.list() == ["persisted"]
    assert freg.lookup("persisted").exec([5], {}) == 15
    m2.delete("persisted")


def test_script_sandbox_blocks_imports(mgr):
    with pytest.raises(Exception):
        mgr.create({"id": "evil", "script":
                    "def exec(args, ctx):\n    import os\n    return 1\n"})
        freg.lookup("evil").exec([], {})


def test_script_sandbox_no_open(mgr):
    mgr.create({"id": "evil2", "script":
                "def exec(args, ctx):\n    return open('/etc/passwd')\n"})
    with pytest.raises(Exception):
        freg.lookup("evil2").exec([], {})


def test_script_validation_rejects_bad_source(mgr):
    with pytest.raises(EngineError):
        mgr.create({"id": "bad", "script": "x = 1"})  # no exec, not an expr


def test_script_op_node_in_graph():
    from ekuiper_tpu.planner.graph import plan_by_graph
    from ekuiper_tpu.planner.planner import RuleDef
    from ekuiper_tpu.io.memory import publish, subscribe
    from ekuiper_tpu.utils import timex

    rule = RuleDef(id="gsc", sql="", graph={
        "nodes": {
            "src": {"type": "source", "nodeType": "memory",
                    "props": {"datasource": "gsct"}},
            "sc": {"type": "operator", "nodeType": "script",
                   "props": {"script":
                             "def exec(msg, meta):\n"
                             "    if msg['v'] < 0:\n"
                             "        return None\n"
                             "    msg['v2'] = msg['v'] ** 2\n"
                             "    return msg\n"}},
            "out": {"type": "sink", "nodeType": "memory",
                    "props": {"topic": "gscout"}},
        },
        "topo": {"sources": ["src"],
                 "edges": {"src": ["sc"], "sc": ["out"]}},
    })
    got = []
    unsub = subscribe("gscout", lambda t, d: got.append(d))
    timex.use_real_clock()
    topo = plan_by_graph(rule, kv.get_store())
    topo.open()
    time.sleep(0.3)
    publish("gsct", {"v": 3})
    publish("gsct", {"v": -1})
    publish("gsct", {"v": 4})
    time.sleep(1.0)
    topo.close()
    unsub()
    rows = [r for g in got for r in (g if isinstance(g, list) else [g])]
    assert sorted(r["v2"] for r in rows) == [9, 16]


def test_ruleset_carries_scripts(mgr):
    """Export/import round-trips scripts; an untranslated JS body reports a
    per-script error while the rest imports (docs/JS_MIGRATION.md)."""
    from ekuiper_tpu.server.processors import RulesetProcessor

    mgr.create({"id": "halve", "script": "args[0] / 2"})
    rp = RulesetProcessor(kv.get_store())
    doc = rp.export()
    assert "halve" in doc["scripts"]
    mgr.delete("halve")
    counts = rp.import_ruleset(doc)
    assert counts["scripts"] == 1
    assert freg.lookup("halve").exec([10], {}) == 5

    bad = {"scripts": {
        "jsfunc": "function jsfunc(x) { return x * 2; }",  # untranslated JS
        "good": {"id": "good", "script": "args[0] + 1"},
    }}
    counts = rp.import_ruleset(bad)
    assert counts["scripts"] == 1
    assert "jsfunc" in counts["script_errors"]
    assert freg.lookup("good").exec([1], {}) == 2
    mgr.delete("good")
