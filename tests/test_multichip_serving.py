"""Multi-chip sharded serving (ISSUE 15): planner selection, cross-mesh
checkpoint restore, touch-column dtype parity, sharded pane stores,
mesh-aware ingest prep, placement-aware admission, and the sliding
fallback's attributability — all on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

from ekuiper_tpu.data.batch import ColumnBatch
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.emit import build_direct_emit
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.parallel.mesh import make_mesh
from ekuiper_tpu.parallel.sharded import ShardedGroupBy
from ekuiper_tpu.runtime.events import Trigger, recorder
from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode
from ekuiper_tpu.sql.parser import parse_select


@pytest.fixture(scope="module")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


HOP_SQL = ("SELECT k, sum(v) AS s, count(*) AS c, min(v) AS mn "
           "FROM d GROUP BY k, HOPPINGWINDOW(ss, 4, 2)")


def _mk_node(mesh, capacity=64):
    stmt = parse_select(HOP_SQL)
    plan = extract_kernel_plan(stmt)
    node = FusedWindowAggNode(
        "mc_test", stmt.window, plan, [d.expr for d in stmt.dimensions],
        capacity=capacity, micro_batch=128, prefinalize_lead_ms=0,
        direct_emit=build_direct_emit(stmt, plan, ["k"]),
        emit_columnar=False, mesh=mesh)
    node.state = node.gb.init_state()
    out = []
    node.emit = lambda item, count=None, _o=out: _o.append(item)
    return node, out


def _batch(ids, vals):
    ids = np.array(ids, dtype=np.object_)
    return ColumnBatch(
        n=len(ids),
        columns={"k": ids, "v": np.asarray(vals, np.float64)},
        timestamps=np.zeros(len(ids), np.int64), emitter="d")


def _flat(msgs):
    rows = {}
    for m in msgs:
        for r in (m if isinstance(m, list) else [m]):
            rows[tuple(sorted(r.items()))] = \
                rows.get(tuple(sorted(r.items())), 0) + 1
    return rows


class TestCrossMeshRestore:
    """Satellite: kill a sharded rule, restore at a different shard
    count (8->1 and 1->8) — KeyTable slots, pane cursors, and emitted
    windows byte-identical to an unsharded run."""

    def _feed(self, nodes, ids, vals):
        for n in nodes:
            n.process(_batch(list(ids), list(vals)))

    def _fire(self, nodes, ts):
        for n in nodes:
            n.on_trigger(Trigger(ts=ts))
            n._drain_async_emits()

    def test_restore_8_to_1_and_back(self, eight_devices, mock_clock):
        rng = np.random.default_rng(3)
        sharded, out_s = _mk_node(make_mesh(rows=2, keys=4))
        ids = [f"k{i}" for i in range(90)]  # forces a grow past 64
        vals = np.rint(rng.normal(40, 9, len(ids)))
        self._feed([sharded], ids, vals)
        self._fire([sharded], 2000)
        assert sharded.cur_pane == 1

        snap8 = sharded.snapshot_state()
        single, out_1 = _mk_node(None)
        single.restore_state(snap8)
        assert single.kt.decode_all() == sharded.kt.decode_all()
        assert single.cur_pane == sharded.cur_pane

        tail_ids = [f"k{i}" for i in range(30, 120)]
        tail_vals = np.rint(rng.normal(40, 9, len(tail_ids)))
        self._feed([sharded, single], tail_ids, tail_vals)
        out_s.clear()
        self._fire([sharded, single], 4000)
        assert _flat(out_1) == _flat(out_s)

        # 1 -> 8: snapshot the single-chip node, restore onto the mesh
        snap1 = single.snapshot_state()
        remesh, out_8 = _mk_node(make_mesh(rows=1, keys=8))
        remesh.restore_state(snap1)
        assert remesh.kt.decode_all() == single.kt.decode_all()
        assert remesh.cur_pane == single.cur_pane
        # capacity rounds UP to shard divisibility, never truncates
        assert remesh.gb.capacity >= single.gb.capacity
        assert remesh.gb.capacity % 8 == 0
        self._feed([remesh, single], ids, vals)
        out_1.clear()
        self._fire([remesh, single], 6000)
        assert _flat(out_8) == _flat(out_1)

    def test_restore_rounds_odd_capacity(self, eight_devices, mock_clock):
        plain, _ = _mk_node(None, capacity=24)
        plain.process(_batch([f"k{i}" for i in range(10)],
                             np.ones(10)))
        snap = plain.snapshot_state()
        remesh, out = _mk_node(make_mesh(rows=1, keys=8), capacity=24)
        remesh.restore_state(snap)
        assert remesh.gb.capacity % 8 == 0
        remesh.on_trigger(Trigger(ts=2000))
        remesh._drain_async_emits()
        got = _flat(out)
        assert sum(got.values()) == 10  # every restored key emits


class TestAutoShardSelection:
    def test_mesh_request_resolution(self, monkeypatch):
        from ekuiper_tpu.planner.planner import (RuleDef, merged_options,
                                                 mesh_request)

        plan = extract_kernel_plan(parse_select(HOP_SQL))
        monkeypatch.setenv("KUIPER_MESH", "2x4")
        opts = merged_options(RuleDef(
            id="a", sql=HOP_SQL,
            options={"planOptimizeStrategy": {"shards": "auto"}}))
        req = mesh_request(opts, plan)
        assert req["mode"] == "sharded"
        assert req["cfg"] == {"rows": 2, "keys": 4}
        # env acts as the deployment default for silent rules
        req2 = mesh_request(
            merged_options(RuleDef(id="b", sql=HOP_SQL)), plan)
        assert req2["mode"] == "sharded"
        assert req2["source"] == "KUIPER_MESH"
        # shards=off pins single-chip even under the env
        req3 = mesh_request(merged_options(RuleDef(
            id="c", sql=HOP_SQL,
            options={"planOptimizeStrategy": {"shards": "off"}})), plan)
        assert req3["mode"] == "single-chip"
        # integer shard counts need no env
        monkeypatch.delenv("KUIPER_MESH")
        req4 = mesh_request(merged_options(RuleDef(
            id="d", sql=HOP_SQL,
            options={"planOptimizeStrategy": {"shards": 4}})), plan)
        assert req4["mode"] == "sharded"
        assert req4["cfg"] == {"rows": 1, "keys": 4}

    def test_heavy_hitters_falls_back_single_chip(self, monkeypatch):
        from ekuiper_tpu.planner.planner import (RuleDef, merged_options,
                                                 mesh_request)

        hh_sql = ("SELECT k, heavy_hitters(t, 2) AS hh FROM d "
                  "GROUP BY k, TUMBLINGWINDOW(ss, 2)")
        plan = extract_kernel_plan(parse_select(hh_sql))
        assert plan is not None
        monkeypatch.setenv("KUIPER_MESH", "1x8")
        req = mesh_request(merged_options(RuleDef(id="h", sql=hh_sql)),
                           plan)
        assert req["mode"] == "single-chip"
        assert "heavy_hitters" in req["reason"]

    def test_planner_builds_sharded_node(self, eight_devices, monkeypatch):
        from ekuiper_tpu.planner.planner import RuleDef, plan_rule
        from ekuiper_tpu.server.processors import StreamProcessor
        from ekuiper_tpu.store import kv
        from ekuiper_tpu.utils.infra import PlanError

        monkeypatch.setenv("KUIPER_MESH", "2x4")
        store = kv.get_store()
        try:
            StreamProcessor(store).exec_stmt(
                'CREATE STREAM mc_sel (k STRING, v FLOAT) '
                'WITH (DATASOURCE="mc/in", TYPE="memory", FORMAT="JSON")')
        except PlanError:
            pass
        rule = RuleDef(
            id="mc_auto",
            sql=("SELECT k, avg(v) AS a FROM mc_sel "
                 "GROUP BY k, TUMBLINGWINDOW(ss, 10)"),
            actions=[{"nop": {}}],
            options={"sharedFold": False,
                     "planOptimizeStrategy": {"shards": "auto"}})
        topo = plan_rule(rule, store)
        fused = next(n for n in topo.ops
                     if isinstance(n, FusedWindowAggNode))
        assert isinstance(fused.gb, ShardedGroupBy)
        assert fused.shard_info["mode"] == "sharded"
        assert fused.shard_info["mesh"] == {"rows": 2, "keys": 4}

    def test_explain_shards_and_sliding_sections(self, eight_devices,
                                                 monkeypatch):
        from ekuiper_tpu.planner.planner import RuleDef, explain
        from ekuiper_tpu.store import kv

        monkeypatch.setenv("KUIPER_MESH", "1x8")
        store = kv.get_store()
        out = explain(RuleDef(
            id="ex1",
            sql=("SELECT k, avg(v) AS a FROM d "
                 "GROUP BY k, TUMBLINGWINDOW(ss, 10)"),
            options={"planOptimizeStrategy": {"shards": "auto"}}), store)
        assert out["shards"]["mode"] == "sharded"
        assert out["shards"]["shards"] == 8
        sl = explain(RuleDef(
            id="ex2",
            sql=("SELECT k, count(*) AS c FROM d GROUP BY k, "
                 "SLIDINGWINDOW(ss, 2) OVER (WHEN v > 90)")), store)
        assert sl["sliding"]["impl"] == "refold"
        assert "sharded" in sl["sliding"]["fallback_reason"]
        monkeypatch.delenv("KUIPER_MESH")
        sl2 = explain(RuleDef(
            id="ex3",
            sql=("SELECT k, count(*) AS c FROM d GROUP BY k, "
                 "SLIDINGWINDOW(ss, 2) OVER (WHEN v > 90)")), store)
        assert sl2["sliding"]["impl"] == "daba"
        assert sl2["sliding"]["fallback_reason"] is None


class TestShardedTouchColumn:
    """Satellite: grow/state_from_host carry the uint32 touch column the
    same way DeviceGroupBy does — no forked dtype logic for a later
    sharded tier."""

    def test_touch_parity_across_grow_and_restore(self, eight_devices):
        sql = ("SELECT k, avg(v) AS a FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(parse_select(sql))
        mesh = make_mesh(rows=2, keys=4)
        sgb = ShardedGroupBy(plan, mesh, capacity=32, micro_batch=64,
                             track_touch=True)
        gb = DeviceGroupBy(extract_kernel_plan(parse_select(sql)),
                           capacity=32, micro_batch=64, track_touch=True)
        kt = KeyTable(32)
        rng = np.random.default_rng(5)
        keys = np.array([f"k{rng.integers(20)}" for _ in range(200)],
                        dtype=np.object_)
        slots, _ = kt.encode_column(keys)
        cols = {"v": rng.normal(0, 1, 200).astype(np.float32)}
        ss = sgb.fold(sgb.init_state(), cols, slots)
        ds = gb.fold(gb.init_state(), cols, slots)
        np.testing.assert_array_equal(np.asarray(ss["touch"]),
                                      np.asarray(ds["touch"]))
        ss = sgb.grow(ss, 64)
        ds = gb.grow(ds, 64)
        assert np.asarray(ss["touch"]).dtype == np.uint32
        np.testing.assert_array_equal(np.asarray(ss["touch"]),
                                      np.asarray(ds["touch"]))
        # roundtrip through checkpoint typing: uint32 survives
        host, cap = sgb.host_from_partials(sgb.state_to_host(ss))
        assert host["touch"].dtype == np.uint32
        ss2 = sgb.state_from_host(host)
        np.testing.assert_array_equal(np.asarray(ss2["touch"]),
                                      np.asarray(ds["touch"]))


class TestShardedPaneStore:
    def test_pane_store_mesh_parity(self, eight_devices):
        from ekuiper_tpu.ops.panestore import PaneStore

        sql = ("SELECT k, sum(v) AS s, min(v) AS mn FROM d "
               "GROUP BY k, HOPPINGWINDOW(ss, 4, 2)")
        plan = extract_kernel_plan(parse_select(sql))
        mesh = make_mesh(rows=2, keys=4)
        sharded = PaneStore(plan, 2000, 4, capacity=32, micro_batch=64,
                            tier_budget_mb=0.0, mesh=mesh)
        plain = PaneStore(extract_kernel_plan(parse_select(sql)), 2000, 4,
                          capacity=32, micro_batch=64, tier_budget_mb=0.0)
        assert isinstance(sharded.gb, ShardedGroupBy)
        assert sharded.tier is None
        kt = KeyTable(32)
        rng = np.random.default_rng(9)
        for pane in range(3):
            keys = np.array([f"k{rng.integers(40)}" for _ in range(120)],
                            dtype=np.object_)
            slots, grew = kt.encode_column(keys)
            cols = {"v": rng.normal(5, 2, 120).astype(np.float32)}
            for st in (sharded, plain):
                st.kt.restore(kt.decode_all())
                st.fold(dict(cols), {}, slots, pane)
        souts, sact = sharded.combine([0, 1, 2], kt.n_keys)
        pouts, pact = plain.combine([0, 1, 2], kt.n_keys)
        np.testing.assert_array_equal(sact, pact)
        for i in range(len(souts)):
            np.testing.assert_allclose(souts[i], pouts[i], rtol=1e-5,
                                       atol=1e-5)

    def test_sharing_store_key_carries_mesh_facet(self, monkeypatch):
        from ekuiper_tpu.planner.planner import RuleDef, merged_options
        from ekuiper_tpu.planner.sharing import store_key

        stmt = parse_select(HOP_SQL)
        opts_plain = merged_options(RuleDef(id="a", sql=HOP_SQL))
        monkeypatch.setenv("KUIPER_MESH", "2x4")
        opts_mesh = merged_options(RuleDef(id="b", sql=HOP_SQL))
        k_mesh = store_key("sub", stmt, opts_mesh)
        monkeypatch.delenv("KUIPER_MESH")
        k_plain = store_key("sub", stmt, opts_plain)
        assert k_mesh != k_plain
        assert "mesh=2x4" in k_mesh


class TestMeshAwarePrep:
    def test_device_input_fold_parity(self, eight_devices):
        sql = ("SELECT k, avg(v) AS a, count(*) AS c FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(parse_select(sql))
        mesh = make_mesh(rows=1, keys=8)
        sgb = ShardedGroupBy(plan, mesh, capacity=64, micro_batch=256)
        assert sgb.accepts_device_inputs
        from ekuiper_tpu.runtime.ingest import (pad_col_for_device,
                                                pad_slots_for_device)

        kt = KeyTable(64)
        rng = np.random.default_rng(13)
        keys = np.array([f"k{rng.integers(30)}" for _ in range(200)],
                        dtype=np.object_)
        slots, _ = kt.encode_column(keys)
        vals = rng.normal(3, 1, 200).astype(np.float32)
        dv, _ = pad_col_for_device(vals, None, 256,
                                   sharding=sgb.batch_sharding)
        ds = pad_slots_for_device(slots, 256, False,
                                  sharding=sgb.batch_sharding)
        st_dev = sgb.fold(sgb.init_state(), {"v": dv}, ds, n_rows=200)
        st_host = sgb.fold(sgb.init_state(), {"v": vals}, slots)
        o1, a1 = sgb.finalize(st_dev, kt.n_keys)
        o2, a2 = sgb.finalize(st_host, kt.n_keys)
        np.testing.assert_array_equal(a1, a2)
        for i in range(len(o1)):
            np.testing.assert_allclose(o1[i], o2[i], rtol=1e-6)

    def test_shard_metrics_render(self, eight_devices):
        from ekuiper_tpu.parallel import sharded as sharded_mod

        sql = ("SELECT k, count(*) AS c FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(parse_select(sql))
        sgb = ShardedGroupBy(plan, make_mesh(rows=1, keys=8),
                             capacity=64, micro_batch=64)
        kt = KeyTable(64)
        keys = np.array([f"k{i}" for i in range(40)], dtype=np.object_)
        slots, _ = kt.encode_column(keys)
        sgb.fold(sgb.init_state(), {}, slots)
        sgb.note_rows(slots, n_keys=kt.n_keys)
        out: list = []
        sharded_mod.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        assert "kuiper_shard_rows_total" in text
        assert 'shard="0"' in text
        stats = sgb.shard_stats()
        assert sum(s["rows"] for s in stats) >= 40
        assert sum(s["keys"] for s in stats) == 40


class TestPlacementAdmission:
    """The QoS control plane's per-chip ledger: a rule the single-chip
    HBM budget would 429 is placed across the mesh instead."""

    FAT_SQL = ("SELECT k, avg(v) AS a, sum(v) AS s FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")

    def _fat_rule(self):
        from ekuiper_tpu.planner.planner import RuleDef

        return RuleDef(id="fat", sql=self.FAT_SQL,
                       options={"key_slots": 524288, "sharedFold": False,
                                "tierStore": "off"})

    def test_single_chip_rejects_mesh_accepts(self, monkeypatch):
        from ekuiper_tpu.runtime import control
        from ekuiper_tpu.store import kv

        store = kv.get_store()
        monkeypatch.setenv("KUIPER_HBM_BUDGET_MB", "8")
        ctl = control.install(lambda: [], start=False)
        try:
            rejected = control.admit_rule(self._fat_rule(), store)
            assert rejected["decision"] == "reject"
            monkeypatch.setenv("KUIPER_MESH", "1x8")
            placed = control.admit_rule(self._fat_rule(), store)
            assert placed["decision"] == "accept"
            placement = placed["price"]["placement"]
            assert placement["mode"] == "sharded"
            assert placement["shards"] == list(range(8))
            # commit bills every chip; release clears the ledger
            ctl.commit("fat", 1.0, placement=placement)
            loads = ctl.shard_loads(8)
            assert all(v == placement["bytes_per_shard"] for v in loads)
            ctl.release("fat")
            assert all(v == 0 for v in ctl.shard_loads(8))
        finally:
            control.reset()

    def test_single_chip_rule_lands_least_loaded(self, monkeypatch):
        from ekuiper_tpu.planner.planner import RuleDef
        from ekuiper_tpu.runtime import control
        from ekuiper_tpu.store import kv

        store = kv.get_store()
        monkeypatch.setenv("KUIPER_HBM_BUDGET_MB", "8")
        monkeypatch.setenv("KUIPER_MESH", "1x4")
        ctl = control.install(lambda: [], start=False)
        try:
            # a small single-chip-pinned rule: placed whole on one chip
            small = RuleDef(
                id="small", sql=self.FAT_SQL,
                options={"key_slots": 4096, "sharedFold": False,
                         "tierStore": "off",
                         "planOptimizeStrategy": {"shards": "off"}})
            ctl.commit("existing", 1.0, placement={
                "mode": "single", "shards": [0],
                "bytes_per_shard": 4 << 20})
            d = control.admit_rule(small, store)
            assert d["decision"] == "accept"
            placement = d["price"]["placement"]
            assert placement["mode"] == "single"
            assert placement["shards"][0] != 0  # avoided the loaded chip
        finally:
            control.reset()

    def test_placement_in_diagnostics(self, monkeypatch):
        from ekuiper_tpu.runtime import control

        monkeypatch.setenv("KUIPER_MESH", "1x4")
        ctl = control.QoSController(lambda: [])
        ctl.commit("r1", 1.0, placement={
            "mode": "sharded", "shards": [0, 1, 2, 3],
            "bytes_per_shard": 100})
        diag = ctl.diagnostics()
        assert diag["placement"]["shards"] == 4
        assert diag["placement"]["committed_bytes_per_shard"] == [100] * 4
        assert "r1" in diag["placement"]["rules"]


class TestSlidingFallbackEvent:
    def test_sharded_daba_request_records_flight_event(self,
                                                       eight_devices,
                                                       mock_clock):
        sql = ("SELECT k, count(*) AS c FROM d GROUP BY k, "
               "SLIDINGWINDOW(ss, 2) OVER (WHEN v > 90)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)
        recorder().clear()
        node = FusedWindowAggNode(
            "mc_slide", stmt.window, plan,
            [d.expr for d in stmt.dimensions],
            capacity=32, micro_batch=64,
            mesh=make_mesh(rows=2, keys=4), sliding_impl="daba")
        assert node.sliding_impl == "refold"
        evs = [e for e in recorder().events(kind="sliding_impl_fallback")]
        assert evs, "no sliding_impl_fallback flight event"
        assert evs[-1]["reason"] == "sharded_kernel"
        assert evs[-1]["action"] == "refold"
        assert evs[-1]["requested"] == "daba"


class TestShardMetricMonotonicity:
    """Satellite (fleet observatory): `kuiper_shard_rows_total` must be
    monotonic per (rule, shard) across kill/restore at a different shard
    count — a retired kernel's rows roll into the module ledger
    (parallel/sharded.py `retired_rows`) instead of vanishing from the
    scrape when the weakref registry drops it."""

    def _rows(self):
        from ekuiper_tpu.parallel import sharded as sharded_mod

        out: list = []
        sharded_mod.render_prometheus(out, lambda s: s)
        vals = {}
        for line in out:
            if line.startswith("kuiper_shard_rows_total{"):
                labels, _, v = line.rpartition(" ")
                vals[labels] = float(v)
        return vals

    @staticmethod
    def _assert_monotonic(before, after):
        for k, v in before.items():
            assert after.get(k, -1.0) >= v, \
                f"{k} regressed: {v} -> {after.get(k)}"

    def test_rows_total_monotonic_8_1_8(self, eight_devices, mock_clock):
        import gc

        from ekuiper_tpu.utils.rulelog import set_rule_context

        set_rule_context("mono_rule")
        try:
            node8, _ = _mk_node(make_mesh(rows=2, keys=4))
        finally:
            set_rule_context(None)
        ids = [f"k{i}" for i in range(60)]
        node8.process(_batch(ids, np.ones(60)))
        t_live = self._rows()
        assert sum(t_live.values()) >= 60
        snap8 = node8.snapshot_state()

        # kill the mesh kernel: its rows must survive via the rollup
        del node8
        gc.collect()
        t_dead = self._rows()
        self._assert_monotonic(t_live, t_dead)

        # 8 -> 1: the single-chip interlude renders no NEW shard rows,
        # but the retired totals must keep the scrape monotonic
        single, _ = _mk_node(None)
        single.restore_state(snap8)
        single.process(_batch(ids[:30], np.ones(30)))
        t_mid = self._rows()
        self._assert_monotonic(t_dead, t_mid)
        snap1 = single.snapshot_state()

        # 1 -> 8: a fresh mesh kernel starts its live counters at zero —
        # rendered = retired + live must never dip below the dead totals
        set_rule_context("mono_rule")
        try:
            remesh, _ = _mk_node(make_mesh(rows=1, keys=8))
        finally:
            set_rule_context(None)
        remesh.restore_state(snap1)
        t_restored = self._rows()
        self._assert_monotonic(t_mid, t_restored)
        remesh.process(_batch(ids, np.ones(60)))
        t_fed = self._rows()
        self._assert_monotonic(t_restored, t_fed)
        assert sum(t_fed.values()) > sum(t_dead.values())

    def test_retired_rollup_and_reset(self, eight_devices, mock_clock):
        import gc

        from ekuiper_tpu.parallel import sharded as sharded_mod
        from ekuiper_tpu.utils.rulelog import set_rule_context

        sql = ("SELECT k, count(*) AS c FROM d "
               "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(parse_select(sql))
        set_rule_context("retire_rule")
        try:
            sgb = ShardedGroupBy(plan, make_mesh(rows=2, keys=4),
                                 capacity=64, micro_batch=64)
        finally:
            set_rule_context(None)
        kt = KeyTable(64)
        slots, _ = kt.encode_column(
            np.array([f"k{i}" for i in range(40)], dtype=np.object_))
        sgb.fold(sgb.init_state(), {}, slots)
        sgb.note_rows(slots, n_keys=kt.n_keys)
        live_total = sum(s["rows"] for s in sgb.shard_stats())
        assert live_total >= 40
        del sgb
        gc.collect()
        retired = sharded_mod.retired_rows()
        assert sum(v for (rule, _s), v in retired.items()
                   if rule == "retire_rule") == live_total
        # the render seeds its aggregation from the ledger
        assert sum(self._rows().values()) >= live_total
        # reset() (test isolation) clears the ledger and bumps the
        # generation so in-flight finalizers of dead kernels can't
        # resurrect stale rows afterwards
        sharded_mod.reset()
        assert sharded_mod.retired_rows() == {}
