"""SQL source/sink/lookup hardening (round-3 advisor findings): identifier
validation against injection via untrusted stream row keys, WHERE-clause
composition with tracking columns, and sliding-window restore dedup.

Reference analogue: extensions/sql (sqlsource/sqlsink) builds statements from
config + row keys the same way and is the parity point for behavior.
"""
import sqlite3
import time

import numpy as np
import pytest

from ekuiper_tpu.io.sql_io import SqlLookupSource, SqlSink, SqlSource
from ekuiper_tpu.utils.infra import EngineError


@pytest.fixture
def db(tmp_path):
    path = tmp_path / "t.db"
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE readings (id INTEGER, temp REAL)")
    conn.executemany("INSERT INTO readings VALUES (?, ?)",
                     [(i, 20.0 + i) for i in range(5)])
    conn.execute("CREATE TABLE out_t (a TEXT, b REAL)")
    conn.commit()
    conn.close()
    return str(path)


def _props(db, **kw):
    return {"url": f"sqlite://{db}", **kw}


class TestSqlSource:
    def _poll_once(self, src):
        got = []
        done = []

        def ingest(rows):
            got.extend(rows)
            done.append(1)
            src._stop.set()

        src.open(ingest)
        deadline = time.time() + 5
        while not done and time.time() < deadline:
            time.sleep(0.01)
        src.close()
        return got

    def test_tracking_with_where_in_query_wraps_subselect(self, db):
        """A user query that already contains WHERE must still compose with
        the tracking predicate (advisor: '... WHERE x WHERE tc > ?')."""
        src = SqlSource()
        src.configure("", _props(
            db, query="SELECT * FROM readings WHERE temp > 21.5",
            trackingColumn="id", startValue=2, interval=50))
        rows = self._poll_once(src)
        # temp > 21.5 -> ids 2,3,4; id > 2 -> ids 3,4
        assert [r["id"] for r in rows] == [3, 4]

    def test_tracking_without_where_appends(self, db):
        src = SqlSource()
        src.configure("readings", _props(
            db, trackingColumn="id", startValue=3, interval=50))
        rows = self._poll_once(src)
        assert [r["id"] for r in rows] == [4]

    def test_bad_tracking_identifier_rejected(self, db):
        src = SqlSource()
        with pytest.raises(EngineError):
            src.configure("readings", _props(
                db, trackingColumn="id; DROP TABLE readings--"))

    def test_bad_table_identifier_rejected(self, db):
        src = SqlSource()
        with pytest.raises(EngineError):
            src.configure('readings"; DROP TABLE readings--', _props(db))


class TestSqlSink:
    def test_insert_and_untrusted_key_dropped(self, db):
        sink = SqlSink()
        sink.configure(_props(db, table="out_t"))
        sink.connect()
        sink.collect([
            {"a": "x", "b": 1.5},
            # a crafted key straight off a broker must not reach the SQL
            {"a": "y", "b": 2.5, 'b") VALUES (0,0); DROP TABLE out_t;--': 1},
        ])
        sink.close()
        conn = sqlite3.connect(db)
        rows = conn.execute("SELECT a, b FROM out_t ORDER BY a").fetchall()
        conn.close()
        assert rows == [("x", 1.5), ("y", 2.5)]

    def test_bad_table_rejected(self, db):
        sink = SqlSink()
        with pytest.raises(EngineError):
            sink.configure(_props(db, table="out_t; DROP TABLE out_t"))

    def test_bad_fields_rejected(self, db):
        sink = SqlSink()
        with pytest.raises(EngineError):
            sink.configure(_props(db, table="out_t", fields=["a", "b,c"]))


class TestSqlLookup:
    def test_lookup_and_bad_key_rejected(self, db):
        src = SqlLookupSource()
        src.configure("readings", _props(db))
        src.open()
        rows = src.lookup(["temp"], ["id"], [3])
        assert rows == [{"temp": 23.0}]
        with pytest.raises(EngineError):
            src.lookup(["temp"], ["id=1 OR 1=1 --"], [3])
        src.close()


class TestSlidingRestore:
    def test_slid_rows_do_not_retrigger_after_restore(self, mock_clock):
        """Checkpoint-restore must not re-emit sliding windows for rows that
        already triggered (advisor: _slid_ids lost in snapshot)."""
        from ekuiper_tpu.runtime.events import Watermark
        from ekuiper_tpu.runtime.nodes_window import WindowNode
        from ekuiper_tpu.data.rows import Tuple
        from ekuiper_tpu.sql import ast

        win = ast.Window(window_type=ast.WindowType.SLIDING_WINDOW,
                         length=1, time_unit="SS")

        def mknode():
            node = WindowNode("w", win, is_event_time=True)
            got = []
            node.broadcast = lambda item: got.append(item)
            node.emit = lambda item, count=1: got.append(item)
            return node, got

        node, got = mknode()
        rows = [Tuple(emitter="s", message={"v": i}, timestamp=1000 + i * 100)
                for i in range(3)]
        for r in rows:
            node.process(r)
        node.on_watermark(Watermark(ts=1250))  # rows @1000,@1100,@1200 trigger
        n_before = len([g for g in got if not isinstance(g, Watermark)])
        assert n_before == 3

        snap = node.snapshot_state()
        node2, got2 = mknode()
        node2.restore_state(snap)
        node2.on_watermark(Watermark(ts=1251))  # same rows: must NOT re-fire
        again = [g for g in got2 if not isinstance(g, Watermark)]
        assert again == []
