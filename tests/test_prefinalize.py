"""Latency-hiding emit pipeline (ops/prefinalize.py): the pre-issued device
finalize + host tail shadow must agree with the synchronous device finalize
bit-for-bit in structure and to float32 accumulation order in values.

Scenario mirrors the real node sequence: fold head batches → prefinalize_begin
(snapshot dispatched) → fold tail batches into device state AND HostShadow →
prefinalize_merge vs a plain finalize over everything.
"""
import numpy as np
import pytest

from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.ops.prefinalize import HostShadow
from ekuiper_tpu.sql.parser import parse_select


def _plan(sql):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None
    return plan


def _cols_for(plan, cols, n):
    """Materialize kernel columns (incl. derived __hll__ copies) the way
    FusedWindowAggNode._fold does."""
    from ekuiper_tpu.ops.aggspec import (
        HLL_COL_PREFIX, _hll_encode_numeric, hash_column_for_hll)

    out = {}
    for name in plan.columns:
        if name.startswith(HLL_COL_PREFIX):
            raw = cols[name[len(HLL_COL_PREFIX):]]
            if raw.dtype == np.object_:
                out[name] = hash_column_for_hll(raw)
            else:
                out[name] = _hll_encode_numeric(raw)
        else:
            out[name] = np.asarray(cols[name], dtype=np.float32)
    return out


def _run_split(plan, head, tail, valid_head=None, valid_tail=None,
               capacity=64, n_panes=1, pane_head=0, pane_tail=0):
    """Fold head, pre-issue, fold tail (device + shadow), merge.
    Returns (merged_outs, merged_act, sync_outs, sync_act, n_keys)."""
    kt = KeyTable(capacity)
    gb = DeviceGroupBy(plan, capacity=capacity, n_panes=n_panes, micro_batch=32)
    state = gb.init_state()

    def fold(state, batch, valid, pane, shadow=None):
        key_col, cols = batch
        slots, grew = kt.encode_column(key_col)
        if grew:
            state = gb.grow(state, kt.capacity)
        dev_cols = _cols_for(plan, cols, len(key_col))
        gb.observe_dtypes(dev_cols)
        state = gb.fold(state, dev_cols, slots, valid, pane)
        if shadow is not None:
            shadow.fold(dev_cols, slots, valid)
        return state

    state = fold(state, head, valid_head, pane_head)
    pending = gb.prefinalize_begin(state)
    shadow = HostShadow(plan, gb.comp_specs, kt.capacity)
    state = fold(state, tail, valid_tail, pane_tail, shadow)

    n_keys = kt.n_keys
    merged_outs, merged_act = gb.prefinalize_merge(pending, shadow, n_keys)
    sync_outs, sync_act = gb.finalize(state, n_keys)
    return merged_outs, merged_act, sync_outs, sync_act, n_keys


def _batch(rng, n, n_keys, extra=None):
    keys = np.array([f"k{i}" for i in rng.integers(0, n_keys, n)],
                    dtype=np.object_)
    cols = {"temp": rng.normal(20, 5, n).astype(np.float32)}
    if extra:
        for name in extra:
            cols[name] = rng.normal(0, 10, n).astype(np.float32)
    return keys, cols


def _assert_parity(mo, ma, so, sa):
    np.testing.assert_allclose(ma, sa, rtol=1e-5)
    for m, s in zip(mo, so):
        np.testing.assert_allclose(
            np.asarray(m, dtype=np.float64), np.asarray(s, dtype=np.float64),
            rtol=1e-4, equal_nan=True)


class TestPrefinalizeParity:
    def test_basic_aggs(self):
        plan = _plan("SELECT avg(temp), count(*), min(temp), max(temp), "
                     "sum(temp), stddev(temp) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        rng = np.random.default_rng(1)
        out = _run_split(plan, _batch(rng, 100, 10), _batch(rng, 60, 10))
        _assert_parity(*out[:4])

    def test_where_and_filter(self):
        plan = _plan("SELECT count(*) FILTER (WHERE temp > 22), avg(temp) "
                     "FROM s WHERE temp > 15 "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        assert plan.host_foldable
        rng = np.random.default_rng(2)
        out = _run_split(plan, _batch(rng, 80, 8), _batch(rng, 80, 8))
        _assert_parity(*out[:4])

    def test_validity_masks(self):
        plan = _plan("SELECT avg(temp), count(temp), min(temp) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        rng = np.random.default_rng(3)
        head, tail = _batch(rng, 50, 6), _batch(rng, 50, 6)
        vh = {"temp": rng.random(50) > 0.3}
        vt = {"temp": rng.random(50) > 0.3}
        out = _run_split(plan, head, tail, valid_head=vh, valid_tail=vt)
        _assert_parity(*out[:4])

    def test_sketches(self):
        plan = _plan("SELECT distinct_count_approx(temp), "
                     "percentile_approx(temp, 0.9) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        assert plan.host_foldable
        rng = np.random.default_rng(4)
        out = _run_split(plan, _batch(rng, 200, 4), _batch(rng, 200, 4))
        _assert_parity(*out[:4])

    def test_grow_during_tail(self):
        """Keys first seen in the tail exist only in the shadow; the device
        result must be padded, not truncated."""
        plan = _plan("SELECT count(*), sum(temp) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        rng = np.random.default_rng(5)
        head = _batch(rng, 30, 4)
        tail_keys = np.array([f"new{i}" for i in range(40)], dtype=np.object_)
        tail = (tail_keys, {"temp": rng.normal(0, 1, 40).astype(np.float32)})
        out = _run_split(plan, head, tail, capacity=8)
        mo, ma, so, sa, n_keys = out
        assert n_keys == 44
        _assert_parity(mo, ma, so, sa)

    def test_hopping_panes(self):
        """Tail rows land in a different pane; pre-issued finalize merged all
        panes at snapshot, shadow covers the tail regardless of pane."""
        plan = _plan("SELECT avg(temp), max(temp) FROM s "
                     "GROUP BY deviceId, HOPPINGWINDOW(ss, 10, 5)")
        rng = np.random.default_rng(6)
        out = _run_split(plan, _batch(rng, 60, 5), _batch(rng, 60, 5),
                         n_panes=2, pane_head=0, pane_tail=1)
        _assert_parity(*out[:4])

    def test_empty_tail(self):
        plan = _plan("SELECT avg(temp) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        rng = np.random.default_rng(7)
        head = _batch(rng, 50, 5)
        kt = KeyTable(32)
        gb = DeviceGroupBy(plan, capacity=32, micro_batch=32)
        state = gb.init_state()
        slots, _ = kt.encode_column(head[0])
        cols = _cols_for(plan, head[1], 50)
        state = gb.fold(state, cols, slots)
        pending = gb.prefinalize_begin(state)
        mo, ma = gb.prefinalize_merge(pending, None, kt.n_keys)
        so, sa = gb.finalize(state, kt.n_keys)
        _assert_parity(mo, ma, so, sa)

    def test_int_semantics(self):
        plan = _plan("SELECT sum(temp), avg(temp), count(*) FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        rng = np.random.default_rng(8)
        keys = np.array(["a", "a", "b"] * 10, dtype=np.object_)
        ints = rng.integers(0, 100, 30)
        kt = KeyTable(32)
        gb = DeviceGroupBy(plan, capacity=32, micro_batch=16)
        state = gb.init_state()
        slots, _ = kt.encode_column(keys[:20])
        # int input observed -> integral sum/avg on both paths
        gb.observe_dtypes({"temp": ints[:20]})
        cols = {"temp": ints[:20].astype(np.float32)}
        state = gb.fold(state, cols, slots)
        pending = gb.prefinalize_begin(state)
        shadow = HostShadow(plan, gb.comp_specs, kt.capacity)
        slots2, _ = kt.encode_column(keys[20:])
        cols2 = {"temp": ints[20:].astype(np.float32)}
        state = gb.fold(state, cols2, slots2)
        shadow.fold(cols2, slots2, None)
        mo, ma = gb.prefinalize_merge(pending, shadow, kt.n_keys)
        so, sa = gb.finalize(state, kt.n_keys)
        assert mo[2].dtype == np.int64 and so[2].dtype == np.int64
        _assert_parity(mo, ma, so, sa)


class TestFrozenTailGrow:
    def test_no_truncation_when_device_grow_deferred(self):
        """Keys first seen during a frozen (host-only) tail grow the key
        table but NOT the device state; merge must still emit them."""
        plan = _plan("SELECT count(*) AS c, sum(temp) AS s FROM s "
                     "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        kt = KeyTable(8)
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=16)
        state = gb.init_state()
        slots, _ = kt.encode_column(np.array(["a", "b"] * 8, dtype=np.object_))
        state = gb.fold(state, {"temp": np.arange(16, dtype=np.float32)}, slots)
        pending = gb.prefinalize_begin(state)
        shadow = HostShadow(plan, gb.comp_specs, kt.capacity)
        slots2, grew = kt.encode_column(
            np.array([f"n{i}" for i in range(20)], dtype=np.object_))
        assert grew  # 8 -> 32
        shadow.fold({"temp": np.ones(20, dtype=np.float32)}, slots2, None)
        outs, act = gb.prefinalize_merge(pending, shadow, kt.n_keys)
        assert kt.n_keys == 22
        assert len(outs[0]) == 22 and len(act) == 22
        np.testing.assert_array_equal(outs[0][2:], np.ones(20, dtype=np.int64))


class TestColumnarNulls:
    def test_null_agg_stays_explicit_none(self):
        """A NULL aggregate (empty group min) must appear as an explicit
        None in sink messages, exactly like the dict emit path — not as an
        omitted key."""
        from ekuiper_tpu.ops.emit import build_direct_emit

        sql = ("SELECT deviceId, min(temp) AS mn FROM s "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        stmt = parse_select(sql)
        plan = extract_kernel_plan(stmt)
        direct = build_direct_emit(stmt, plan, ["deviceId"])
        dims = {"deviceId": np.array(["a", "b"], dtype=np.object_)}
        aggs = [np.array([3.5, np.nan], dtype=np.float32)]
        cb = direct.run_columnar(dims, aggs, 0, 10_000)
        msgs = [t.message for t in cb.to_tuples()]
        dict_msgs = direct.run(dims, aggs, 0, 10_000)
        assert msgs[1]["mn"] is None
        assert msgs == dict_msgs


def _node_bits():
    from ekuiper_tpu.data.batch import ColumnBatch
    from ekuiper_tpu.ops.emit import build_direct_emit
    from ekuiper_tpu.runtime.nodes_fused import FusedWindowAggNode

    sql = ("SELECT deviceId, avg(temp) AS a, count(*) AS c FROM s "
           "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
    stmt = parse_select(sql)
    rng = np.random.default_rng(9)

    def mkbatch(n):
        keys = np.array([f"d{i}" for i in rng.integers(0, 5, n)],
                        dtype=np.object_)
        return ColumnBatch(
            n=n, columns={"deviceId": keys,
                          "temp": rng.normal(20, 5, n).astype(np.float32)},
            timestamps=np.zeros(n, dtype=np.int64), emitter="s")

    def mknode(prefinalize, tail_mode="device"):
        plan = extract_kernel_plan(stmt)
        node = FusedWindowAggNode(
            "t", stmt.window, plan,
            dims=[d.expr for d in stmt.dimensions], capacity=64,
            micro_batch=32,
            direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
            prefinalize_lead_ms=250 if prefinalize else 0,
            tail_mode=tail_mode,
        )
        node.state = node.gb.init_state()
        got = []
        node.broadcast = lambda item: got.append(item)
        return node, got

    return stmt, mkbatch, mknode


def _flat(items):
    out = []
    for item in items:
        out.extend(item if isinstance(item, list) else [item])
    return {(m.message if hasattr(m, "message") else m)["deviceId"]:
            (round((m.message if hasattr(m, "message") else m)["a"], 3),
             (m.message if hasattr(m, "message") else m)["c"])
            for m in out}


class TestNodePrefinalize:
    @pytest.mark.parametrize("tail_mode", ["device", "host"])
    def test_node_emits_via_pretrigger(self, tail_mode):
        """Drive FusedWindowAggNode through PreTrigger→data→Trigger and
        assert the merged emit matches a sync-emit node on the same data,
        for both tail modes (device: tail rows fold to device AND shadow;
        host: device frozen at pre-issue, tail rows shadow-only)."""
        from ekuiper_tpu.runtime.events import PreTrigger, Trigger

        _, mkbatch, mknode = _node_bits()
        batches = [mkbatch(40) for _ in range(4)]

        def run(prefinalize):
            node, got = mknode(prefinalize, tail_mode)
            node.process(batches[0])
            node.process(batches[1])
            if prefinalize:
                node.on_pre_trigger(PreTrigger(ts=10_000))
                assert node._pipeline
            node.process(batches[2])
            node.process(batches[3])
            node.on_trigger(Trigger(ts=10_000))
            return got

        sync = run(False)
        merged = run(True)
        assert len(sync) == len(merged) > 0
        assert _flat(sync) == _flat(merged)

    def test_device_tail_mode_across_windows(self):
        """Device tail mode: rows arriving after the pre-issue fold into
        both device state and shadow; the boundary reset must leave the
        NEXT window counting only its own rows (no loss, no double
        count), across several consecutive windows."""
        from ekuiper_tpu.runtime.events import PreTrigger, Trigger

        _, mkbatch, mknode = _node_bits()
        batches = [mkbatch(40) for _ in range(8)]
        node, got = mknode(True, "device")
        sync_node, sync_got = mknode(False, "device")
        for w in range(4):
            for i in range(2):
                node.process(batches[2 * w + i])
                sync_node.process(batches[2 * w + i])
                if i == 0:
                    node.on_pre_trigger(PreTrigger(ts=10_000 * (w + 1)))
            node.on_trigger(Trigger(ts=10_000 * (w + 1)))
            sync_node.on_trigger(Trigger(ts=10_000 * (w + 1)))
        # boundaries without a landed pre-issue defer to the emit worker
        # (_emit_late_async) — drain before asserting, like the count/
        # sliding async tests; without this the check raced the worker
        node._drain_async_emits()
        sync_node._drain_async_emits()
        assert len(got) == len(sync_got) == 4
        for a, b in zip(got, sync_got):
            assert _flat([a]) == _flat([b])

    def test_inflight_fetch_cap(self):
        """No more than two un-landed device fetches may stack: each is a
        full components download on a serialized link (r02 post-mortem)."""
        from ekuiper_tpu.ops.prefinalize import IdentityFinalize, PendingFinalize
        from ekuiper_tpu.runtime.events import PreTrigger

        _, mkbatch, mknode = _node_bits()
        node, _ = mknode(True, "device")
        node.process(mkbatch(40))

        class NeverReady(PendingFinalize):
            def ready(self):
                return False

        orig = node.gb.prefinalize_begin
        node.gb.prefinalize_begin = lambda state, panes=None: NeverReady(
            orig(state, panes).stacked, node.gb.capacity,
            node.gb._components_layout())
        for _ in range(5):
            node.on_pre_trigger(PreTrigger(ts=10_000))
        real = [e for e in node._pipeline
                if not isinstance(e[0], IdentityFinalize)]
        assert len(real) == 2


class TestKeyTableFastPath:
    def test_miss_then_hit(self):
        kt = KeyTable(16)
        col = np.array(["a", "b", "a", None], dtype=np.object_)
        slots, _ = kt.encode_column(col)
        assert slots[0] == slots[2]
        # None normalizes to "" and aliases; next batch is a pure fast path
        slots2, _ = kt.encode_column(col)
        np.testing.assert_array_equal(slots, slots2)
        assert kt.decode(int(slots[3])) == ""

    def test_none_and_empty_share_slot(self):
        kt = KeyTable(16)
        s1, _ = kt.encode_column(np.array([None], dtype=np.object_))
        s2, _ = kt.encode_column(np.array([""], dtype=np.object_))
        assert s1[0] == s2[0]

    def test_multi_none_alias(self):
        kt = KeyTable(16)
        a = np.array(["x", None], dtype=np.object_)
        b = np.array([1, 2])
        s1, _ = kt.encode_multi([a, b])
        s2, _ = kt.encode_multi([a, b])
        np.testing.assert_array_equal(s1, s2)
        assert kt.decode(int(s1[1])) == ("", 2)

    def test_unhashable_fallback(self):
        kt = KeyTable(16)
        col = np.empty(3, dtype=np.object_)
        col[0] = [1, 2]
        col[1] = [1, 2]
        col[2] = [3]
        slots, _ = kt.encode_column(col)
        assert slots[0] == slots[1] != slots[2]

    def test_unhashable_in_tuple(self):
        kt = KeyTable(16)
        a = np.empty(2, dtype=np.object_)
        a[0] = {"x": 1}
        a[1] = {"x": 1}
        b = np.array(["u", "v"], dtype=np.object_)
        slots, _ = kt.encode_multi([a, b])
        assert slots[0] != slots[1]
        slots2, _ = kt.encode_multi([a, b])
        np.testing.assert_array_equal(slots, slots2)

    def test_growth_from_hashed_path(self):
        kt = KeyTable(2)
        slots, grew = kt.encode_column(
            np.array(["a", "b", "c"], dtype=np.object_))
        assert grew and kt.capacity == 4


class TestEngineClockTelemetry:
    """ISSUE 8 regression: PendingFinalize timing used raw time.time()
    (wall clock) — under the mock clock its fetch_ms telemetry drifted
    with real scheduling while everything else in the engine stood
    still. It now rides timex, so a frozen mock clock yields exact,
    deterministic timestamps."""

    def test_pending_finalize_rides_the_mock_clock(self, mock_clock):
        from ekuiper_tpu.ops.prefinalize import PendingFinalize

        mock_clock.set(5_000_000)
        _, mkbatch, mknode = _node_bits()
        node, _ = mknode(True, "device")
        node.process(mkbatch(40))
        p = node.gb.prefinalize_begin(node.state)
        assert isinstance(p, PendingFinalize)
        # wall-clock epoch would be ~1.7e12 ms; the engine clock says 5e6
        assert p.t_created == 5_000_000
        p.get()  # the fetch thread lands in real time...
        # ...but stamps engine time: frozen clock -> exactly 0 ms, not
        # "whatever the OS scheduler did" (the old nondeterminism)
        assert p.t_done == 5_000_000
        assert p.fetch_ms() == 0.0

    def test_fetch_ms_engine_clock_math(self):
        from ekuiper_tpu.ops import prefinalize as pf

        # fetch_ms is pure engine-clock arithmetic on the stamps: the
        # in-flight sentinel stays -1, landed deltas are exact ms
        q = pf.PendingFinalize.__new__(pf.PendingFinalize)
        q.t_created, q.t_done = 1000, None
        assert q.fetch_ms() == -1.0
        q.t_done = 1250
        assert q.fetch_ms() == 250.0
