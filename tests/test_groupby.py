"""Device group-by kernel tests: fold/finalize vs the row interpreter."""
import numpy as np
import pytest

from ekuiper_tpu.data.rows import GroupedTuples, Tuple
from ekuiper_tpu.ops.aggspec import extract_kernel_plan
from ekuiper_tpu.ops.groupby import DeviceGroupBy
from ekuiper_tpu.ops.keytable import KeyTable
from ekuiper_tpu.sql.eval import Evaluator
from ekuiper_tpu.sql.parser import parse_select


class TestKeyTable:
    def test_encode_stable(self):
        kt = KeyTable()
        col = np.array(["b", "a", "b", "c"], dtype=np.object_)
        slots, grew = kt.encode_column(col)
        assert not grew
        assert slots[0] == slots[2]
        assert len(set(slots.tolist())) == 3
        # same keys later -> same slots
        slots2, _ = kt.encode_column(np.array(["a", "c"], dtype=np.object_))
        assert slots2[0] == slots[1] and slots2[1] == slots[3]
        assert kt.decode(int(slots[0])) == "b"

    def test_growth_signal(self):
        kt = KeyTable(initial_capacity=2)
        slots, grew = kt.encode_column(np.array(["a", "b", "c"], dtype=np.object_))
        assert grew and kt.capacity == 4

    def test_multi_column_key(self):
        kt = KeyTable()
        a = np.array(["x", "x", "y"], dtype=np.object_)
        b = np.array([1, 2, 1])
        slots, _ = kt.encode_multi([a, b])
        assert len(set(slots.tolist())) == 3
        assert kt.decode(int(slots[0])) == ("x", 1)


def _plan(sql):
    stmt = parse_select(sql)
    plan = extract_kernel_plan(stmt)
    assert plan is not None, "expected device-eligible plan"
    return stmt, plan


class TestKernelPlan:
    def test_eligible(self):
        _, plan = _plan(
            "SELECT avg(temp), count(*), min(temp), max(hum), stddev(temp) "
            "FROM demo WHERE temp > 0 GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        assert len(plan.specs) == 5
        assert plan.columns == {"temp", "hum"}
        assert plan.filter is not None

    def test_dedup_having_reuses_field_agg(self):
        _, plan = _plan(
            "SELECT avg(temp) FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10) "
            "HAVING avg(temp) > 20"
        )
        assert len(plan.specs) == 1

    def test_not_eligible_string_agg(self):
        stmt = parse_select("SELECT collect(name) FROM demo GROUP BY TUMBLINGWINDOW(ss, 10)")
        assert extract_kernel_plan(stmt) is None

    def test_not_eligible_no_aggs(self):
        stmt = parse_select("SELECT a FROM demo")
        assert extract_kernel_plan(stmt) is None


def _fold_rows(gb, state, kt, rows, key="dev"):
    devs = np.array([r[key] for r in rows], dtype=np.object_)
    slots, grew = kt.encode_column(devs)
    if grew:
        state = gb.grow(state, kt.capacity)
    cols = {}
    for name in gb.plan.columns:
        cols[name] = np.array(
            [r.get(name, np.nan) for r in rows], dtype=np.float32
        )
    gb.observe_dtypes(cols)
    return gb.fold(state, cols, slots)


class TestDeviceGroupBy:
    def test_tumbling_avg_matches_interpreter(self):
        stmt, plan = _plan(
            "SELECT avg(temp), count(*), min(temp), max(temp), stddev(temp) "
            "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        rng = np.random.default_rng(0)
        rows = [
            {"dev": f"d{rng.integers(5)}", "temp": float(rng.normal(20, 5))}
            for _ in range(500)
        ]
        gb = DeviceGroupBy(plan, capacity=64, micro_batch=128)
        kt = KeyTable(64)
        state = _fold_rows(gb, gb.init_state(), kt, rows)
        outs, act = gb.finalize(state, kt.n_keys)

        # reference result via the interpreter over per-key groups
        ev = Evaluator()
        by_key = {}
        for r in rows:
            by_key.setdefault(r["dev"], []).append(
                Tuple(message={"temp": r["temp"]})
            )
        for slot in range(kt.n_keys):
            key = kt.decode(slot)
            g = GroupedTuples(content=by_key[key])
            for i, (call, col) in enumerate(zip(plan.specs, outs)):
                exp = ev.eval(call.call, g)
                got = float(col[slot])
                assert abs(got - float(exp)) < 1e-2, (
                    f"{call.kind} key={key}: {got} vs {exp}"
                )
            assert act[slot] == len(by_key[key])

    def test_where_filter_on_device(self):
        stmt, plan = _plan(
            "SELECT count(*) FROM demo WHERE temp > 25 "
            "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)"
        )
        rows = [
            {"dev": "a", "temp": 20.0}, {"dev": "a", "temp": 30.0},
            {"dev": "b", "temp": 26.0}, {"dev": "b", "temp": 27.0},
        ]
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        state = _fold_rows(gb, gb.init_state(), kt, rows)
        outs, act = gb.finalize(state, kt.n_keys)
        assert outs[0][kt._ids["a"]] == 1
        assert outs[0][kt._ids["b"]] == 2
        # a group with zero post-filter rows must not emit
        rows2 = [{"dev": "c", "temp": 10.0}]
        state = _fold_rows(gb, state, kt, rows2)
        outs, act = gb.finalize(state, kt.n_keys)
        assert act[kt._ids["c"]] == 0

    def test_nan_null_excluded(self):
        stmt, plan = _plan(
            "SELECT count(temp), sum(temp) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        rows = [
            {"dev": "a", "temp": 1.0}, {"dev": "a"},  # missing temp -> NaN
            {"dev": "a", "temp": 2.0},
        ]
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        state = _fold_rows(gb, gb.init_state(), kt, rows)
        outs, act = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 2  # count skips null
        assert outs[1][0] == 3.0
        assert act[0] == 3  # group still has 3 rows

    def test_empty_group_nan(self):
        stmt, plan = _plan(
            "SELECT avg(temp) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        rows = [{"dev": "a"}]  # row with null temp
        state = _fold_rows(gb, gb.init_state(), kt, rows)
        outs, act = gb.finalize(state, kt.n_keys)
        assert np.isnan(outs[0][0])  # NULL avg
        assert act[0] == 1  # but the group exists

    def test_reset_between_windows(self):
        stmt, plan = _plan(
            "SELECT count(*) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        state = _fold_rows(gb, gb.init_state(), kt, [{"dev": "a"}] * 3)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 3
        state = gb.reset_pane(state, 0)
        outs, act = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 0 and act[0] == 0
        state = _fold_rows(gb, state, kt, [{"dev": "a"}] * 2)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 2

    def test_hopping_panes(self):
        # hopping window length=4 interval=2 -> 2 panes; emit merges both
        stmt, plan = _plan(
            "SELECT sum(v) FROM demo GROUP BY dev, HOPPINGWINDOW(ss, 4, 2)"
        )
        gb = DeviceGroupBy(plan, capacity=8, n_panes=2, micro_batch=8)
        kt = KeyTable(8)
        state = gb.init_state()
        devs = np.array(["a", "a"], dtype=np.object_)
        slots, _ = kt.encode_column(devs)
        # pane 0: v=1,2 ; pane 1: v=10,20
        state = gb.fold(state, {"v": np.array([1.0, 2.0], np.float32)}, slots, pane_idx=0)
        state = gb.fold(state, {"v": np.array([10.0, 20.0], np.float32)}, slots, pane_idx=1)
        outs, _ = gb.finalize(state, kt.n_keys)  # both panes
        assert outs[0][0] == 33.0
        outs, _ = gb.finalize(state, kt.n_keys, panes=[1])
        assert outs[0][0] == 30.0
        # expire pane 0, fold new data into it
        state = gb.reset_pane(state, 0)
        state = gb.fold(state, {"v": np.array([5.0, 5.0], np.float32)}, slots, pane_idx=0)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 40.0

    def test_capacity_growth_preserves_state(self):
        stmt, plan = _plan(
            "SELECT count(*) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=2, micro_batch=4)
        kt = KeyTable(2)
        state = _fold_rows(gb, gb.init_state(), kt, [{"dev": "a"}, {"dev": "b"}])
        # force growth
        state = _fold_rows(gb, state, kt, [{"dev": "c"}, {"dev": "a"}])
        assert gb.capacity == 4
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][kt._ids["a"]] == 2
        assert outs[0][kt._ids["c"]] == 1

    def test_int_input_semantics(self):
        stmt, plan = _plan(
            "SELECT avg(n), sum(n) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        rows = [{"dev": "a", "n": 1}, {"dev": "a", "n": 2}]
        devs = np.array(["a", "a"], dtype=np.object_)
        slots, _ = kt.encode_column(devs)
        cols = {"n": np.array([1, 2], dtype=np.int64)}
        gb.observe_dtypes(cols)
        state = gb.fold(gb.init_state(), cols, slots)
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 1.0  # truncating int avg: (1+2)//2
        assert outs[1][0] == 3.0

    def test_agg_filter_clause(self):
        stmt, plan = _plan(
            "SELECT sum(v) FILTER (WHERE v > 1.0) FROM demo "
            "GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=8, micro_batch=8)
        kt = KeyTable(8)
        slots, _ = kt.encode_column(np.array(["a"] * 3, dtype=np.object_))
        state = gb.fold(
            gb.init_state(), {"v": np.array([0.5, 2.0, 3.0], np.float32)}, slots
        )
        outs, _ = gb.finalize(state, kt.n_keys)
        assert outs[0][0] == 5.0

    def test_large_batch_chunks(self):
        stmt, plan = _plan(
            "SELECT count(*), sum(v) FROM demo GROUP BY dev, TUMBLINGWINDOW(ss, 10)"
        )
        gb = DeviceGroupBy(plan, capacity=16, micro_batch=64)
        kt = KeyTable(16)
        n = 1000  # > micro_batch -> multiple chunks + padding
        slots, _ = kt.encode_column(
            np.array([f"d{i % 10}" for i in range(n)], dtype=np.object_)
        )
        state = gb.fold(
            gb.init_state(), {"v": np.ones(n, np.float32)}, slots
        )
        outs, act = gb.finalize(state, kt.n_keys)
        assert outs[0].sum() == n
        assert outs[1].sum() == float(n)
