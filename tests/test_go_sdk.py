"""Go SDK interop: golden byte fixtures replayed through the real engine.

The image has no Go toolchain, so sdk/go is validated the way the round-3/4
verdicts prescribed: tests/fixtures/go_sdk/frames.json pins the EXACT wire
payloads the Go runtime marshals (encoding/json struct-field order — see the
wire structs in sdk/go/runtime/runtime.go), and goworker.py — installed as a
"language": "binary" plugin, exactly how a compiled Go worker installs —
replays those bytes over raw unix sockets with the 4-byte LE framing of
sdk/go/connection/connection.go against the REAL engine side
(PluginIns handshake, control req/rep, PortableFunc/Source/Sink channels).

If the Go toolchain ever lands in the image, test_go_build compiles the SDK
for real (skipped otherwise)."""
import json
import os
import shutil
import struct
import subprocess
import time

import pytest

from ekuiper_tpu.plugin.manager import PluginMeta, PortableManager
from ekuiper_tpu.plugin.portable import PortableFunc, PortableSink, PortableSource

HERE = os.path.dirname(__file__)
FIXDIR = os.path.join(HERE, "fixtures", "go_sdk")
WORKER = os.path.join(FIXDIR, "goworker.py")
GO_SDK = os.path.join(HERE, "..", "sdk", "go")

with open(os.path.join(FIXDIR, "frames.json")) as f:
    FRAMES = json.load(f)


# ------------------------------------------------------------------- framing
def test_golden_payloads_are_valid_json():
    for name, payload in FRAMES["worker_to_engine"].items():
        doc = json.loads(payload)
        assert isinstance(doc, dict), name


def test_frame_layout_matches_engine_framing(tmp_path):
    """A frame built per connection.go (uint32 LE + payload) must be decoded
    intact by the engine's ipc layer (both implementations)."""
    from ekuiper_tpu.plugin import ipc

    payload = FRAMES["worker_to_engine"]["handshake"].encode()
    frame = struct.pack("<I", len(payload)) + payload

    import socket as pysock
    import threading

    url = f"ipc://{tmp_path}/frame.ipc"
    host = ipc.Socket(ipc.PAIR)
    host.listen(url)

    def raw_dial_and_send():
        s = pysock.socket(pysock.AF_UNIX, pysock.SOCK_STREAM)
        deadline = time.time() + 5
        while True:
            try:
                s.connect(str(tmp_path / "frame.ipc"))
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        s.sendall(frame)
        time.sleep(0.2)
        s.close()

    t = threading.Thread(target=raw_dial_and_send)
    t.start()
    got = host.recv(5000)
    t.join(timeout=5)
    host.close()
    assert got == payload


# ------------------------------------------------------- engine interop (e2e)
@pytest.fixture
def go_manager(tmp_path, monkeypatch):
    log = tmp_path / "frames.log"
    monkeypatch.setenv("GO_WORKER_LOG", str(log))
    mgr = PortableManager()
    mgr.register(PluginMeta(
        name="gomirror", executable=WORKER, language="binary",
        sources=["random"], sinks=["file"], functions=["echo"],
    ))
    yield mgr, log
    mgr.kill_all()


def _engine_frames(log, channel):
    if not log.exists():
        return []
    return [json.loads(l)["payload"] for l in log.read_text().splitlines()
            if json.loads(l)["channel"].startswith(channel)]


def test_go_worker_function_roundtrip(go_manager):
    mgr, log = go_manager
    fn = PortableFunc(mgr, "gomirror", "echo")
    assert fn.exec("abc") == "abc"
    assert fn.validate(["x"]) == ""
    assert fn.is_aggregate() is False
    fn.close()
    # the engine->worker bytes must match what runtime.go's funcCall expects
    sent = [json.loads(p) for p in _engine_frames(log, "func_echo")]
    execs = [m for m in sent if m.get("func") == "Exec"]
    exp = FRAMES["expect_engine_to_worker"]["func_exec"]
    assert execs and execs[0]["args"][:1] == exp["args_prefix"]
    assert {m["func"] for m in sent} >= {"Exec", "Validate", "IsAggregate"}
    ctrl = [json.loads(p) for p in _engine_frames(log, "control")]
    starts = [m for m in ctrl if m.get("cmd") == "start"]
    assert starts and starts[0]["ctrl"]["symbolName"] == "echo"
    assert starts[0]["ctrl"]["pluginType"] == "function"


def test_go_worker_source_pushes_golden_tuples(go_manager):
    mgr, log = go_manager
    src = PortableSource(mgr, "gomirror", "random")
    src.configure("", {})
    got = []
    src.open(lambda payload, meta=None: got.append(payload))
    deadline = time.monotonic() + 10
    while len(got) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    src.close()
    assert [t["count"] for t in got[:3]] == [1, 2, 3]
    assert got[0]["value"] == 0.25


def test_go_worker_sink_receives_rows(go_manager):
    mgr, log = go_manager
    sink = PortableSink(mgr, "gomirror", "file")
    sink.configure({"path": "/dev/null"})
    sink.connect()
    sink.collect({"a": 1})
    sink.collect([{"b": 2}, {"b": 3}])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(_engine_frames(log, "sink_")) >= 2:
            break
        time.sleep(0.05)
    sink.close()
    rows = [json.loads(p) for p in _engine_frames(log, "sink_")]
    assert {"a": 1} in rows and [{"b": 2}, {"b": 3}] in rows


def test_go_worker_unknown_symbol_errors(go_manager):
    mgr, log = go_manager
    ins = mgr.get_or_start("gomirror")
    from ekuiper_tpu.utils.infra import EngineError

    with pytest.raises(EngineError, match="not found"):
        ins.command("start", {"symbolName": "nope", "pluginType": "function",
                              "meta": {}})


# ----------------------------------------------------------- real toolchain
@pytest.mark.skipif(shutil.which("go") is None, reason="no Go toolchain")
def test_go_build():
    r = subprocess.run(["go", "build", "./..."], cwd=GO_SDK,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
