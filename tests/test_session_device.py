"""Device-path SESSION windows: processing-time sessions fold on the fused
kernel (single pane, gap/cap-timer driven emission) with output parity
against the host buffered path (reference: window_op.go session semantics —
per-stream gap; any row extends; length cap force-closes).
"""
import time

import pytest

from ekuiper_tpu.planner.planner import RuleDef, device_path_eligible, plan_rule
from ekuiper_tpu.server.processors import StreamProcessor
from ekuiper_tpu.sql.parser import parse_select
from ekuiper_tpu.store import kv
from ekuiper_tpu.utils.config import RuleOptionConfig
import ekuiper_tpu.io.memory as mem

SQL = ("SELECT deviceId, count(*) AS c, avg(v) AS a FROM sess "
       "GROUP BY deviceId, SESSIONWINDOW(ss, 10, 2)")  # cap 10s, gap 2s


def _mk_stream(store):
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM sess (deviceId STRING, v FLOAT) '
        'WITH (DATASOURCE="t/sess", TYPE="memory", FORMAT="JSON")')


def _results(sink):
    out = []
    for item in list(sink.results):
        msgs = item if isinstance(item, list) else [item]
        out.append(sorted((m["deviceId"], m["c"], round(m["a"], 4))
                          for m in msgs))
    return out


def _wait(sink, n, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline and len(sink.results) < n:
        time.sleep(0.02)
    return len(sink.results)


class TestSessionDevice:
    def test_eligibility(self):
        stmt = parse_select(SQL)
        assert device_path_eligible(stmt, RuleOptionConfig()) is not None
        # event-time sessions are device-eligible (watermark-time
        # per-session finalize), mesh included since round 5 — the session
        # split is host-side, the folds/finalizes shard like any window
        assert device_path_eligible(
            stmt, RuleOptionConfig(is_event_time=True)) is not None
        assert device_path_eligible(
            stmt, RuleOptionConfig(
                is_event_time=True,
                plan_optimize_strategy={"mesh": "2x4"})) is not None

    def test_parity_gap_and_cap(self, mock_clock):
        """Two sessions split by a gap, then a cap-forced close — device and
        host paths emit identical windows."""
        mem.reset()
        store = kv.get_store()
        _mk_stream(store)
        topo_d = plan_rule(RuleDef(
            id="sd", sql=SQL,
            actions=[{"memory": {"topic": "sess/d"}}], options={}), store)
        topo_h = plan_rule(RuleDef(
            id="sh", sql=SQL,
            actions=[{"memory": {"topic": "sess/h"}}],
            options={"use_device_kernel": False}), store)
        assert any("Fused" in type(n).__name__ for n in topo_d.ops)
        assert not any("Fused" in type(n).__name__ for n in topo_h.ops)
        sink_d, sink_h = topo_d.sinks[0], topo_h.sinks[0]
        fused = next(n for n in topo_d.ops if "Fused" in type(n).__name__)
        topo_d.open()
        topo_h.open()
        try:
            def feed(rows):
                for r in rows:
                    mem.publish("t/sess", r)
                mock_clock.advance(20)  # linger flush
                time.sleep(0.3)

            # warm: the device node jit-compiles for seconds on first use —
            # mock-clock advances must not race past timer arming. Run one
            # throwaway session to completion, then clear.
            feed([{"deviceId": "w", "v": 0.0}])
            deadline = time.time() + 60
            while time.time() < deadline and fused.stats.records_in < 1:
                time.sleep(0.05)
            mock_clock.advance(2500)
            _wait(sink_d, 1, 10)
            _wait(sink_h, 1, 10)
            sink_d.results.clear()
            sink_h.results.clear()

            # session 1: two bursts 1s apart (inside the 2s gap)
            feed([{"deviceId": "a", "v": 1.0}, {"deviceId": "b", "v": 3.0}])
            mock_clock.advance(1000)
            feed([{"deviceId": "a", "v": 2.0}])
            # silence > gap closes session 1
            mock_clock.advance(2500)
            assert _wait(sink_d, 1) == 1 and _wait(sink_h, 1) == 1
            # session 2: keep feeding every 1.5s; the 10s cap must close it
            for _ in range(8):
                feed([{"deviceId": "a", "v": 5.0}])
                mock_clock.advance(1500)
            assert _wait(sink_d, 2) >= 2 and _wait(sink_h, 2) >= 2
            assert _results(sink_d)[:2] == _results(sink_h)[:2]
            # session 1 exact content
            assert _results(sink_d)[0] == [("a", 2, 1.5), ("b", 1, 3.0)]
        finally:
            topo_d.close()
            topo_h.close()
            mem.reset()

    def test_checkpoint_restore_reopens_session(self, mock_clock):
        """An open session's partials + start ride the checkpoint; after
        restore the session closes on gap with the restored content."""
        mem.reset()
        store = kv.get_store()
        _mk_stream(store)

        def mk():
            return plan_rule(RuleDef(
                id="sr", sql=SQL,
                actions=[{"memory": {"topic": "sess/r"}}],
                options={"qos": 1, "checkpointInterval": 3_600_000}), store)

        topo = mk()
        sink = topo.sinks[0]
        topo.open()
        mem.publish("t/sess", {"deviceId": "a", "v": 4.0})
        mem.publish("t/sess", {"deviceId": "a", "v": 6.0})
        mock_clock.advance(20)
        time.sleep(0.3)
        assert topo.wait_idle(10)
        topo.trigger_checkpoint()
        deadline = time.time() + 5
        ck = store.kv("checkpoint:sr")
        while time.time() < deadline:
            snap, ok = ck.get_ok("latest")
            if ok:
                break
            time.sleep(0.02)
        topo.close()
        sink.results.clear()

        topo2 = mk()
        sink2 = topo2.sinks[0]
        topo2.open()
        try:
            mock_clock.advance(2500)  # gap expires -> restored session emits
            assert _wait(sink2, 1) == 1
            msgs = sink2.results[0]
            msgs = msgs if isinstance(msgs, list) else [msgs]
            assert msgs[0]["c"] == 2 and msgs[0]["a"] == pytest.approx(5.0)
        finally:
            topo2.close()
            mem.reset()
