"""XLA compile watcher (observability/devwatch.py): trace-vs-cache-hit
accounting, recompile-storm detection, compile-histogram export math.

All mock-clock/CPU tier-1 — the watcher rides jit semantics (the wrapped
body executes only under tracing), so a CPU jit exercises exactly the
code the TPU path runs.
"""
import numpy as np
import pytest

from ekuiper_tpu.observability import devwatch
from ekuiper_tpu.observability.devwatch import (
    COMPILE_BOUNDS_US,
    STORM_SIGNATURES,
    watched_jit,
)
from ekuiper_tpu.runtime.events import recorder
from ekuiper_tpu.utils.rulelog import set_rule_context


@pytest.fixture(autouse=True)
def _clean_registry():
    devwatch.registry().clear()
    set_rule_context(None)
    yield
    devwatch.registry().clear()
    set_rule_context(None)


def _sum2(x):
    return x * 2.0


class TestTraceAccounting:
    def test_same_shape_folds_hit_cache(self):
        """Repeated same-shape calls: exactly ONE trace, the rest cache
        hits — the steady-state invariant the acceptance criteria pin
        (kuiper_xla_compile_total flat after warmup)."""
        fn = watched_jit(_sum2, op="test.fold")
        x = np.zeros(64, dtype=np.float32)
        for _ in range(5):
            fn(x)
        snap = fn.rec.snapshot()
        assert snap["calls"] == 5
        assert snap["compiles"] == 1
        assert snap["cache_hits"] == 4
        assert snap["distinct_signatures"] == 1
        assert snap["storms"] == 0
        assert snap["compile_us"]["count"] == 1

    def test_new_shape_retraces(self):
        fn = watched_jit(_sum2, op="test.fold")
        fn(np.zeros(8, dtype=np.float32))
        fn(np.zeros(16, dtype=np.float32))
        fn(np.zeros(8, dtype=np.float32))  # back to a cached executable
        snap = fn.rec.snapshot()
        assert snap["compiles"] == 2
        assert snap["cache_hits"] == 1
        assert snap["distinct_signatures"] == 2

    def test_dtype_change_retraces_and_signature_names_it(self):
        fn = watched_jit(_sum2, op="test.fold")
        fn(np.zeros(8, dtype=np.float32))
        fn(np.zeros(8, dtype=np.int32))
        assert fn.rec.snapshot()["compiles"] == 2
        sigs = set(fn.rec.signatures)
        assert any("float32[8]" in s for s in sigs)
        assert any("int32[8]" in s for s in sigs)

    def test_static_argnums_respecialize_counts(self):
        def f(x, k):
            return x * k

        fn = watched_jit(f, op="test.static", static_argnums=(1,))
        x = np.zeros(4, dtype=np.float32)
        fn(x, 2)
        fn(x, 2)
        fn(x, 3)  # new static value -> new executable
        snap = fn.rec.snapshot()
        assert snap["compiles"] == 2
        assert snap["cache_hits"] == 1

    def test_jit_kwargs_pass_through(self):
        """donate_argnums reaches the underlying jit (result correctness
        is the observable: donation still computes the right value)."""
        def f(state, d):
            return {k: v + d for k, v in state.items()}

        fn = watched_jit(f, op="test.donate", donate_argnums=(0,))
        import jax.numpy as jnp

        out = fn({"a": jnp.zeros(4)}, 1.0)
        assert np.allclose(np.asarray(out["a"]), 1.0)
        assert fn.rec.snapshot()["compiles"] == 1

    def test_rule_attribution_from_thread_context(self):
        set_rule_context("rule_w")
        fn = watched_jit(_sum2, op="test.fold")
        fn(np.zeros(4, dtype=np.float32))
        assert fn.rec.rule == "rule_w"
        status = devwatch.registry().rule_status("rule_w")
        assert status["test.fold"]["compiles"] == 1


class TestStormDetection:
    def test_shape_churn_triggers_exactly_one_storm_event(self):
        """Deliberate shape churn: one storm event in the flight recorder
        when the distinct-signature count crosses the threshold — and
        ONLY one, no matter how long the churn continues."""
        fn = watched_jit(_sum2, op="churn.fold")
        for n in range(1, STORM_SIGNATURES + 20):
            fn(np.zeros(n, dtype=np.float32))
        snap = fn.rec.snapshot()
        assert snap["compiles"] == STORM_SIGNATURES + 19
        assert snap["storms"] == 1
        storms = recorder().events(kind="compile_storm")
        assert len(storms) == 1
        ev = storms[0]
        assert ev["op"] == "churn.fold"
        assert ev["signatures"] == STORM_SIGNATURES + 1
        assert "float32" in ev["last_signature"]

    def test_legitimate_respecialization_stays_quiet(self):
        """Capacity-doubling style respecialization (a handful of shapes)
        must NOT be flagged."""
        fn = watched_jit(_sum2, op="grow.fold")
        for n in (1, 2, 4, 8, 16, 32):  # 6 shapes < threshold
            fn(np.zeros(n, dtype=np.float32))
        assert fn.rec.snapshot()["storms"] == 0
        assert recorder().events(kind="compile_storm") == []

    def test_signature_table_bounded(self):
        w = devwatch.registry().register("bound.op", None)
        for i in range(devwatch.SIG_CAP + 50):
            w.on_compile(10.0, (i,), {})  # every int reprs to a new sig
        assert len(w.signatures) == devwatch.SIG_CAP
        assert w.sig_overflow == 50
        # overflow still counts toward the distinct total
        assert w.snapshot()["distinct_signatures"] == devwatch.SIG_CAP + 50


class TestHistogramExport:
    def test_compile_seconds_exposition_math(self):
        """kuiper_xla_compile_seconds: le ladder rendered in SECONDS,
        cumulative buckets conservative (a sample never lands below its
        true bound), +Inf == count, sum in seconds."""
        w = devwatch.registry().register("exp.fold", "r1")
        w.calls = 3
        # 2ms, 30ms, 0.8s compiles
        for us in (2_000, 30_000, 800_000):
            w.on_compile(float(us), (), {})
        out = []
        devwatch.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        assert '# TYPE kuiper_xla_compile_seconds histogram' in text
        lbl = 'op="exp.fold",rule="r1"'

        def bucket(le):
            for line in out:
                if line.startswith(
                        f'kuiper_xla_compile_seconds_bucket{{{lbl},le="{le}"}}'):
                    return int(line.rsplit(" ", 1)[1])
            raise AssertionError(f"no bucket le={le}: {text}")

        # ladder bounds are COMPILE_BOUNDS_US rendered /1e6
        assert bucket("0.001") == 0          # nothing at or under 1ms
        assert bucket("0.005") >= 1          # the 2ms compile
        assert bucket("0.1") >= 2            # + the 30ms compile
        assert bucket("1") == 3              # everything
        assert bucket("+Inf") == 3
        # monotone non-decreasing across the whole ladder
        seq = [bucket(f"{b / 1e6:g}") for b in COMPILE_BOUNDS_US]
        assert seq == sorted(seq)
        sum_line = next(l for l in out if l.startswith(
            f"kuiper_xla_compile_seconds_sum{{{lbl}}}"))
        total_s = float(sum_line.rsplit(" ", 1)[1])
        assert abs(total_s - 0.832) < 1e-6
        cnt_line = next(l for l in out if l.startswith(
            f"kuiper_xla_compile_seconds_count{{{lbl}}}"))
        assert int(cnt_line.rsplit(" ", 1)[1]) == 3

    def test_counter_families_render(self):
        w = devwatch.registry().register("fam.fold", None)
        w.calls = 7
        w.on_compile(5_000.0, (), {})
        out = []
        devwatch.render_prometheus(out, lambda s: s)
        text = "\n".join(out)
        assert ('kuiper_xla_compile_total{op="fam.fold",'
                'rule="__engine__"} 1') in text
        assert ('kuiper_xla_cache_hit_total{op="fam.fold",'
                'rule="__engine__"} 6') in text
        assert ('kuiper_xla_compile_signatures{op="fam.fold",'
                'rule="__engine__"} 1') in text


class TestRegistryBounds:
    def test_dead_watches_retire_counters_monotonically(self):
        """Rule restart churn: collected watches fold their counts into
        the retired rollup (counters never reset), while LIVE watches are
        never evicted no matter how many siblings churned past them."""
        import gc

        reg = devwatch.registry()
        survivor = reg.register("churny.op", "r")
        survivor.calls = 5
        survivor.traces = 1
        for _ in range(300):
            w = reg.register("churny.op", "r")
            w.calls = 2
            w.traces = 1
            del w  # owner collected -> __del__ retires the counts
        gc.collect()
        agg = reg.aggregate()[("churny.op", "r")]
        assert agg["calls"] == 5 + 2 * 300
        assert agg["compiles"] == 1 + 300
        # the live watch is still individually visible (not frozen)
        assert survivor in reg.watches()
        survivor.calls += 1
        assert reg.aggregate()[("churny.op", "r")]["calls"] == 6 + 600

    def test_unused_watches_vanish_without_metric_rows(self):
        """A site registered but never called (e.g. a subclass re-wrapping
        its base's jit attrs) leaves NO permanent zero-valued rows."""
        import gc

        reg = devwatch.registry()
        w = reg.register("orphan.op", "r")
        del w
        gc.collect()
        assert ("orphan.op", "r") not in reg.aggregate()
        out = []
        devwatch.render_prometheus(out, lambda s: s)
        assert not any("orphan.op" in l for l in out)


class TestDeviceGroupByIntegration:
    def test_fold_sites_registered_and_steady_state_flat(self):
        """A real DeviceGroupBy fold: repeated same-shape batches compile
        once and then only hit the cache — through the actual engine
        kernel, not a toy fn."""
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.sql.parser import parse_select

        set_rule_context("gb_rule")
        stmt = parse_select(
            "SELECT deviceId, count(*) AS c, avg(temperature) AS a "
            "FROM demo GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(stmt)
        gb = DeviceGroupBy(plan, capacity=64, micro_batch=64)
        state = gb.init_state()
        cols = {"temperature": np.full(64, 20.0, dtype=np.float32)}
        slots = np.zeros(64, dtype=np.int32)
        for _ in range(4):
            state = gb.fold(state, dict(cols), slots, pane_idx=0)
        status = devwatch.registry().rule_status("gb_rule")
        fold = status["groupby.fold"]
        assert fold["compiles"] == 1
        assert fold["cache_hits"] == 3
        assert fold["storms"] == 0
        # finalize executes + registers too
        outs, act = gb.finalize(state, 1)
        assert float(act[0]) == 64.0 * 4
        assert "groupby.finalize" in devwatch.registry().rule_status(
            "gb_rule")
