"""KeyTable coverage (round 7): sorted-fallback vs hashed-path slot parity,
None/"" alias normalization (one slot per normalized key, regression for
the repr-fallback double-slot bug), native-vs-Python slot parity across
decode shard counts including the new-key appendix sync, checkpoint
restore round-trips, and the uint16/int32 slot-dtype switch at capacity
growth."""
import json

import numpy as np
import pytest

from ekuiper_tpu.io import fastjson
from ekuiper_tpu.ops.groupby import slot_dtype
from ekuiper_tpu.ops.keytable import KeyTable


def python_table() -> KeyTable:
    """A KeyTable pinned to the pure-Python paths (parity reference)."""
    kt = KeyTable()
    kt._native_ok = False
    return kt


@pytest.fixture(scope="module")
def native():
    fastjson.ensure_native(background=False)
    mod = fastjson._load()
    if mod is None or not fastjson.has_keytab():
        pytest.skip("native keytab unavailable (no toolchain)")
    return mod


def obj_col(vals):
    col = np.empty(len(vals), dtype=object)
    col[:] = vals
    return col


class TestAliasNormalization:
    def test_none_and_empty_share_one_slot_hashed(self):
        kt = python_table()
        s, _ = kt.encode_column(obj_col([None, "", "x", None]))
        assert s[0] == s[1] == s[3]
        assert kt.decode(int(s[0])) == ""

    def test_mixed_batch_repr_fallback_no_double_slot(self):
        """Regression: a batch with None, "" AND an unhashable element used
        to take the blanket-repr sort fallback, storing '' under its repr
        "''" — a later hashed batch then assigned '' a SECOND slot."""
        kt = python_table()
        s1, _ = kt.encode_column(obj_col([None, "", [1], "x"]))
        assert s1[0] == s1[1]
        s2, _ = kt.encode_column(obj_col(["", None, "x"]))
        assert s2[0] == s2[1] == s1[0]
        assert s2[2] == s1[3]
        # exactly one slot exists for the normalized empty key
        assert kt.decode_all().count("") == 1

    def test_tuple_variants_share_one_slot(self):
        kt = python_table()
        s1, _ = kt.encode_multi([obj_col(["a", "a"]),
                                 obj_col([None, ""])])
        assert s1[0] == s1[1]
        # unhashable element elsewhere routes through the _h stringify path
        s2, _ = kt.encode_multi([obj_col(["a", "a"]),
                                 obj_col(["", None])])
        assert set(s2.tolist()) == {s1[0]}
        assert kt.decode(int(s1[0])) == ("a", "")

    def test_mixed_strings_keep_identity_across_paths(self):
        """A plain string in a mixed (repr-fallback) batch must get the
        same slot the hashed path would assign it."""
        kt = python_table()
        s1, _ = kt.encode_column(obj_col(["dev1", {"u": 1}]))
        s2, _ = kt.encode_column(obj_col(["dev1"]))
        assert s2[0] == s1[0]


class TestSortedHashedParity:
    def test_unicode_vs_object_same_slots(self):
        """The same key sequence through the sorted (fixed-width unicode)
        and hashed (object) paths assigns consistent slots."""
        ka, kb = python_table(), python_table()
        vals = ["b", "a", "", "b", "c", "a"]
        sa, _ = ka.encode_column(np.array(vals, dtype="U"))
        sb, _ = kb.encode_column(obj_col(vals))
        # slot NUMBERING differs (sorted path assigns in sorted order) but
        # grouping must agree and cross-path reuse must resolve
        assert [ka.decode(int(x)) for x in sa] == vals
        assert [kb.decode(int(x)) for x in sb] == vals
        s2, _ = ka.encode_column(obj_col(vals))  # hashed batch, same table
        np.testing.assert_array_equal(s2, sa)

    def test_sorted_none_matches_hashed_alias(self):
        ka = python_table()
        sa, _ = ka.encode_column(np.array([None, "", "x"], dtype=object))
        kb = python_table()
        # numeric->unicode col with empty string via sorted path
        sb1, _ = kb.encode_column(np.array(["", "x"], dtype="U"))
        sb2, _ = kb.encode_column(obj_col([None]))
        assert sb2[0] == sb1[0]
        assert ka.decode(int(sa[0])) == kb.decode(int(sb2[0])) == ""


class TestNativeParity:
    def test_random_parity_and_appendix_sync(self, native):
        rng = np.random.default_rng(11)
        kn, kp = KeyTable(), python_table()
        for batch in range(8):
            vals = [f"dev_{int(rng.integers(0, 300))}" for _ in range(400)]
            for i in range(0, 400, 17):
                vals[i] = None
            for i in range(3, 400, 41):
                vals[i] = ""
            col = obj_col(vals)
            sn, gn = kn.encode_column(col)
            sp, gp = kp.encode_column(col)
            np.testing.assert_array_equal(sn, sp)
            assert gn == gp
        assert kn._ntab is not None and kn._native_ok
        assert kn.decode_all() == kp.decode_all()
        assert kn.capacity == kp.capacity

    def test_parity_across_decode_shards(self, native):
        """Key columns decoded with 1/2/4 native parse shards feed the
        native slot encode; slots + appendix must be identical to the
        Python table fed the same column."""
        from ekuiper_tpu.data.types import DataType, Field, Schema

        schema = Schema(fields=[Field("deviceId", DataType.STRING),
                                Field("v", DataType.FLOAT)])
        spec = fastjson.schema_field_spec(schema)
        rng = np.random.default_rng(5)
        payloads = []
        for i in range(3000):
            m = {"v": float(i)}
            if i % 9 != 0:  # ~1/9 rows miss the key (None -> "" slot)
                m["deviceId"] = f"d{int(rng.integers(0, 150))}"
            payloads.append(json.dumps(m).encode())
        ref_slots = None
        for shards in (1, 2, 4):
            cols, valid, bad = fastjson.decode_columns(
                payloads, spec, shards=shards)
            kn, kp = KeyTable(), python_table()
            sn, _ = kn.encode_column(cols["deviceId"])
            sp, _ = kp.encode_column(cols["deviceId"])
            np.testing.assert_array_equal(sn, sp)
            assert kn.decode_all() == kp.decode_all()
            if ref_slots is None:
                ref_slots = sn
            else:
                np.testing.assert_array_equal(sn, ref_slots)

    def test_native_catches_up_after_python_only_batches(self, native):
        kn, kp = KeyTable(), python_table()
        # sorted path first (unicode col): keys enter WITHOUT the native tab
        for kt in (kn, kp):
            kt.encode_column(np.array(["s1", "s2"], dtype="U"))
        sn, _ = kn.encode_column(obj_col(["s2", "new", None]))
        sp, _ = kp.encode_column(obj_col(["s2", "new", None]))
        np.testing.assert_array_equal(sn, sp)
        assert kn._native_n == kn.n_keys  # mirror caught up

    def test_tuple_keys_disable_mirror_without_divergence(self, native):
        kn, kp = KeyTable(), python_table()
        for kt in (kn, kp):
            kt.encode_multi([obj_col(["a", "b"]), obj_col([1, None])])
        sn, _ = kn.encode_column(obj_col(["z", "a"]))
        sp, _ = kp.encode_column(obj_col(["z", "a"]))
        np.testing.assert_array_equal(sn, sp)
        assert kn._native_ok is False  # tuples can't mirror natively
        assert kn.decode_all() == kp.decode_all()

    def test_restore_roundtrip(self, native):
        kn = KeyTable()
        kn.encode_column(obj_col(["a", None, "b"]))
        saved = kn.decode_all()
        kr = KeyTable()
        kr.restore(saved)
        s, _ = kr.encode_column(obj_col(["b", "", "c", "a"]))
        assert s.tolist() == [2, 1, 3, 0]
        assert kr.decode_all() == saved + ["c"]

    def test_surrogate_key_falls_back_cleanly(self, native):
        kn, kp = KeyTable(), python_table()
        col = obj_col(["ok", "\ud800bad", "ok"])
        sn, _ = kn.encode_column(col)
        sp, _ = kp.encode_column(col)
        np.testing.assert_array_equal(sn, sp)
        assert kn.decode_all() == kp.decode_all()
        # and the mirror still serves later clean batches
        sn2, _ = kn.encode_column(obj_col(["ok", "fresh"]))
        sp2, _ = kp.encode_column(obj_col(["ok", "fresh"]))
        np.testing.assert_array_equal(sn2, sp2)


class TestSlotDtypeSwitch:
    def test_boundary(self):
        assert slot_dtype(16384) is np.uint16
        assert slot_dtype(65535) is np.uint16
        assert slot_dtype(65536) is np.int32
        assert slot_dtype(131072) is np.int32

    def test_fold_switches_dtype_at_growth_and_stays_exact(self):
        """Capacity doubling past the uint16 boundary mid-stream: folds
        before the grow ship uint16, after ship int32; per-slot counts
        stay exact across the switch (the grow preserves partials)."""
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.sql.parser import parse_select

        stmt = parse_select(
            "SELECT count(*) FROM s GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(stmt)
        gb = DeviceGroupBy(plan, capacity=65536 // 2, n_panes=1,
                           micro_batch=64)
        assert slot_dtype(gb.capacity) is np.uint16
        state = gb.init_state()
        # fold rows into slots near the top of the uint16 range
        lo_slots = np.array([0, 1, 32766, 32767] * 16, dtype=np.int32)
        state = gb.fold(state, {}, lo_slots, pane_idx=0)
        # grow past the boundary (as a 65k+1-th key would force)
        state = gb.grow(state, 65536 * 2)
        assert slot_dtype(gb.capacity) is np.int32
        hi_slots = np.array([0, 70000, 100000, 32767] * 16, dtype=np.int32)
        state = gb.fold(state, {}, hi_slots, pane_idx=0)
        outs, act = gb.finalize(state, 100001)
        counts = outs[0]
        assert counts[0] == 32 and counts[1] == 16
        assert counts[32766] == 16 and counts[32767] == 32
        assert counts[70000] == 16 and counts[100000] == 16

    def test_cached_uint16_batches_refold_after_growth(self):
        """Sliding _dev_ring scenario: pre-padded uint16 slot arrays cached
        BEFORE a grow must refold exactly against the grown state (their
        values predate the grow, so no invalidation is needed), alongside
        new int32 uploads."""
        import jax.numpy as jnp

        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.groupby import DeviceGroupBy
        from ekuiper_tpu.sql.parser import parse_select

        stmt = parse_select(
            "SELECT count(*), sum(v) FROM s "
            "GROUP BY k, TUMBLINGWINDOW(ss, 10)")
        plan = extract_kernel_plan(stmt)
        mb = 32
        gb = DeviceGroupBy(plan, capacity=65536 // 2, n_panes=2,
                           micro_batch=mb)
        state = gb.init_state()
        # cached entry built while capacity allowed uint16
        slots_a = np.arange(mb, dtype=np.int32) % 7
        dev_a = {
            "v": jnp.asarray(np.full(mb, 2.0, dtype=np.float32)),
            "__valid_v": None,
        }
        s_dev_a = jnp.asarray(slots_a.astype(slot_dtype(gb.capacity)))
        assert s_dev_a.dtype == jnp.uint16
        state = gb.grow(state, 65536 * 2)  # capacity doubles past 65,536
        # post-grow upload ships int32
        slots_b = np.full(mb, 90000, dtype=np.int32)
        s_dev_b = jnp.asarray(slots_b.astype(slot_dtype(gb.capacity)))
        assert s_dev_b.dtype == jnp.int32
        dev_b = {
            "v": jnp.asarray(np.full(mb, 3.0, dtype=np.float32)),
            "__valid_v": None,
        }
        mask = np.ones(mb, dtype=np.bool_)
        state = gb.fold_masked(state, dev_a, s_dev_a, mask, 0)
        state = gb.fold_masked(state, dev_b, s_dev_b, mask, 0)
        outs, act = gb.finalize(state, 90001)
        counts, sums = outs
        assert counts[0] == 5 and counts[6] == 4  # 32 rows over slots 0..6
        assert counts[90000] == mb and sums[90000] == 3.0 * mb
        assert sums[0] == 2.0 * counts[0]
