"""Tier-1 metrics-exposition lint (tools/check_metrics.py): every metric
the Prometheus layer can emit must be kuiper_-prefixed, carry # TYPE and
# HELP, and be cataloged in docs/OBSERVABILITY.md — a new metric added
without docs fails the suite, like tools/check_native.py does for a
silently-broken native build."""
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_check_metrics_passes():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_metrics.py")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        "metrics exposition lint FAILED:\n"
        f"{proc.stdout}\n{proc.stderr}")
    assert "OK" in proc.stdout


def test_lint_catches_undocumented_metric():
    """The lint itself must detect a violation, not just pass vacuously."""
    sys.path.insert(0, str(REPO))
    from tools.check_metrics import lint

    text = ("# TYPE kuiper_bogus_total counter\n"
            "# HELP kuiper_bogus_total not in docs\n"
            'kuiper_bogus_total{rule="r"} 1\n'
            "no_prefix_metric 2\n")
    errors = lint(text, "docs without that name")
    msgs = "\n".join(errors)
    assert "kuiper_bogus_total: not documented" in msgs
    assert "no_prefix_metric: not kuiper_-prefixed" in msgs
    assert "no_prefix_metric: no # TYPE header" in msgs
