"""Parser golden tests — modeled on the reference's parser test strategy
(internal/xsql/parser_test.go, parser_agg_test.go)."""
import pytest

from ekuiper_tpu.data.types import DataType
from ekuiper_tpu.sql import ast
from ekuiper_tpu.sql.parser import parse, parse_select
from ekuiper_tpu.utils.infra import ParseError


class TestSelect:
    def test_simple(self):
        stmt = parse_select("SELECT * FROM demo")
        assert isinstance(stmt.fields[0].expr, ast.Wildcard)
        assert stmt.sources[0].name == "demo"

    def test_fields_alias(self):
        stmt = parse_select("SELECT temperature AS t, humidity FROM demo")
        assert stmt.fields[0].alias == "t"
        assert stmt.fields[0].name == "temperature"
        assert stmt.fields[1].name == "humidity"

    def test_where_precedence(self):
        stmt = parse_select(
            "SELECT a FROM demo WHERE a > 1 AND b < 2 OR c = 3"
        )
        cond = stmt.condition
        assert isinstance(cond, ast.BinaryExpr) and cond.op == "OR"
        assert cond.lhs.op == "AND"
        assert cond.lhs.lhs.op == ">"

    def test_arith_precedence(self):
        stmt = parse_select("SELECT a + b * c FROM demo")
        e = stmt.fields[0].expr
        assert e.op == "+" and e.rhs.op == "*"

    def test_parens(self):
        stmt = parse_select("SELECT (a + b) * c FROM demo")
        e = stmt.fields[0].expr
        assert e.op == "*" and e.lhs.op == "+"

    def test_qualified_ref(self):
        stmt = parse_select("SELECT demo.temperature FROM demo")
        ref = stmt.fields[0].expr
        assert ref.stream == "demo" and ref.name == "temperature"

    def test_function_call(self):
        stmt = parse_select("SELECT avg(temperature) AS t FROM demo")
        call = stmt.fields[0].expr
        assert isinstance(call, ast.Call) and call.name == "avg"
        assert isinstance(call.args[0], ast.FieldRef)

    def test_count_star(self):
        stmt = parse_select("SELECT count(*) FROM demo")
        call = stmt.fields[0].expr
        assert call.name == "count" and isinstance(call.args[0], ast.Wildcard)
        assert stmt.fields[0].name == "count"

    def test_case_when(self):
        stmt = parse_select(
            "SELECT CASE WHEN a > 1 THEN 'big' ELSE 'small' END AS size FROM demo"
        )
        case = stmt.fields[0].expr
        assert isinstance(case, ast.CaseExpr)
        assert case.value is None and len(case.whens) == 1
        assert case.else_expr.val == "small"

    def test_case_value(self):
        stmt = parse_select("SELECT CASE color WHEN 'red' THEN 1 WHEN 'blue' THEN 2 END FROM demo")
        case = stmt.fields[0].expr
        assert isinstance(case.value, ast.FieldRef) and len(case.whens) == 2

    def test_in_between_like(self):
        stmt = parse_select(
            "SELECT a FROM demo WHERE a IN (1, 2, 3) AND b BETWEEN 1 AND 10 AND c LIKE 'x%'"
        )
        cond = stmt.condition
        assert isinstance(cond.rhs, ast.LikeExpr)
        assert isinstance(cond.lhs.rhs, ast.BetweenExpr)
        assert isinstance(cond.lhs.lhs, ast.InExpr)
        assert len(cond.lhs.lhs.values) == 3

    def test_not_variants(self):
        stmt = parse_select("SELECT a FROM demo WHERE a NOT IN (1) AND b NOT BETWEEN 1 AND 2 AND c NOT LIKE 'z'")
        c = stmt.condition
        assert c.rhs.negate and c.lhs.rhs.negate and c.lhs.lhs.negate

    def test_json_path_ops(self):
        stmt = parse_select("SELECT data->device->id, readings[0], values[1:3] FROM demo")
        arrow = stmt.fields[0].expr
        assert isinstance(arrow, ast.ArrowExpr) and arrow.name == "id"
        assert isinstance(arrow.value, ast.ArrowExpr)
        idx = stmt.fields[1].expr
        assert isinstance(idx, ast.IndexExpr) and not idx.is_slice
        sl = stmt.fields[2].expr
        assert sl.is_slice and sl.lo.val == 1 and sl.hi.val == 3

    def test_joins(self):
        stmt = parse_select(
            "SELECT * FROM s1 LEFT JOIN s2 ON s1.id = s2.id INNER JOIN t1 ON s1.id = t1.id"
        )
        assert stmt.joins[0].join_type == ast.JoinType.LEFT
        assert stmt.joins[1].join_type == ast.JoinType.INNER
        assert stmt.joins[0].table.name == "s2"

    def test_group_having_order_limit(self):
        stmt = parse_select(
            "SELECT deviceId, avg(temp) FROM demo GROUP BY deviceId "
            "HAVING avg(temp) > 20 ORDER BY deviceId DESC LIMIT 10"
        )
        assert len(stmt.dimensions) == 1
        assert stmt.having.op == ">"
        assert not stmt.sorts[0].ascending
        assert stmt.limit == 10

    def test_wildcard_except_replace(self):
        stmt = parse_select("SELECT * EXCEPT(a, b) REPLACE(c*2 AS c) FROM demo")
        wc = stmt.fields[0].expr
        assert wc.except_names == ["a", "b"]
        assert wc.replaces[0].alias == "c"


class TestWindows:
    def test_tumbling(self):
        stmt = parse_select(
            "SELECT count(*) FROM demo GROUP BY TUMBLINGWINDOW(ss, 10)"
        )
        w = stmt.window
        assert w.window_type == ast.WindowType.TUMBLING_WINDOW
        assert w.time_unit == "SS" and w.length == 10
        assert w.length_ms() == 10_000

    def test_hopping(self):
        stmt = parse_select(
            "SELECT * FROM demo GROUP BY deviceId, HOPPINGWINDOW(mi, 10, 5)"
        )
        w = stmt.window
        assert w.window_type == ast.WindowType.HOPPING_WINDOW
        assert w.length == 10 and w.interval == 5
        assert len(stmt.dimensions) == 1

    def test_sliding_with_delay(self):
        stmt = parse_select("SELECT * FROM demo GROUP BY SLIDINGWINDOW(ss, 10, 2)")
        w = stmt.window
        assert w.window_type == ast.WindowType.SLIDING_WINDOW
        assert w.length == 10 and w.delay == 2 and not w.interval

    def test_session(self):
        stmt = parse_select("SELECT * FROM demo GROUP BY SESSIONWINDOW(ss, 10, 5)")
        w = stmt.window
        assert w.window_type == ast.WindowType.SESSION_WINDOW
        assert w.length == 10 and w.interval == 5

    def test_count_window(self):
        stmt = parse_select("SELECT * FROM demo GROUP BY COUNTWINDOW(5)")
        assert stmt.window.window_type == ast.WindowType.COUNT_WINDOW
        assert stmt.window.length == 5
        stmt2 = parse_select("SELECT * FROM demo GROUP BY COUNTWINDOW(10, 5)")
        assert stmt2.window.interval == 5

    def test_count_window_invalid(self):
        with pytest.raises(ParseError):
            parse_select("SELECT * FROM demo GROUP BY COUNTWINDOW(5, 10)")

    def test_window_bad_unit(self):
        with pytest.raises(ParseError):
            parse_select("SELECT * FROM demo GROUP BY TUMBLINGWINDOW(xx, 10)")

    def test_window_bad_arity(self):
        with pytest.raises(ParseError):
            parse_select("SELECT * FROM demo GROUP BY TUMBLINGWINDOW(ss, 10, 5)")

    def test_two_windows_rejected(self):
        with pytest.raises(ParseError):
            parse_select(
                "SELECT * FROM demo GROUP BY TUMBLINGWINDOW(ss, 10), COUNTWINDOW(5)"
            )

    def test_sliding_over_when(self):
        stmt = parse_select(
            "SELECT * FROM demo GROUP BY SLIDINGWINDOW(ss, 10) OVER (WHEN temp > 30)"
        )
        assert stmt.window.trigger_condition is not None

    def test_window_filter(self):
        stmt = parse_select(
            "SELECT * FROM demo GROUP BY TUMBLINGWINDOW(ss, 10) FILTER (WHERE temp > 0)"
        )
        assert stmt.window.filter is not None

    def test_state_window(self):
        stmt = parse_select(
            "SELECT * FROM demo GROUP BY STATEWINDOW(a > 1, a < 0)"
        )
        w = stmt.window
        assert w.window_type == ast.WindowType.STATE_WINDOW
        assert w.begin_condition is not None and w.emit_condition is not None


class TestAnalytic:
    def test_lag_partition(self):
        stmt = parse_select(
            "SELECT lag(temp) OVER (PARTITION BY deviceId) FROM demo"
        )
        call = stmt.fields[0].expr
        assert call.name == "lag" and len(call.partition) == 1

    def test_filter_clause(self):
        stmt = parse_select("SELECT count(*) FILTER (WHERE a > 1) FROM demo")
        assert stmt.fields[0].expr.filter is not None

    def test_func_ids_distinct(self):
        stmt = parse_select("SELECT lag(a), lag(b) FROM demo")
        assert stmt.fields[0].expr.func_id != stmt.fields[1].expr.func_id


class TestDDL:
    def test_create_stream(self):
        stmt = parse(
            'CREATE STREAM demo (deviceId STRING, temp FLOAT, ok BOOLEAN) '
            'WITH (DATASOURCE="topic/demo", FORMAT="JSON", TYPE="mqtt")'
        )
        assert isinstance(stmt, ast.StreamStmt)
        assert not stmt.is_table
        assert [f.name for f in stmt.fields] == ["deviceId", "temp", "ok"]
        assert stmt.fields[1].type == DataType.FLOAT
        assert stmt.options.datasource == "topic/demo"
        assert stmt.options.format == "JSON"
        assert stmt.options.type == "mqtt"

    def test_create_schemaless(self):
        stmt = parse('CREATE STREAM demo () WITH (DATASOURCE="t", SHARED="true")')
        assert stmt.fields == [] and stmt.options.shared

    def test_create_nested_types(self):
        stmt = parse(
            "CREATE STREAM demo (tags ARRAY(STRING), info STRUCT(id BIGINT, name STRING)) "
            'WITH (DATASOURCE="t")'
        )
        assert stmt.fields[0].type == DataType.ARRAY
        assert stmt.fields[0].elem_type == DataType.STRING
        assert stmt.fields[1].type == DataType.STRUCT
        assert stmt.fields[1].fields[0].name == "id"

    def test_create_table(self):
        stmt = parse('CREATE TABLE t1 (id BIGINT) WITH (DATASOURCE="lookup.json", KIND="lookup")')
        assert stmt.is_table and stmt.options.kind == "lookup"

    def test_show_describe_drop(self):
        assert parse("SHOW STREAMS").target == "STREAMS"
        assert parse("SHOW TABLES").target == "TABLES"
        d = parse("DESCRIBE STREAM demo")
        assert d.target == "STREAM" and d.name == "demo"
        assert parse("DROP STREAM demo").name == "demo"
        assert parse("DROP TABLE t1").target == "TABLE"

    def test_bad_option(self):
        with pytest.raises(ParseError):
            parse('CREATE STREAM demo () WITH (BOGUS="x")')


class TestErrors:
    def test_no_from(self):
        with pytest.raises(ParseError):
            parse("SELECT a")

    def test_trailing_garbage(self):
        with pytest.raises(ParseError):
            parse("SELECT a FROM demo extra extra")

    def test_unterminated_string(self):
        with pytest.raises(ParseError):
            parse("SELECT 'abc FROM demo")

    def test_join_requires_on(self):
        with pytest.raises(ParseError):
            parse("SELECT * FROM a LEFT JOIN b")

    def test_cross_join_no_on(self):
        stmt = parse_select("SELECT * FROM a CROSS JOIN b")
        assert stmt.joins[0].join_type == ast.JoinType.CROSS
