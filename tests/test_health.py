"""Health plane (observability/health.py): SLO burn-rate windows, the
verdict FSM with hysteresis, bottleneck attribution, watermark-lag
tracking (including shared-fold members), queue-depth high-water marks,
and the on-demand profile capture — all mock-clock, CPU, tier-1."""
import json
import os
import queue

import numpy as np
import pytest

from ekuiper_tpu.observability import health
from ekuiper_tpu.observability.health import (
    BREACHING, DEGRADED, HEALTHY, HealthEvaluator, parse_slo)
from ekuiper_tpu.observability.histogram import LatencyHistogram
from ekuiper_tpu.runtime.events import recorder
from ekuiper_tpu.utils.metrics import StatManager
import ekuiper_tpu.io.memory as mem


# --------------------------------------------------------------- fixtures
class FakeNode:
    """Minimal node shape the evaluator samples: stats + inq + op_type."""

    def __init__(self, name, op_type="op", rule_id="r1"):
        self.name = name
        self.op_type = op_type
        self.stats = StatManager(op_type, name)
        self.stats.rule_id = rule_id
        self.inq = queue.Queue()


class FakeTopo:
    def __init__(self, nodes):
        self.e2e_hist = LatencyHistogram()
        self._nodes = nodes

    def all_nodes(self):
        return self._nodes

    def live_shared(self):
        return []


def _evaluator(topo, options=None, **kw):
    kw.setdefault("up_ticks", 2)
    kw.setdefault("down_ticks", 3)
    return HealthEvaluator(lambda: [("r1", topo, options or {})], **kw)


# -------------------------------------------------------------- SLO config
class TestParseSlo:
    def test_defaults(self):
        slo = parse_slo(None)
        assert slo["latency_p99_ms"] == 1000
        assert slo["target"] == 0.99
        assert slo["max_drop_ratio"] == 0.01
        assert slo["max_watermark_lag_ms"] is None

    def test_aliases_camel_and_snake(self):
        slo = parse_slo({"slo": {"latencyP99Ms": 50, "target": 0.999,
                                 "maxDropRatio": 0.05,
                                 "max_watermark_lag_ms": 2000}})
        assert slo["latency_p99_ms"] == 50
        assert slo["target"] == 0.999
        assert slo["max_drop_ratio"] == 0.05
        assert slo["max_watermark_lag_ms"] == 2000

    def test_malformed_values_keep_defaults(self):
        slo = parse_slo({"slo": {"latencyP99Ms": "soon", "target": 7,
                                 "maxDropRatio": -1, "bogus": 1}})
        assert slo == parse_slo(None)
        assert parse_slo({"slo": "not-a-dict"}) == parse_slo(None)


# ----------------------------------------------------- histogram windows
class TestBucketCountDeltas:
    def test_roundtrip_and_delta(self):
        src = LatencyHistogram()
        for v in (3, 70, 900, 15_000):
            src.record(v)
        before = src.bucket_counts()
        src.record(70)
        delta = [c - p for c, p in zip(src.bucket_counts(), before)]
        assert sum(delta) == 1
        win = LatencyHistogram()
        win.record_bucket_counts(src.bucket_counts())
        assert win.count == 5
        # bucket-resolution reconstruction: same ≤6.25% error contract
        assert win.percentile(50) == pytest.approx(src.percentile(50))
        assert win.max >= 15_000
        win.record_bucket_counts([0] * len(before))  # no-op delta
        assert win.count == 5


# ------------------------------------------------------- burn + verdict FSM
class TestBurnRateFSM:
    def _bad(self, topo, n=100):
        for _ in range(n):
            topo.e2e_hist.record(5_000)  # default bound is 1000ms

    def _good(self, topo, n=10_000):
        for _ in range(n):
            topo.e2e_hist.record(2)

    def test_healthy_under_good_latency(self):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        self._good(topo, 100)
        v = ev.tick()["r1"]
        assert v["state"] == HEALTHY
        assert v["burn_rate"]["fast"] < 1.0
        assert ev.peak_burn("r1") < 1.0

    def test_escalation_needs_up_ticks(self):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        self._bad(topo)
        v = ev.tick()["r1"]
        # both windows burn ≥ breach threshold, but hysteresis holds one
        assert v["burn_rate"]["fast"] >= ev.breach_burn
        assert v["state"] == HEALTHY
        self._bad(topo)
        v = ev.tick()["r1"]
        assert v["state"] == BREACHING
        assert v["reasons"]
        assert ev.peak_burn("r1") >= ev.breach_burn
        evs = recorder().events(kind="rule_health", rule="r1")
        assert len(evs) == 1
        assert evs[0]["state"] == BREACHING
        assert evs[0]["previous"] == HEALTHY
        assert evs[0]["severity"] == "error"

    def test_recovery_steps_down_one_level_per_down_ticks(self):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        self._bad(topo)
        ev.tick()
        self._bad(topo)
        assert ev.tick()["r1"]["state"] == BREACHING
        states = []
        for _ in range(8):  # good traffic: windows flush the violations
            self._good(topo)
            states.append(ev.tick()["r1"]["state"])
        # one level per down_ticks window, never a two-level jump
        assert states[-1] == HEALTHY
        assert DEGRADED in states
        assert states.index(DEGRADED) < states.index(HEALTHY)
        trans = [(e["previous"], e["state"])
                 for e in recorder().events(kind="rule_health", rule="r1")]
        assert trans == [(HEALTHY, BREACHING), (BREACHING, DEGRADED),
                         (DEGRADED, HEALTHY)]

    def test_drop_burn_escalates(self):
        src = FakeNode("src", "source")
        topo = FakeTopo([src])
        ev = _evaluator(topo)
        src.stats.inc_in(1000)
        src.stats.inc_dropped("buffer_full", n=500)  # ratio 0.5 ≫ 0.01
        ev.tick()
        v = ev.tick()["r1"]
        assert v["state"] == BREACHING
        assert v["burn_rate"]["drop_fast"] >= ev.breach_burn
        assert any("drop burn" in r for r in v["reasons"])

    def test_single_spike_cannot_flap(self):
        """Multi-window shape: one bad tick decays out of the fast window
        before the slow window alone can escalate the verdict."""
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        self._good(topo, 1000)
        ev.tick()
        self._bad(topo, 20)  # spike: 20 bad among the decayed good
        ev.tick()
        for _ in range(6):
            self._good(topo)
            assert ev.tick()["r1"]["state"] == HEALTHY

    def test_rules_fn_errors_are_contained(self):
        ev = HealthEvaluator(lambda: (_ for _ in ()).throw(RuntimeError()))
        assert ev.tick() == {}  # never raises
        ev2 = HealthEvaluator(lambda: [("r1", None, {}), "garbage"])
        assert ev2.tick() == {}

    def test_departed_rule_track_is_dropped(self):
        topo = FakeTopo([FakeNode("src", "source")])
        rules = [("r1", topo, {})]
        ev = HealthEvaluator(lambda: list(rules))
        ev.tick()
        assert "r1" in ev.verdicts()
        rules.clear()
        ev.tick()
        assert ev.verdicts() == {}


# ------------------------------------------------------------- bottleneck
class TestBottleneckAttribution:
    def test_dominant_stage_and_share(self):
        src = FakeNode("src", "source")
        fold = FakeNode("fused", "op")
        sink = FakeNode("sink", "sink")
        topo = FakeTopo([src, fold, sink])
        ev = _evaluator(topo)
        src.stats.observe_stage("decode", 10_000)
        fold.stats.observe_stage("upload", 5_000)
        fold.stats.observe_stage("fold", 85_000)
        v = ev.tick()["r1"]
        bn = v["bottleneck"]
        assert bn["stage"] == "fold"
        assert bn["node"] == "fused"
        assert bn["share"] == pytest.approx(0.85)
        assert bn["stage_us"]["decode"] == 10_000
        assert v["state"] == HEALTHY  # attribution alone never degrades

    def test_attribution_is_per_tick_delta(self):
        src = FakeNode("src", "source")
        topo = FakeTopo([src])
        ev = _evaluator(topo)
        src.stats.observe_stage("decode", 90_000)
        assert ev.tick()["r1"]["bottleneck"]["stage"] == "decode"
        # next tick: only NEW time counts — fold now dominates the delta
        src.stats.observe_stage("fold", 1_000)
        assert ev.tick()["r1"]["bottleneck"]["stage"] == "fold"

    def test_unstaged_busy_time_classified_by_node_kind(self):
        sink = FakeNode("sink", "sink")
        sink.stats.process_time_us_total = 50_000
        topo = FakeTopo([FakeNode("src", "source"), sink])
        ev = _evaluator(topo)
        assert ev.tick()["r1"]["bottleneck"]["stage"] == "sink"

    def test_backpressure_direction_upstream_of_bottleneck(self):
        src = FakeNode("src", "source")
        fold = FakeNode("fused", "op")
        sink = FakeNode("sink", "sink")
        topo = FakeTopo([src, fold, sink])
        ev = _evaluator(topo)
        fold.stats.observe_stage("fold", 80_000)
        src.stats.note_queue_depth(900)  # queue grows UPSTREAM of fold
        bp = ev.tick()["r1"]["bottleneck"]["backpressure"]
        assert bp["forming"] == "upstream"
        assert bp["upstream"]["peak"] == 900
        assert bp["downstream"]["peak"] == 0


# ------------------------------------------------------- queue-depth peaks
class TestQueueDepthPeaks:
    def test_independent_read_and_reset_marks(self):
        sm = StatManager("op", "n")
        sm.note_queue_depth(3)
        sm.note_queue_depth(9)
        sm.note_queue_depth(5)
        # two consumers, two marks: a scrape must not blind the tick
        assert sm.take_queue_peak_scrape() == 9
        assert sm.take_queue_peak_tick() == 9
        assert sm.take_queue_peak_scrape() == 0
        sm.note_queue_depth(2)
        assert sm.take_queue_peak_tick() == 2

    def test_node_put_notes_enqueue_depth(self):
        from ekuiper_tpu.runtime.node import Node

        n = Node("qp", buffer_length=8)
        for item in (1, 2, 3):
            n.put(item)
        # never dispatched: the high-water mark saw the full backlog
        assert n.stats.take_queue_peak_tick() == 3

    def test_scrape_reports_peak_not_just_live(self):
        from ekuiper_tpu.observability.prometheus import render

        node = FakeNode("spiky", "op", rule_id="rq")
        node.stats.inc_in(1)
        node.stats.note_queue_depth(77)  # spike that drained: inq empty

        class Reg:
            @staticmethod
            def list():
                return [{"id": "rq"}]

            @staticmethod
            def state(_rid):
                class RS:
                    topo = FakeTopo([node])
                return RS()

        line = [ln for ln in render(Reg()).splitlines()
                if ln.startswith('kuiper_node_queue_depth{rule="rq"')][0]
        assert line.endswith(" 77")


# ---------------------------------------------- watermark lag (e2e, REST)
@pytest.fixture
def api_env(mock_clock):
    from ekuiper_tpu.server.processors import StreamProcessor
    from ekuiper_tpu.server.rest import RestApi
    from ekuiper_tpu.store import kv

    store = kv.get_store()
    StreamProcessor(store).exec_stmt(
        'CREATE STREAM hd (deviceId STRING, temperature FLOAT, ts BIGINT) '
        'WITH (DATASOURCE="hp/d", TYPE="memory", FORMAT="JSON", '
        'TIMESTAMP="ts")')
    api = RestApi(store)
    # deterministic ticks: the test drives the evaluator by hand
    api.health_evaluator.stop()
    yield api, mock_clock
    api.rules.stop_all()


def _start_rule(api, rid, options):
    import time

    code, _out = api.dispatch("POST", "/rules", {
        "id": rid,
        "sql": "SELECT deviceId, count(*) AS c FROM hd "
               "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
        "actions": [{"memory": {"topic": f"hp/{rid}"}}],
        "options": options}, {})
    assert code in (200, 201)
    deadline = time.time() + 10.0
    while time.time() < deadline:
        rs = api.rules.state(rid)
        if rs is not None and rs.topo is not None:
            return rs.topo
        time.sleep(0.05)
    raise AssertionError(f"rule {rid} topo never came up")


def _publish(topo, mock_clock, ts):
    import time

    mem.publish("hp/d", {"deviceId": "a", "temperature": 1.0, "ts": ts})
    mock_clock.advance(20)  # linger flush
    assert topo.wait_idle(10)
    time.sleep(0.05)


WM_OPTIONS = {"isEventTime": True, "lateTolerance": 0,
              "slo": {"latencyP99Ms": 600_000,
                      "maxWatermarkLagMs": 2_000}}


class TestWatermarkLag:
    def test_lag_rises_breaches_and_recovers(self, api_env):
        api, clock = api_env
        topo = _start_rule(api, "hw1", WM_OPTIONS)
        ev = api.health_evaluator
        clock.advance(1_000)  # ts=0 would read as "no watermark yet"
        _publish(topo, clock, ts=clock.now_ms())
        v = ev.tick()["hw1"]
        assert v["watermark"]["event_time"] is True
        lag0 = v["watermark"]["lag_ms"]
        assert lag0 is not None and lag0 <= 100
        assert v["state"] == HEALTHY

        # event time stalls while the engine clock advances: lag rises
        clock.advance(3_000)
        v = ev.tick()["hw1"]
        assert v["watermark"]["lag_ms"] > lag0
        assert v["watermark"]["lag_ms"] > 2_000  # over bound → degrading
        assert v["state"] == HEALTHY  # hysteresis: first tick over
        v = ev.tick()["hw1"]
        assert v["state"] == DEGRADED
        assert any("watermark lag" in r for r in v["reasons"])

        # metrics family carries the rising lag
        from ekuiper_tpu.observability.prometheus import render
        text = render(api.rules)
        line = [ln for ln in text.splitlines()
                if ln.startswith('kuiper_watermark_lag_ms{rule="hw1"}')][0]
        assert float(line.split()[-1]) > 2_000
        assert 'kuiper_rule_health{rule="hw1"} 1' in text

        # 3x the bound: degraded → breaching (again two ticks)
        clock.advance(4_000)
        ev.tick()
        v = ev.tick()["hw1"]
        assert v["state"] == BREACHING

        # fresh events advance the watermark: lag collapses, then the
        # FSM walks back one level per down_ticks quiet ticks
        _publish(topo, clock, ts=clock.now_ms())
        states = []
        for _ in range(7):
            _publish(topo, clock, ts=clock.now_ms())
            states.append(ev.tick()["hw1"]["state"])
        assert states[-1] == HEALTHY
        assert DEGRADED in states
        trans = [(e["previous"], e["state"])
                 for e in recorder().events(kind="rule_health",
                                            rule="hw1")]
        assert trans == [(HEALTHY, DEGRADED), (DEGRADED, BREACHING),
                         (BREACHING, DEGRADED), (DEGRADED, HEALTHY)]

    def test_rest_endpoints_serve_verdicts(self, api_env):
        api, clock = api_env
        topo = _start_rule(api, "hw2", WM_OPTIONS)
        _publish(topo, clock, ts=clock.now_ms())
        code, v = api.dispatch("GET", "/rules/hw2/health", None, {})
        assert code == 200
        assert v["state"] in (HEALTHY, DEGRADED, BREACHING)
        assert "burn_rate" in v and "bottleneck" in v and "watermark" in v
        assert v["slo"]["max_watermark_lag_ms"] == 2_000
        code, d = api.dispatch("GET", "/diagnostics/health", None, {})
        assert code == 200
        assert "hw2" in d["rules"]
        assert d["evaluator"]["ticks"] >= 1
        assert "trend_bytes_per_min" in d["hbm"]
        json.dumps(d)  # REST serves it verbatim
        code, _ = api.dispatch("GET", "/rules/nope/health", None, {})
        assert code == 400
        # status JSON rides the last verdict without forcing a tick
        code, st = api.dispatch("GET", "/rules/hw2/status", None, {})
        assert code == 200
        assert st["health"]["state"] == v["state"]

    def test_shared_fold_members_report_lag_per_rule(self):
        from ekuiper_tpu.ops.aggspec import extract_kernel_plan
        from ekuiper_tpu.ops.emit import build_direct_emit
        from ekuiper_tpu.ops.panestore import union_plan
        from ekuiper_tpu.runtime.nodes_sharedfold import (
            MemberSpec, SharedEmitNode, SharedFoldNode)
        from ekuiper_tpu.sql.parser import parse_select

        sqls = ["SELECT deviceId, count(*) AS c FROM demo "
                "GROUP BY deviceId, TUMBLINGWINDOW(ss, 10)",
                "SELECT deviceId, count(*) AS c FROM demo "
                "GROUP BY deviceId, TUMBLINGWINDOW(ss, 20)"]
        stmts = [parse_select(s) for s in sqls]
        plans = [extract_kernel_plan(s) for s in stmts]
        union, _ = union_plan(plans)
        store = SharedFoldNode("k", "sf", union, 10_000, 4,
                               subtopo_ref=None, capacity=64,
                               micro_batch=128, is_event_time=True)
        for i, (stmt, plan) in enumerate(zip(stmts, plans)):
            w = stmt.window
            spec = MemberSpec(
                rule_id=f"m{i}", length_ms=w.length_ms(),
                interval_ms=w.interval_ms() or w.length_ms(), plan=plan,
                direct_emit=build_direct_emit(stmt, plan, ["deviceId"]),
                dims=["deviceId"], emit_columnar=True)
            assert store.attach_rule(spec, SharedEmitNode(f"m{i}_e"), None)
        # members advanced to different windows on the SAME store
        store._members["m0"].last_end_ms = 10_000
        store._members["m1"].last_end_ms = 20_000
        store._wm_node.max_ts = 25_000
        nodes = store.pipeline_nodes()
        p0 = HealthEvaluator._watermark_probe("m0", nodes, now=30_000)
        p1 = HealthEvaluator._watermark_probe("m1", nodes, now=30_000)
        assert p0["event_time"] and p1["event_time"]
        assert p0["lag_ms"] == p1["lag_ms"] == 5_000  # store watermark
        assert p0["emit_cursor_ms"] == 10_000  # but cursors are PER RULE
        assert p1["emit_cursor_ms"] == 20_000
        assert "pane_occupancy" in p0


# --------------------------------------------------- events: severity/since
class TestEventSeverityAndSince:
    def test_severity_defaults_and_clamps(self):
        recorder().record("plain")
        recorder().record("graded", severity="error")
        recorder().record("bogus", severity="catastrophic")
        sevs = {e["kind"]: e["severity"] for e in recorder().events()}
        assert sevs == {"plain": "info", "graded": "error",
                        "bogus": "info"}

    def test_since_tails_incrementally(self):
        from ekuiper_tpu.runtime.events import FlightRecorder

        fr = FlightRecorder(capacity=16)
        for i in range(5):
            fr.record("k", i=i)
        d = fr.diagnostics(limit=2)
        assert [e["i"] for e in d["events"]] == [3, 4]
        assert d["last_seq"] == 5
        tail = fr.diagnostics(since=d["last_seq"])
        assert tail["events"] == []
        assert tail["last_seq"] == 5  # caller's cursor echoed back
        fr.record("k", i=5)
        tail = fr.diagnostics(since=d["last_seq"])
        assert [e["i"] for e in tail["events"]] == [5]
        assert tail["last_seq"] == 6

    def test_rest_since_param(self, api_env):
        api, _clock = api_env
        recorder().record("a")
        recorder().record("b")
        # the global recorder's seq is monotonic across tests: tail from
        # the seq the ring itself reports for "a"
        seq_a = recorder().events(kind="a")[-1]["seq"]
        code, out = api.dispatch("GET", "/diagnostics/events", None,
                                 {"since": str(seq_a)})
        assert code == 200
        assert [e["kind"] for e in out["events"]] == ["b"]
        assert out["last_seq"] == seq_a + 1
        code, _ = api.dispatch("GET", "/diagnostics/events", None,
                               {"since": "bogus"})
        assert code == 400


# ------------------------------------------------------- profile capture
class TestProfileCapture:
    def test_bundle_dir_and_dump(self, tmp_path):
        out = health.capture_profile(duration_ms=60,
                                     out_dir=str(tmp_path / "p1"))
        assert os.path.isdir(out["dir"])
        assert out["duration_ms"] == 60
        assert "devwatch_dump.json" in out["files"]
        with open(os.path.join(out["dir"], "devwatch_dump.json")) as f:
            dump = json.load(f)
        assert "xla" in dump and "memory" in dump

    def test_duration_is_clamped(self, tmp_path):
        out = health.capture_profile(duration_ms=1,
                                     out_dir=str(tmp_path / "p2"))
        assert out["duration_ms"] == 50  # floor: a 1ms trace is noise
        assert health.PROFILE_MAX_MS == 30_000  # REST can never block long

    def test_concurrent_capture_rejected(self, tmp_path):
        assert health._profile_lock.acquire(blocking=False)
        try:
            with pytest.raises(RuntimeError):
                health.capture_profile(duration_ms=60,
                                       out_dir=str(tmp_path / "p3"))
        finally:
            health._profile_lock.release()

    def test_rest_endpoint(self, api_env):
        from ekuiper_tpu.utils.config import get_config

        api, _clock = api_env
        # over HTTP the capture must land under the store path
        out_dir = os.path.join(get_config().store.path, "profiles",
                               "test_p4")
        code, out = api.dispatch(
            "POST", "/diagnostics/profile",
            {"duration_ms": 60, "out_dir": out_dir}, {})
        assert code == 200
        assert os.path.isdir(out["dir"])
        code, _ = api.dispatch("POST", "/diagnostics/profile",
                               {"duration_ms": "soon"}, {})
        assert code == 400

    def test_rest_rejects_out_dir_escape(self, api_env, tmp_path):
        """The unauthenticated REST boundary must not allow directory
        creation / file writes outside the store path."""
        api, _clock = api_env
        for bad in (str(tmp_path / "evil"), "/etc/cron.d",
                    "data/../outside"):
            code, _ = api.dispatch(
                "POST", "/diagnostics/profile",
                {"duration_ms": 60, "out_dir": bad}, {})
            assert code == 400, bad


# ------------------------------------------------------------ evaluator
class TestEvaluatorLifecycle:
    def test_periodic_ticks_on_engine_clock(self, mock_clock):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = health.install(lambda: [("r1", topo, {})], interval_ms=1000)
        try:
            assert ev.ticks == 0
            mock_clock.advance(1000)
            assert ev.ticks == 1
            mock_clock.advance(3000)  # re-arms after each fire
            assert ev.ticks >= 2
            assert "r1" in ev.verdicts()
        finally:
            health.reset()
        mock_clock.advance(1000)
        assert ev.ticks <= 4  # stopped: no further fires

    def test_rule_verdict_never_forces_tick(self):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = health.install(lambda: [("r1", topo, {})], start=False)
        assert health.rule_verdict("r1") is None
        assert ev.ticks == 0
        ev.tick()
        assert health.rule_verdict("r1")["state"] == HEALTHY

    def test_tick_cost_is_recorded(self):
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        ev.tick()
        assert ev.last_tick_us > 0  # bench reads this for the <1% check


# ------------------------------------------------- review-hardening fixes
class TestReviewHardening:
    def test_transient_rules_fn_failure_keeps_tracks(self):
        """One registry hiccup must not reset FSM state or re-seed the
        full cumulative e2e history as a single tick's delta."""
        topo = FakeTopo([FakeNode("src", "source")])
        calls = {"n": 0}

        def rules_fn():
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("registry hiccup")
            return [("r1", topo, {})]

        ev = HealthEvaluator(rules_fn)
        for _ in range(200):
            topo.e2e_hist.record(5_000)
        ev.tick()
        ev.tick()  # rules_fn raises: nothing evaluated, tracks KEPT
        assert ev.has_track("r1")
        prev_e2e = ev._tracks["r1"].prev_e2e
        assert prev_e2e is not None  # delta baseline survives
        v = ev.tick()["r1"]  # recovery: delta is empty, not full history
        assert v["latency"]["window_fast"]["count"] < 200

    def test_watermark_none_during_late_tolerance_warmup(self):
        """A tolerance-adjusted watermark ≤ 0 was never broadcast and
        must not read as a (hugely lagging) watermark."""
        from ekuiper_tpu.runtime.nodes_window import WatermarkNode

        wm = WatermarkNode("wm", late_tolerance_ms=10_000)
        assert wm.watermark_ts() is None
        wm.max_ts = 500  # first event: adjusted wm is -9500
        assert wm.watermark_ts() is None
        wm.max_ts = 10_500
        assert wm.watermark_ts() == 500

    def test_shared_node_queue_peak_seen_by_every_member(self):
        """take_queue_peak_tick is read-and-reset; a node shared by N
        member rules must report the same tick peak to all of them."""
        shared = FakeNode("shared_src", "source")
        shared.stats.note_queue_depth(500)
        ev = HealthEvaluator(
            lambda: [("r1", FakeTopo([shared]), {}),
                     ("r2", FakeTopo([shared]), {})])
        ev.tick()
        for rid in ("r1", "r2"):
            assert ev._tracks[rid].prev_queue["shared_src"] == 500

    def test_rule_health_does_not_retick_per_poll(self):
        """A rule with a track but no verdict (eval persistently raises)
        must not cost one off-cadence tick PER REST POLL — that would
        decay every other rule's burn windows and hysteresis."""
        class BadTopo(FakeTopo):
            def all_nodes(self):
                raise RuntimeError("boom")

        ev = HealthEvaluator(lambda: [("r1", BadTopo([]), {})])
        assert ev.rule_health("r1") is None  # one seeding tick
        assert ev.ticks == 1
        assert ev.rule_health("r1") is None  # track exists: no re-tick
        assert ev.rule_health("r1") is None
        assert ev.ticks == 1

    def test_since_with_limit_pages_forward(self):
        """since+limit keeps the OLDEST n so a tailer never skips events
        between its cursor and the window."""
        from ekuiper_tpu.runtime.events import FlightRecorder

        fr = FlightRecorder(capacity=16)
        for i in range(5):
            fr.record("k", i=i)
        page = fr.diagnostics(since=0, limit=2)
        assert [e["i"] for e in page["events"]] == [0, 1]
        assert page["last_seq"] == 2
        page = fr.diagnostics(since=page["last_seq"], limit=2)
        assert [e["i"] for e in page["events"]] == [2, 3]
        page = fr.diagnostics(since=page["last_seq"], limit=2)
        assert [e["i"] for e in page["events"]] == [4]
        assert page["last_seq"] == 5

    def test_mixed_level_escalation_lands_on_min_sustained(self):
        """One breach-level spike inside an otherwise-degraded pending
        run escalates to DEGRADED, not BREACHING."""
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo)
        for _ in range(3):
            topo.e2e_hist.record(5_000)  # ~1.x burn: degraded band
        for _ in range(100):
            topo.e2e_hist.record(2)
        assert ev.tick()["r1"]["state"] == HEALTHY  # pend=1 @ degraded
        for _ in range(500):
            topo.e2e_hist.record(5_000)  # breach-level spike
        v = ev.tick()["r1"]  # pend=2, min level sustained = degraded
        assert v["state"] == DEGRADED
        for _ in range(500):
            topo.e2e_hist.record(5_000)
        ev.tick()
        v = ev.tick()["r1"]  # breach level held for up_ticks: escalate
        assert v["state"] == BREACHING

    def test_shared_member_emit_stage_not_cross_charged(self):
        """A shared node's emit[<rule>] stage time lands only on that
        member's verdict; other members must not report it as theirs."""
        shared = FakeNode("shared_fold")
        shared.stats.observe_stage("emit[r1]", 1_000, rows=1)
        shared.stats.observe_stage("emit[r2]", 50_000, rows=1)
        shared.stats.observe_stage("fold", 100, rows=1)
        ev = HealthEvaluator(
            lambda: [("r1", FakeTopo([shared]), {}),
                     ("r2", FakeTopo([shared]), {})])
        vs = ev.tick()
        s1 = vs["r1"]["bottleneck"]["stage_us"]
        s2 = vs["r2"]["bottleneck"]["stage_us"]
        assert s1.get("emit_combine", 0) == 1_000  # r2's 50ms not charged
        assert s2.get("emit_combine", 0) == 50_000
        assert vs["r1"]["bottleneck"]["stage"] == "emit_combine"

    def test_rest_distinguishes_failing_eval_from_stopped(self, api_env):
        """A running rule whose evaluation persistently raises must not
        be reported as 'not running'."""
        api, _clock = api_env
        topo = _start_rule(api, "hf1", {})
        ev = api.health_evaluator
        # sabotage the topo's node walk: eval raises, track exists
        topo.all_nodes = lambda: (_ for _ in ()).throw(RuntimeError("x"))
        ev.tick()
        code, out = api.dispatch("GET", "/rules/hf1/health", None, {})
        assert code == 200
        assert out["state"] == "unknown"
        assert "evaluation is failing" in out["reason"]

    def test_cross_signal_burns_do_not_combine(self):
        """A fast-window-only latency burn coinciding with a slow-window
        -only drop burn must not escalate: each SIGNAL must burn in both
        of ITS OWN windows (mixing them would also emit a reason-less
        transition, since the reasons guards are per signal)."""
        topo = FakeTopo([FakeNode("src", "source")])
        ev = _evaluator(topo, up_ticks=1)
        ev.tick()  # create the track
        tr = ev._tracks["r1"]
        # latency: fast window 100% violating, slow window far under the
        # 1% budget — a spike the slow window has already absorbed. The
        # fast window carries real sample mass (burn is weighted by
        # samples observed per window; a 1-sample window can't burn)
        for _ in range(150):
            tr.fast_hist.record(5_000)
        for _ in range(20_000):
            tr.slow_hist.record(2)
        tr.slow_hist.record(5_000)
        # drops: slow window still remembers a burst the fast window has
        # fully diluted
        tr.fast_drops, tr.fast_in = 0.0, 1000.0
        tr.slow_drops, tr.slow_in = 500.0, 1000.0
        v = ev.tick()["r1"]
        br = v["burn_rate"]
        assert br["latency_fast"] >= 1.0 > br["latency_slow"]
        assert br["drop_slow"] >= 1.0 > br["drop_fast"]
        # per-window maxima both burn — but no single signal does
        assert br["fast"] >= 1.0 and br["slow"] >= 1.0
        assert v["state"] == HEALTHY
        assert "reasons" not in v

    def test_partial_health_sample_skips_node_for_tick(self):
        """A lock-race-degraded sample must not become the delta
        baseline (the next tick would replay cumulative history)."""
        node = FakeNode("op1")
        node.stats.observe_stage("fold", 10_000, rows=5)
        topo = FakeTopo([node])
        ev = _evaluator(topo)
        ev.tick()  # baseline: fold=10000 recorded in prev
        node.stats.observe_stage("fold", 500, rows=1)
        real_sample = node.stats.health_sample
        node.stats.health_sample = lambda: {**real_sample(),
                                            "stages": {}, "dropped": 0,
                                            "partial": True}
        v = ev.tick()["r1"]  # degraded sample: node skipped, prev kept
        assert not v["bottleneck"].get("stage_us")
        node.stats.health_sample = real_sample
        v = ev.tick()["r1"]  # recovery: delta vs ORIGINAL baseline
        assert v["bottleneck"]["stage_us"].get("fold", 0) == 500


class TestSampleCountAwareBurn:
    """ISSUE 10 satellite: when the evaluator ticks faster than a rule
    emits, the burn windows must hold their evidence between emissions
    instead of decaying to zero and flapping the verdict (churn_soak had
    to pin KUIPER_HEALTH_INTERVAL_MS=1500 to dodge exactly this)."""

    def _slow_emitter(self, options=None, **kw):
        topo = FakeTopo([FakeNode("src", "source")])
        # sub-second cadence: the interval only matters for the timer;
        # driving tick() directly models an evaluator far outpacing the
        # rule's ~per-window emission rate
        ev = _evaluator(topo, options=options, interval_ms=200, **kw)
        return topo, ev

    def test_breaching_slow_emitter_holds_across_empty_ticks(self):
        """A rule emitting a violating window every 5th evaluator tick
        must reach breaching and STAY there — empty ticks carry no new
        evidence and must not decay the verdict toward healthy."""
        topo, ev = self._slow_emitter(
            options={"slo": {"latencyP99Ms": 100, "target": 0.9}})
        states = []
        for i in range(20):
            if i % 5 == 0:  # one window emission: all samples violating
                for _ in range(20):
                    topo.e2e_hist.record(5_000)
            states.append(ev.tick()["r1"]["state"])
        assert BREACHING in states
        # once breaching, the verdict never steps down during the run —
        # pre-fix, the 4 empty ticks between emissions decayed the
        # windows to zero samples and the FSM flapped down every cycle
        first = states.index(BREACHING)
        assert set(states[first:]) == {BREACHING}

    def test_healthy_slow_emitter_stays_healthy(self):
        topo, ev = self._slow_emitter()
        for i in range(20):
            if i % 5 == 0:
                topo.e2e_hist.record(2)
                topo.e2e_hist.record(3)
            assert ev.tick()["r1"]["state"] == HEALTHY

    def test_single_stray_violation_cannot_degrade(self):
        """One violating sample in an otherwise-empty window is below
        the budget's statistical resolution (~1/budget samples) — the
        weighted burn must stay under the degrade line no matter how
        many sub-second ticks re-read the held window."""
        topo, ev = self._slow_emitter()  # default target 0.99
        topo.e2e_hist.record(5_000)
        for _ in range(10):
            v = ev.tick()["r1"]
            assert v["state"] == HEALTHY
            assert v["burn_rate"]["latency_fast"] < 1.0

    def test_empty_ticks_do_not_decay_drop_windows(self):
        src = FakeNode("src", "source")
        topo = FakeTopo([src])
        ev = _evaluator(topo)
        src.stats.inc_in(1000)
        src.stats.inc_dropped("buffer_full", n=500)
        ev.tick()
        states = [ev.tick()["r1"]["state"] for _ in range(8)]
        # no new traffic at all: the drop evidence holds, the verdict
        # does not silently relax back to healthy
        assert states[-1] == BREACHING

    def test_dead_traffic_rule_ages_out_of_breaching(self):
        """The evidence hold is BOUNDED (IDLE_HOLD_TICKS): a rule whose
        traffic stops entirely — dead broker, disconnected source —
        must age back to healthy instead of freezing at breaching
        forever (which would permanently trip the breach-defer
        admission gate and keep the shed plane acting on a dead
        rule)."""
        src = FakeNode("src", "source")
        topo = FakeTopo([src])
        ev = _evaluator(topo)
        src.stats.inc_in(1000)
        src.stats.inc_dropped("buffer_full", n=500)
        ev.tick()
        assert ev.tick()["r1"]["state"] == BREACHING
        states = [ev.tick()["r1"]["state"] for _ in range(40)]
        # held well past the flap horizon (sub-second-cadence evidence),
        # then decays out and steps down through the FSM
        assert states[health.IDLE_HOLD_TICKS - 2] == BREACHING
        assert states[-1] == HEALTHY

    def test_dead_latency_evidence_ages_out(self):
        topo, ev = self._slow_emitter(
            options={"slo": {"latencyP99Ms": 100, "target": 0.9}})
        for _ in range(40):
            topo.e2e_hist.record(5_000)
        ev.tick()
        assert ev.tick()["r1"]["state"] == BREACHING
        states = [ev.tick()["r1"]["state"] for _ in range(40)]
        assert states[-1] == HEALTHY

    def test_window_sample_mass_is_reported(self):
        topo, ev = self._slow_emitter()
        for _ in range(7):
            topo.e2e_hist.record(2)
        v = ev.tick()["r1"]
        assert v["latency"]["tick_samples"] == 7
        assert v["latency"]["samples_fast"] == 7
        # the observing tick decayed the window toward the next one
        # (7 -> 3); empty ticks HOLD that mass instead of halving it
        # again and again toward zero
        for _ in range(3):
            v = ev.tick()["r1"]
            assert v["latency"]["tick_samples"] == 0
            assert v["latency"]["samples_fast"] == 3


class TestSeedingSingleFlight:
    def test_concurrent_polls_tick_once(self):
        """rule_health's seeding tick runs OUTSIDE the evaluator lock
        (the clock/evaluator ABBA fix) but must stay single-flight:
        N concurrent polls for an untracked rule produce ONE
        off-cadence tick, not one each (review regression — each extra
        tick decays every rule's burn windows)."""
        import threading
        import time

        topo = FakeTopo([FakeNode("fold")])
        ev = _evaluator(topo)
        ticks = []
        orig_tick = ev.tick

        def slow_tick():
            ticks.append(1)
            time.sleep(0.05)  # widen the race window
            return orig_tick()

        ev.tick = slow_tick
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(ev.rule_health("r1")))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(ticks) == 1, f"{len(ticks)} seeding ticks fired"
        assert len(results) == 4
        assert all(r is not None for r in results)
