"""ekuiper_tpu — a TPU-native streaming-SQL rule engine.

A from-scratch reimplementation of the capabilities of LF Edge eKuiper
(reference mounted at /root/reference) designed TPU-first: rules whose
window->GROUP BY->aggregate pipelines compile to fused XLA kernels over
columnar micro-batches, with key-axis sharding over a jax device mesh for
scale-out, and a lightweight Python rule runtime (planner, rule FSM, REST
API, connectors) around the device data plane.
"""

__version__ = "0.1.0"
