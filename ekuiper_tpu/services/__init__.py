"""External services — gRPC/REST/msgpack-rpc endpoints as SQL functions
(analogue of the reference's internal/service subsystem)."""
from .manager import ServiceManager  # noqa: F401
