"""External service manager — descriptor JSON in, SQL functions out
(analogue of internal/service/manager.go:48-266).

A service descriptor (same shape as the reference's sample.json) declares
interfaces; each interface has an address, protocol (rest/grpc/msgpack-rpc),
optional protobuf schema, and function mappings. Every mapped function —
or, with a protobuf schema and no explicit mapping, every service method —
becomes callable from SQL through the binder provider chain
(functions/registry.py): `SELECT myfn(temperature) FROM s`.

Descriptors persist in the KV store and re-register at boot. Executors are
built lazily on first call and cached per interface.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

from ..functions import registry as fn_registry
from ..utils.infra import EngineError, logger
from .executors import new_executor
from .schema import ProtoServiceSchema


class _Interface:
    def __init__(self, service: str, name: str, spec: Dict[str, Any]) -> None:
        self.service = service
        self.name = name
        self.address = spec.get("address", "")
        self.protocol = spec.get("protocol", "rest")
        self.options = spec.get("options") or {}
        self.schema_type = spec.get("schemaType", "")
        # reference reads schemaFile from the etc dir; we accept inline
        # proto source (schemaContent) or a file path (schemaFile)
        self.schema_content = spec.get("schemaContent", "")
        self.schema_file = spec.get("schemaFile", "")
        self.functions = spec.get("functions") or []
        self._schema: Optional[ProtoServiceSchema] = None
        self._executor = None
        self._lock = threading.Lock()
        if not self.address:
            raise EngineError(f"interface {name}: address is required")

    def schema(self) -> Optional[ProtoServiceSchema]:
        if self.schema_type != "protobuf":
            return None
        if self._schema is None:
            content = self.schema_content
            if not content and self.schema_file:
                with open(self.schema_file) as f:
                    content = f.read()
            if not content:
                raise EngineError(
                    f"interface {self.name}: protobuf schema declared but no "
                    "schemaContent/schemaFile")
            self._schema = ProtoServiceSchema(content)
        return self._schema

    def function_map(self) -> Dict[str, str]:
        """SQL function name -> wire method/serviceName."""
        out: Dict[str, str] = {}
        if self.functions:
            for m in self.functions:
                out[m.get("name") or m["serviceName"]] = m["serviceName"]
            return out
        schema = self.schema()
        if schema is not None:
            for method in schema.methods:
                out[method] = method
        return out

    def call(self, target: str, args: List[Any]) -> Any:
        with self._lock:
            if self._executor is None:
                self._executor = new_executor(
                    self.protocol, self.address, self.options, self.schema())
            ex = self._executor
        return ex.call(target, args)

    def close(self) -> None:
        with self._lock:
            if self._executor is not None:
                self._executor.close()
                self._executor = None


class ServiceManager:
    _instance: Optional["ServiceManager"] = None
    _provider_registered = False

    def __init__(self, store=None) -> None:
        self._kv = store.kv("service") if store is not None else None
        self._services: Dict[str, Dict[str, Any]] = {}
        self._interfaces: Dict[str, _Interface] = {}  # "svc/iface"
        #: SQL function name -> (interface key, wire target)
        self._functions: Dict[str, tuple] = {}
        self._mu = threading.RLock()
        # one chain-wide provider delegating to the CURRENT global instance
        # (a fresh manager per test/boot must not stack stale providers)
        ServiceManager._instance = self
        if not ServiceManager._provider_registered:
            fn_registry.add_provider(
                lambda n: (ServiceManager._instance._provide(n)
                           if ServiceManager._instance is not None else None))
            ServiceManager._provider_registered = True
        if self._kv is not None:
            for name in self._kv.keys():
                try:
                    raw = self._kv.get(name)
                    self._register(name, json.loads(raw)
                                   if isinstance(raw, str) else raw)
                except Exception as exc:
                    logger.warning("service %s restore failed: %s", name, exc)

    @classmethod
    def global_instance(cls) -> "ServiceManager":
        if cls._instance is None:
            cls._instance = ServiceManager()
        return cls._instance

    @classmethod
    def set_global(cls, mgr: "ServiceManager") -> None:
        cls._instance = mgr

    # ------------------------------------------------------------------ CRUD
    def create(self, name: str, descriptor: Any,
               overwrite: bool = False) -> None:
        if isinstance(descriptor, str):
            # reference clients send {"name", "file"}: accept a local json
            # descriptor path; remote zip bundles are not supported
            import os

            if os.path.isfile(descriptor):
                with open(descriptor) as f:
                    descriptor = json.load(f)
            else:
                raise EngineError(
                    "service 'file' must be a local descriptor json path; "
                    "inline the definition under 'descriptor' otherwise")
        if not isinstance(descriptor, dict):
            raise EngineError("service descriptor must be a json object")
        if not name:
            raise EngineError("service name is required")
        with self._mu:
            if not overwrite and name in self._services:
                raise EngineError(f"service {name} already exists")
            # validate + build into temporaries FIRST: a bad descriptor on
            # overwrite must not tear down the running service (functions
            # still owned by the old registration don't count as clashes —
            # the `fname not in self._functions` check covers them)
            new_ifaces, new_fns = self._build(name, descriptor)
            if name in self._services:
                self._unregister(name)
            self._services[name] = descriptor
            self._interfaces.update(new_ifaces)
            self._functions.update(new_fns)
            if self._kv is not None:
                self._kv.set(name, json.dumps(descriptor))

    def delete(self, name: str) -> None:
        with self._mu:
            if name not in self._services:
                raise EngineError(f"service {name} not found")
            self._unregister(name)
            if self._kv is not None:
                self._kv.delete(name)

    def list(self) -> List[str]:
        with self._mu:
            return sorted(self._services)

    def describe(self, name: str) -> Dict[str, Any]:
        with self._mu:
            if name not in self._services:
                raise EngineError(f"service {name} not found")
            return self._services[name]

    def list_functions(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [
                {"name": fname, "serviceName": target,
                 "interface": ikey.split("/", 1)[1],
                 "service": ikey.split("/", 1)[0]}
                for fname, (ikey, target) in sorted(self._functions.items())
            ]

    def describe_function(self, fname: str) -> Dict[str, Any]:
        fname = fname.lower()  # registered names are lowercased
        with self._mu:
            got = self._functions.get(fname)
            if got is None:
                raise EngineError(f"external function {fname} not found")
            ikey, target = got
            return {"name": fname, "serviceName": target,
                    "service": ikey.split("/", 1)[0],
                    "interface": ikey.split("/", 1)[1]}

    # -------------------------------------------------------------- internal
    def _build(self, name: str, descriptor: Dict[str, Any]):
        """Validate a descriptor and build its interface/function tables
        without touching live state."""
        interfaces = descriptor.get("interfaces") or {}
        if not interfaces:
            raise EngineError("service descriptor has no interfaces")
        new_ifaces: Dict[str, _Interface] = {}
        new_fns: Dict[str, tuple] = {}
        for iname, spec in interfaces.items():
            iface = _Interface(name, iname, spec)
            key = f"{name}/{iname}"
            new_ifaces[key] = iface
            for fname, target in iface.function_map().items():
                fname = fname.lower()  # SQL function names are case-insensitive
                clash = fn_registry.lookup(fname)
                if clash is not None and fname not in self._functions:
                    raise EngineError(
                        f"function {fname} already exists (builtin wins; "
                        "rename via the functions mapping)")
                new_fns[fname] = (key, target)
        return new_ifaces, new_fns

    def _register(self, name: str, descriptor: Dict[str, Any]) -> None:
        new_ifaces, new_fns = self._build(name, descriptor)
        self._services[name] = descriptor
        self._interfaces.update(new_ifaces)
        self._functions.update(new_fns)

    def _unregister(self, name: str) -> None:
        self._services.pop(name, None)
        for key in [k for k in self._interfaces if k.startswith(name + "/")]:
            self._interfaces.pop(key).close()
        for fname in [f for f, (k, _) in self._functions.items()
                      if k.startswith(name + "/")]:
            del self._functions[fname]

    # ------------------------------------------------- binder provider chain
    def _provide(self, fname: str):
        with self._mu:
            got = self._functions.get(fname)
            if got is None:
                return None
            ikey, target = got
            iface = self._interfaces[ikey]

        def call(args, ctx=None):  # engine convention: exec(args_list, ctx)
            return iface.call(target, list(args))

        return fn_registry.FunctionDef(
            name=fname, ftype=fn_registry.SCALAR, exec=call)
