"""Protobuf service schemas for external services.

Compiles a .proto source with `protoc` (base toolchain) into a descriptor
pool and indexes its `service` definitions: method name → (input message
class, output message class). The reference does the same through
protoreflect's dynamic messages (internal/service/schema.go); here the
google.protobuf descriptor pool + message factory play that role.
"""
from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Any, Dict, Optional, Tuple

from ..utils.infra import EngineError


class ProtoServiceSchema:
    """Parsed proto: message classes + service method index."""

    def __init__(self, content: str) -> None:
        from google.protobuf import (
            descriptor_pb2, descriptor_pool, message_factory)

        self.content = content
        with tempfile.TemporaryDirectory() as td:
            proto_path = os.path.join(td, "svc.proto")
            with open(proto_path, "w") as f:
                f.write(content)
            desc_path = proto_path + ".pb"
            res = subprocess.run(
                ["protoc", f"--proto_path={td}", f"--descriptor_set_out={desc_path}",
                 "svc.proto"],
                capture_output=True, timeout=30,
            )
            if res.returncode != 0:
                raise EngineError(
                    "protoc failed: "
                    + res.stderr.decode(errors="replace").strip())
            with open(desc_path, "rb") as f:
                fds = descriptor_pb2.FileDescriptorSet.FromString(f.read())
        pool = descriptor_pool.DescriptorPool()
        self._pool = pool
        #: method name -> (service full name, input class, output class)
        self.methods: Dict[str, Tuple[str, Any, Any]] = {}
        for fdp in fds.file:
            pool.Add(fdp)
        for fdp in fds.file:
            pkg = fdp.package
            for svc in fdp.service:
                full = f"{pkg}.{svc.name}" if pkg else svc.name
                for m in svc.method:
                    in_desc = pool.FindMessageTypeByName(
                        m.input_type.lstrip("."))
                    out_desc = pool.FindMessageTypeByName(
                        m.output_type.lstrip("."))
                    self.methods[m.name] = (
                        full,
                        message_factory.GetMessageClass(in_desc),
                        message_factory.GetMessageClass(out_desc),
                    )

    def method(self, name: str) -> Tuple[str, Any, Any]:
        try:
            return self.methods[name]
        except KeyError:
            raise EngineError(f"service method {name!r} not in schema")

    # ------------------------------------------------------------- marshaling
    def build_request(self, method: str, args) -> Any:
        """Positional args fill the input message's fields in declaration
        order; a single dict argument fills by name (reference
        externalFunc.go arg mapping)."""
        from google.protobuf import json_format

        _, in_cls, _ = self.method(method)
        msg = in_cls()
        fields = in_cls.DESCRIPTOR.fields
        if len(args) == 1 and isinstance(args[0], dict):
            json_format.ParseDict(args[0], msg, ignore_unknown_fields=True)
            return msg
        if len(args) > len(fields):
            raise EngineError(
                f"{method} takes at most {len(fields)} args, got {len(args)}")
        for fd, val in zip(fields, args):
            if hasattr(val, "item"):  # numpy scalar from a column
                val = val.item()
            if fd.label == fd.LABEL_REPEATED:
                getattr(msg, fd.name).extend(val)
            elif fd.message_type is not None:
                json_format.ParseDict(val, getattr(msg, fd.name),
                                      ignore_unknown_fields=True)
            else:
                setattr(msg, fd.name, val)
        return msg

    def result_to_value(self, method: str, msg) -> Any:
        """Single-field responses unwrap to the bare value (the reference
        unwraps single-output messages the same way)."""
        from google.protobuf import json_format

        d = json_format.MessageToDict(msg, preserving_proto_field_name=True)
        fields = msg.DESCRIPTOR.fields
        if len(fields) == 1:
            return d.get(fields[0].name)
        return d
