"""Per-protocol executors for external service functions (analogue of
internal/service/executors.go + executors_msgpack.go).

One executor per interface definition; each call maps SQL function args to
the wire format and the response back to a SQL value.
"""
from __future__ import annotations

import itertools
import json
import socket
import threading
import urllib.request
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

from ..utils.infra import EngineError
from .schema import ProtoServiceSchema

_DEFAULT_TIMEOUT_S = 5.0


class RestExecutor:
    """JSON-over-HTTP: POST {address}/{serviceName} with the request body
    built from args; protobuf schemas marshal through json_format, giving
    the same field mapping as the reference's httpExecutor."""

    def __init__(self, address: str, options: Dict[str, Any],
                 schema: Optional[ProtoServiceSchema]) -> None:
        self.address = address.rstrip("/")
        self.headers = dict(options.get("headers") or {})
        self.timeout = float(options.get("timeout", _DEFAULT_TIMEOUT_S * 1000)) / 1000.0
        self.schema = schema

    def call(self, service_name: str, args: List[Any]) -> Any:
        if self.schema is not None:
            from google.protobuf import json_format

            msg = self.schema.build_request(service_name, args)
            body = json_format.MessageToDict(
                msg, preserving_proto_field_name=True)
        elif len(args) == 1 and isinstance(args[0], (dict, list)):
            body = args[0]
        elif len(args) == 0:
            body = {}
        else:
            body = args if len(args) > 1 else args[0]
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"{self.address}/{service_name}", data=data, method="POST",
            headers={"Content-Type": "application/json", **self.headers},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
        if not raw:
            return None
        out = json.loads(raw)
        if self.schema is not None:
            from google.protobuf import json_format

            _, _, out_cls = self.schema.method(service_name)
            msg = out_cls()
            json_format.ParseDict(out, msg, ignore_unknown_fields=True)
            return self.schema.result_to_value(service_name, msg)
        return out

    def close(self) -> None:
        pass


class GrpcExecutor:
    """Dynamic unary gRPC: method path from the proto's service definition,
    (de)serialization through the compiled message classes — no generated
    stubs needed (the reference uses protoreflect/grpcdynamic)."""

    def __init__(self, address: str, options: Dict[str, Any],
                 schema: Optional[ProtoServiceSchema]) -> None:
        if schema is None:
            raise EngineError("grpc services require a protobuf schema")
        import grpc

        self.schema = schema
        u = urlparse(address if "//" in address else f"grpc://{address}")
        self.target = u.netloc or address
        self.timeout = float(options.get("timeout", _DEFAULT_TIMEOUT_S * 1000)) / 1000.0
        self._channel = grpc.insecure_channel(self.target)

    def call(self, service_name: str, args: List[Any]) -> Any:
        full, in_cls, out_cls = self.schema.method(service_name)
        msg = self.schema.build_request(service_name, args)
        rpc = self._channel.unary_unary(
            f"/{full}/{service_name}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=out_cls.FromString,
        )
        resp = rpc(msg, timeout=self.timeout)
        return self.schema.result_to_value(service_name, resp)

    def close(self) -> None:
        self._channel.close()


class MsgpackExecutor:
    """msgpack-rpc over TCP: request [0, msgid, method, params], response
    [1, msgid, error, result] (executors_msgpack.go semantics)."""

    def __init__(self, address: str, options: Dict[str, Any],
                 schema: Optional[ProtoServiceSchema]) -> None:
        u = urlparse(address if "//" in address else f"tcp://{address}")
        self.host = u.hostname or "127.0.0.1"
        self.port = int(u.port or 0)
        self.timeout = float(options.get("timeout", _DEFAULT_TIMEOUT_S * 1000)) / 1000.0
        self.schema = schema
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            self._sock = s
        return self._sock

    def call(self, service_name: str, args: List[Any]) -> Any:
        import msgpack

        req = msgpack.packb([0, next(self._ids), service_name, list(args)])
        with self._lock:
            try:
                s = self._connect()
                s.sendall(req)
                unp = msgpack.Unpacker(raw=False)
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise EngineError("msgpack-rpc peer closed")
                    unp.feed(chunk)
                    for frame in unp:
                        if frame[0] == 1:
                            if frame[2] is not None:
                                raise EngineError(
                                    f"msgpack-rpc error: {frame[2]}")
                            return frame[3]
            except (OSError, socket.timeout):
                self.close()
                raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


_EXECUTORS = {
    "rest": RestExecutor,
    "grpc": GrpcExecutor,
    "msgpack-rpc": MsgpackExecutor,
}


def new_executor(protocol: str, address: str, options: Dict[str, Any],
                 schema: Optional[ProtoServiceSchema]):
    cls = _EXECUTORS.get(protocol)
    if cls is None:
        raise EngineError(f"unknown service protocol {protocol!r} "
                          f"(want rest/grpc/msgpack-rpc)")
    return cls(address, options, schema)
