"""Logical-plan optimizations (analogue of the reference's
internal/topo/planner/optimizer.go rules).

Two passes matter for this engine's shape:

- Predicate placement: _build_host_chain already sits WHERE before the
  window (push-down past windowing), and fused rules compile WHERE into the
  device fold. What remained was the decode edge:
- Column pruning (ColumnPruner in the reference): compute the set of
  columns the statement can ever read and drop everything else right where
  rows enter the rule — at the private source's micro-batcher or at the
  rule's shared-source entry (a pooled pipeline serves rules with different
  needs, so pruning is always per-rule). For wide payloads this shrinks
  every downstream batch, tuple materialization, and device upload.
"""
from __future__ import annotations

from typing import Optional, Set

from ..sql import ast


def referenced_columns(stmt: ast.SelectStatement) -> Optional[Set[str]]:
    """Every column name the statement can reference, or None when pruning
    is unsafe (wildcard anywhere — projection, count(*) args are fine — or
    a construct that reads whole rows)."""
    cols: Set[str] = set()
    for f in stmt.fields:
        if isinstance(f.expr, ast.Wildcard):
            return None
    # stmt.expressions() already yields join ON clauses and window exprs
    for root in stmt.expressions():
        if root is None:
            continue
        for node in ast.walk(root):
            if isinstance(node, ast.Wildcard):
                # e.g. an SRF/func over *: needs the whole row
                if not _is_countish_parent(root, node):
                    return None
            elif isinstance(node, ast.FieldRef):
                cols.add(node.name)
    return cols


def _is_countish_parent(root: ast.Expr, wc: ast.Wildcard) -> bool:
    """count(*)-style wildcards read no columns; any other wildcard does."""
    for node in ast.walk(root):
        # identity, not dataclass equality: two bare wildcards compare
        # equal, and the wrong parent would misattribute the wildcard
        if isinstance(node, ast.Call) and any(
            a is wc for a in getattr(node, "args", [])
        ):
            return node.name.lower() in ("count", "inc_count")
    return False
