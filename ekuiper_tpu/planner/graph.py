"""Graph API planner — analogue of PlanByGraph
(reference: internal/topo/planner/planner_graph.go:50-443).

Rules defined as a Node-RED-style JSON DAG instead of SQL:

    {"id": "g1", "graph": {
        "nodes": {
            "src":  {"type": "source",   "nodeType": "memory",
                     "props": {"datasource": "t"}},
            "flt":  {"type": "operator", "nodeType": "filter",
                     "props": {"expr": "temperature > 20"}},
            "out":  {"type": "sink",     "nodeType": "memory",
                     "props": {"topic": "res"}}},
        "topo": {"sources": ["src"],
                 "edges": {"src": ["flt"], "flt": ["out"]}}}}

Operator nodeTypes (planner_graph.go:118-240): filter, pick, function,
aggfunc, window, groupby, orderby, having, join, switch, watermark,
ratelimit, dedup_trigger. Light IO-kind compatibility checking mirrors
internal/topo/graph/io.go:69 (row vs collection producers/consumers).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..io import registry as io_registry
from ..io.converters import get_converter
from ..runtime.nodes_chain import DedupTriggerNode, RateLimitNode
from ..runtime.nodes_join import JoinNode
from ..runtime.nodes_ops import (
    AggregateNode, FilterNode, HavingNode, OrderNode, ProjectNode,
)
from ..runtime.node import Node
from ..runtime.nodes_source import SourceNode
from ..runtime.nodes_switch import SwitchNode
from ..runtime.nodes_window import WatermarkNode, WindowNode
from ..runtime.topo import Topo
from ..sql import ast
from ..sql.eval import Evaluator
from ..sql.parser import Parser
from ..utils.infra import PlanError
from .planner import _build_sink_chain, merged_options


# ------------------------------------------------------------ expr helpers
def _parse_expr(text: str) -> ast.Expr:
    return Parser(text).parse_expr()


def _parse_fields(field_specs: List[str]) -> List[ast.Field]:
    """Parse pick/function field specs: "expr [AS alias]" each — reuse the
    SELECT-list grammar."""
    p = Parser("SELECT " + ", ".join(field_specs) + " FROM __g")
    stmt = p.parse_select()
    return stmt.fields


class _GraphFuncNode(Node):
    """function/aggfunc operator: computes "expr as alias" and APPENDS the
    result to rows (affiliate/cal column), unlike pick which projects
    (reference: parseFunc, planner_graph.go:131-145)."""

    def __init__(self, name: str, fields: List[ast.Field], is_agg: bool,
                 **kw) -> None:
        super().__init__(name, op_type="op", **kw)
        self.fields = fields
        self.is_agg = is_agg
        self.ev = Evaluator()

    def process(self, item: Any) -> None:
        from ..data.batch import ColumnBatch
        from ..data.rows import GroupedTuplesSet, Row, WindowTuples

        if self.is_agg:
            if isinstance(item, GroupedTuplesSet):
                for g in item.groups:
                    for f in self.fields:
                        val = self.ev.eval(f.expr, g)
                        for r in g.rows():
                            r.set_cal_col(f.output_name, val)
                self.emit(item)
                return
            if isinstance(item, WindowTuples):
                for f in self.fields:
                    val = self.ev.eval(f.expr, item)
                    for r in item.rows():
                        r.set_cal_col(f.output_name, val)
                self.emit(item)
                return
            raise PlanError("aggfunc requires a window/grouped input")
        rows: List[Row]
        if isinstance(item, ColumnBatch):
            rows = item.to_tuples()
        elif isinstance(item, WindowTuples):
            rows = list(item.rows())
        elif isinstance(item, Row):
            rows = [item]
        else:
            self.emit(item)
            return
        for r in rows:
            for f in self.fields:
                r.set_cal_col(f.output_name, self.ev.eval(f.expr, r))
        if isinstance(item, ColumnBatch):
            # cal-cols live on the materialized tuples, not the batch — emit
            # rows one by one so downstream operator nodes process each
            for r in rows:
                self.emit(r)
        else:
            self.emit(item)


# ------------------------------------------------------------- IO kinds
# producers: what flows out; consumers: what must flow in
# "row" single rows/batches; "collection" windowed/grouped; "any" either
_OP_IO = {
    "filter": ("same", "any"),
    "pick": ("same", "any"),
    "function": ("same", "any"),
    "aggfunc": ("collection", "collection"),
    "window": ("collection", "row"),
    "groupby": ("collection", "collection"),
    "orderby": ("same", "collection"),
    "having": ("collection", "collection"),
    "join": ("collection", "collection"),
    "switch": ("same", "any"),
    "watermark": ("row", "row"),
    "ratelimit": ("same", "any"),
    "dedup_trigger": ("same", "any"),
}


def _check_io(graph: Dict[str, Any]) -> None:
    """Propagate produced kinds along edges, reject impossible links
    (analogue of graph.Fit, io.go:69)."""
    nodes = graph["nodes"]
    edges = graph.get("topo", {}).get("edges", {})
    produced: Dict[str, str] = {}
    for name, spec in nodes.items():
        if spec.get("type") == "source":
            produced[name] = "row"
    # simple fixpoint over the DAG (small graphs)
    for _ in range(len(nodes) + 1):
        for frm, tos in edges.items():
            if frm not in produced:
                continue
            for to in _flat(tos):
                spec = nodes.get(to)
                if spec is None:
                    raise PlanError(f"edge to undefined node {to}")
                if spec["type"] == "sink":
                    continue
                nt = (spec.get("nodeType") or "").lower()
                out_kind, in_kind = _OP_IO.get(nt, ("any", "any"))
                got = produced[frm]
                if in_kind != "any" and got != "any" and got != in_kind:
                    raise PlanError(
                        f"node {to} ({nt}) expects {in_kind} input but "
                        f"{frm} produces {got}")
                produced[to] = got if out_kind == "same" else out_kind


def _flat(tos: Any) -> List[str]:
    out: List[str] = []
    for t in tos:
        if isinstance(t, list):
            out.extend(t)
        else:
            out.append(t)
    return out


# --------------------------------------------------------------- planning
def plan_by_graph(rule, store) -> Topo:
    graph = rule.graph
    if not graph:
        raise PlanError("no graph")
    nodes_spec = graph.get("nodes") or {}
    topo_spec = graph.get("topo") or {}
    src_names = topo_spec.get("sources") or []
    edges = topo_spec.get("edges") or {}
    if not src_names:
        raise PlanError("graph has no sources")
    _check_io(graph)

    opts = merged_options(rule)
    topo = Topo(rule.id, qos=opts.qos,
                checkpoint_interval_ms=opts.checkpoint_interval_ms)
    built: Dict[str, Any] = {}
    sink_counter = [0]  # per-plan sink chain index

    for name, spec in nodes_spec.items():
        typ = spec.get("type")
        nt = (spec.get("nodeType") or "").lower()
        props = spec.get("props") or {}
        if typ == "source":
            if name not in edges:
                raise PlanError(f"no edge defined for source node {name}")
            connector = io_registry.create_source(nt)
            connector.configure(props.get("datasource", ""), props)
            conv = get_converter(props.get("format", "json"),
                                 delimiter=props.get("delimiter", ","))
            built[name] = SourceNode(
                name, connector, converter=conv,
                micro_batch_rows=opts.micro_batch_rows,
                linger_ms=opts.micro_batch_linger_ms,
                buffer_length=opts.buffer_length,
            )
            topo.add_source(built[name])
        elif typ == "sink":
            if name in edges:
                raise PlanError(f"sink {name} has edge")
            built[name] = ("sink", nt, props)  # assembled at wiring time
        elif typ == "operator":
            node = _build_operator(name, nt, props, opts, rule.id, store)
            built[name] = node
            topo.add_op(node)
        else:
            raise PlanError(f"unknown node type {typ!r} for {name}")

    # wiring
    for frm, tos in edges.items():
        src = built.get(frm)
        if src is None:
            raise PlanError(f"edge from undefined node {frm}")
        if isinstance(src, SwitchNode):
            if not tos or not all(isinstance(t, list) for t in tos):
                raise PlanError(
                    f"switch {frm}: edges must be nested per-case lists, "
                    f"e.g. [[\"a\"],[\"b\"]]")
            for case_idx, case_tos in enumerate(tos):
                if case_idx >= len(src.cases):
                    raise PlanError(
                        f"switch {frm}: more edge groups than cases")
                for to in case_tos:
                    dst = _sink_or_node(topo, built, to, opts, rule.id,
                                        store, sink_counter)
                    src.connect_case(case_idx, dst)
        else:
            for to in _flat(tos):
                dst = _sink_or_node(topo, built, to, opts, rule.id, store,
                                    sink_counter)
                src.connect(dst)
    return topo


def _sink_or_node(topo, built, to, opts, rule_id, store, counter):
    entry = built.get(to)
    if entry is None:
        raise PlanError(f"edge to undefined node {to}")
    if isinstance(entry, tuple) and entry[0] == "sink":
        _, nt, props = entry
        # the chain is built on first use; later edges reuse its head node
        tail = _Tail()
        _build_sink_chain(topo, tail, nt, props, counter[0], opts,
                          rule_id, store)
        counter[0] += 1
        built[to] = tail.head
        return tail.head
    return entry


class _Tail:
    """Shim standing in for the upstream of a sink chain: captures the chain's
    first node so graph edges can connect to it."""

    def __init__(self) -> None:
        self.head = None

    def connect(self, node):
        if self.head is None:
            self.head = node
        return node


def _build_operator(name: str, nt: str, props: Dict[str, Any], opts,
                    rule_id: str, store):
    if nt == "filter":
        return FilterNode(name, _parse_expr(props["expr"]),
                          buffer_length=opts.buffer_length)
    if nt == "pick":
        return ProjectNode(name, _parse_fields(props["fields"]),
                           rule_id=rule_id, buffer_length=opts.buffer_length)
    if nt in ("function", "aggfunc"):
        expr = props.get("expr")
        specs = [expr] if isinstance(expr, str) else list(expr)
        return _GraphFuncNode(name, _parse_fields(specs), is_agg=nt == "aggfunc",
                              buffer_length=opts.buffer_length)
    if nt == "window":
        return WindowNode(name, _parse_window(props),
                          is_event_time=opts.is_event_time, rule_id=rule_id,
                          buffer_length=opts.buffer_length)
    if nt == "groupby":
        dims = [_parse_expr(d) for d in props["dimensions"]]
        return AggregateNode(name, dims, buffer_length=opts.buffer_length)
    if nt == "orderby":
        sorts = [ast.SortField(name=s["field"],
                               ascending=not s.get("desc", False),
                               expr=_parse_expr(s["field"]))
                 for s in props["sorts"]]
        return OrderNode(name, sorts, buffer_length=opts.buffer_length)
    if nt == "having":
        return HavingNode(name, _parse_expr(props["expr"]), rule_id=rule_id,
                          buffer_length=opts.buffer_length)
    if nt == "join":
        stmt = _parse_join(props)
        return JoinNode(name, stmt.joins, left_name=stmt.sources[0].ref_name,
                        buffer_length=opts.buffer_length)
    if nt == "switch":
        cases = [_parse_expr(c) for c in props["cases"]]
        return SwitchNode(name, cases,
                          stop_at_first_match=bool(props.get("stopAtFirstMatch")),
                          buffer_length=opts.buffer_length)
    if nt == "watermark":
        return WatermarkNode(name, late_tolerance_ms=opts.late_tolerance_ms,
                             buffer_length=opts.buffer_length)
    if nt == "ratelimit":
        return RateLimitNode(name, interval_ms=int(props["interval"]),
                             buffer_length=opts.buffer_length)
    if nt == "dedup_trigger":
        return DedupTriggerNode(
            name, alias=props.get("aliasName", "dedup_trigger"),
            start_field=props.get("startField", "start"),
            end_field=props.get("endField", "end"),
            now_field=props.get("nowField", ""),
            expire_ms=int(props.get("expire", 3_600_000)),
            buffer_length=opts.buffer_length)
    if nt == "script":
        try:
            from ..plugin.script import ScriptOpNode
        except ImportError as exc:
            raise PlanError(f"script operator unavailable: {exc}")
        return ScriptOpNode(name, props.get("script", ""),
                            is_agg=bool(props.get("isAgg")),
                            buffer_length=opts.buffer_length)
    raise PlanError(f"unknown operator nodeType {nt!r} for {name}")


def _parse_window(props: Dict[str, Any]) -> ast.Window:
    """Graph window props {type, unit, size, interval} -> ast.Window
    (reference: parseWindow, planner_graph.go:638-700)."""
    wt_map = {
        "tumblingwindow": ast.WindowType.TUMBLING_WINDOW,
        "hoppingwindow": ast.WindowType.HOPPING_WINDOW,
        "slidingwindow": ast.WindowType.SLIDING_WINDOW,
        "sessionwindow": ast.WindowType.SESSION_WINDOW,
        "countwindow": ast.WindowType.COUNT_WINDOW,
    }
    wt = wt_map.get((props.get("type") or "").lower())
    if wt is None:
        raise PlanError(f"unknown window type {props.get('type')!r}")
    unit_map = {"dd": "DD", "hh": "HH", "mi": "MI", "ss": "SS", "ms": "MS"}
    unit = unit_map.get((props.get("unit") or "ss").lower(), "SS")
    return ast.Window(
        window_type=wt,
        time_unit=None if wt == ast.WindowType.COUNT_WINDOW else unit,
        length=int(props["size"]),
        interval=int(props.get("interval", 0)) or None,
    )


def _parse_join(props: Dict[str, Any]) -> ast.SelectStatement:
    """Graph join props {from, joins:[{name,type,on}]} -> parsed statement
    fragment (reference: parseJoinAst)."""
    frm = props["from"]
    parts = []
    for j in props.get("joins", []):
        jt = (j.get("type") or "inner").upper()
        parts.append(f"{jt} JOIN {j['name']} ON {j['on']}")
    sql = f"SELECT * FROM {frm} " + " ".join(parts)
    return Parser(sql).parse_select()
