"""Rule planner — analogue of eKuiper's planner.Plan (internal/topo/planner/
planner.go:39): parse SQL, load stream definitions, build the logical chain
(DataSource → AnalyticFuncs? → Window? → Filter → Join? → Aggregate → Having →
WindowFuncs? → Order → ProjectSet? → Project → sinks), then choose the
physical form:

**Fused device path** (the incremental-agg rewrite taken to its conclusion,
reference planner.go:910-999): processing-time TUMBLING/HOPPING/COUNT window
whose aggregates, WHERE and dimensions all compile to the device kernel →
SourceNode → FusedWindowAggNode → [Having] → [Order] → Project → sinks.

**Host path**: everything else, with the full operator chain and vectorized
filtering where expressions allow.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..data.types import Field as SchemaField, Schema
from ..functions import registry
from ..io import registry as io_registry
from ..ops.aggspec import extract_kernel_plan
from ..runtime.nodes_fused import FusedWindowAggNode
from ..runtime.nodes_join import JoinNode
from ..runtime.nodes_ops import (
    AggregateNode, AnalyticNode, FilterNode, HavingNode, OrderNode,
    ProjectNode, ProjectSetNode, WindowFuncNode,
)
from ..runtime.nodes_sink import SinkNode
from ..runtime.nodes_source import SourceNode
from ..runtime.nodes_window import WatermarkNode, WindowNode
from ..runtime.topo import Topo
from ..sql import ast
from ..sql.parser import parse_select
from ..utils.config import RuleOptionConfig, get_config
from ..utils.cron import parse_duration_ms
from ..utils.infra import PlanError, logger


@dataclass
class RuleDef:
    """Rule definition JSON shape (reference: internal/pkg/def/rule.go)."""

    id: str
    sql: str
    actions: List[Dict[str, Dict[str, Any]]] = field(default_factory=list)
    options: Dict[str, Any] = field(default_factory=dict)
    graph: Optional[Dict[str, Any]] = None  # graph-API rule (PlanByGraph)
    tags: List[str] = field(default_factory=list)  # rule.go Tags

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RuleDef":
        return RuleDef(
            id=d.get("id", ""),
            sql=d.get("sql", ""),
            actions=d.get("actions", []),
            options=d.get("options", {}),
            graph=d.get("graph"),
            tags=list(d.get("tags") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "id": self.id, "sql": self.sql,
            "actions": self.actions, "options": self.options,
        }
        if self.graph is not None:
            out["graph"] = self.graph
        if self.tags:
            out["tags"] = self.tags
        return out


def resolve_tier_budget_mb(opts: RuleOptionConfig) -> float:
    """The HBM budget (MB) driving a rule's tiered key-state placement
    (ops/tierstore.py): `tierHotMb` when set, else the engine-wide
    KUIPER_HBM_BUDGET_MB the QoS admission ledger already prices
    against; 0 disables. `tierStore="on"` without any budget is a plan
    error — a forced tier with no budget has no hot target."""
    mode = (opts.tier_store or "auto").lower()
    if mode == "off":
        return 0.0
    from ..ops.tierstore import env_hbm_budget_mb

    budget = float(opts.tier_hot_mb or 0)
    if budget <= 0:
        budget = env_hbm_budget_mb()
    if mode == "on" and budget <= 0:
        raise PlanError(
            "tierStore=on needs a budget: set tierHotMb or "
            "KUIPER_HBM_BUDGET_MB")
    return max(budget, 0.0)


def mesh_request(opts: RuleOptionConfig, plan=None) -> Dict[str, Any]:
    """The sharding decision for one rule, WITHOUT building a mesh (pure
    option/env parse — safe for explain, sharing store keys, and QoS
    pricing). Resolution order:

      1. `planOptimizeStrategy.mesh = {"rows": R, "keys": K}` — explicit
         geometry (the original opt-in; build failures are PlanErrors).
      2. `planOptimizeStrategy.shards = "auto" | K | "off"` — the serving
         mode: "auto" takes KUIPER_MESH when set, else every local device
         on the keys axis; an integer K puts K shards on the keys axis;
         "off"/0 pins the rule single-chip even under KUIPER_MESH.
      3. `KUIPER_MESH` env ("RxK", "K", or "auto") — the deployment-wide
         default for rules that say nothing.

    Returns {"mode": "sharded"|"single-chip", "cfg": dict|None,
    "source": str|None, "reason": str}. Auto/env selections degrade to
    single-chip (never PlanError) — the fallback reason lands in the
    explain "shards" section and the planner log."""
    from ..parallel.mesh import mesh_cfg_from_env

    strategy = getattr(opts, "plan_optimize_strategy", None) or {}
    explicit = strategy.get("mesh")
    if explicit:
        return {"mode": "sharded", "cfg": dict(explicit),
                "source": "planOptimizeStrategy.mesh",
                "reason": "explicit mesh geometry"}
    shards = strategy.get("shards")
    cfg, source = None, None
    if shards is not None:
        s = str(shards).strip().lower()
        if s in ("0", "off", "none", "false", "1"):
            return {"mode": "single-chip", "cfg": None,
                    "source": f"shards={shards}",
                    "reason": "sharding disabled by rule option"}
        if s == "auto":
            cfg = mesh_cfg_from_env() or {"auto": True}
            source = "shards=auto"
        else:
            try:
                k = int(s)
            except ValueError:
                raise PlanError(
                    f"invalid shards option {shards!r}: use 'auto', "
                    "'off', or a shard count")
            cfg = {"rows": 1, "keys": k}
            source = f"shards={k}"
    else:
        cfg = mesh_cfg_from_env()
        if cfg is not None:
            source = "KUIPER_MESH"
    if cfg is None:
        return {"mode": "single-chip", "cfg": None, "source": None,
                "reason": "no mesh requested"}
    if plan is not None and any(
            s.kind == "heavy_hitters" for s in plan.specs):
        return {"mode": "single-chip", "cfg": None, "source": source,
                "reason": "heavy_hitters state is node-local (value "
                          "dictionary) — single-chip kernel"}
    return {"mode": "sharded", "cfg": cfg, "source": source,
            "reason": "key-range-partitioned GROUP BY state across the "
                      "device mesh"}


def merged_options(rule: RuleDef) -> RuleOptionConfig:
    base = get_config().rule
    opts = RuleOptionConfig(**{**base.__dict__})
    alias = {
        "isEventTime": "is_event_time",
        "lateTolerance": "late_tolerance_ms",
        "bufferLength": "buffer_length",
        "sendError": "send_error",
        "checkpointInterval": "checkpoint_interval_ms",
        "qos": "qos",
        "concurrency": "concurrency",
        "debug": "debug",
        "planOptimizeStrategy": "plan_optimize_strategy",
        "tailMode": "tail_mode",
        "prefinalizeLeadMs": "prefinalize_lead_ms",
        "decodePoolSize": "decode_pool_size",
        "decodeShards": "decode_shards",
        "ingestRingDepth": "ingest_ring_depth",
        "ingestPrepUpload": "ingest_prep_upload",
        "slidingDevRingMb": "sliding_dev_ring_mb",
        "slidingImpl": "sliding_impl",
        "joinImpl": "join_impl",
        "analyticImpl": "analytic_impl",
        "sharedFold": "shared_fold",
        "tierStore": "tier_store",
        "tierHotMb": "tier_hot_mb",
        "tierScanMs": "tier_scan_ms",
    }
    for k, v in rule.options.items():
        key = alias.get(k, k)
        if not hasattr(opts, key):
            continue
        cur = getattr(opts, key)
        try:
            if key.endswith("_ms"):
                # int ms (reference form) or Go-style duration ('1s', '5m');
                # '' and bools would coerce to degenerate 0/1ms — reject
                if isinstance(v, bool) or (isinstance(v, str) and not v.strip()):
                    raise ValueError(f"not a duration: {v!r}")
                v = parse_duration_ms(v)
            elif isinstance(cur, bool):
                if isinstance(v, str):
                    low = v.strip().lower()
                    if low in ("true", "1"):
                        v = True
                    elif low in ("false", "0"):
                        v = False
                    else:
                        raise ValueError(f"not a boolean: {v!r}")
                else:
                    v = bool(v)
            elif isinstance(cur, int) and not isinstance(v, bool):
                v = int(v)
        except Exception as exc:
            raise PlanError(f"invalid rule option {k}={v!r}: {exc}") from exc
        setattr(opts, key, v)
    return opts


def load_stream_def(name: str, store) -> ast.StreamStmt:
    from ..sql.parser import parse

    table = store.kv("stream")
    raw, ok = table.get_ok(name)
    if not ok:
        table = store.kv("table")
        raw, ok = table.get_ok(name)
    if not ok:
        raise PlanError(f"stream {name} not found")
    stmt = parse(raw["sql"] if isinstance(raw, dict) else raw)
    if not isinstance(stmt, ast.StreamStmt):
        raise PlanError(f"definition of {name} is not a stream/table")
    return stmt


def schema_of(stream: ast.StreamStmt) -> Schema:
    return Schema(fields=[
        SchemaField(name=f.name, type=f.type, elem_type=f.elem_type)
        for f in stream.fields
    ])


# ---------------------------------------------------------------- analysis
def _analytic_calls(stmt: ast.SelectStatement) -> List[ast.Call]:
    out, seen = [], set()
    for root in stmt.expressions():
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and registry.is_analytic(node.name):
                if node.func_id not in seen:
                    seen.add(node.func_id)
                    out.append(node)
    return out


def _window_func_calls(stmt: ast.SelectStatement) -> List[ast.Call]:
    out = []
    for root in stmt.expressions():
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                fd = registry.lookup(node.name)
                if fd is not None and fd.ftype == registry.WINDOW_FUNC:
                    out.append(node)
    return out


def _srf_field(stmt: ast.SelectStatement) -> Optional[ast.Field]:
    for f in stmt.fields:
        if isinstance(f.expr, ast.Call) and registry.is_srf(f.expr.name):
            return f
    return None


def _has_aggregates(stmt: ast.SelectStatement) -> bool:
    for root in stmt.expressions():
        if ast.has_aggregate(root):
            return True
    return False


def device_path_eligible(
    stmt: ast.SelectStatement, opts: RuleOptionConfig
) -> Optional[Any]:
    """Returns the KernelPlan if the rule can take the fused device path."""
    if not opts.use_device_kernel:
        return None
    w = stmt.window
    if w is None:
        return None
    if w.window_type not in (
        ast.WindowType.TUMBLING_WINDOW,
        ast.WindowType.HOPPING_WINDOW,
        ast.WindowType.COUNT_WINDOW,
        ast.WindowType.SLIDING_WINDOW,
        ast.WindowType.SESSION_WINDOW,
        ast.WindowType.STATE_WINDOW,
    ):
        return None
    # event-time sessions: the per-session structure resolves host-side at
    # watermark time (sort/split), then each session is a plain pane-0 fold
    # + sync finalize — both run through the sharded kernel, so mesh is OK
    if w.window_type == ast.WindowType.STATE_WINDOW:
        from ..sql.compiler import try_compile

        # device state windows: vectorizable begin/emit conditions.
        # Event time OK — the watermark node orders rows, after which the
        # begin/emit toggle scan is identical to processing time (the host
        # path's _ingest_row STATE branch is watermark-agnostic too).
        # Mesh OK — the toggle scan runs host-side; span folds + the sync
        # finalize run through the sharded kernel like any other window.
        # A WHERE clause filters BEFORE the window on the host path — a
        # filtered row must not toggle the window, so such rules stay
        # host-side (the same pre/post-WHERE divergence as COUNT windows)
        if stmt.condition is not None:
            return None
        if try_compile(w.begin_condition, mode="host") is None or \
                try_compile(w.emit_condition, mode="host") is None:
            return None
    if w.window_type == ast.WindowType.SLIDING_WINDOW:
        from ..sql.compiler import try_compile

        # device sliding: processing-time, trigger-gated (per-row emission
        # without a condition belongs on the exact host path). Mesh OK:
        # pane-vector folds, the scratch refold, and the dyn finalize all
        # run sharded (parallel/sharded.py); heavy_hitters plans are
        # already mesh-excluded below (node-local value dictionary)
        if opts.is_event_time:
            return None
        if w.trigger_condition is None or try_compile(
            w.trigger_condition, mode="host"
        ) is None:
            return None
    # event-time COUNT: the watermark node late-drops + orders rows, after
    # which a count window folds exactly like processing time (the host
    # path's _ingest_row is watermark-agnostic too, nodes_window.py:235)
    # event-time × mesh: supported — the sharded kernel routes per-row pane
    # vectors under shard_map (parallel/sharded.py _build_fold_vec), with
    # the scalar fast path for single-bucket batches
    if opts.is_event_time and w.window_type in (
        ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW
    ):
        # pane ids ship as uint8 — shapes needing >255 live panes (window
        # span + late-tolerance slack) stay on the host buffering path
        bucket = (w.interval_ms()
                  if w.window_type == ast.WindowType.HOPPING_WINDOW
                  and w.interval_ms() else w.length_ms())
        span = max(w.length_ms() // max(bucket, 1), 1)
        slack = -(-max(opts.late_tolerance_ms, 0) // max(bucket, 1))
        if max(span + slack + 2, 4) > 255:
            return None
    if w.window_type == ast.WindowType.COUNT_WINDOW:
        if w.interval:
            return None  # overlapping count windows -> host buffering
        if stmt.condition is not None:
            # count-window length counts post-WHERE rows (host path filters
            # before the window); the kernel can't know the filtered count
            # per batch without a sync, so keep these on the host path
            return None
    if w.window_type == ast.WindowType.HOPPING_WINDOW:
        iv, ln = w.interval or 0, w.length or 0
        if iv <= 0 or iv > ln or ln % iv != 0:
            # pane decomposition requires interval | length; otherwise merged
            # panes would span more time than the window
            return None
    if w.filter is not None:
        return None
    if (w.trigger_condition is not None
            and w.window_type != ast.WindowType.SLIDING_WINDOW):
        return None
    if stmt.joins or _srf_field(stmt) or _analytic_calls(stmt) or _window_func_calls(stmt):
        return None
    dims: List[ast.FieldRef] = []
    for d in stmt.dimensions:
        if not isinstance(d.expr, ast.FieldRef):
            return None
        dims.append(d.expr)
    dim_names = {d.name for d in dims}
    allowed_scalars = {"window_start", "window_end", "window_trigger"}
    for f in stmt.fields:
        if isinstance(f.expr, ast.Wildcard):
            return None
        for node in ast.walk(f.expr):
            if isinstance(node, ast.FieldRef) and not _under_agg(f.expr, node):
                if node.name not in dim_names:
                    return None
            if isinstance(node, ast.Call) and not registry.is_aggregate(node.name):
                fd = registry.lookup(node.name)
                if fd is None:
                    return None
                if fd.ftype != registry.SCALAR or fd.stateful:
                    if node.name not in allowed_scalars:
                        return None
    if stmt.having is not None:
        for node in ast.walk(stmt.having):
            if isinstance(node, ast.FieldRef) and not _under_agg(stmt.having, node):
                if node.name not in dim_names:
                    return None
    # ORDER BY exprs must read only dims or kernel aggregates — groups carry
    # a single synthetic representative row
    for sf in stmt.sorts:
        expr = sf.expr if sf.expr is not None else ast.FieldRef(sf.name, sf.stream)
        for node in ast.walk(expr):
            if isinstance(node, ast.FieldRef) and not _under_agg(expr, node):
                if node.name not in dim_names:
                    return None
    plan = extract_kernel_plan(stmt)
    if plan is not None and any(
        s.kind == "heavy_hitters" for s in plan.specs
    ):
        # heavy_hitters: the reversible value dictionary lives on the single
        # fused node (codes are node-local), so the sharded kernel is out;
        # and the result is a list — it must be a bare SELECT field, not an
        # operand of HAVING/ORDER/composite expressions
        if (opts.plan_optimize_strategy or {}).get("mesh"):
            return None
        roots = ([stmt.having] if stmt.having is not None else []) + [
            sf.expr for sf in stmt.sorts if sf.expr is not None
        ]
        for f in stmt.fields:
            if not (isinstance(f.expr, ast.Call)
                    and f.expr.name == "heavy_hitters"):
                roots.append(f.expr)
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Call) and node.name == "heavy_hitters":
                    return None
    return plan


def _under_agg(root: ast.Expr, target: ast.Expr) -> bool:
    """Is `target` inside an aggregate call within `root`?"""
    found = [False]

    def walk_in(e: ast.Expr, in_agg: bool) -> None:
        if e is target and in_agg:
            found[0] = True
            return
        child_in_agg = in_agg or (
            isinstance(e, ast.Call) and registry.is_aggregate(e.name)
        )
        for c in e.children():
            walk_in(c, child_in_agg)

    walk_in(root, False)
    return found[0]


# ------------------------------------------------------------------- build
def plan_rule(rule: RuleDef, store) -> Topo:
    if rule.graph is not None:
        from .graph import plan_by_graph

        return plan_by_graph(rule, store)
    if not rule.sql:
        raise PlanError("rule has no sql")
    stmt = parse_select(rule.sql)
    opts = merged_options(rule)
    topo = Topo(
        rule.id, qos=opts.qos, checkpoint_interval_ms=opts.checkpoint_interval_ms
    )

    # joined tables that are registered lookup TABLEs get a LookupJoinNode;
    # joined STREAMs get their own source + the stream-stream JoinNode
    lookup_joins: List[ast.Join] = []
    stream_joins: List[ast.Join] = []
    for j in stmt.joins:
        if _is_lookup_table(j.table.name, store):
            lookup_joins.append(j)
        else:
            stream_joins.append(j)

    if stream_joins and stmt.window is None:
        # same contract as the reference: stream-stream joins pair rows
        # WITHIN a window collection (join_operator.go); without one the
        # pairing set is undefined
        raise PlanError("stream-stream JOIN requires a window")

    # sources — shared via the subtopo pool (one ingest+decode pipeline per
    # stream config, reference subtopo_pool.go:34) when the rule is qos=0;
    # checkpointed rules keep a private source so barriers stay rule-scoped
    stream_tbls = list(stmt.sources) + [j.table for j in stream_joins]
    # alias-qualified refs resolve against the emitter name, so any join
    # (including lookup-only) keeps ref_name naming
    multi = len(stream_tbls) > 1 or bool(stmt.joins)
    # column pruning (optimizer.py ColumnPruner analogue): drop columns the
    # statement can never read at the rule's ingest edge
    from .optimizer import referenced_columns

    needed = referenced_columns(stmt)
    kernel_plan = device_path_eligible(stmt, opts)
    # expression host fallbacks: when the ONLY thing keeping this rule
    # off the fused device path is an uncompilable expression, count it
    # (kuiper_expr_host_fallback_total{reason}) so the health plane can
    # name host expression eval instead of binning it as "other"
    from ..ops.aggspec import take_expr_fallbacks
    from ..sql.compiler import record_host_fallback

    expr_notes = take_expr_fallbacks()
    if kernel_plan is None and expr_notes:
        for note in expr_notes:
            record_host_fallback(note["reason"])
        logger.info(
            "rule %s: host expression path — %s", rule.id,
            "; ".join(f"{n['kind']}: {n['reason']}" for n in expr_notes))

    # shared pane fold (planner/sharing.py): correlated rules over one
    # stream fold once into a pooled pane store and combine per window —
    # when the rewrite applies, the rule needs no per-rule source entry at
    # all (its data flows source → shared fold → its emit hop)
    tail = None
    if kernel_plan is not None and len(stream_tbls) == 1 and not stmt.joins:
        from .sharing import try_plan_shared

        tail = try_plan_shared(topo, stmt, kernel_plan, opts, rule, store)

    if tail is None:
        source_nodes: List[SourceNode] = []
        for tbl in stream_tbls:
            src_name = tbl.ref_name if multi else tbl.name
            source_nodes.append(
                _plan_stream_source(tbl.name, src_name, opts, store, topo,
                                    project_columns=needed))

        if kernel_plan is not None and len(source_nodes) == 1 \
                and not lookup_joins:
            tail = _build_device_chain(
                topo, stmt, kernel_plan, source_nodes[0], opts,
                rule_id=rule.id
            )
        else:
            tail = _build_host_chain(
                topo, stmt, source_nodes, opts, rule.id,
                stream_joins=stream_joins, lookup_joins=lookup_joins,
                store=store,
                source_names=[t.ref_name if multi else t.name
                              for t in stream_tbls])

    # sinks
    actions = rule.actions or [{"log": {}}]
    for i, action in enumerate(actions):
        for sink_type, props in action.items():
            _build_sink_chain(topo, tail, sink_type, props or {}, i, opts,
                              rule.id, store)
    return topo


def plan_rule_group(group_id: str, rules: List[RuleDef], store) -> Topo:
    """Plan N homogeneous rules as ONE topology: shared ingest, one
    vmapped device program (parallel/multirule.py), per-rule sink chains.
    The rules must share a single source and be identical up to numeric
    literals in WHERE; all run at qos=0 (the group is a fan-out optimization,
    reference test/benchmark/multiple_rules)."""
    from ..ops.emit import build_direct_emit
    from ..parallel.multirule import build_rule_batch
    from ..runtime.nodes_multirule import MultiRuleFusedNode
    from ..runtime.subtopo import SharedEntryNode

    if not rules:
        raise PlanError("empty rule group")
    stmts = [parse_select(r.sql) for r in rules]
    srcs = {tuple(t.name for t in s.sources) for s in stmts}
    if len(srcs) != 1 or len(stmts[0].sources) != 1:
        raise PlanError("rule group must share exactly one source stream")
    try:
        spec = build_rule_batch([r.id for r in rules], stmts)
    except ValueError as exc:
        raise PlanError(str(exc))
    stmt = spec.stmt
    opts = merged_options(rules[0])
    opts.qos = 0
    topo = Topo(group_id, qos=0)
    from .optimizer import referenced_columns

    needed = referenced_columns(stmt)
    if needed is not None:
        # canonicalized WHERE literals are injected params, not columns
        needed = {c for c in needed if not c.startswith("__param_")}
    src = _plan_stream_source(stmt.sources[0].name, stmt.sources[0].name,
                              opts, store, topo, project_columns=needed)
    dims = [d.expr for d in stmt.dimensions]
    direct = build_direct_emit(stmt, spec.plan, [d.name for d in dims])
    if direct is None:
        raise PlanError("rule group tail is not vectorizable")
    node = MultiRuleFusedNode(
        "group_agg", stmt.window, spec, dims=dims,
        capacity=opts.key_slots, micro_batch=opts.micro_batch_rows,
        direct_emit=direct, emit_columnar=opts.emit_columnar,
        buffer_length=opts.buffer_length,
    )
    topo.add_op(node)
    src.connect(node)
    for r in rules:
        entry = SharedEntryNode(f"{r.id}_out", buffer_length=opts.buffer_length)
        topo.add_op(entry)
        node.add_rule_output(r.id, entry)
        actions = r.actions or [{"log": {}}]
        for i, action in enumerate(actions):
            for sink_type, props in action.items():
                _build_sink_chain(topo, entry, sink_type, props or {}, i,
                                  opts, r.id, store)
    return topo


def _is_lookup_table(name: str, store) -> bool:
    _, ok = store.kv("table").get_ok(name)
    return ok


def _make_lookup_join_node(lj: ast.Join, k: int, opts, store):
    from ..runtime.nodes_join import LookupJoinNode

    tdef = load_stream_def(lj.table.name, store)
    tprops = _source_props(tdef, store)
    if tdef.options.key:
        tprops.setdefault("key", tdef.options.key)
    lookup = io_registry.create_lookup(tdef.options.type or "memory")
    lookup.configure(tdef.options.datasource, tprops)
    return LookupJoinNode(
        f"lookup_join_{k}" if k else "lookup_join", lookup, lj,
        key_fields=_equality_key_fields(lj),
        cache_ttl_ms=int(tprops.get("cacheTtl", 60_000)),
        buffer_length=opts.buffer_length,
    )


def _stream_side_qualifiers(join: ast.Join) -> set:
    """Stream aliases referenced by the ON clause's non-table sides — the
    chains a LookupJoinNode must sit on."""
    table = join.table.ref_name
    out = set()
    if join.on is not None:
        for node in ast.walk(join.on):
            if isinstance(node, ast.FieldRef) and node.stream and \
                    node.stream != table:
                out.add(node.stream)
    return out


def _equality_key_fields(join: ast.Join) -> List:
    """(stream_field, table_field) pairs from an equality ON clause; exactly
    one side of each equality must be qualified by the joined table's
    ref_name (silently guessing would query the wrong column)."""
    table = join.table.ref_name
    pairs = []

    def walk(e):
        if isinstance(e, ast.BinaryExpr):
            if e.op == "AND":
                walk(e.lhs)
                walk(e.rhs)
                return
            if e.op == "=" and isinstance(e.lhs, ast.FieldRef) and isinstance(
                e.rhs, ast.FieldRef
            ):
                if e.lhs.stream == table and e.rhs.stream != table:
                    pairs.append((e.rhs.name, e.lhs.name))
                    return
                if e.rhs.stream == table and e.lhs.stream != table:
                    pairs.append((e.lhs.name, e.rhs.name))
                    return
                raise PlanError(
                    f"lookup join ON equality must qualify exactly one side "
                    f"with the table alias {table!r}: {e!r}")
        raise PlanError(
            f"lookup join ON clause must be equality conditions, got {e!r}")

    if join.on is not None:
        walk(join.on)
    return pairs


def _with_ts_field(project_columns, stream, opts):
    """Pruning set + the event-time timestamp field (which the stream must
    always retain) — THE one definition, shared by the subtopo builder and
    the per-rule entry projection so the two can never drift."""
    ts_field = stream.options.timestamp if opts.is_event_time else ""
    if project_columns is not None and ts_field:
        return set(project_columns) | {ts_field}
    return project_columns


def _subtopo_spec(stream_name: str, src_name: str, opts, store,
                  project_columns=None):
    """(subtopo pool key, node builder, stream def) for one stream's
    shareable ingest pipeline — factored out of _plan_stream_source so the
    shared-fold pass (planner/sharing.py) can key its pane stores on the
    same identity without planning a per-rule entry."""
    stream = load_stream_def(stream_name, store)
    props = _source_props(stream, store)
    ts_field = stream.options.timestamp if opts.is_event_time else ""
    project_columns = _with_ts_field(project_columns, stream, opts)

    def build_nodes(name=src_name):
        nodes = []
        stype = stream.options.type or "memory"
        connector = io_registry.create_source(stype)
        connector.configure(stream.options.datasource, props)
        from ..io.converters import get_converter

        converter = get_converter(
            stream.options.format or "json",
            delimiter=stream.options.delimiter or ",",
            fields=[f.name for f in stream.fields] or None,
            schema_id=stream.options.schemaid,
        )
        if props.get("decompression"):
            # bytes payloads are decompressed before FORMAT decode
            # (reference: planner_source.go decompress stage)
            from ..utils.codecs import get_compressor

            _, decomp = get_compressor(props["decompression"])
            converter = _DecompressingConverter(converter, decomp)
        if props.get("decryption"):
            from ..utils.codecs import get_encryptor

            converter = _DecryptingConverter(
                converter, get_encryptor(props["decryption"], props))
        node = SourceNode(
            name, connector, converter=converter,
            schema=schema_of(stream),
            timestamp_field=ts_field,
            strict_validation=stream.options.strict_validation,
            micro_batch_rows=opts.micro_batch_rows,
            linger_ms=opts.micro_batch_linger_ms,
            buffer_length=opts.buffer_length,
            decode_pool_size=opts.decode_pool_size,
            decode_shards=opts.decode_shards,
            ring_depth=opts.ingest_ring_depth,
            prep_upload=opts.ingest_prep_upload,
            # private pipeline: prune at decode. Shared pipelines must stay
            # unpruned (other riders need other columns) — see the entry.
            project_columns=(None if opts.share_source and opts.qos == 0
                             else project_columns),
        )
        nodes.append(node)
        # per-interval latest-batch throttle (planner_source.go:146). A
        # dedicated prop, NOT `interval`: poll sources (file/httppull/
        # simulator) already use `interval` as their poll period.
        if props.get("rateLimitInterval"):
            from ..runtime.nodes_chain import RateLimitNode

            rl = RateLimitNode(f"{name}_ratelimit",
                               interval_ms=int(props["rateLimitInterval"]),
                               buffer_length=opts.buffer_length)
            node.connect(rl)
            nodes.append(rl)
        return nodes

    from ..runtime import subtopo as subtopo_pool

    key = subtopo_pool.subtopo_key(stream_name, {
        # everything that changes what the pipeline emits, including the
        # emitter name (join rules match rows by emitter == alias) and
        # the connector identity (type/datasource can change across
        # DROP/CREATE STREAM between plans)
        "name": src_name,
        "type": stream.options.type or "memory",
        "datasource": stream.options.datasource,
        "props": props,
        "format": stream.options.format or "json",
        "fields": [f.name for f in stream.fields],
        "ts": ts_field,
        "strict": stream.options.strict_validation,
        "mb": opts.micro_batch_rows,
        "linger": opts.micro_batch_linger_ms,
        "pool": [opts.decode_pool_size, opts.decode_shards,
                 opts.ingest_ring_depth, opts.ingest_prep_upload],
    })
    return key, build_nodes, stream


def _plan_stream_source(stream_name: str, src_name: str, opts, store,
                        topo: Topo, project_columns=None):
    """Build (or ride) the ingest+decode pipeline for one stream: a pooled
    shared subtopo for qos=0 rules, a topo-private SourceNode otherwise.
    Returns the node rule chains connect to."""
    key, build_nodes, stream = _subtopo_spec(
        stream_name, src_name, opts, store, project_columns=project_columns)
    project_columns = _with_ts_field(project_columns, stream, opts)

    if opts.share_source and opts.qos == 0:
        from ..runtime.subtopo import SharedEntryNode, SubTopoRef

        entry = SharedEntryNode(f"{src_name}_shared",
                                project_columns=project_columns,
                                buffer_length=opts.buffer_length)
        topo.add_op(entry)
        topo.add_shared_source(SubTopoRef(key, build_nodes), entry)
        return entry

    if opts.share_source and opts.qos > 0:
        # explicit, logged fallback (ISSUE 4 satellite): the qos=0-only
        # restriction on pooled pipelines was silent convention before —
        # checkpoint barriers are rule-scoped and cannot flow through a
        # pipeline serving other rules
        logger.info(
            "rule %s: qos=%d requires rule-scoped checkpoint barriers — "
            "using a private source pipeline (shared subtopos and shared "
            "folds serve qos=0 rules only)", topo.rule_id, opts.qos)

    nodes = build_nodes()
    topo.add_source(nodes[0])
    for extra in nodes[1:]:
        topo.add_op(extra)
    return nodes[-1]


def _build_sink_chain(topo: Topo, tail, sink_type: str, props: Dict[str, Any],
                      idx: int, opts: RuleOptionConfig, rule_id: str,
                      store) -> None:
    """Assemble the per-action sink chain (planner_sink.go:36-253):
    [batch] → [encode] → [compress] → [encrypt] → [cache] → sink."""
    from ..io.converters import get_converter
    from ..runtime.nodes_chain import (
        BatchNode, CacheNode, CompressNode, EncryptNode,
    )

    head = tail
    batch_size = int(props.get("batchSize", 0))
    linger_ms = int(props.get("lingerInterval", 0))
    if batch_size > 0 or linger_ms > 0:
        node = BatchNode(f"{sink_type}_{idx}_batch", size=batch_size,
                         linger_ms=linger_ms, buffer_length=opts.buffer_length)
        topo.add_op(node)
        head = head.connect(node)
    # bytes stages only make sense for bytes-capable sinks (file/mqtt/...);
    # FORMAT-encoding for them happens inside the sink itself unless a
    # compression/encryption stage forces an explicit encode here
    compression = props.get("compression", "")
    encryption = props.get("encryption", "")
    transform_in_chain = bool(compression or encryption)
    if transform_in_chain:
        # transform must precede encode so the projected/templated payload is
        # what gets compressed/encrypted (planner_sink.go chain order); the
        # terminal SinkNode then passes opaque payloads through untouched
        from ..runtime.nodes_chain import EncodeNode, TransformNode

        tr = TransformNode(
            f"{sink_type}_{idx}_transform",
            send_single=bool(props.get("sendSingle", False)),
            fields=props.get("fields"),
            exclude_fields=props.get("excludeFields"),
            data_template=props.get("dataTemplate", ""),
            omit_if_empty=bool(props.get("omitIfEmpty", False)),
            buffer_length=opts.buffer_length,
        )
        topo.add_op(tr)
        head = head.connect(tr)
        conv = get_converter(props.get("format", "json"),
                             delimiter=props.get("delimiter", ","),
                             schema_id=props.get("schemaId", ""))
        enc = EncodeNode(f"{sink_type}_{idx}_encode", conv,
                         buffer_length=opts.buffer_length)
        topo.add_op(enc)
        head = head.connect(enc)
    if compression:
        node = CompressNode(f"{sink_type}_{idx}_compress", compression,
                            buffer_length=opts.buffer_length)
        topo.add_op(node)
        head = head.connect(node)
    if encryption:
        node = EncryptNode(f"{sink_type}_{idx}_encrypt", encryption, props,
                           buffer_length=opts.buffer_length)
        topo.add_op(node)
        head = head.connect(node)
    cache_node = None
    if props.get("enableCache"):
        cache_node = CacheNode(
            f"{sink_type}_{idx}_cache",
            store_kv=store.kv(f"sinkcache:{rule_id}:{sink_type}_{idx}"),
            memory_threshold=int(props.get("memoryCacheThreshold", 1024)),
            max_disk_cache=int(props.get("maxDiskCache", 1024 * 1024)),
            resend_interval_ms=int(props.get("resendInterval", 100)),
            buffer_length=opts.buffer_length,
        )
        topo.add_op(cache_node)
        head = head.connect(cache_node)
    sink = io_registry.create_sink(sink_type)
    sink.configure(props)
    node = SinkNode(
        f"{sink_type}_{idx}",
        sink,
        send_single=(not transform_in_chain
                     and bool(props.get("sendSingle", False))),
        fields=None if transform_in_chain else props.get("fields"),
        exclude_fields=(None if transform_in_chain
                        else props.get("excludeFields")),
        data_template=("" if transform_in_chain
                       else props.get("dataTemplate", "")),
        omit_if_empty=(not transform_in_chain
                       and bool(props.get("omitIfEmpty", False))),
        retry_count=int(props.get("retryCount", 0)),
        retry_interval_ms=int(props.get("retryInterval", 1000)),
        cache_node=cache_node,
        buffer_length=opts.buffer_length,
    )
    topo.add_sink(node)
    head.connect(node)


class _DecompressingConverter:
    """Wrap a FORMAT converter so bytes payloads are decompressed first
    (reference: planner_source.go decompress stage)."""

    def __init__(self, inner, decompress) -> None:
        self._inner = inner
        self._decompress = decompress

    def decode(self, raw):
        return self._inner.decode(self._decompress(bytes(raw)))

    def encode(self, data):
        return self._inner.encode(data)


class _DecryptingConverter:
    """Wrap a FORMAT converter so bytes payloads are decrypted first."""

    def __init__(self, inner, encryptor) -> None:
        self._inner = inner
        self._enc = encryptor

    def decode(self, raw):
        return self._inner.decode(self._enc.decrypt(bytes(raw)))

    def encode(self, data):
        return self._inner.encode(data)


def _source_props(stream: ast.StreamStmt, store) -> Dict[str, Any]:
    """Source props from conf_key profiles stored in the config KV
    (reference: internal/conf/yaml_config_ops.go)."""
    props: Dict[str, Any] = {}
    if stream.options.conf_key:
        conf = store.kv("source_conf")
        stored, ok = conf.get_ok(
            f"{stream.options.type or 'memory'}:{stream.options.conf_key}"
        )
        if ok and isinstance(stored, dict):
            props.update(stored)
    return props


def _build_device_chain(
    topo: Topo, stmt, kernel_plan, src: SourceNode, opts: RuleOptionConfig,
    rule_id: str,
):
    from ..ops.emit import build_direct_emit

    dims = [d.expr for d in stmt.dimensions]
    # full fusion: compile HAVING/ORDER/LIMIT/projection into the vectorized
    # emit tail when possible — the whole rule becomes fold + direct emit
    direct = build_direct_emit(stmt, kernel_plan, [d.name for d in dims])
    mesh = None
    req = mesh_request(opts, kernel_plan)
    shard_info: Dict[str, Any] = {k: req.get(k)
                                  for k in ("mode", "source", "reason")}
    if req["mode"] == "sharded":
        from ..parallel.mesh import mesh_from_options, resolve_auto_cfg

        cfg = req["cfg"]
        explicit = req["source"] == "planOptimizeStrategy.mesh"
        try:
            resolved = resolve_auto_cfg(cfg)
            if resolved is None:
                raise ValueError("fewer than 2 devices visible")
            mesh = mesh_from_options(resolved)
            shard_info["mesh"] = dict(resolved)
            shard_info["shards"] = int(resolved["keys"])
        except Exception as exc:
            if explicit:
                raise PlanError(f"cannot build device mesh {cfg}: {exc}")
            # auto/env selection degrades to the single-chip kernel —
            # a deployment-wide KUIPER_MESH must not brick rule create
            # on a 1-device box
            mesh = None
            shard_info = {"mode": "single-chip", "source": req["source"],
                          "reason": f"mesh unavailable ({exc}) — "
                                    "single-chip fallback"}
            logger.info("rule %s: %s", rule_id, shard_info["reason"])
    # sliding ring geometry is chosen HERE, at plan time, from the
    # window/delay/pane declarations (ops/slidingring.py) — the node and
    # the jitcert certificates both consume the same layout
    ring_layout = None
    if stmt.window.window_type == ast.WindowType.SLIDING_WINDOW:
        from ..ops.slidingring import ring_layout_for

        # budget-aware geometry: wide sketch plans (hll front stacks)
        # coarsen their buckets until the ring's static HBM footprint
        # fits slidingDevRingMb, instead of silently refolding
        ring_layout = ring_layout_for(
            stmt.window, kernel_plan, capacity=opts.key_slots,
            budget_mb=opts.sliding_dev_ring_mb)
    # tiered key state (ops/tierstore.py): resolve the HBM budget that
    # drives the hot/cold placement at PLAN time. Gated off for shapes
    # where spilled-group emission can't ride the direct tail (ORDER BY /
    # LIMIT order across the device+spilled split) and for mesh kernels;
    # the node itself gates window types and heavy_hitters.
    tier_budget_mb = resolve_tier_budget_mb(opts)
    if tier_budget_mb and (stmt.sorts or stmt.limit is not None
                           or mesh is not None):
        tier_budget_mb = 0.0
    fused = FusedWindowAggNode(
        "window_agg", stmt.window, kernel_plan, dims,
        capacity=opts.key_slots, micro_batch=opts.micro_batch_rows,
        rule_id=rule_id, buffer_length=opts.buffer_length,
        direct_emit=direct, mesh=mesh,
        prefinalize_lead_ms=opts.prefinalize_lead_ms,
        tail_mode=opts.tail_mode,
        emit_columnar=opts.emit_columnar,
        is_event_time=opts.is_event_time,
        late_tolerance_ms=opts.late_tolerance_ms,
        dev_ring_budget_mb=opts.sliding_dev_ring_mb,
        sliding_impl=opts.sliding_impl,
        ring_layout=ring_layout,
        tier_budget_mb=tier_budget_mb,
        tier_scan_ms=opts.tier_scan_ms,
    )
    fused.shard_info = shard_info  # explain/status "shards" section twin
    topo.add_op(fused)
    # hand the kernel-input shape to the source's ingest prep at PLAN time
    # (runtime/ingest.py IngestPrepCtx): the decode pool's upload stage then
    # pre-encodes keys + device_puts kernel columns from the FIRST batch.
    # Paths without the hook (rate-limited chains, host path) still get
    # registered by the fused node's first _shared_device_inputs call.
    reg = getattr(src, "register_prep_spec", None)
    if reg is not None and getattr(fused.gb, "accepts_device_inputs", False) \
            and fused.wt != ast.WindowType.SLIDING_WINDOW:
        # sliding excluded: its folds upload through _upload_sliding_inputs
        # (whose pre-padded buffers the _dev_ring must own for trigger-time
        # mask refolds) — a prep upload would be a second, unused copy
        reg(fused.prep_spec())
    if fused.tier is not None:
        # async prefetch: the decode pool's ordered drainer spots
        # returning demoted keys in batch k+1 and starts their packed
        # rows' H2D copy while batch k still folds (runtime/ingest.py)
        reg2 = getattr(src, "register_tier_prefetch", None)
        if reg2 is not None:
            reg2(fused.tier.prefetch)
    if opts.is_event_time:
        # event-time: watermark generation + late drop feeds the kernel's
        # per-row pane routing (columnar all the way)
        wm = WatermarkNode("watermark",
                           late_tolerance_ms=opts.late_tolerance_ms,
                           buffer_length=opts.buffer_length)
        topo.add_op(wm)
        src.connect(wm)
        wm.connect(fused)
    else:
        src.connect(fused)
    if direct is not None:
        return fused  # tail ops folded into the vectorized emit
    tail = fused
    if stmt.having is not None:
        hv = HavingNode("having", stmt.having, rule_id=rule_id,
                        buffer_length=opts.buffer_length)
        topo.add_op(hv)
        tail = tail.connect(hv)
    if stmt.sorts:
        on = OrderNode("order", stmt.sorts, buffer_length=opts.buffer_length)
        topo.add_op(on)
        tail = tail.connect(on)
    proj = ProjectNode("project", stmt.fields, rule_id=rule_id,
                       limit=stmt.limit, buffer_length=opts.buffer_length)
    topo.add_op(proj)
    return tail.connect(proj)


def _make_join_node(stmt, stream_joins, opts: RuleOptionConfig,
                    rule_id: str) -> JoinNode:
    """Stream-stream join operator: the device ring when the ON clause
    lowers (planner/relational.py), else the host nested loop — with the
    structured reason recorded so /explain and the fallback counter name
    exactly why the plan stayed on host."""
    left = stmt.sources[0].ref_name
    if opts.join_impl == "device":
        from ..sql.compiler import record_host_fallback
        from ..sql.expr_ir import NotVectorizable

        from . import relational
        from ..runtime.nodes_relational import DeviceJoinNode

        try:
            lowering = relational.lower_join(stmt, stream_joins)
            return DeviceJoinNode("join", stream_joins, left_name=left,
                                  lowering=lowering,
                                  buffer_length=opts.buffer_length)
        except NotVectorizable as nv:
            record_host_fallback(nv.reason)
    return JoinNode("join", stream_joins, left_name=left,
                    buffer_length=opts.buffer_length)


def _make_analytic_node(stmt, analytic, opts: RuleOptionConfig,
                        rule_id: str) -> AnalyticNode:
    if opts.analytic_impl == "device":
        from ..sql.compiler import record_host_fallback
        from ..sql.expr_ir import NotVectorizable

        from . import relational
        from ..runtime.nodes_relational import DeviceAnalyticNode

        try:
            lowering = relational.lower_analytics(analytic)
            return DeviceAnalyticNode("analytic", analytic,
                                      lowering=lowering, rule_id=rule_id,
                                      buffer_length=opts.buffer_length)
        except NotVectorizable as nv:
            record_host_fallback(nv.reason)
    return AnalyticNode("analytic", analytic, rule_id=rule_id,
                        buffer_length=opts.buffer_length)


def _make_window_func_node(wf, opts: RuleOptionConfig) -> WindowFuncNode:
    """rank/dense_rank/lead are whole-collection functions — they always
    route through the vector operator (a per-row exec cannot see the
    value order); `analytic_impl` only decides whether exact-float32 rank
    batches use the segscan sort kernel."""
    from . import relational

    if not any(c.name in relational.VECTOR_WINDOW_FUNCS for c in wf):
        return WindowFuncNode("window_func", wf,
                              buffer_length=opts.buffer_length)
    from ..sql.compiler import record_host_fallback
    from ..sql.expr_ir import NotVectorizable
    from ..runtime.nodes_relational import VectorWindowFuncNode

    use_device = False
    if opts.analytic_impl == "device":
        try:
            lowering = relational.lower_window_funcs(wf)
            use_device = lowering.device_eligible()
        except NotVectorizable as nv:
            record_host_fallback(nv.reason)
    return VectorWindowFuncNode("window_func", wf, use_device=use_device,
                                buffer_length=opts.buffer_length)


def _build_host_chain(
    topo: Topo, stmt, source_nodes: List[SourceNode], opts: RuleOptionConfig,
    rule_id: str, stream_joins=None, lookup_joins=None, store=None,
    source_names=None,
):
    if stream_joins is None:
        stream_joins = stmt.joins
    lookup_joins = lookup_joins or []
    # lookup joins bind per-STREAM, before the watermark merge and before
    # WHERE/window (reference lookup_node.go sits right after decode): the
    # node must only see rows of the stream its ON clause references, even
    # under event time where all chains later merge at the watermark node.
    # Targeting tracks each stream's CURRENT tail by stream name (node names
    # drift through _shared/_ratelimit/lookup hops).
    names = source_names or [n.name for n in source_nodes]
    tails = dict(zip(names, source_nodes))
    for k, lj in enumerate(lookup_joins):
        node = _make_lookup_join_node(lj, k, opts, store)
        qs = sorted(_stream_side_qualifiers(lj) & tails.keys())
        if not qs:
            qs = list(tails.keys())
        topo.add_op(node)
        for t in {id(tails[q]): tails[q] for q in qs}.values():
            t.connect(node)
        for q in qs:
            tails[q] = node
    seen_ids: set = set()
    tail_of_sources = []
    for t in tails.values():
        if id(t) not in seen_ids:
            seen_ids.add(id(t))
            tail_of_sources.append(t)

    # event-time: watermark generation + late drop
    if opts.is_event_time:
        wm = WatermarkNode("watermark", late_tolerance_ms=opts.late_tolerance_ms,
                           buffer_length=opts.buffer_length)
        topo.add_op(wm)
        for s in tail_of_sources:
            s.connect(wm)
        chain = [wm]
    else:
        chain = list(tail_of_sources)

    def attach(node):
        topo.add_op(node)
        for t in chain:
            t.connect(node)
        chain.clear()
        chain.append(node)
        return node

    analytic = _analytic_calls(stmt)
    if analytic:
        attach(_make_analytic_node(stmt, analytic, opts, rule_id))
    # predicate pushdown: WHERE before the window when it has no analytic refs
    where_pushed = False
    if stmt.condition is not None and not analytic:
        attach(FilterNode("filter", stmt.condition, buffer_length=opts.buffer_length))
        where_pushed = True
    if stmt.window is not None:
        attach(WindowNode("window", stmt.window,
                          is_event_time=opts.is_event_time, rule_id=rule_id,
                          buffer_length=opts.buffer_length))
    if stmt.condition is not None and not where_pushed:
        attach(FilterNode("filter", stmt.condition, buffer_length=opts.buffer_length))
    if stream_joins:
        attach(_make_join_node(stmt, stream_joins, opts, rule_id))
    if stmt.dimensions:
        attach(AggregateNode("aggregate", [d.expr for d in stmt.dimensions],
                             buffer_length=opts.buffer_length))
    if stmt.having is not None:
        attach(HavingNode("having", stmt.having, rule_id=rule_id,
                          buffer_length=opts.buffer_length))
    wf = _window_func_calls(stmt)
    if wf:
        attach(_make_window_func_node(wf, opts))
    if stmt.sorts:
        attach(OrderNode("order", stmt.sorts, buffer_length=opts.buffer_length))
    tail = attach(ProjectNode(
        "project", stmt.fields, rule_id=rule_id, limit=stmt.limit,
        is_agg=_has_aggregates(stmt) and not stmt.dimensions,
        buffer_length=opts.buffer_length,
    ))
    srf = _srf_field(stmt)
    if srf is not None:
        # project computed the SRF list column; expand it into rows
        tail = attach(ProjectSetNode(
            "project_set", srf.output_name or srf.name,
            buffer_length=opts.buffer_length,
        ))
    return tail


def explain(rule: RuleDef, store) -> Dict[str, Any]:
    """Plan explanation (REST /rules/{id}/explain analogue)."""
    stmt = parse_select(rule.sql)
    opts = merged_options(rule)
    kernel_plan = device_path_eligible(stmt, opts)
    sharing_info = None
    if kernel_plan is not None and len(stmt.sources) == 1 and not stmt.joins:
        from . import sharing as sharing_mod

        try:
            sharing_info = sharing_mod.explain_decision(
                rule, stmt, opts, kernel_plan, store)
        except Exception as exc:  # explain must never fail on the probe
            sharing_info = {"decision": "private", "reason": str(exc)}
    shared = bool(sharing_info and sharing_info.get("decision") == "shared")
    path = ("device-fused-shared" if shared
            else "device-fused" if kernel_plan is not None else "host")
    ops: List[str] = ["source"]
    if shared:
        ops.append("shared_pane_fold[TPU]")
        ops.append("emit_combine")
    elif kernel_plan is not None:
        ops.append("fused_window_groupby_agg[TPU]")
        if stmt.having is not None:
            ops.append("having")
        if stmt.sorts:
            ops.append("order")
        ops.append("project")
    else:
        if opts.is_event_time:
            ops.append("watermark")
        if _analytic_calls(stmt):
            ops.append("analytic")
        if stmt.condition is not None:
            ops.append("filter")
        if stmt.window is not None:
            ops.append(f"window[{stmt.window.window_type.value}]")
        if stmt.joins:
            ops.append("join")
        if stmt.dimensions:
            ops.append("aggregate")
        if stmt.having is not None:
            ops.append("having")
        if stmt.sorts:
            ops.append("order")
        ops.append("project")
    ops.append("sink")
    out: Dict[str, Any] = {"path": path, "operators": ops}
    if sharing_info is not None:
        out["sharing"] = sharing_info
    # shards section: the placement decision this rule's plan would make
    # (docs/DISTRIBUTED.md serving mode) — resolved against the devices
    # this process can see, but never building a mesh (explain is a probe)
    if kernel_plan is not None:
        try:
            req = mesh_request(opts, kernel_plan)
            info: Dict[str, Any] = {k: req.get(k)
                                    for k in ("mode", "source", "reason")}
            if req["mode"] == "sharded":
                from ..parallel.mesh import resolve_auto_cfg

                try:
                    resolved = resolve_auto_cfg(req["cfg"])
                except Exception:
                    resolved = None
                if resolved is None:
                    info = {"mode": "single-chip",
                            "source": req["source"],
                            "reason": "mesh unavailable (fewer than 2 "
                                      "devices) — single-chip fallback"}
                else:
                    info["mesh"] = dict(resolved)
                    info["shards"] = int(resolved["keys"])
            out["shards"] = info
        except Exception as exc:  # explain must never fail on the probe
            out["shards"] = {"mode": "unknown", "reason": str(exc)}
    # mesh section (fleet observatory): LIVE skew + rebalance-hint state
    # for a rule already serving sharded — read-only off meshwatch and
    # the installed controller, never building a mesh (explain stays a
    # probe; the signal feeds ROADMAP item 2's rebalancer)
    if (out.get("shards") or {}).get("mode") == "sharded":
        try:
            from ..observability import meshwatch
            from ..runtime import control as _control

            mesh_info: Dict[str, Any] = {
                "skew": meshwatch.rule_skew(rule.id),
                "threshold": meshwatch.skew_threshold(),
            }
            ctl = _control.controller()
            if ctl is not None:
                ctl_mesh = ctl._mesh_diagnostics()
                mesh_info["hint"] = ctl_mesh["rules"].get(rule.id)
                mesh_info["rebalance_hints_total"] = (
                    ctl_mesh["rebalance_hints_total"])
            out["mesh"] = mesh_info
        except Exception as exc:  # explain must never fail on the probe
            out["mesh"] = {"error": str(exc)}
    # sliding section (ISSUE 15 satellite): which sliding implementation
    # this plan takes and WHY a DABA request falls back to the exact
    # refold — the mesh ring is future work, so a sharded plan's refold
    # must be attributable here and in the flight recorder, never silent
    if kernel_plan is not None and stmt.window is not None and \
            stmt.window.window_type == ast.WindowType.SLIDING_WINDOW:
        requested = opts.sliding_impl
        impl, reason = "daba", None
        if requested != "daba":
            impl, reason = "refold", f"slidingImpl={requested} requested"
        elif (out.get("shards") or {}).get("mode") == "sharded":
            impl, reason = ("refold",
                            "sharded kernel: the mesh DABA ring is future "
                            "work — exact refold path")
        elif any(s.kind == "heavy_hitters" for s in kernel_plan.specs):
            impl, reason = ("refold",
                            "heavy_hitters finalize is host-assembled — "
                            "exact refold path")
        out["sliding"] = {"requested": requested, "impl": impl,
                          "fallback_reason": reason}
    # aot section (docs/AOT_CACHE.md): the executable-cache posture of
    # this plan's certified compile surface — how many signatures the
    # jitcert certificate closes over, how many a fleet bake already
    # persisted (cache hits at boot), and the live per-site hit/miss
    # counters once the rule is serving. A "cached: 0" on a warm fleet
    # image is a bake gap: first emit will pay compiles
    if kernel_plan is not None:
        try:
            from ..observability import jitcert as _jitcert
            from ..runtime import aotcache

            ring_slots = 0
            if (stmt.window is not None
                    and stmt.window.window_type
                    == ast.WindowType.SLIDING_WINDOW
                    and opts.sliding_impl == "daba"):
                from ..ops.slidingring import ring_layout_for

                ring_slots = ring_layout_for(
                    stmt.window, kernel_plan).n_ring_panes
            aot = aotcache.plan_compile_price(_jitcert.estimate_plan_certs(
                kernel_plan, 1, opts.micro_batch_rows, opts.key_slots,
                sliding_ring_slots=ring_slots))
            live = aotcache.site_report(rule.id)
            if live:
                aot["serving"] = live
            out["aot"] = aot
        except Exception as exc:  # explain must never fail on the probe
            out["aot"] = {"error": str(exc)}
    # structured expression-compilation report: which WHERE/arg/FILTER
    # pieces device-compile and which fall back to the row interpreter
    # (with NotVectorizable reason slugs) — so "path: host" is
    # attributable instead of opaque
    from ..ops.aggspec import explain_expressions, take_expr_fallbacks

    try:
        out["expressions"] = explain_expressions(stmt)
    except Exception as exc:  # explain must never fail on the probe
        out["expressions"] = {"error": str(exc)}
    # relational pieces (joins / analytic / window funcs) join the same
    # report: each names its device-vs-host verdict with the reason slug
    # the fallback counter would carry
    try:
        from . import relational

        pieces = relational.explain_relational(
            stmt, stream_joins=stmt.joins)
        for p in pieces:  # rule options veto the lowering verdict
            if p["kind"] == "join" and opts.join_impl != "device":
                p.update(path="host", reason="join_impl_option")
            elif p["kind"] in ("analytic", "window_func") \
                    and opts.analytic_impl != "device":
                p.update(path="host", reason="analytic_impl_option")
        if pieces and isinstance(out["expressions"], dict):
            out["expressions"].setdefault("pieces", []).extend(pieces)
            hosted = [p for p in pieces if p.get("path") == "host"]
            if hosted:
                out["expressions"]["host_fallbacks"] = (
                    out["expressions"].get("host_fallbacks", 0)
                    + len(hosted))
    except Exception as exc:  # explain must never fail on the probe
        if isinstance(out.get("expressions"), dict):
            out["expressions"]["relational_error"] = str(exc)
    take_expr_fallbacks()  # drop probe-recorded notes (explain is read-only)
    return out
