"""Relational-tier lowering: stream-stream joins and analytic/window
functions onto the device kernels (ops/joinring.py, ops/segscan.py).

The lowering is a classifier over plan text, mirroring how ops/aggspec.py
lowers scalar expressions: every decision either produces a device plan
or raises NotVectorizable with a structured `join_*`/`analytic_*` reason
slug — recorded through sql/compiler.record_host_fallback and surfaced
in /rules/{id}/explain, so a rule that stays on the host nested loop is
attributable, never silent.

Join ON clauses split into AND conjuncts and classify three ways:

  equi     l.k = r.k            -> KeyTable slot equality (composite OK)
  band     l.ts - r.ts REL c    -> int32 banded gather bounds (affine
                                   forms over +/- and integer literals;
                                   TiLT-style index arithmetic)
  residual anything else        -> expr-IR three-valued ON residual,
                                   compiled for device AND host from the
                                   same renamed tree (__jl_*/__jr_*)

Anything outside that grammar (non-integral band literals, band over
several column pairs, unqualified refs, IR-rejected residuals) falls
back with its named reason. The host nested loop stays bit-identical
because the mask only decides PAIRING — emitted tuples are the original
host rows in the reference emission order.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..sql import ast
from ..sql.expr_ir import (NotVectorizable, collect_str_consts,
                           compile_expr_ir, plan_anchor_ms)

_REL_OPS = {"<", "<=", ">", ">=", "="}

#: window functions computed collection-wide by the vector path
#: (runtime/nodes_relational.py); row_number keeps its per-row exec
VECTOR_WINDOW_FUNCS = {"rank", "dense_rank", "lead"}


def _nv(msg: str, reason: str) -> NotVectorizable:
    exc = NotVectorizable(msg)
    exc.reason = reason
    return exc


# ------------------------------------------------------------------ joins
@dataclass
class JoinLowering:
    """Device plan for one stream-stream join step."""

    join_type: ast.JoinType
    left: str
    right: str
    key_l: List[str] = field(default_factory=list)
    key_r: List[str] = field(default_factory=list)
    band_l: Optional[str] = None
    band_r: Optional[str] = None
    lo: Optional[int] = None
    hi: Optional[int] = None
    residual_dev: Any = None
    residual_host: Any = None
    raw_l: List[str] = field(default_factory=list)  # __jl_* raw columns
    raw_r: List[str] = field(default_factory=list)  # __jr_* raw columns

    def build_ring(self, capacity: int = 4096, bucket_ms: int = 1000):
        from ..ops.joinring import JoinRing

        derived = self.residual_dev.derived if self.residual_dev else ()
        dtypes = dict(self.residual_dev.col_dtypes) \
            if self.residual_dev else {}
        return JoinRing(
            n_key_cols=len(self.key_l),
            band=self.band_l is not None,
            lo=self.lo, hi=self.hi,
            residual=self.residual_dev,
            residual_host=self.residual_host,
            derived=derived, col_dtypes=dtypes,
            capacity=capacity, bucket_ms=bucket_ms)

    def resid_signature(self) -> Tuple[Dict[str, str], Dict[str, str]]:
        """(left, right) residual column dtype maps — the jitcert
        _derive_join / admission-pricing inputs."""
        if self.residual_dev is None:
            return {}, {}
        dt = self.residual_dev.col_dtypes
        cols = sorted(self.residual_dev.columns)
        return ({c: dt.get(c, "float32") for c in cols if "__jl_" in c},
                {c: dt.get(c, "float32") for c in cols if "__jr_" in c})


def _conjuncts(e: Optional[ast.Expr]) -> List[ast.Expr]:
    if e is None:
        return []
    if isinstance(e, ast.BinaryExpr) and e.op == "AND":
        return _conjuncts(e.lhs) + _conjuncts(e.rhs)
    return [e]


def _side_of(ref: ast.FieldRef, left: str, right: str) -> str:
    if ref.stream == left:
        return "l"
    if ref.stream == right:
        return "r"
    if not ref.stream:
        raise _nv(f"unqualified column {ref.name!r} in join ON "
                  "(qualify with the stream name)",
                  "join_on_unqualified")
    raise _nv(f"column {ref.stream}.{ref.name} references neither join "
              "side", "join_on_unqualified")


def _affine(e: ast.Expr, left: str, right: str
            ) -> Optional[Tuple[Dict[Tuple[str, str], int], int]]:
    """Affine form of an expression over qualified FieldRefs, `+`, `-`
    and integer literals: ({(side, col): coeff}, const). None = not
    affine (classify as residual). Non-integral literals inside an
    otherwise-affine form are a named fallback — a fractional band
    cannot be exact int32 index arithmetic."""
    if isinstance(e, ast.IntegerLiteral):
        return {}, int(e.val)
    if isinstance(e, ast.NumberLiteral):
        if float(e.val).is_integer():
            return {}, int(e.val)
        raise _nv(f"non-integral literal {e.val!r} in temporal band",
                  "join_band_literal")
    if isinstance(e, ast.FieldRef):
        return {(_side_of(e, left, right), e.name): 1}, 0
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        inner = _affine(e.expr, left, right)
        if inner is None:
            return None
        return {k: -v for k, v in inner[0].items()}, -inner[1]
    if isinstance(e, ast.BinaryExpr) and e.op in ("+", "-"):
        a = _affine(e.lhs, left, right)
        b = _affine(e.rhs, left, right)
        if a is None or b is None:
            return None
        sign = 1 if e.op == "+" else -1
        cols = dict(a[0])
        for k, v in b[0].items():
            cols[k] = cols.get(k, 0) + sign * v
        return ({k: v for k, v in cols.items() if v},
                a[1] + sign * b[1])
    return None


def _rename_residual(e: ast.Expr, left: str, right: str) -> ast.Expr:
    """Deep-copy a residual conjunct with each qualified FieldRef
    renamed to its device column (__jl_<col> / __jr_<col>) — left and
    right column namespaces must not collide inside one IR tree."""
    e = copy.deepcopy(e)
    for node in ast.walk(e):
        if isinstance(node, ast.FieldRef):
            side = _side_of(node, left, right)
            node.name = f"__j{side}_{node.name}"
            node.stream = ""
    return e


def _and_tree(parts: List[ast.Expr]) -> ast.Expr:
    out = parts[0]
    for p in parts[1:]:
        out = ast.BinaryExpr(op="AND", lhs=out, rhs=p)
    return out


def lower_join(stmt: ast.SelectStatement, joins: List[ast.Join]
               ) -> JoinLowering:
    """Lower the stream-stream join step to a JoinRing plan or raise
    NotVectorizable with a `join_*` reason slug."""
    if len(joins) != 1:
        raise _nv(f"{len(joins)}-way stream join (device tier lowers "
                  "exactly one stream-stream step)", "join_multiway")
    join = joins[0]
    left = stmt.sources[0].ref_name
    right = join.table.ref_name
    low = JoinLowering(join_type=join.join_type, left=left, right=right)
    if join.join_type == ast.JoinType.CROSS:
        return low
    if join.on is None:
        raise _nv("stream join without ON clause", "join_no_on")

    residual_parts: List[ast.Expr] = []
    band_pair: Optional[Tuple[str, str]] = None
    lo: Optional[int] = None
    hi: Optional[int] = None

    def tighten(rel: str, v: int) -> None:
        nonlocal lo, hi
        if rel == ">=":
            lo = v if lo is None else max(lo, v)
        elif rel == ">":
            tighten(">=", v + 1)
        elif rel == "<=":
            hi = v if hi is None else min(hi, v)
        elif rel == "<":
            tighten("<=", v - 1)
        elif rel == "=":
            tighten(">=", v)
            tighten("<=", v)

    _FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    for c in _conjuncts(join.on):
        # equi-key: plain cross-stream column equality
        if (isinstance(c, ast.BinaryExpr) and c.op == "="
                and isinstance(c.lhs, ast.FieldRef)
                and isinstance(c.rhs, ast.FieldRef)):
            sl = _side_of(c.lhs, left, right)
            sr = _side_of(c.rhs, left, right)
            if sl != sr:
                a, b = ((c.lhs, c.rhs) if sl == "l" else (c.rhs, c.lhs))
                low.key_l.append(a.name)
                low.key_r.append(b.name)
                continue
            # same-side equality: residual filter
        # temporal band: affine comparison touching both streams
        if isinstance(c, ast.BinaryExpr) and c.op in _REL_OPS:
            fa = _affine(c.lhs, left, right)
            fb = _affine(c.rhs, left, right)
            if fa is not None and fb is not None:
                cols = dict(fa[0])
                for k, v in fb[0].items():
                    cols[k] = cols.get(k, 0) - v
                cols = {k: v for k, v in cols.items() if v}
                const = fa[1] - fb[1]
                sides = {k[0] for k in cols}
                if sides == {"l", "r"}:
                    lcols = [k for k in cols if k[0] == "l"]
                    rcols = [k for k in cols if k[0] == "r"]
                    if (len(lcols) != 1 or len(rcols) != 1
                            or abs(cols[lcols[0]]) != 1
                            or cols[lcols[0]] != -cols[rcols[0]]):
                        # not l.ts - r.ts REL c shaped: residual lane
                        residual_parts.append(c)
                        continue
                    pair = (lcols[0][1], rcols[0][1])
                    if band_pair is not None and band_pair != pair:
                        # one dt lane: first pair keeps it, later pairs
                        # ride the residual (float32 compare)
                        residual_parts.append(c)
                        continue
                    band_pair = pair
                    # diff = s*dt + const REL 0, s = sign of the l coeff
                    s = cols[lcols[0]]
                    rel = c.op if s > 0 else _FLIP[c.op]
                    tighten(rel, -const if s > 0 else const)
                    continue
                if not sides:
                    # constant comparison — fold host-side as residual
                    pass
        residual_parts.append(c)

    if band_pair is not None:
        low.band_l, low.band_r = band_pair
        low.lo, low.hi = lo, hi
    if residual_parts:
        renamed = _and_tree([_rename_residual(p, left, right)
                             for p in residual_parts])
        anchor = plan_anchor_ms()
        seed = collect_str_consts(renamed)
        try:
            low.residual_dev = compile_expr_ir(
                renamed, mode="device", want="bool", anchor_ms=anchor,
                str_seed=seed)
            low.residual_host = compile_expr_ir(
                renamed, mode="host", want="bool", anchor_ms=anchor,
                str_seed=seed)
        except NotVectorizable as exc:
            raise _nv(f"ON residual not device-compilable: {exc}",
                      "join_on_residual") from exc
        raws = sorted(low.residual_dev.raw_columns)
        low.raw_l = [c for c in raws if c.startswith("__jl_")]
        low.raw_r = [c for c in raws if c.startswith("__jr_")]
    if not low.key_l and low.band_l is None and low.residual_dev is None:
        raise _nv("join ON has no device-lowerable conjunct",
                  "join_no_equi_key")
    return low


# -------------------------------------------------------------- analytics
_LITERALS = (ast.IntegerLiteral, ast.NumberLiteral, ast.StringLiteral)


def _literal_value(e: ast.Expr) -> Any:
    return e.val


@dataclass
class AnalyticCallPlan:
    """One lifted lag() instance: read `col`, partition by `partition`
    columns, default when the partition is fresh."""

    call: ast.Call
    col: str
    partition: List[ast.FieldRef] = field(default_factory=list)
    default: Any = None


@dataclass
class AnalyticLowering:
    calls: List[AnalyticCallPlan] = field(default_factory=list)


def lower_analytics(calls: List[ast.Call]) -> AnalyticLowering:
    """Lower AnalyticNode's pre-computed calls to the segscan shift
    kernel. All calls must lift (state ordering is shared), else the
    whole node stays host with the FIRST blocking reason."""
    low = AnalyticLowering()
    for call in calls:
        if call.name != "lag":
            raise _nv(f"analytic function {call.name}() has no device "
                      "lowering", "analytic_func")
        if call.when is not None:
            raise _nv("lag() OVER(WHEN ...) gates state updates per "
                      "row", "analytic_when")
        if not call.args or not isinstance(call.args[0], ast.FieldRef):
            raise _nv("lag() first argument must be a plain column",
                      "analytic_args")
        if len(call.args) > 1:
            idx = call.args[1]
            if not (isinstance(idx, ast.IntegerLiteral)
                    and int(idx.val) == 1):
                raise _nv("lag() with index != 1 (device carry holds "
                          "one value per partition)", "analytic_args")
        default = None
        if len(call.args) > 2:
            if not isinstance(call.args[2], _LITERALS):
                raise _nv("lag() default must be a literal",
                          "analytic_args")
            default = _literal_value(call.args[2])
        if len(call.args) > 3:
            raise _nv("lag() takes at most 3 arguments", "analytic_args")
        part: List[ast.FieldRef] = []
        for p in call.partition:
            if not isinstance(p, ast.FieldRef):
                raise _nv("PARTITION BY must list plain columns",
                          "analytic_partition")
            part.append(p)
        low.calls.append(AnalyticCallPlan(
            call=call, col=call.args[0].name, partition=part,
            default=default))
    return low


@dataclass
class WindowFuncCallPlan:
    call: ast.Call
    name: str
    col: Optional[str] = None          # None for row_number
    partition: List[ast.FieldRef] = field(default_factory=list)
    offset: int = 1                    # lead
    default: Any = None                # lead


@dataclass
class WindowFuncLowering:
    calls: List[WindowFuncCallPlan] = field(default_factory=list)

    def device_eligible(self) -> bool:
        """rank/dense_rank/row_number emit exact int32 ranks — the
        segscan sort kernel serves them; lead's value assignment is an
        exact host shift either way."""
        return any(c.name in ("rank", "dense_rank") for c in self.calls)


def lower_window_funcs(calls: List[ast.Call]) -> WindowFuncLowering:
    """Lower WindowFuncNode's calls to the collection-wide vector path
    (segscan sort kernel for the numeric rank family)."""
    low = WindowFuncLowering()
    for call in calls:
        if call.name not in ("row_number", "rank", "dense_rank", "lead"):
            raise _nv(f"window function {call.name}() has no device "
                      "lowering", "analytic_func")
        part: List[ast.FieldRef] = []
        for p in call.partition:
            if not isinstance(p, ast.FieldRef):
                raise _nv("PARTITION BY must list plain columns",
                          "analytic_partition")
            part.append(p)
        plan = WindowFuncCallPlan(call=call, name=call.name,
                                  partition=part)
        if call.name == "row_number":
            if call.args:
                raise _nv("row_number() takes no arguments",
                          "analytic_args")
        else:
            if not call.args or not isinstance(call.args[0],
                                               ast.FieldRef):
                raise _nv(f"{call.name}() first argument must be a "
                          "plain column", "analytic_args")
            plan.col = call.args[0].name
            if call.name == "lead":
                if len(call.args) > 1:
                    if not isinstance(call.args[1], ast.IntegerLiteral):
                        raise _nv("lead() offset must be an integer "
                                  "literal", "analytic_args")
                    plan.offset = int(call.args[1].val)
                if len(call.args) > 2:
                    if not isinstance(call.args[2], _LITERALS):
                        raise _nv("lead() default must be a literal",
                                  "analytic_args")
                    plan.default = _literal_value(call.args[2])
            elif len(call.args) > 1:
                raise _nv(f"{call.name}() takes one argument",
                          "analytic_args")
        low.calls.append(plan)
    return low


# ---------------------------------------------------------------- explain
def explain_relational(stmt: ast.SelectStatement,
                       stream_joins: Optional[List[ast.Join]] = None
                       ) -> List[Dict[str, Any]]:
    """Extra "expressions" pieces for /rules/{id}/explain: the join and
    analytic/window-function lowering verdicts with their structured
    reasons. Read-only probe — never registers fallback counters."""
    pieces: List[Dict[str, Any]] = []

    def probe(kind: str, detail: str, fn) -> None:
        entry: Dict[str, Any] = {"kind": kind, "expr": detail}
        try:
            fn()
            entry["path"] = "device"
        except NotVectorizable as exc:
            entry["path"] = "host"
            entry["reason"] = getattr(exc, "reason", "other")
            entry["detail"] = str(exc)
        pieces.append(entry)

    joins = stmt.joins if stream_joins is None else stream_joins
    if joins:
        probe("join", " ".join(j.join_type.value for j in joins),
              lambda: lower_join(stmt, joins))
    an = [n for f_ in stmt.fields for n in ast.walk(f_.expr)
          if isinstance(n, ast.Call)]
    if stmt.condition is not None:
        an += [n for n in ast.walk(stmt.condition)
               if isinstance(n, ast.Call)]
    from ..functions import registry as freg

    acalls = [c for c in an if freg.is_analytic(c.name)]
    wcalls = [c for c in an
              if (fd := freg.lookup(c.name)) is not None
              and fd.ftype == freg.WINDOW_FUNC]
    if acalls:
        probe("analytic", ",".join(sorted({c.name for c in acalls})),
              lambda: lower_analytics(acalls))
    if wcalls:
        probe("window_func", ",".join(sorted({c.name for c in wcalls})),
              lambda: lower_window_funcs(wcalls))
    return pieces
