"""Cost-based cross-rule window-aggregate sharing — the planner pass that
rewrites correlated rules onto one shared pane fold.

A fleet of dashboards/alert rules over one stream typically watches the
SAME stream with the SAME GROUP BY and correlated windows (the ROADMAP's
"millions of users" shape); the engine already shares the source, decode,
key encode and device upload across them (runtime/subtopo.py +
runtime/ingest.py), but the expensive ops/groupby.py device fold still ran
once per rule. Following "Factor Windows" (arxiv 2008.12379), rules whose
windows are integer multiples of a common pane (the GCD of their
lengths/intervals) can share one pane-granular fold and reconstruct each
window as a pane merge — the constant-time merge structure the kernel
already uses for hopping windows (arxiv 2009.13768).

This module decides WHEN that rewrite pays off and wires it up:

- **Correlation test** — same stream config (subtopo key), same GROUP BY
  key set, unionable device aggregate specs, tumbling/hopping windows
  whose length/interval are multiples of the common pane. WHERE clauses
  need NOT match: each member's predicate lifts into per-spec device
  FILTER masks + a private activity spec over the pooled fold
  (ops/aggspec.py lift_predicate, per "On the Semantic Overlap of
  Operators in Stream Processing Engines") — identical-WHERE peers
  still dedup their specs outright, different-WHERE peers coexist as
  masked specs in ONE fold dispatch. Everything else keeps a private
  fold.
- **Cost model** — sharing saves one whole fold dispatch per batch per
  member rule, and costs a finer-grained pane merge at each member's
  window emit. The rewrite happens only when the estimated per-second
  fold savings exceed the emit-combine overhead; the decision (and both
  estimates) is visible in `GET /rules/{id}/explain` and
  `tools/probe_sharing.py`.
- **Declarations** — rules declare their windows at plan time, so a batch
  of correlated rules created together gets a store whose pane is the GCD
  across ALL of them (the store's pane is fixed once built; later rules
  join only if their windows are multiples of it — otherwise they get an
  explicit, logged private-fold fallback).

qos>0 rules always fall back to a private fold (rule-scoped checkpoint
barriers cannot flow through a shared pipeline) — explicitly logged, not
silent convention.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..ops.aggspec import KernelPlan
from ..sql import ast
from ..utils.infra import logger

#: hard cap on how many shared panes one window may span — past this the
#: per-emit pane merge and the (n_panes, capacity, k) state footprint stop
#: paying for the saved fold
MAX_SPAN_PANES = 64

# Cost-model coefficients (µs), calibrated against the bench's recorded
# per-stage timings: a fused fold dispatch costs a fixed kernel-launch +
# input-build overhead plus a per-spec increment; an emit-time pane merge
# costs per extra pane merged. Absolute values matter less than the ratio:
# folds happen per BATCH (tens-hundreds/s), emit combines per WINDOW
# (typically < 1/s), which is why sharing nearly always wins except for
# very short windows or very wide pane spans.
FOLD_DISPATCH_US = 150.0
FOLD_SPEC_US = 12.0
COMBINE_PANE_US = 4.0

_decl_lock = threading.Lock()
#: store_key -> rule_id -> {"length_ms", "interval_ms", "plan"}
_declared: Dict[str, Dict[str, Dict[str, Any]]] = {}


def reset() -> None:
    """Test hook: forget every plan-time declaration."""
    with _decl_lock:
        _declared.clear()


def declare(store_key: str, rule_id: str, length_ms: int, interval_ms: int,
            plan: KernelPlan) -> None:
    with _decl_lock:
        _declared.setdefault(store_key, {})[rule_id] = {
            "length_ms": int(length_ms),
            "interval_ms": int(interval_ms),
            "plan": plan,
        }


def declarations(store_key: str) -> List[Dict[str, Any]]:
    with _decl_lock:
        return list(_declared.get(store_key, {}).values())


@contextmanager
def probe_declarations(rule_id: str):
    """Scope a planning PROBE (rule validation): any declaration the probe
    makes or overwrites for `rule_id` is rolled back on exit, while
    concurrent declare/undeclare for OTHER rules (REST handlers are
    threaded) pass through untouched — a wholesale snapshot/restore would
    resurrect concurrently-deleted rules' declarations."""
    with _decl_lock:
        before = {k: dict(v[rule_id]) for k, v in _declared.items()
                  if rule_id in v}
    try:
        yield
    finally:
        with _decl_lock:
            for k in list(_declared):
                if rule_id in _declared[k] and k not in before:
                    del _declared[k][rule_id]
                    if not _declared[k]:
                        del _declared[k]
            for k, old in before.items():
                _declared.setdefault(k, {})[rule_id] = old


def snapshot_declarations() -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Deep-enough copy of the declaration registry — probe paths
    (rule validation) plan without leaving OR OVERWRITING candidacy."""
    with _decl_lock:
        return {k: dict(v) for k, v in _declared.items()}


def restore_declarations(snap) -> None:
    with _decl_lock:
        _declared.clear()
        _declared.update(snap)


def undeclare(rule_id: str) -> None:
    """Forget a rule's sharing candidacy (rule delete/update): ghost
    declarations would otherwise skew the peer count — a later lone rule
    would 'share' with deleted peers forever — and permanently constrain
    the pane GCD of future stores."""
    with _decl_lock:
        for key in list(_declared):
            _declared[key].pop(rule_id, None)
            if not _declared[key]:
                del _declared[key]


def _peer_decls(store_key: str, rule_id: str) -> List[Dict[str, Any]]:
    with _decl_lock:
        return [d for rid, d in _declared.get(store_key, {}).items()
                if rid != rule_id]


@dataclass
class Decision:
    share: bool
    reason: str
    store_key: str = ""
    estimates: Dict[str, Any] = field(default_factory=dict)
    #: structurally shareable (declared as a candidate even when share is
    #: False — e.g. no peers yet): a later correlated rule then sees this
    #: one as a peer, and a replan of this rule joins the fleet
    eligible: bool = False


def _window_ms(w: ast.Window) -> tuple:
    length = w.length_ms()
    if w.window_type == ast.WindowType.HOPPING_WINDOW:
        interval = w.interval_ms() or length
    else:
        interval = length
    return length, interval


def store_key(subtopo_key: str, stmt: ast.SelectStatement, opts) -> str:
    """Identity of a shareable pane store: the stream pipeline plus every
    plan facet that must match bit-for-bit across members — the GROUP BY
    key set and the time domain. The WHERE clause is deliberately NOT a
    facet any more: predicate lifting (ops/aggspec.py lift_predicate)
    turns each member's WHERE into per-spec device FILTER masks over one
    pooled fold, so rules that differ only in predicate share a store
    (PAPERS.md "On the Semantic Overlap of Operators in Stream
    Processing Engines")."""
    dims = ",".join(d.expr.name for d in stmt.dimensions
                    if isinstance(d.expr, ast.FieldRef))
    return (f"{subtopo_key}|fold|dims={dims}"
            f"|evt={int(opts.is_event_time)}:{opts.late_tolerance_ms}"
            f"{_mesh_facet(opts)}")


def _mesh_facet(opts) -> str:
    """Mesh identity facet of the store key: rules whose sharding
    decision differs must never pool one pane store (a replicated and a
    key-range-sharded ring have different placement). Pure option/env
    parse — the unresolved form ("auto") is the facet, so the key stays
    stable between plan and store build."""
    from .planner import mesh_request

    req = mesh_request(opts)
    if req["mode"] != "sharded":
        return ""
    cfg = req["cfg"] or {}
    if cfg.get("auto"):
        return "|mesh=auto"
    return f"|mesh={cfg.get('rows', 1)}x{cfg.get('keys', 1)}"


def decide(stmt: ast.SelectStatement, opts, plan: KernelPlan,
           subtopo_key: str, rule_id: str,
           has_direct_emit: bool = True,
           lifted: Optional[KernelPlan] = None) -> Decision:
    """The sharing decision for one rule. Pure: consults live stores and
    plan-time declarations but mutates neither (explain/probe call this
    repeatedly). `plan` is the rule's private plan; `lifted` its
    predicate-lifted form (computed here when absent) — declarations,
    union coverage, and the cost model all run on the LIFTED plan, the
    shape that would actually join the pooled fold."""
    from ..ops.aggspec import lift_predicate

    if lifted is None:
        lifted = lift_predicate(plan, stmt.condition)
    key = store_key(subtopo_key, stmt, opts)
    if lifted is None:
        # the WHERE∧FILTER conjunction does not device-compile (pieces
        # conflicted when conjoined): an unlifted filtered plan must
        # never enter a pooled union — stay private, don't declare
        return Decision(
            False, "predicate lift not compilable (WHERE/FILTER "
            "conjunction has no device form) — private fold", key)
    plan = lifted

    def no(reason: str, est: Optional[dict] = None) -> Decision:
        return Decision(False, reason, key, est or {})

    w = stmt.window
    if not getattr(opts, "shared_fold", True):
        return no("sharedFold option off")
    if opts.qos > 0:
        return no(f"qos={opts.qos} requires rule-scoped checkpoint "
                  "barriers; shared folds serve qos=0 only")
    if not opts.share_source:
        return no("share_source off (no shared subtopo to ride)")
    if w is None or w.window_type not in (
            ast.WindowType.TUMBLING_WINDOW, ast.WindowType.HOPPING_WINDOW):
        wt = w.window_type.value if w is not None else "none"
        return no(f"window type {wt} is not pane-decomposable across rules")
    # mesh-sharded rules POOL like any others — the store key's mesh
    # facet groups same-mesh peers onto one key-range-sharded pane
    # store (ops/panestore.py mesh=). Only the placement differs.
    if any(s.kind == "heavy_hitters" for s in plan.specs):
        return no("heavy_hitters state is node-local (value dictionary)")
    if not has_direct_emit:
        return no("post-agg tail is not vectorizable (no direct emit)")
    length, interval = _window_ms(w)

    from ..ops.panestore import pane_gcd, spec_map_into, union_plan
    from ..runtime import nodes_sharedfold

    peers = _peer_decls(key, rule_id)
    live = nodes_sharedfold.get_store(key)
    if live is not None:
        pane = live.pane_ms
        if length % pane or interval % pane:
            return no(f"live store pane {pane}ms does not divide this "
                      f"window ({length}/{interval}ms)")
        if length // pane > live.n_panes - 1:
            return no(f"window spans {length // pane} panes; live store "
                      f"holds {live.n_panes}")
        try:
            spec_map_into(live.plan, plan)
        except KeyError:
            return no("live store's union plan does not cover this "
                      "rule's aggregates")
        n_new = 0  # covered by the live union
    else:
        vals = [length, interval]
        for d in peers:
            vals += [d["length_ms"], d["interval_ms"]]
        pane = pane_gcd(vals)
        if peers:
            union, _ = union_plan([d["plan"] for d in peers] + [plan])
            n_new = len(union.specs) - len(
                union_plan([d["plan"] for d in peers])[0].specs)
        else:
            n_new = 0
    span = length // pane
    if span > MAX_SPAN_PANES:
        return no(f"window spans {span} panes at the {pane}ms shared pane "
                  f"(cap {MAX_SPAN_PANES})")
    if live is None and not peers:
        # a lone rule gains nothing from a shared fold and would give up
        # the private node's latency-hiding emit pipeline — stay private,
        # but the caller DECLARES this rule (eligible=True) so the next
        # correlated rule shares, and a replan of this one joins the fleet
        return Decision(
            False, "no correlated peer rules declared yet — a lone rule "
            "keeps the private fused node (latency-hiding emit); declared "
            "as a sharing candidate for future peers",
            key, {"pane_ms": pane, "span_panes": span, "peers": 0},
            eligible=True)

    # ---- cost model: saved fold/s vs added emit-combine/s ----
    batches_per_s = 1000.0 / max(opts.micro_batch_linger_ms, 1)
    windows_per_s = 1000.0 / max(interval, 1)
    own_panes = (1 if w.window_type == ast.WindowType.TUMBLING_WINDOW
                 else max(length // max(interval, 1), 1))
    # once one peer rides the store, this rule's whole private fold
    # disappears; the union fold only grows by this rule's NEW specs
    saved_us_per_s = (FOLD_DISPATCH_US
                      + FOLD_SPEC_US * (len(plan.specs) - n_new)) \
        * batches_per_s
    overhead_us_per_s = COMBINE_PANE_US * max(span - own_panes, 0) \
        * windows_per_s
    est = {
        "pane_ms": pane,
        "span_panes": span,
        "peers": len(peers),
        "saved_fold_us_per_s": round(saved_us_per_s, 1),
        "emit_overhead_us_per_s": round(overhead_us_per_s, 1),
        "assumed_batches_per_s": round(batches_per_s, 1),
    }
    if saved_us_per_s <= overhead_us_per_s:
        return Decision(
            False,
            f"estimated fold savings ({saved_us_per_s:.0f}us/s) do not "
            f"cover the emit-combine overhead ({overhead_us_per_s:.0f}us/s)",
            key, est, eligible=True)
    return Decision(
        True,
        f"correlated with {len(peers)} declared peer rule(s); saves "
        f"~{saved_us_per_s:.0f}us/s of fold for "
        f"~{overhead_us_per_s:.0f}us/s of emit combine",
        key, est, eligible=True)


def _store_builder(store_key_: str, subtopo_key: str, build_nodes,
                   display: str, opts, is_event_time: bool,
                   late_tolerance_ms: int, fallback_decl: Dict[str, Any]):
    """Builder the pool calls when the first member resolves: the pane is
    the GCD across every window DECLARED for this key by then, so a batch
    of correlated rules created together gets one store serving all of
    them. `fallback_decl` is the resolving rule's own declaration — a
    concurrent delete/update can empty the key's declarations between
    plan and open, and the store must still serve at least its resolver."""

    def build():
        from ..ops.panestore import pane_gcd, union_plan
        from ..runtime import nodes_sharedfold as sf
        from ..runtime.subtopo import SubTopoRef

        # a declaration made AFTER some member's decide() can shrink the
        # GCD enough to blow that member's span past the cap (decide-time
        # vs build-time race): drop the finest-grained declarations from
        # the pane computation until every surviving span fits — the
        # dropped rules fail their attach, and their restart replans
        # against the live store's pane (private-fold fallback)
        decls = sorted(declarations(store_key_) or [fallback_decl],
                       key=lambda d: (d["interval_ms"], d["length_ms"]))
        while True:
            vals: List[int] = []
            for d in decls:
                vals += [d["length_ms"], d["interval_ms"]]
            pane = pane_gcd(vals)
            spans = [d["length_ms"] // pane for d in decls] or [1]
            if max(spans) <= MAX_SPAN_PANES or len(decls) <= 1:
                break
            decls = decls[1:]
        slack = (-(-max(late_tolerance_ms, 0) // pane)
                 if is_event_time else 0)
        n_panes = min(max(spans) + slack + 2, 255)
        union, _ = union_plan([d["plan"] for d in decls])
        # same-mesh members (the store key's mesh facet) get a key-range-
        # sharded pane ring: resolve the rule options' mesh request here
        # at build time (device backends are up by now)
        from .planner import mesh_request

        req = mesh_request(opts)
        mesh_cfg = req["cfg"] if req["mode"] == "sharded" else None
        return sf.SharedFoldNode(
            store_key_, display, union, pane, n_panes,
            subtopo_ref=SubTopoRef(subtopo_key, build_nodes),
            capacity=opts.key_slots, micro_batch=opts.micro_batch_rows,
            is_event_time=is_event_time,
            late_tolerance_ms=late_tolerance_ms,
            buffer_length=opts.buffer_length,
            mesh_cfg=mesh_cfg)

    return build


def try_plan_shared(topo, stmt: ast.SelectStatement, kernel_plan: KernelPlan,
                    opts, rule, store):
    """Attempt the shared-fold rewrite for one rule. Returns the rule's
    emit-hop node (the chain tail the sinks connect to) when the rewrite
    applies, else None (the caller builds the private device chain).
    Fallbacks are logged — loudly when the rule explicitly asked for
    sharing (the qos>0 case of ISSUE satellite #2)."""
    from ..ops.emit import build_direct_emit
    from ..runtime import nodes_sharedfold as sf
    from .planner import _subtopo_spec

    ropts = rule.options or {}
    # both spellings reach merged_options (alias table), so both count as
    # an explicit request for the loud-fallback logging contract
    explicit = bool(ropts.get("sharedFold", ropts.get("shared_fold")))
    tbl = stmt.sources[0]
    try:
        subkey, build_nodes, stream = _subtopo_spec(
            tbl.name, tbl.name, opts, store)
    except Exception as exc:
        logger.debug("rule %s: no shareable source pipeline (%s)",
                     rule.id, exc)
        return None
    dims = [d.expr.name for d in stmt.dimensions]
    direct = build_direct_emit(stmt, kernel_plan, dims)
    # predicate lifting: the member's WHERE becomes per-spec device
    # FILTER masks + a private activity spec over the pooled fold
    # (ops/aggspec.py lift_predicate) — this LIFTED plan is what the
    # rule declares, joins, and emits from
    from ..ops.aggspec import lift_predicate

    lifted = lift_predicate(kernel_plan, stmt.condition)
    decision = decide(stmt, opts, kernel_plan, subkey, rule.id,
                      has_direct_emit=direct is not None, lifted=lifted)
    length, interval = _window_ms(stmt.window)
    if decision.eligible:
        # candidacy is declared even when this rule stays private (no
        # peers yet / cost) so later correlated rules see it as a peer
        # and the store's pane GCD covers its windows
        declare(decision.store_key, rule.id, length, interval, lifted)
    if not decision.share:
        loud = explicit or opts.qos > 0
        log = logger.warning if loud else logger.debug
        log("rule %s: shared-fold rewrite declined — %s; planning a "
            "private fold", rule.id, decision.reason)
        if loud:
            # the operator asked for sharing (or qos forces privacy):
            # leave a flight-recorder breadcrumb, not just a log line
            from ..runtime.events import recorder

            recorder().record(
                "qos_private_fallback", rule=rule.id,
                reason=decision.reason, qos=opts.qos, explicit=explicit)
        return None
    # display name must be UNIQUE per store: two stores on the same
    # stream+dims (different WHERE / time-domain facets) with one name
    # would emit duplicate Prometheus series and invalidate the scrape
    import zlib

    tag = zlib.crc32(decision.store_key.encode()) & 0xFFFF
    display = f"shared_fold[{tbl.name}:{'+'.join(dims) or '*'}#{tag:04x}]"
    builder = _store_builder(
        decision.store_key, subkey, build_nodes, display, opts,
        opts.is_event_time, opts.late_tolerance_ms,
        fallback_decl={"length_ms": length, "interval_ms": interval,
                       "plan": lifted})
    spec = sf.MemberSpec(
        rule_id=rule.id, length_ms=length, interval_ms=interval,
        plan=lifted, direct_emit=direct, dims=dims,
        emit_columnar=opts.emit_columnar, act_idx=lifted.act_idx)
    entry = sf.SharedEmitNode(f"{rule.id}_shared_emit",
                              buffer_length=opts.buffer_length)
    topo.add_op(entry)
    topo.add_shared_source(
        sf.SharedFoldRef(decision.store_key, spec, builder), entry)
    logger.info("rule %s: window aggregates ride %s — %s",
                rule.id, display, decision.reason)
    return entry


def explain_decision(rule, stmt: ast.SelectStatement, opts,
                     kernel_plan: KernelPlan, store) -> Dict[str, Any]:
    """The sharing section of GET /rules/{id}/explain: decision, reason,
    cost estimates, and the live store (if one exists) this rule would
    join. Read-only — never declares or builds."""
    from ..ops.emit import build_direct_emit
    from ..runtime import nodes_sharedfold as sf
    from .planner import _subtopo_spec

    tbl = stmt.sources[0]
    try:
        subkey, _, _ = _subtopo_spec(tbl.name, tbl.name, opts, store)
    except Exception as exc:
        return {"decision": "private", "reason": f"no source pipeline: {exc}"}
    dims = [d.expr.name for d in stmt.dimensions
            if isinstance(d.expr, ast.FieldRef)]
    direct = build_direct_emit(stmt, kernel_plan, dims)
    d = decide(stmt, opts, kernel_plan, subkey, rule.id,
               has_direct_emit=direct is not None)
    out: Dict[str, Any] = {
        "decision": "shared" if d.share else "private",
        "reason": d.reason,
        "estimates": d.estimates,
    }
    live = sf.get_store(d.store_key)
    if live is not None:
        out["live_store"] = {
            "name": live.name,
            "members": live.member_count(),
            "pane_ms": live.pane_ms,
            "n_panes": live.n_panes,
            "fold_dedup_ratio": round(live.fold_dedup_ratio(), 4),
        }
    return out
