"""Portable-plugin test harness (analogue of
tools/plugin_server/plugin_test_server.go): runs the engine side of the
plugin wire protocol WITHOUT the engine, so plugin authors can exercise
their worker standalone.

Usage:
    python -m ekuiper_tpu.tools.plugin_test_server <plugin.json> \
        [--invoke symbol arg1 arg2 ...] [--source symbol] [--sink symbol]

plugin.json is the same descriptor the engine installs:
    {"name": "...", "executable": "path.py", "language": "python",
     "functions": [...], "sources": [...], "sinks": [...]}

--invoke calls a function symbol once with the given (json-parsed) args.
--source starts a source symbol and prints everything it emits for 10s.
--sink starts a sink symbol and feeds it one sample row.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from ..plugin import ipc
from ..plugin.manager import PluginIns as _Worker, PluginMeta


def _parse_arg(a: str):
    try:
        return json.loads(a)
    except ValueError:
        return a


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("descriptor", help="plugin json descriptor path")
    p.add_argument("--invoke", nargs="+", metavar=("SYMBOL", "ARG"),
                   help="call a function symbol with args")
    p.add_argument("--source", metavar="SYMBOL",
                   help="start a source symbol, print emissions for --seconds")
    p.add_argument("--sink", metavar="SYMBOL",
                   help="start a sink symbol, feed one sample row")
    p.add_argument("--seconds", type=float, default=10.0)
    args = p.parse_args(argv)

    with open(args.descriptor) as f:
        desc = json.load(f)
    meta = PluginMeta.from_dict(desc)
    worker = _Worker(meta)
    print(f"starting plugin {meta.name} ({meta.executable}) ...")
    worker.start()
    print("handshake ok")
    try:
        if args.invoke:
            symbol, fn_args = args.invoke[0], [
                _parse_arg(a) for a in args.invoke[1:]]
            ctrl = {"symbolName": symbol, "pluginType": "function",
                    "meta": {}}
            worker.command("start", ctrl)
            ch = ipc.Socket(ipc.PAIR)
            ch.dial(ipc.ipc_url(f"func_{symbol}"), timeout_ms=5000)
            ch.send(json.dumps({"func": symbol, "args": fn_args}).encode())
            reply = json.loads(ch.recv(10_000))
            print("result:", json.dumps(reply, indent=2))
            ch.close()
            worker.command("stop", ctrl)
        elif args.source:
            meta = {"ruleId": "test", "opId": "op", "instanceId": 0}
            ctrl = {"symbolName": args.source, "pluginType": "source",
                    "dataSource": "", "config": {}, "meta": meta}
            ch = ipc.Socket(ipc.PULL)
            ch.listen(ipc.ipc_url("source_test_op_0"))
            worker.command("start", ctrl)
            deadline = time.time() + args.seconds
            print(f"listening for {args.seconds}s ...")
            while time.time() < deadline:
                try:
                    data = ch.recv(timeout_ms=500)
                except Exception:
                    continue
                if data:
                    print("emit:", data.decode(errors="replace"))
            ch.close()
            worker.command("stop", ctrl)
        elif args.sink:
            meta = {"ruleId": "test", "opId": "op", "instanceId": 0}
            ctrl = {"symbolName": args.sink, "pluginType": "sink",
                    "config": {}, "meta": meta}
            worker.command("start", ctrl)
            ch = ipc.Socket(ipc.PUSH)
            ch.dial(ipc.ipc_url("sink_test_op_0"), timeout_ms=5000)
            sample = {"test": True, "value": 42}
            ch.send(json.dumps(sample).encode())
            print("sent sample row:", sample)
            time.sleep(1.0)
            ch.close()
            worker.command("stop", ctrl)
        else:
            print("plugin started and handshook; no action requested "
                  "(--invoke/--source/--sink)")
        return 0
    finally:
        worker.kill()


if __name__ == "__main__":
    sys.exit(main())
