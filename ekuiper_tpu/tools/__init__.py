"""Developer tools (analogue of the reference's tools/ directory)."""
