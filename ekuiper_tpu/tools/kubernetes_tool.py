"""Kubernetes deployment tool (analogue of tools/kubernetes: a sidecar that
watches a command directory and replays json command files against the
engine's REST API — declarative stream/rule provisioning for k8s deploys).

Command file shape is the reference's exactly:
    {"commands": [{"url": "/streams", "method": "post",
                   "description": "...", "data": {...}}, ...]}

Processed files are recorded in `.history` (name + loadTime) next to the
command files; a file re-processes when its mtime passes its recorded load
time. Run once (--once) or as a watch loop (--interval seconds).

Usage:
    python -m ekuiper_tpu.tools.kubernetes_tool --dir /commands \
        --endpoint http://127.0.0.1:9081 [--once] [--interval 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List


def _history_path(cmd_dir: str) -> str:
    return os.path.join(cmd_dir, ".history")


def load_history(cmd_dir: str) -> Dict[str, float]:
    try:
        with open(_history_path(cmd_dir)) as f:
            return {e["name"]: e["loadTime"] for e in json.load(f)}
    except (OSError, ValueError):
        return {}


def save_history(cmd_dir: str, hist: Dict[str, float]) -> None:
    with open(_history_path(cmd_dir), "w") as f:
        json.dump([{"name": k, "loadTime": v} for k, v in sorted(hist.items())],
                  f, indent=1)


def run_command(endpoint: str, cmd: Dict[str, Any]) -> Any:
    url = endpoint.rstrip("/") + cmd["url"]
    method = cmd.get("method", "get").upper()
    data = cmd.get("data")
    body = json.dumps(data).encode() if data is not None else None
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def process_dir(cmd_dir: str, endpoint: str) -> List[str]:
    """Execute every new/updated command file; returns processed names."""
    hist = load_history(cmd_dir)
    done: List[str] = []
    for name in sorted(os.listdir(cmd_dir)):
        if not name.endswith(".json") or name.startswith("."):
            continue
        path = os.path.join(cmd_dir, name)
        if hist.get(name, 0) >= os.path.getmtime(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as exc:
            print(f"[kubernetes-tool] {name}: bad json: {exc}", file=sys.stderr)
            continue
        ok = True
        for cmd in doc.get("commands", []):
            desc = cmd.get("description", cmd.get("url", ""))
            try:
                out = run_command(endpoint, cmd)
                print(f"[kubernetes-tool] {name}: {desc}: {out}")
            except urllib.error.HTTPError as exc:
                ok = False
                print(f"[kubernetes-tool] {name}: {desc} FAILED "
                      f"({exc.code}): {exc.read().decode(errors='replace')}",
                      file=sys.stderr)
            except Exception as exc:
                ok = False
                print(f"[kubernetes-tool] {name}: {desc} FAILED: {exc}",
                      file=sys.stderr)
        if ok:
            hist[name] = time.time()
            done.append(name)
    save_history(cmd_dir, hist)
    return done


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", required=True, help="command file directory")
    p.add_argument("--endpoint", default="http://127.0.0.1:9081")
    p.add_argument("--once", action="store_true")
    p.add_argument("--interval", type=float, default=5.0)
    args = p.parse_args(argv)
    while True:
        process_dir(args.dir, args.endpoint)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
