"""Kubernetes deployment tool (analogue of tools/kubernetes: a sidecar that
watches a command directory and replays json command files against the
engine's REST API — declarative stream/rule provisioning for k8s deploys).

Command file shape is the reference's exactly:
    {"commands": [{"url": "/streams", "method": "post",
                   "description": "...", "data": {...}}, ...]}

Processed files are recorded in `.history` (name + loadTime) next to the
command files; a file re-processes when its mtime passes its recorded load
time. Run once (--once) or as a watch loop (--interval seconds).

Usage:
    python -m ekuiper_tpu.tools.kubernetes_tool --dir /commands \
        --endpoint http://127.0.0.1:9081 [--once] [--interval 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


def _history_path(cmd_dir: str, history: str = "") -> str:
    # configMap volumes are read-only: the history file must be able to
    # live elsewhere (--history), default beside the command files
    return history or os.path.join(cmd_dir, ".history")


def load_history(cmd_dir: str, history: str = "") -> Dict[str, dict]:
    try:
        with open(_history_path(cmd_dir, history)) as f:
            out = {}
            for e in json.load(f):
                out[e["name"]] = {"loadTime": e["loadTime"],
                                  "failed": e.get("failed", [])}
            return out
    except (OSError, ValueError):
        return {}


def save_history(cmd_dir: str, hist: Dict[str, dict],
                 history: str = "") -> None:
    with open(_history_path(cmd_dir, history), "w") as f:
        json.dump([{"name": k, **v} for k, v in sorted(hist.items())],
                  f, indent=1)


def run_command(endpoint: str, cmd: Dict[str, Any]) -> Any:
    url = endpoint.rstrip("/") + cmd["url"]
    method = cmd.get("method", "get").upper()
    data = cmd.get("data")
    body = json.dumps(data).encode() if data is not None else None
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def process_dir(cmd_dir: str, endpoint: str, history: str = "",
                hist: Optional[Dict[str, dict]] = None) -> List[str]:
    """Execute new/updated command files; already-succeeded commands of a
    partially failed file are NOT replayed — only the failed indices retry
    until they succeed (non-idempotent POSTs must run once). Pass a
    persistent `hist` dict in watch mode so an unwritable history file
    can't cause replays within the process lifetime. Returns the names
    where at least one command succeeded this pass."""
    if hist is None:
        hist = load_history(cmd_dir, history)
    done: List[str] = []
    for name in sorted(os.listdir(cmd_dir)):
        if not name.endswith(".json") or name.startswith("."):
            continue
        path = os.path.join(cmd_dir, name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue  # atomic configMap swap mid-scan
        entry = hist.get(name)
        if entry and entry["loadTime"] >= mtime and not entry["failed"]:
            continue
        retry_only = (entry["failed"] if entry
                      and entry["loadTime"] >= mtime else None)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"[kubernetes-tool] {name}: bad json: {exc}", file=sys.stderr)
            continue
        failed: List[int] = []
        n_ok = 0
        for i, cmd in enumerate(doc.get("commands", [])):
            if retry_only is not None and i not in retry_only:
                continue
            desc = cmd.get("description", cmd.get("url", ""))
            try:
                out = run_command(endpoint, cmd)
                n_ok += 1
                print(f"[kubernetes-tool] {name}: {desc}: {out}")
            except urllib.error.HTTPError as exc:
                failed.append(i)
                print(f"[kubernetes-tool] {name}: {desc} FAILED "
                      f"({exc.code}): {exc.read().decode(errors='replace')}",
                      file=sys.stderr)
            except Exception as exc:
                failed.append(i)
                print(f"[kubernetes-tool] {name}: {desc} FAILED: {exc}",
                      file=sys.stderr)
        hist[name] = {"loadTime": time.time(), "failed": failed}
        if n_ok:
            done.append(name)
    try:
        save_history(cmd_dir, hist, history)
    except OSError as exc:
        # the in-memory hist (watch mode) still prevents replays; warn so
        # the operator fixes the mount — do NOT fail the successful commands
        print(f"[kubernetes-tool] cannot persist history: {exc}",
              file=sys.stderr)
    return done


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", required=True, help="command file directory")
    p.add_argument("--endpoint", default="http://127.0.0.1:9081")
    p.add_argument("--once", action="store_true")
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--history", default="",
                   help="history file path (outside a read-only command dir)")
    args = p.parse_args(argv)
    if args.once:
        # batch mode (k8s Job / init container): failures must fail the job
        hist = load_history(args.dir, args.history)
        process_dir(args.dir, args.endpoint, history=args.history, hist=hist)
        return 1 if any(e.get("failed") for e in hist.values()) else 0
    hist = load_history(args.dir, args.history)
    while True:
        try:
            process_dir(args.dir, args.endpoint, history=args.history,
                        hist=hist)
        except Exception as exc:  # long-running sidecar: never die on a poll
            print(f"[kubernetes-tool] poll error: {exc}", file=sys.stderr)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
