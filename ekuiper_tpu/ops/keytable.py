"""GROUP BY key table — dictionary encoding of group keys to dense slot ids.

The reference builds a string group key per row and hashes into a Go map
(internal/topo/operator/aggregate_operator.go:34-74). On TPU the per-key
state lives in dense device arrays, so keys must become stable integer slots.
The key table is the host-side dictionary: a C-level dict map per batch in
steady state (no sort once all keys are known), a sort-based np.unique path
for numeric/unicode and unhashable keys, and a reverse list for decoding
emitted slots back to key values.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _native_keytab_module():
    """ekjsoncol when it is loaded AND carries the keytab API, else None.
    Never triggers a build (io/fastjson.py owns that lifecycle)."""
    try:
        from ..io import fastjson

        if fastjson.has_keytab():
            return fastjson.native_module()
    except Exception:
        pass
    return None


class KeyTable:
    def __init__(self, initial_capacity: int = 16384) -> None:
        self.capacity = initial_capacity
        self._ids: Dict[Any, int] = {}
        self._keys: List[Any] = []
        # native slot-encode fast path (native/jsoncol.cpp keytab_*): a
        # persistent byte-keyed hash table assigns slots in one C pass for
        # plain str/None key columns — the dominant GROUP BY shape. The
        # Python table REMAINS the source of truth (reverse decode,
        # checkpointing, every non-str shape); the native table mirrors it
        # via the ordered new-key appendix and a lazy catch-up, and any
        # batch the C side can't represent byte-identically falls back
        # here without ever diverging the two.
        self._ntab = None
        self._native_n = 0  # python keys already mirrored into the native tab
        self._native_ok = True
        # tiered key state (ops/tierstore.py): retired (demoted) slots
        # recycle through this free list instead of forcing capacity
        # growth; `track_new` turns on the new-key log the tier manager
        # drains at the slot-encode admission point
        self._free: List[int] = []
        self.track_new = False
        self._new_log: List[Tuple[Any, int]] = []

    # -------------------------------------------------------------- native
    def _native_encode(self, lst: list) -> Optional[Tuple[np.ndarray, bool]]:
        """One-pass C slot encode for str/None key lists; None when the
        native path is unavailable or this table's history can't mirror
        (non-string keys seen) — the caller runs the Python path."""
        if not self._native_ok:
            return None
        mod = _native_keytab_module()
        if mod is None:
            return None
        try:
            if self._ntab is None:
                self._ntab = mod.keytab_new()
            if self._native_n < len(self._keys):
                # catch up: keys that arrived via Python paths (sorted
                # fallback, tuples, restore) feed the native table in slot
                # order so both sides assign identical ids from here on
                missing = self._keys[self._native_n:]
                if not all(type(k) is str for k in missing):
                    self._native_ok = False  # tuples/numerics: python-only
                    return None
                mod.keytab_encode(self._ntab, missing)
                self._native_n = len(self._keys)
            slots, appendix = mod.keytab_encode(self._ntab, lst)
        except Exception:
            # ekjsoncol.Fallback (non-str / lone-surrogate key) or any
            # native fault: the table was NOT mutated — python path
            return None
        if appendix:
            ids = self._ids
            start = len(self._keys)
            ids.update(zip(appendix, range(start, start + len(appendix))))
            self._keys.extend(appendix)
            self._native_n = len(self._keys)
            if self.track_new:
                self._new_log.extend(
                    zip(appendix, range(start, start + len(appendix))))
        grew = False
        while len(self._keys) > self.capacity:
            self.capacity *= 2
            grew = True
        return slots, grew

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def n_keys(self) -> int:
        return len(self._keys)

    def encode_column(self, col: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Encode a key column to int32 slots. Returns (slots, grew) where
        `grew` signals the device state must be re-allocated (capacity x2).

        Steady-state fast path: one C-level dict lookup per row
        (map(dict.__getitem__) + np.fromiter ≈ 10M rows/s) — after warmup
        every key already has a slot, so no sort is needed at all. A KeyError
        (new key) drops to the insertion loop; unhashable values drop to the
        sort-based legacy path below."""
        if col.dtype == np.object_ and len(col):
            lst = col.tolist()
            out = self._native_encode(lst)
            if out is not None:
                return out
            try:
                return self._encode_hashed(lst)
            except TypeError:
                pass  # unhashable elements — legacy sort path
        return self._encode_sorted(col)

    def _encode_hashed(self, lst: list) -> Tuple[np.ndarray, bool]:
        """Dict-encode a list of hashable keys. Raises TypeError on
        unhashable elements (caller falls back to the sort path)."""
        ids = self._ids
        n = len(lst)
        try:
            return (
                np.fromiter(map(ids.__getitem__, lst), dtype=np.int32, count=n),
                False,
            )
        except KeyError:
            pass
        # miss path, all C-speed bulk ops (the cold-dictionary window of a
        # 1M-key rule runs this every batch — a per-key Python loop here was
        # the 759k-rows/s cold bottleneck, VERDICT r4 weak #6):
        #   1. one membership scan keeps only missing keys
        #   2. dict.fromkeys dedupes them ordered
        #   3. ids.update(zip(...)) + keys.extend assign dense slots
        # Keys needing normalization (None -> "" nil-key rule, tuples with
        # None) are rare and fall to the per-key loop; plain strings — the
        # overwhelmingly common GROUP BY key shape — never do.
        keys = self._keys
        missing = dict.fromkeys(k for k in lst if k not in ids)
        if all(type(k) is str for k in missing) and not self._free:
            start = len(keys)
            ids.update(zip(missing, range(start, start + len(missing))))
            keys.extend(missing)
            if self.track_new:
                self._new_log.extend(
                    zip(missing, range(start, start + len(missing))))
        else:
            for k in missing:
                if k in ids:
                    continue
                norm = self._normalize(k)
                slot = ids.get(norm)
                if slot is None:
                    slot = self._assign_slot(norm)
                if norm is not k:
                    ids[k] = slot  # alias raw form (None / tuple with None)
        out = np.fromiter(map(ids.__getitem__, lst), dtype=np.int32, count=n)
        grew = False
        while len(keys) > self.capacity:
            self.capacity *= 2
            grew = True
        return out, grew

    @staticmethod
    def _normalize(k: Any) -> Any:
        if k is None:
            return ""
        if isinstance(k, tuple):
            return tuple("" if v is None else v for v in k)
        return k

    def _assign_slot(self, k: Any) -> int:
        """Assign a dense slot to a NEW key: a recycled free slot when
        one exists (tiered demotion freed it), else the next append —
        capacity growth stays the last resort."""
        if self._free:
            slot = self._free.pop()
            self._keys[slot] = k
        else:
            slot = len(self._keys)
            self._keys.append(k)
        self._ids[k] = slot
        if self.track_new:
            self._new_log.append((k, slot))
        return slot

    # --------------------------------------------------- tiered key state
    def retire(self, slots: Sequence[int], keys: Sequence[Any]) -> None:
        """Demote keys out of the table: their slots join the free list
        and recycle to future new keys. The native mirror cannot
        represent holes, so retirement pins this table to the Python
        path. Callers must pass the keys currently holding the slots
        (the tier manager re-validates via decode before demoting)."""
        self._native_ok = False
        for slot, key in zip(slots, keys):
            if self._keys[slot] != key:
                continue  # raced a re-encode; leave the slot live
            self._ids.pop(key, None)
            self._keys[slot] = None
            self._free.append(slot)
        self._approx_bytes_cache = None

    def drain_new_keys(self) -> List[Tuple[Any, int]]:
        """(key, slot) pairs assigned since the last drain — the tier
        manager's admission signal (only NEW keys can be returning
        demoted keys, so the store lookup is bounded by this log, not
        the batch)."""
        out, self._new_log = self._new_log, []
        return out

    def free_slots(self) -> List[int]:
        return list(self._free)

    def _encode_sorted(self, col: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Sort-based encode for numeric/unicode columns and object columns
        holding unhashable values: np.unique sorts (numeric ~30M rows/s,
        fixed-width unicode ~3M), then one dict lookup per distinct key."""
        if col.dtype == np.object_ and len(col):
            none_mask = col == None  # noqa: E711 — elementwise None test
            if none_mask.any():
                # nil group key becomes the empty string (reference behavior:
                # null dimensions group under the empty key); also keeps
                # np.unique's object sort from comparing str against None
                col = col.copy()
                col[none_mask] = ""
            if isinstance(col[0], str):
                try:
                    col = col.astype("U")
                except (ValueError, TypeError):
                    pass  # mixed types — keep object
        try:
            uniq, inverse = np.unique(col, return_inverse=True)
        except TypeError:
            # mixed incomparable types: keep hashable values as THEMSELVES
            # and stringify only unhashable elements (matching
            # encode_multi's _h). The old blanket repr() gave every value a
            # second identity in mixed batches — '' became "''", so a key
            # seen via this path and via the hashed path got TWO slots.
            normed = []
            for x in col.tolist():
                try:
                    hash(x)
                except TypeError:
                    normed.append(repr(x))
                else:
                    normed.append(x)
            return self._encode_hashed(normed)
        uids = np.empty(len(uniq), dtype=np.int32)
        ids = self._ids
        keys = self._keys
        for i, k in enumerate(uniq):
            k = k.item() if isinstance(k, np.generic) else k
            try:
                slot = ids.get(k)
            except TypeError:
                # unhashable key (list/dict): stringify, like the reference's
                # string group keys (aggregate_operator.go builds a string)
                k = repr(k)
                slot = ids.get(k)
            if slot is None:
                slot = self._assign_slot(k)
            uids[i] = slot
        grew = False
        while len(keys) > self.capacity:
            self.capacity *= 2
            grew = True
        return uids[inverse].astype(np.int32), grew

    def encode_multi(self, cols: Sequence[np.ndarray]) -> Tuple[np.ndarray, bool]:
        """Composite key: tuple of column values per row. tolist() converts
        numpy scalars to Python values, zip builds the tuples at C speed, and
        the hashed path aliases raw (None-bearing) tuples to their normalized
        slot — so steady state is still one dict lookup per row."""
        if len(cols) == 1:
            return self.encode_column(cols[0])
        try:
            combos = list(zip(*(c.tolist() for c in cols)))
            return self._encode_hashed(combos)
        except TypeError:
            pass
        # unhashable element inside a tuple (list/dict group key): stringify
        # just those elements so the key stays a per-dim tuple for decode
        def _h(v):
            if v is None:
                return ""
            try:
                hash(v)
                return v
            except TypeError:
                return repr(v)

        combos = [tuple(_h(v) for v in row)
                  for row in zip(*(c.tolist() for c in cols))]
        return self._encode_hashed(combos)

    def approx_bytes(self) -> int:
        """Approximate host bytes held by the table (memory accounting,
        observability/memwatch.py). A full walk is O(n_keys), so the
        result is cached until the key count changes — scrapes of a
        steady-state million-key table cost one comparison."""
        n = len(self._keys)
        cached = getattr(self, "_approx_bytes_cache", None)
        if cached is not None and cached[0] == n:
            return cached[1]
        key_bytes = 0
        for k in self._keys:
            if k is None:
                continue  # retired slot (tiered demotion hole)
            if type(k) is str:
                key_bytes += 56 + len(k)  # CPython str header + payload
            elif isinstance(k, tuple):
                key_bytes += 56 + 64 * len(k)
            else:
                key_bytes += 64
        # ids dict holds ~the same keys again by reference + int values;
        # ~100B/entry of dict/list machinery covers both containers
        total = key_bytes + n * 100
        self._approx_bytes_cache = (n, total)
        return total

    def decode(self, slot: int) -> Any:
        return self._keys[slot]

    def decode_all(self) -> List[Any]:
        return list(self._keys)

    def keys_slice(self, start: int, end: int) -> List[Any]:
        """Keys for slots [start, end) in insertion order — slot ids are
        dense and insertion-ordered, so a second table fed exactly these
        keys (in order) assigns identical ids (shared-source slot reuse)."""
        return self._keys[start:end]

    def clear(self) -> None:
        self._ids.clear()
        self._keys.clear()
        # drop the native mirror; the next native encode re-feeds from
        # _keys (empty now), so both sides restart in lockstep
        self._ntab = None
        self._native_n = 0
        self._native_ok = True
        self._free.clear()
        self._new_log.clear()

    def restore(self, keys: List[Any]) -> None:
        """Rebuild in the exact slot order of a checkpoint (slot ids index
        the saved device partials, so order must be preserved). The native
        mirror re-syncs lazily via the catch-up in _native_encode. A None
        entry is a retired (tiered-demotion) hole: the slot rejoins the
        free list; None is never a live key (nil keys normalize to "")."""
        self.clear()
        for i, k in enumerate(keys):
            self._keys.append(k)
            if k is None:
                self._free.append(i)
                self._native_ok = False
            else:
                self._ids[k] = i
        while len(self._keys) > self.capacity:
            self.capacity *= 2
