"""GROUP BY key table — dictionary encoding of group keys to dense slot ids.

The reference builds a string group key per row and hashes into a Go map
(internal/topo/operator/aggregate_operator.go:34-74). On TPU the per-key
state lives in dense device arrays, so keys must become stable integer slots.
The key table is the host-side dictionary: batch-vectorized encode via
np.unique (one dict lookup per *distinct* key per batch, not per row) and a
reverse list for decoding emitted slots back to key values.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class KeyTable:
    def __init__(self, initial_capacity: int = 16384) -> None:
        self.capacity = initial_capacity
        self._ids: Dict[Any, int] = {}
        self._keys: List[Any] = []

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def n_keys(self) -> int:
        return len(self._keys)

    def encode_column(self, col: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Encode a key column to int32 slots. Returns (slots, grew) where
        `grew` signals the device state must be re-allocated (capacity x2).

        np.unique on object arrays does python-level compares (~2M rows/s);
        numeric keys sort at ~30M rows/s and fixed-width unicode at ~3M, so
        convert when the column allows it."""
        if col.dtype == np.object_ and len(col):
            none_mask = col == None  # noqa: E711 — elementwise None test
            if none_mask.any():
                # nil group key becomes the empty string (reference behavior:
                # null dimensions group under the empty key); also keeps
                # np.unique's object sort from comparing str against None
                col = col.copy()
                col[none_mask] = ""
            if isinstance(col[0], str):
                try:
                    col = col.astype("U")
                except (ValueError, TypeError):
                    pass  # mixed types — keep object
        try:
            uniq, inverse = np.unique(col, return_inverse=True)
        except TypeError:
            # mixed incomparable types: fall back to stringified sort key
            col = np.array([repr(x) for x in col], dtype="U")
            uniq, inverse = np.unique(col, return_inverse=True)
        uids = np.empty(len(uniq), dtype=np.int32)
        ids = self._ids
        keys = self._keys
        for i, k in enumerate(uniq):
            k = k.item() if isinstance(k, np.generic) else k
            slot = ids.get(k)
            if slot is None:
                slot = len(keys)
                ids[k] = slot
                keys.append(k)
            uids[i] = slot
        grew = False
        while len(keys) > self.capacity:
            self.capacity *= 2
            grew = True
        return uids[inverse].astype(np.int32), grew

    def encode_multi(self, cols: Sequence[np.ndarray]) -> Tuple[np.ndarray, bool]:
        """Composite key: tuple of column values per row."""
        if len(cols) == 1:
            return self.encode_column(cols[0])
        n = len(cols[0])
        combo = np.empty(n, dtype=np.object_)
        for i in range(n):
            # None elements normalize to "" (nil-key rule, see encode_column)
            combo[i] = tuple(
                "" if c[i] is None
                else (c[i].item() if isinstance(c[i], np.generic) else c[i])
                for c in cols
            )
        return self.encode_column(combo)

    def decode(self, slot: int) -> Any:
        return self._keys[slot]

    def decode_all(self) -> List[Any]:
        return list(self._keys)

    def clear(self) -> None:
        self._ids.clear()
        self._keys.clear()

    def restore(self, keys: List[Any]) -> None:
        """Rebuild in the exact slot order of a checkpoint (slot ids index
        the saved device partials, so order must be preserved)."""
        self.clear()
        for i, k in enumerate(keys):
            self._ids[k] = i
            self._keys.append(k)
        while len(self._keys) > self.capacity:
            self.capacity *= 2
