"""Device stream-stream joins — the banded-gather join ring.

The reference joins two windowed streams with a per-pair nested loop
(runtime/nodes_join.py, analogue of internal/topo/operator/join_operator.go):
every (left, right) candidate runs the ON expression through the row
interpreter. This module is the device half of the relational tier: both
sides key-encode through one KeyTable (ops/keytable.py — identical values
get identical int32 slots), event time rebases to a per-call int32 offset,
and the join predicate becomes pure index arithmetic over a padded
[PL, PR] candidate block (TiLT, arxiv 2301.12030: temporal predicates
lower to tensor index math, not per-row interpretation):

    eq[i,j]   = slot_l[i] == slot_r[j]           -- equi-key conjuncts
    band[i,j] = lo <= ts_l[i] - ts_r[j] <= hi    -- interval conjuncts
    mask      = eq & band & valid & residual      -- expr-IR 3VL residual

NULL key components encode as one reserved dictionary value (KEY_NULL):
this engine's `=` evaluates NULL = NULL as true (sql/eval.py), so NULL
keys pair with each other but never with a real value, and
LEFT/RIGHT/FULL validity falls out of the row-wise any() reductions of
the same mask. The ON residual
(conjuncts that are neither equi-key nor band) compiles through the
expression IR (sql/expr_ir.py) with want="bool": NULL folds to False,
exactly the host evaluator's `v is True` join semantics.

Ring storage: each side keeps time-bucketed columnar chunks
(generalizing ops/panestore.py's pane ring to a dual-side event-time
ring). A banded lookup visits only the buckets an interval predicate
can reach — index arithmetic again, this time over bucket ids — and
eviction drops whole buckets below the watermark.

Exactness: slot equality is exact (dictionary encoding), the band is
exact integer arithmetic (rebased int32; per-call range is bounded, see
TS_RANGE_CAP), and the residual shares host NULL semantics by IR
construction — so the device mask is bit-identical to the nested-loop
decision on every supported plan. Anything outside that contract raises
JoinWindowFallback and the window runs the host nested loop instead.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .keytable import KeyTable

#: pow-2 pad floor per join side — one executable serves every window
#: side up to the floor, doublings cover the rest (jitcert certifies the
#: (PL, PR) pad-pair ladder as this site's closed signature set)
JOIN_PAD_FLOOR = 256

#: certified top of the per-side pad ladder: capacity doublings past the
#: construction capacity stop here (a window side beyond 2^20 rows is a
#: planning bug worth surfacing as an uncertified signature)
JOIN_PAD_CAP = 1 << 20

#: max rebased event-time range per match call; with band bounds clamped
#: to +-BAND_CLAMP every dt the kernel forms stays inside int32
TS_RANGE_CAP = 1 << 28
BAND_CLAMP = 1 << 28

#: "no band predicate" bounds — admit every dt the data range can form
BAND_OPEN = 1 << 30

#: NULL event-time sentinels: dt against a real ts (range-capped) can
#: never re-enter the clamped band, so a NULL-timestamped row matches
#: nothing — the host evaluator's NULL-comparison semantics
_TS_NULL_L = -(1 << 30)
_TS_NULL_R = 1 << 30

#: reserved key value a NULL equi-key component encodes as — NULL = NULL
#: is true in this engine, so NULLs share one dictionary slot. Distinct
#: from "" (KeyTable normalizes None to "", which would conflate the two)
KEY_NULL = "\x00\x00sql-null\x00\x00"


class JoinWindowFallback(Exception):
    """One window's data stepped outside the device contract (non-integer
    event time, range past TS_RANGE_CAP). The caller runs the host
    nested loop for that window; the plan stays lifted."""

    def __init__(self, msg: str, reason: str = "join_runtime") -> None:
        super().__init__(msg)
        self.reason = reason


def _pad_pow2(n: int) -> int:
    b = JOIN_PAD_FLOOR
    while b < n:
        b <<= 1
    return b


def _is_null(v: Any) -> bool:
    return v is None or (isinstance(v, float) and v != v)


def _num32(values: Sequence[Any], n: int) -> np.ndarray:
    """Raw column -> float32 with NaN at NULL/non-numeric rows (the
    expression IR's null encoding for plain numeric columns)."""
    if len(values) == n and n:
        arr = np.asarray(values)
        # homogeneous numeric column: no None/str possible, NaN rows are
        # already the null encoding — skip the per-element scan
        if arr.ndim == 1 and arr.dtype.kind in "iufb":
            return arr.astype(np.float32)
    out = np.full(n, np.nan, dtype=np.float32)
    for i, v in enumerate(values):
        if isinstance(v, bool):
            out[i] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[i] = v
    return out


@dataclass
class SideBatch:
    """One join side, staged columnar in arrival order. `key_cols` holds
    one value-list per equi-key component; `band` the raw event-time
    column (None entries are SQL NULL); `cols` the raw columns the ON
    residual reads, keyed by their stream-renamed device name."""

    n: int
    key_cols: List[List[Any]] = field(default_factory=list)
    band: Optional[List[Any]] = None
    cols: Dict[str, List[Any]] = field(default_factory=dict)


class JoinRing:
    """Dual-side, time-bucketed join state + the certified match kernel.

    `match(left, right)` returns the exact [nl, nr] boolean join mask;
    `match_host` is the numpy shadow twin emitted from the same lowering
    (same slots, same rebased band, same residual IR in host mode) used
    by the parity gates. Ring append/evict/window give interval-mode
    streaming the banded bucket gather."""

    #: jitcert/devwatch site family for this kernel's jit sites
    watch_prefix = "joinring"

    def __init__(self, n_key_cols: int = 1, band: bool = False,
                 lo: Optional[int] = None, hi: Optional[int] = None,
                 residual=None, residual_host=None,
                 derived: Tuple[Any, ...] = (),
                 col_dtypes: Optional[Dict[str, str]] = None,
                 capacity: int = 4096, bucket_ms: int = 1000) -> None:
        self.n_key_cols = int(n_key_cols)
        self.band = bool(band)
        self.lo = BAND_OPEN * -1 if lo is None else max(lo, -BAND_CLAMP)
        self.hi = BAND_OPEN if hi is None else min(hi, BAND_CLAMP)
        self._residual = residual            # CompiledIR, mode="device"
        self._residual_host = residual_host  # CompiledIR, mode="host"
        self._derived = {d.name: d for d in derived}
        self.col_dtypes = dict(col_dtypes or {})
        self.capacity = int(capacity)
        self.bucket_ms = max(int(bucket_ms), 1)
        self.keys = KeyTable(initial_capacity=16384)
        # device column names per side (sorted — the jit pytree order)
        res_cols = sorted(residual.columns) if residual is not None else []
        self.resid_l = [c for c in res_cols if "__jl_" in c]
        self.resid_r = [c for c in res_cols if "__jr_" in c]
        # event-time ring: side -> {bucket_id: [SideBatch, ...]}
        self._buckets: Dict[str, Dict[int, List[Tuple[SideBatch,
                                                      np.ndarray]]]] = {
            "l": {}, "r": {}}
        self._ring_rows = {"l": 0, "r": 0}
        # observability counters (rendered by render_prometheus below)
        self.rows_total = {"l": 0, "r": 0}
        self.matches_total = 0
        self.fallback_windows_total = 0
        from ..observability import jitcert, memwatch
        from ..runtime.aotcache import aot_jit

        self._match = aot_jit(self._match_impl, op="joinring.match",
                              kind="boundary")
        memwatch.register("joinring", self, lambda jr: jr.nbytes())
        jitcert.register_kernel(self)
        _registry.register(self)

    def _watch_op(self, site: str) -> str:
        return f"{self.watch_prefix}.{site}"

    # ------------------------------------------------------------ kernel
    def _match_impl(self, slot_l, ts_l, vl, slot_r, ts_r, vr, lo, hi,
                    cols_l, cols_r):
        import jax.numpy as jnp

        eq = slot_l[:, None] == slot_r[None, :]
        dt = ts_l[:, None] - ts_r[None, :]
        band = (dt >= lo) & (dt <= hi)
        mask = eq & band & vl[:, None] & vr[None, :]
        if self._residual is not None:
            cols = {k: v[:, None] for k, v in cols_l.items()}
            cols.update({k: v[None, :] for k, v in cols_r.items()})
            mask = mask & jnp.asarray(self._residual(cols), bool)
        return mask

    # --------------------------------------------------------- host prep
    def _slots(self, batch: SideBatch, side: str) -> np.ndarray:
        """Dictionary-encode one side's equi-key columns to int32 slots.
        This engine's `=` evaluates NULL = NULL as true (eval.py binary
        semantics, after the reference), so a NULL component encodes as a
        reserved key value shared by both sides — NULL keys pair with
        each other but never with a real value (including "")."""
        if self.n_key_cols == 0:
            return np.zeros(batch.n, dtype=np.int32)  # CROSS: all pairs
        arrays = []
        for comp in batch.key_cols:
            raw = np.asarray(comp, dtype=object)
            if raw.ndim == 1 and len(raw) == batch.n:
                probe = np.asarray(comp)
                if probe.ndim == 1 and probe.dtype.kind in "USiub":
                    # homogeneous str/int/bool column: no NULL possible,
                    # skip the per-element null scan
                    arrays.append(raw)
                    continue
            col = np.empty(batch.n, dtype=object)
            for i, v in enumerate(comp):
                col[i] = KEY_NULL if _is_null(v) else v
            arrays.append(col)
        slots, _ = self.keys.encode_multi(arrays)
        return slots.astype(np.int32, copy=True)

    def _ts32(self, left: SideBatch, right: SideBatch
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Rebase both sides' raw event-time columns to a shared int32
        offset. Differences are invariant under the rebase, so the band
        compare is exact for any integral input whose per-call range
        fits TS_RANGE_CAP."""
        if not self.band:
            return (np.zeros(left.n, dtype=np.int32),
                    np.zeros(right.n, dtype=np.int32))
        sides = [self._ts_col(left.band, left.n),
                 self._ts_col(right.band, right.n)]
        lo = hi = None
        for ints, null in sides:
            if not null.all():
                live = ints[~null]
                lo = int(live.min()) if lo is None else min(lo, int(live.min()))
                hi = int(live.max()) if hi is None else max(hi, int(live.max()))
        base = lo if lo is not None else 0
        if hi is not None and hi - base > TS_RANGE_CAP:
            raise JoinWindowFallback(
                f"event-time range {hi - base} past TS_RANGE_CAP",
                reason="join_ts_range")
        out = []
        for (ints, null), sent in zip(sides, (_TS_NULL_L, _TS_NULL_R)):
            out.append(np.where(null, np.int64(sent),
                                ints - base).astype(np.int32))
        return out[0], out[1]

    @staticmethod
    def _ts_col(vals: Optional[List[Any]],
                n: int) -> Tuple[np.ndarray, np.ndarray]:
        """One side's raw event-time column -> (int64 values, null mask),
        validating the device contract: integral numerics only. A
        homogeneous int/float list takes the vectorized lane; mixed or
        non-numeric columns drop to the per-element scan."""
        vals = vals or []
        if len(vals) == n and n:
            arr = np.asarray(vals)
            if arr.ndim == 1:
                if arr.dtype.kind in "iu":
                    return arr.astype(np.int64), np.zeros(n, dtype=bool)
                if arr.dtype.kind == "f":
                    null = np.isnan(arr)
                    live = arr[~null]
                    if live.size and (
                            not np.isfinite(live).all()
                            or (live != np.rint(live)).any()):
                        raise JoinWindowFallback(
                            "non-integral event time in window",
                            reason="join_ts_type")
                    return (np.where(null, 0.0, arr).astype(np.int64),
                            null)
        null = np.ones(n, dtype=bool)
        ints = np.zeros(n, dtype=np.int64)
        for i, v in enumerate(vals):
            if _is_null(v):
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise JoinWindowFallback(
                    f"non-numeric event time {v!r}", reason="join_ts_type")
            if isinstance(v, float) and not v.is_integer():
                raise JoinWindowFallback(
                    f"non-integral event time {v!r}", reason="join_ts_type")
            ints[i] = int(v)
            null[i] = False
        return ints, null

    def _prep_cols(self, batch: SideBatch, names: List[str],
                   pad: int) -> Dict[str, np.ndarray]:
        """Residual device columns for one side, padded: derived
        (__sd_*/__ts32_*) columns run their DerivedCol encoder, plain
        columns upload float32-with-NaN."""
        out: Dict[str, np.ndarray] = {}
        for name in names:
            d = self._derived.get(name)
            if d is not None:
                raw = np.empty(batch.n, dtype=object)
                vals = batch.cols.get(d.raw, [])
                for i in range(batch.n):
                    v = vals[i] if i < len(vals) else None
                    raw[i] = None if _is_null(v) else v
                col = d.encode(raw, batch.n)
            else:
                col = _num32(batch.cols.get(name, []), batch.n)
            if pad > batch.n:
                col = np.pad(col, (0, pad - batch.n))
            out[name] = col
        return out

    # -------------------------------------------------------------- match
    def match(self, left: SideBatch, right: SideBatch) -> np.ndarray:
        """The exact [nl, nr] join decision mask, via the certified
        device kernel. Raises JoinWindowFallback when this window's data
        steps outside the device contract."""
        import jax.numpy as jnp

        nl, nr = left.n, right.n
        slot_l, slot_r = self._slots(left, "l"), self._slots(right, "r")
        ts_l, ts_r = self._ts32(left, right)
        pl, pr = _pad_pow2(nl), _pad_pow2(nr)
        while self.capacity < max(pl, pr):
            self.capacity *= 2
        vl = np.zeros(pl, dtype=bool)
        vl[:nl] = True
        vr = np.zeros(pr, dtype=bool)
        vr[:nr] = True
        mask = self._match(
            jnp.asarray(np.pad(slot_l, (0, pl - nl))),
            jnp.asarray(np.pad(ts_l, (0, pl - nl))),
            jnp.asarray(vl),
            jnp.asarray(np.pad(slot_r, (0, pr - nr))),
            jnp.asarray(np.pad(ts_r, (0, pr - nr))),
            jnp.asarray(vr),
            jnp.asarray(self.lo, dtype=jnp.int32),
            jnp.asarray(self.hi, dtype=jnp.int32),
            {k: jnp.asarray(v)
             for k, v in self._prep_cols(left, self.resid_l, pl).items()},
            {k: jnp.asarray(v)
             for k, v in self._prep_cols(right, self.resid_r, pr).items()})
        out = np.asarray(mask)[:nl, :nr]
        self.rows_total["l"] += nl
        self.rows_total["r"] += nr
        self.matches_total += int(np.count_nonzero(out))
        return out

    def match_host(self, left: SideBatch, right: SideBatch) -> np.ndarray:
        """Numpy shadow twin of `match` from the same lowering — same
        slots, same rebased band, same residual IR compiled for host.
        The parity gates assert match == match_host bit-for-bit."""
        nl, nr = left.n, right.n
        slot_l, slot_r = self._slots(left, "l"), self._slots(right, "r")
        ts_l, ts_r = self._ts32(left, right)
        eq = slot_l[:, None] == slot_r[None, :]
        dt = ts_l[:, None].astype(np.int64) - ts_r[None, :]
        mask = eq & (dt >= self.lo) & (dt <= self.hi)
        if self._residual_host is not None:
            cols = {k: v[:, None] for k, v in
                    self._prep_cols(left, self.resid_l, nl).items()}
            cols.update({k: v[None, :] for k, v in
                         self._prep_cols(right, self.resid_r, nr).items()})
            mask = mask & np.asarray(self._residual_host(cols), dtype=bool)
        return mask

    # ---------------------------------------------------------- ring store
    def append(self, side: str, batch: SideBatch) -> None:
        """Stage one side's rows into the event-time ring. Band values
        bucket by `bucket_ms`; NULL-timestamped rows ride bucket 0 (they
        can never match a band predicate but LEFT/FULL still emit them)."""
        ts = np.zeros(batch.n, dtype=np.int64)
        if self.band and batch.band is not None:
            for i, v in enumerate(batch.band):
                if not _is_null(v) and isinstance(v, (int, float)):
                    ts[i] = int(v)
        buckets = self._buckets[side]
        for b in np.unique(ts // self.bucket_ms):
            sel = np.nonzero(ts // self.bucket_ms == b)[0]
            sub = SideBatch(
                n=len(sel),
                key_cols=[[c[i] for i in sel] for c in batch.key_cols],
                band=([batch.band[i] for i in sel]
                      if batch.band is not None else None),
                cols={k: [v[i] for i in sel]
                      for k, v in batch.cols.items()})
            buckets.setdefault(int(b), []).append((sub, ts[sel]))
            self._ring_rows[side] += len(sel)

    def window(self, side: str, lo_ts: int, hi_ts: int) -> SideBatch:
        """The banded gather: concatenate only the buckets an interval
        [lo_ts, hi_ts] can reach — bucket selection is index arithmetic
        over bucket ids, never a scan of resident rows."""
        b_lo = lo_ts // self.bucket_ms
        b_hi = hi_ts // self.bucket_ms
        out = SideBatch(n=0, key_cols=[[] for _ in range(self.n_key_cols)])
        if self.band:
            out.band = []
        for b in sorted(self._buckets[side]):
            if b < b_lo or b > b_hi:
                continue
            for sub, ts in self._buckets[side][b]:
                keep = np.nonzero((ts >= lo_ts) & (ts <= hi_ts))[0]
                for ci in range(self.n_key_cols):
                    out.key_cols[ci].extend(
                        sub.key_cols[ci][i] for i in keep)
                if out.band is not None and sub.band is not None:
                    out.band.extend(sub.band[i] for i in keep)
                for k, v in sub.cols.items():
                    out.cols.setdefault(k, []).extend(v[i] for i in keep)
                out.n += len(keep)
        return out

    def evict(self, before_ts: int) -> int:
        """Drop whole buckets strictly below `before_ts` (watermark
        discipline: a bucket is evicted only when no legal band can
        reach it). Returns rows dropped."""
        cut = before_ts // self.bucket_ms
        dropped = 0
        for side, buckets in self._buckets.items():
            for b in [b for b in buckets if b < cut]:
                dropped += sum(s.n for s, _ in buckets.pop(b))
        for side in self._ring_rows:
            self._ring_rows[side] = sum(
                s.n for chunks in self._buckets[side].values()
                for s, _ in chunks)
        return dropped

    def reset_ring(self) -> None:
        self._buckets = {"l": {}, "r": {}}
        self._ring_rows = {"l": 0, "r": 0}

    def ring_rows(self, side: str) -> int:
        return self._ring_rows[side]

    def nbytes(self) -> int:
        """Approximate host bytes held by the ring + key table (memory
        accounting, observability/memwatch.py)."""
        rows = self._ring_rows["l"] + self._ring_rows["r"]
        per_row = 64 * (self.n_key_cols + (1 if self.band else 0)
                        + len(self.resid_l) + len(self.resid_r) + 1)
        return rows * per_row + self.keys.approx_bytes()


# ----------------------------------------------------------- observability
class _Registry:
    """Weakref index of live join rings for /metrics (tierstore's
    ownership model: strong refs stay with the owning node)."""

    def __init__(self) -> None:
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        self._entries: List[Tuple[Any, Optional[str]]] = []

    def register(self, ring, rule: Optional[str] = None) -> None:
        from ..utils.rulelog import current_rule

        with self._lock:
            self._entries = [(r, ru) for (r, ru) in self._entries
                             if r() is not None]
            self._entries.append((self._weakref.ref(ring),
                                  rule or current_rule()))

    def rings(self) -> List[Tuple[Any, Optional[str]]]:
        with self._lock:
            refs = list(self._entries)
        return [(k, rule) for (r, rule) in refs if (k := r()) is not None]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_registry = _Registry()


def registry() -> _Registry:
    return _registry


def reset() -> None:
    """Test hook."""
    _registry.clear()


def render_prometheus(out: List[str], esc) -> None:
    """Append the kuiper_join_* families to a /metrics scrape."""
    fams = (
        ("kuiper_join_rows_total", "counter",
         "rows matched through the device join kernel, by side",
         lambda jr: (("l", jr.rows_total["l"]), ("r", jr.rows_total["r"]))),
        ("kuiper_join_matches_total", "counter",
         "join pairs emitted by the device match mask",
         lambda jr: (("", jr.matches_total),)),
        ("kuiper_join_fallback_windows_total", "counter",
         "windows that fell back to the host nested loop at runtime",
         lambda jr: (("", jr.fallback_windows_total),)),
        ("kuiper_join_ring_bytes", "gauge",
         "host bytes held by the dual-side event-time join ring",
         lambda jr: (("", jr.nbytes()),)),
    )
    rings = _registry.rings()
    for name, mtype, help_txt, fn in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        agg: Dict[Tuple[str, str], int] = {}
        for ring, rule in rings:
            try:
                for side, v in fn(ring):
                    key = (rule or "__engine__", side)
                    agg[key] = agg.get(key, 0) + int(v)
            except Exception:
                continue
        for (rule, side), v in sorted(agg.items()):
            labels = f'rule="{esc(rule)}"'
            if side:
                labels += f',side="{side}"'
            out.append(f"{name}{{{labels}}} {v}")
