"""Tiered key state — HBM-resident hot set with host spill and async prefetch.

Every GROUP BY key's window state has so far had to fit HBM (memwatch
budgets and dev-ring FIFO eviction were the only relief), capping
cardinality near the ~1M-slot bench shape. Following "Support Aggregate
Analytic Window Function over Large Data by Spilling" (arxiv 2007.10385),
this module splits key state into two tiers:

- **hot**: keys keep their dense device slots — today's `DeviceGroupBy`
  state, layout unchanged. A per-slot `uint32` touch column rides the
  state pytree and is bumped inside the existing certified fold (one
  scatter-add — no new host sync), giving the placement policy
  recency/frequency at zero extra round trips.
- **cold**: keys whose touch counter goes idle are demoted at pane
  boundaries: one certified gather (`tierstore.demote`) packs their
  per-pane partial aggregates into a `(D, W)` row block, resets the
  slots to the fold identity, and the freed slots recycle through
  `KeyTable`'s free list — capacity-grow becomes a last resort instead
  of the only move. The packed rows land (async copy, harvested off the
  fold thread by the prefinalize/emit worker) in a pinned host arena
  (`HostTierStore`).

When a demoted key reappears in an ingest batch, the slot-encode path is
the admission point: the batch's new-key log tells us exactly which keys
are returning before the fold runs, and one certified scatter
(`tierstore.promote`) merges their spilled per-pane partials back into a
fresh device slot — add/min/max per component, exactly `absorb`'s
algebra, so the emission is bit-equal to never having demoted. The
ingest prep's upload stage can start the H2D copy of the packed rows a
batch early (`TierManager.prefetch`, runtime/ingest.py).

Exactness across demotion windows: spilled rows remember the per-pane
**reset epoch** they were packed under; a pane reset (window expiry)
bumps the live epoch, so stale pane slices are masked to the fold
identity at promote/emit time instead of leaking a closed window's rows
into a newer one. Spilled keys with live pane data still contribute to
window emissions: `TierManager.window_groups` computes their final
values host-side (the prefinalize numpy tail) and the fused node emits
them alongside the device groups. Sliding/DABA rules demote only
quiescent keys (idle past the whole ring retention), and every
demote/promote marks the ring dirty so the next trigger rebuilds from
the panes (the exact `components_dyn` fallback path).

docs/TIERED_STATE.md documents the policy, the demote/promote protocol,
the exactness argument, and the knobs.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import timex
from .aggspec import WIDE_COMPONENTS
from .groupby import _INIT, _wide_size, apply_int_semantics


# ---------------------------------------------------------------- geometry
@dataclass(frozen=True)
class TierLayout:
    """Plan-time tier geometry — chosen once (planner/planner.py
    plan_tier_layout) and shared with the jitcert derivations, like the
    sliding ring's plan_ring_layout."""

    #: resident-slot target: the demote policy starts evicting cold keys
    #: once live (non-free) slots exceed this
    hot_slots: int
    #: D — slots per demote/promote dispatch; fixed at plan time so each
    #: site compiles ONE executable per capacity-ladder step
    demote_batch: int
    #: placement-policy cadence (engine clock, ms)
    scan_interval_ms: int
    #: consecutive zero-touch-delta scans before a key is demotable
    min_idle_scans: int

    def hot_capacity(self) -> int:
        """The pow2-rounded construction capacity the hot target implies
        — THE one formula shared by node construction (nodes_fused.py)
        and admission pricing (runtime/control.py), so pricing can never
        desynchronize from what gets built."""
        return max(1 << max(self.hot_slots - 1, 1).bit_length(), 1024)


def env_hbm_budget_mb() -> float:
    """The engine-wide KUIPER_HBM_BUDGET_MB (the QoS admission ledger's
    budget), 0 when unset/unparseable — the ONE parse shared by the
    planner's resolve_tier_budget_mb, the shared pane store, and bench."""
    import os

    try:
        return max(float(os.environ.get("KUIPER_HBM_BUDGET_MB", "0")
                         or 0), 0.0)
    except ValueError:
        return 0.0


def state_bytes_per_key(plan, n_panes: int) -> int:
    """Static device bytes per key slot of a plan's group-by state
    (float32 components + act + the uint32 touch column)."""
    comp_specs: Dict[str, int] = {}
    for spec in plan.specs:
        for comp in spec.components:
            comp_specs[comp] = comp_specs.get(comp, 0) + 1
    total = n_panes  # act
    for comp, k in comp_specs.items():
        total += n_panes * k * (_wide_size(comp) if comp in WIDE_COMPONENTS
                                else 1)
    return total * 4 + 4  # + uint32 touch


#: fraction of the HBM budget the hot group-by state may claim (the rest
#: covers micro-batch staging, sliding rings, emit transfers)
HOT_BUDGET_FRACTION = 0.5

DEFAULT_DEMOTE_BATCH = 2048
DEFAULT_MIN_IDLE_SCANS = 2
#: demote dispatches per boundary — bounds the fold-thread work a single
#: boundary can spend evicting (D x this = max slots freed per boundary)
MAX_DEMOTE_BATCHES = 8


def plan_tier_layout(plan, n_panes: int, capacity: int,
                     budget_mb: float, scan_interval_ms: int = 0,
                     window_ms: int = 0) -> Optional[TierLayout]:
    """Tier geometry for a rule: hot-slot target from the HBM budget and
    the plan's per-key state width. None when the budget already covers
    the requested capacity ladder headroom (tiering would be a no-op) —
    unless the budget is tighter than the base capacity, in which case
    the hot target clamps below it."""
    if budget_mb <= 0:
        return None
    per_key = max(state_bytes_per_key(plan, n_panes), 1)
    budget_keys = int(budget_mb * HOT_BUDGET_FRACTION * (1 << 20) / per_key)
    if budget_keys >= capacity * 4:
        # the budget fits two doublings of the requested capacity — the
        # grow ladder has room and eviction pressure would be noise
        return None
    hot = max(min(budget_keys, capacity * 4), 1024)
    scan = int(scan_interval_ms) or max(min(int(window_ms) or 1000, 5000),
                                        250)
    return TierLayout(hot_slots=hot, demote_batch=DEFAULT_DEMOTE_BATCH,
                      scan_interval_ms=scan,
                      min_idle_scans=DEFAULT_MIN_IDLE_SCANS)


# ----------------------------------------------------------- device kernel
class TierStore:
    """The certified demote/promote gather/scatter sites over one
    group-by kernel's state. Packed row layout (per key, float32[W]):
    each component's per-pane block `(n_panes, k[, wide])` flattened
    C-order in sorted component order, then the `(n_panes,)` act block —
    the same sort the state pytree flattens with, so the derivation in
    observability/jitcert.py mirrors the layout exactly."""

    watch_prefix = "tierstore"

    def __init__(self, gb, layout: TierLayout) -> None:
        self.gb = gb
        self.layout = layout
        self.capacity = int(gb.capacity)
        self.demote_batch = int(layout.demote_batch)
        self.n_panes = int(gb.n_panes)
        self.blocks: List[Tuple[str, int, Tuple[int, ...]]] = []
        col = 0
        for comp in sorted(gb.comp_specs):
            tail: Tuple[int, ...] = (len(gb.comp_specs[comp]),)
            if comp in WIDE_COMPONENTS:
                tail = tail + (_wide_size(comp),)
            w = self.n_panes * int(np.prod(tail))
            self.blocks.append((comp, col, tail))
            col += w
        self.blocks.append(("act", col, ()))
        col += self.n_panes
        self.packed_w = col
        from ..runtime.aotcache import aot_jit

        self._demote = aot_jit(self._demote_impl,
                                   op=self._watch_op("demote"),
                                   kind="boundary", donate_argnums=(0,))
        self._promote = aot_jit(self._promote_impl,
                                    op=self._watch_op("promote"),
                                    kind="boundary", donate_argnums=(0,))
        from ..observability import jitcert

        jitcert.register_kernel(self)

    def _watch_op(self, site: str) -> str:
        return f"{self.watch_prefix}.{site}"

    # ------------------------------------------------------------- rows
    def init_row(self) -> np.ndarray:
        """The fold-identity packed row (promote's no-op; also the
        demote result for a slot holding no live data)."""
        row = np.empty(self.packed_w, dtype=np.float32)
        for comp, off, tail in self.blocks:
            w = self.n_panes * int(np.prod(tail)) if tail else self.n_panes
            row[off:off + w] = _INIT[comp]
        return row

    def row_is_idle(self, row: np.ndarray) -> bool:
        """True when a packed row holds no live data — its act block is
        all-zero (act counts post-WHERE rows per pane; every other
        component is init-valued exactly when act is)."""
        comp, off, _ = self.blocks[-1]
        assert comp == "act"
        return not row[off:off + self.n_panes].any()

    def mask_stale_panes(self, row: np.ndarray,
                         stale: np.ndarray) -> np.ndarray:
        """Reset the pane slices of `row` flagged in `stale` (bool[P]) to
        the fold identity — a closed window's rows must never leak into
        the pane's next tenant bucket."""
        if not stale.any():
            return row
        for comp, off, tail in self.blocks:
            w = int(np.prod(tail)) if tail else 1
            seg = row[off:off + self.n_panes * w].reshape(self.n_panes, w)
            seg[stale] = _INIT[comp]
        return row

    # ----------------------------------------------------------- device
    def demote(self, state, slots: np.ndarray):
        """Gather `slots`' per-pane partials into a packed (D, W) device
        block and reset the slots (touch included) to the fold identity.
        `slots` pads to D with duplicates of a real entry — the gather
        rows are ignored by the harvester and the identity set is
        idempotent. Returns (state, packed_dev)."""
        import jax.numpy as jnp

        s = np.asarray(slots, dtype=np.int32)
        if len(s) < self.demote_batch:
            s = np.concatenate([
                s, np.full(self.demote_batch - len(s), s[0], np.int32)])
        return self._demote(state, jnp.asarray(s))

    def promote(self, state, packed: Any, slots: np.ndarray):
        """Scatter-merge packed rows back into device slots: add for the
        additive components (n/s1/s2/hist/hh/act), min/max for mn and
        mx/hll — `absorb`'s algebra, so a promoted key's state is
        bit-equal to never having left. Padding rows must be
        `init_row()` (the combine identity) so duplicate pad slots are
        no-ops. `packed` may be a pre-uploaded device block (prefetch)."""
        import jax
        import jax.numpy as jnp

        s = np.asarray(slots, dtype=np.int32)
        n = len(s)
        if n < self.demote_batch:
            s = np.concatenate([
                s, np.full(self.demote_batch - n, s[0], np.int32)])
        if not isinstance(packed, jax.Array):
            # pad rows past the real entries with the combine IDENTITY —
            # the pad slots are duplicates of a real slot, so anything
            # else would double-merge it
            arr = np.asarray(packed, dtype=np.float32)
            block = np.tile(self.init_row(), (self.demote_batch, 1))
            block[:n] = arr[:n]
            packed = jnp.asarray(block)
        return self._promote(state, packed, jnp.asarray(s))

    def _demote_impl(self, state, slots):
        import jax.numpy as jnp

        parts = []
        for comp, _off, _tail in self.blocks:
            arr = state[comp]  # (P, cap[, k[, wide]])
            g = jnp.moveaxis(jnp.take(arr, slots, axis=1), 1, 0)
            parts.append(g.reshape(g.shape[0], -1))
            state[comp] = arr.at[:, slots].set(
                jnp.asarray(_INIT[comp], dtype=arr.dtype))
        if "touch" in state:
            t = state["touch"]
            state["touch"] = t.at[slots].set(jnp.asarray(0, dtype=t.dtype))
        return state, jnp.concatenate(parts, axis=1)

    def _promote_impl(self, state, packed, slots):
        import jax.numpy as jnp

        col = 0
        for comp, _off, tail in self.blocks:
            arr = state[comp]
            w = int(np.prod(tail)) if tail else 1
            seg = packed[:, col:col + self.n_panes * w]
            col += self.n_panes * w
            seg = seg.reshape(seg.shape[0], self.n_panes, *tail)
            seg = jnp.moveaxis(seg, 0, 1)  # (P, D, ...)
            if comp == "mn":
                state[comp] = arr.at[:, slots].min(seg)
            elif comp in ("mx", "hll"):
                state[comp] = arr.at[:, slots].max(seg)
            else:
                state[comp] = arr.at[:, slots].add(seg)
        return state


# ------------------------------------------------------------- host store
class HostTierStore:
    """Pinned host arena for spilled per-pane partial rows: one growable
    float32 `(rows, W)` block plus an int64 `(rows, P)` epoch sidecar —
    contiguous allocations, not a dict of a million small arrays, so the
    memwatch probe's estimate IS the allocation (tested)."""

    def __init__(self, packed_w: int, n_panes: int,
                 initial_rows: int = 1024) -> None:
        self.packed_w = int(packed_w)
        self.n_panes = int(n_panes)
        n = max(int(initial_rows), 16)
        self._rows = np.zeros((n, self.packed_w), dtype=np.float32)
        self._epochs = np.zeros((n, self.n_panes), dtype=np.int64)
        self._key_row: Dict[Any, int] = {}
        self._row_key: List[Any] = [None] * n
        self._free: List[int] = list(range(n - 1, -1, -1))

    def __len__(self) -> int:
        return len(self._key_row)

    def __contains__(self, key) -> bool:
        return key in self._key_row

    def nbytes(self) -> int:
        """Arena bytes — the tier_host_store memwatch probe."""
        return int(self._rows.nbytes + self._epochs.nbytes)

    def _grow(self) -> None:
        n = len(self._row_key)
        self._rows = np.concatenate(
            [self._rows, np.zeros_like(self._rows)], axis=0)
        self._epochs = np.concatenate(
            [self._epochs, np.zeros_like(self._epochs)], axis=0)
        self._row_key.extend([None] * n)
        self._free.extend(range(2 * n - 1, n - 1, -1))

    def put(self, key, row: np.ndarray, epochs: np.ndarray) -> None:
        at = self._key_row.get(key)
        if at is None:
            if not self._free:
                self._grow()
            at = self._free.pop()
            self._key_row[key] = at
            self._row_key[at] = key
        self._rows[at] = row
        self._epochs[at] = epochs

    def take(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Remove and return (row copy, epoch copy) for a promoted key."""
        at = self._key_row.pop(key, None)
        if at is None:
            return None
        self._row_key[at] = None
        self._free.append(at)
        return self._rows[at].copy(), self._epochs[at].copy()

    def peek(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        at = self._key_row.get(key)
        if at is None:
            return None
        return self._rows[at], self._epochs[at]

    def drop(self, key) -> bool:
        at = self._key_row.pop(key, None)
        if at is None:
            return False
        self._row_key[at] = None
        self._free.append(at)
        return True

    def items_arrays(self):
        """(keys list, rows view, epochs view) over the resident set —
        the vectorized base of window_groups. Views are read-only by
        contract (callers copy before mutating)."""
        if not self._key_row:
            return [], None, None
        idx = np.fromiter(self._key_row.values(), dtype=np.int64,
                          count=len(self._key_row))
        keys = [self._row_key[i] for i in idx]
        return keys, self._rows[idx], self._epochs[idx]


# -------------------------------------------------------------- telemetry
# weakref registry of live TierManagers — the kuiper_spill_* /
# kuiper_tier_host_bytes render source (utils/weakreg.py, THE shared
# ownership model)
from ..utils.weakreg import WeakRegistry as _TierRegistry

_registry = _TierRegistry()


def registry() -> _TierRegistry:
    return _registry


def reset() -> None:
    """Test hook."""
    _registry.clear()


def render_prometheus(out: List[str], esc) -> None:
    """Append the spill metric families to a /metrics scrape."""
    fams = (
        ("kuiper_spill_demoted_total", "counter",
         "key slots demoted to the host cold tier",
         lambda m: m.demoted_total),
        ("kuiper_spill_promoted_total", "counter",
         "demoted keys promoted back to device slots on reappearance",
         lambda m: m.promoted_total),
        ("kuiper_spill_resident_total", "gauge",
         "keys currently resident in the host cold tier",
         lambda m: len(m.store)),
        ("kuiper_tier_host_bytes", "gauge",
         "host arena bytes held by the cold-tier spill store",
         lambda m: m.store.nbytes()),
    )
    mgrs = _registry.managers()
    for name, mtype, help_txt, fn in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        # aggregate per rule label: several managers can share one label
        # (every tiered shared pane store reports as "__shared__") and
        # duplicate sample lines would fail the whole Prometheus scrape
        agg: Dict[str, int] = {}
        for m, rule in mgrs:
            try:
                v = int(fn(m))
            except Exception:
                continue
            label = rule or "__engine__"
            agg[label] = agg.get(label, 0) + v
        for label, v in sorted(agg.items()):
            out.append(f'{name}{{rule="{esc(label)}"}} {v}')


def diagnostics() -> List[Dict[str, Any]]:
    """Per-manager tier state for GET /diagnostics + kuiperdiag."""
    rows = []
    for m, rule in _registry.managers():
        with m._mu:
            rows.append({
                "rule": rule, "hot_slots": m.layout.hot_slots,
                "demote_batch": m.layout.demote_batch,
                "demoted_total": m.demoted_total,
                "promoted_total": m.promoted_total,
                "recycled_total": m.recycled_total,
                "prefetch_hits": m.prefetch_hits,
                "resident": len(m.store),
                "host_bytes": m.store.nbytes(),
            })
    return rows


# ---------------------------------------------------------------- manager
class TierManager:
    """The placement policy + the host tier, bound to one fused node's
    kernel and key table. Thread contract:

    - fold thread: `admit` (promotions at the slot-encode admission
      point), `on_boundary` (apply the pending demote plan + dispatch the
      touch-column scan), `note_pane_reset` (epoch bumps),
      `window_groups` (spilled emissions).
    - prefinalize/emit worker: `worker_task` — harvest landed demote
      blocks into the arena, run the scan policy, prune stale rows.
    - ingest prep pool: `prefetch` — early H2D of packed rows for
      returning keys spotted in a decoding batch.

    `_mu` guards the store/mirror/plan; `KeyTable` is only ever touched
    from the fold thread."""

    def __init__(self, gb, kt, layout: TierLayout, *, rule_id: str = "",
                 key_name: Optional[str] = None,
                 submit: Optional[Callable[[tuple], None]] = None,
                 quiescent_only: bool = False,
                 min_idle_ms: int = 0,
                 on_tier_event: Optional[Callable[..., None]] = None
                 ) -> None:
        self.gb = gb
        self.kt = kt
        self.layout = layout
        self.ts = TierStore(gb, layout)
        self.store = HostTierStore(self.ts.packed_w, self.ts.n_panes)
        self.key_name = key_name
        self.rule_id = rule_id
        self._submit = submit
        self.quiescent_only = bool(quiescent_only)
        self.min_idle_ms = int(min_idle_ms)
        self._on_tier_event = on_tier_event
        self._mu = threading.Lock()
        self._pane_epoch = np.zeros(self.ts.n_panes, dtype=np.int64)
        self._mirror = np.zeros(0, dtype=np.int64)
        self._idle = np.zeros(0, dtype=np.int32)
        self._plan: List[int] = []  # slots pending demotion (worker-chosen)
        # demote blocks dispatched but not yet harvested: key ->
        # (packed_dev, row index, epochs). A key reappearing inside this
        # window must still promote exactly — admit() fetches its row
        # straight off the pending device block
        self._inflight: Dict[Any, Tuple[Any, int, np.ndarray]] = {}
        self._requeue: List[Tuple[Any, np.ndarray, np.ndarray]] = []
        self._prefetch_q: List[Tuple[tuple, Any]] = []  # (keys, dev block)
        self._last_scan_ms = 0
        self.demoted_total = 0
        self.promoted_total = 0
        self.recycled_total = 0
        self.prefetch_hits = 0
        kt.track_new = True
        from ..observability import memwatch

        memwatch.register("tier_host_store", self,
                          lambda m: m.store.nbytes(), rule=rule_id)
        _registry.register(self, rule_id)

    # ------------------------------------------------------------ epochs
    def note_pane_reset(self, pane: int) -> None:
        with self._mu:
            self._pane_epoch[int(pane)] += 1

    def pane_epochs(self) -> np.ndarray:
        with self._mu:
            return self._pane_epoch.copy()

    # ------------------------------------------------------- fold thread
    def admit(self, state):
        """Promotion at the slot-encode admission point: drain the key
        table's new-key log; any returning key (resident in the cold
        tier) gets its spilled partials merged back into its fresh slot
        before the batch folds. Dispatch-only — the scatter is async on
        the device stream, the fold queues behind it."""
        new = self.kt.drain_new_keys()
        requeued: List[Tuple[Any, np.ndarray, np.ndarray]] = []
        if self._requeue:
            with self._mu:
                requeued, self._requeue = self._requeue, []
        if not new and not requeued:
            return state
        batch_keys: List[Any] = []
        batch_slots: List[int] = []
        batch_rows: List[np.ndarray] = []
        pending: List[Tuple[Any, int, Any, int, np.ndarray]] = []
        with self._mu:
            epoch = self._pane_epoch.copy()
            hits = [(k, s) for (k, s) in new if k in self.store]
            rows = {k: self.store.take(k) for (k, _s) in hits}
            for k, s in new:
                entry = self._inflight.pop(k, None)
                if entry is not None:
                    # returned before its demote block was harvested:
                    # read the row straight off the pending device copy
                    pending.append((k, s, entry[0], entry[1], entry[2]))
        for k, s, packed_dev, idx, row_epochs in pending:
            # kuiperlint: ignore[host-sync]: rare promote-before-harvest path — the demote copy was already in flight, this only waits for it
            row = np.asarray(packed_dev)[idx].copy()
            hits.append((k, s))
            rows[k] = (row, row_epochs.copy())
        for key, row, row_epochs in requeued:
            # a non-quiescent demote raced the policy (quiescent mode):
            # the key re-enters the table and its partials go straight
            # back to the device — no data ever drops
            slots, _ = self.kt.encode_column(
                np.array([key], dtype=np.object_))
            hits.append((key, int(slots[0])))
            rows[key] = (row, row_epochs)
        if requeued and self.gb.capacity < self.kt.capacity:
            # the re-encode above ran AFTER the caller's grow check: a
            # slot past the state extent would be silently dropped by
            # the promote scatter — grow first
            state = self.gb.grow(state, self.kt.capacity)
        if not hits:
            return state
        for key, slot in hits:
            row, row_epochs = rows[key]
            stale = row_epochs != epoch
            self.ts.mask_stale_panes(row, stale)
            if self.ts.row_is_idle(row):
                # nothing live survived the stale mask: the key re-seats
                # with a fresh identity slot, no injection needed
                self.recycled_total += 1
                continue
            batch_keys.append(key)
            batch_slots.append(slot)
            batch_rows.append(row)
        if not batch_keys:
            return state
        D = self.ts.demote_batch
        for start in range(0, len(batch_keys), D):
            keys = batch_keys[start:start + D]
            slots = np.asarray(batch_slots[start:start + D],
                               dtype=np.int32)
            packed = self._prefetched_block(tuple(keys))
            if packed is None:
                block = np.tile(self.ts.init_row(), (D, 1))
                block[:len(keys)] = np.stack(batch_rows[start:start + D])
                packed = block
            state = self.ts.promote(state, packed, slots)
            self.promoted_total += len(keys)
        if self._on_tier_event is not None:
            self._on_tier_event("promote", n=len(batch_keys))
        return state

    def _prefetched_block(self, keys: tuple):
        """A device block the ingest prep staged for exactly this key
        run, if any (H2D already done off the fold thread). A block
        whose epoch snapshot no longer matches the live pane epochs is
        DISCARDED — a pane reset since the prefetch means its stale
        masking is out of date, and merging it would leak a closed
        window's partials into the pane's next tenant."""
        with self._mu:
            for i, (pk, dev, ep) in enumerate(self._prefetch_q):
                if pk == keys:
                    del self._prefetch_q[i]
                    if not np.array_equal(ep, self._pane_epoch):
                        return None  # stale prefetch: admit rebuilds
                    self.prefetch_hits += len(keys)
                    return dev
        return None

    def on_boundary(self, state):
        """Pane-boundary hook (fold thread): apply the worker's pending
        demote plan (one certified gather + async device→host copy, the
        harvest runs on the worker) and, on cadence, dispatch the touch
        scan the next plan is computed from."""
        with self._mu:
            plan, self._plan = self._plan, []
        if plan:
            keys: List[Any] = []
            slots: List[int] = []
            cap = self.ts.demote_batch * MAX_DEMOTE_BATCHES
            for slot in plan:
                if len(keys) >= cap:
                    break
                try:
                    key = self.kt.decode(slot)
                except Exception:
                    continue
                if key is None or not self._retirable(key):
                    continue
                keys.append(key)
                slots.append(int(slot))
            D = self.ts.demote_batch
            for start in range(0, len(keys), D):
                ck = keys[start:start + D]
                cs = slots[start:start + D]
                s = np.asarray(cs, dtype=np.int32)
                state, packed_dev = self.ts.demote(state, s)
                try:
                    packed_dev.copy_to_host_async()
                except AttributeError:
                    pass
                self.kt.retire(cs, ck)
                self.demoted_total += len(ck)
                with self._mu:
                    epochs = self._pane_epoch.copy()
                    for i, key in enumerate(ck):
                        self._inflight[key] = (packed_dev, i, epochs)
                self._dispatch(("harvest", packed_dev, ck, epochs))
            if keys and self._on_tier_event is not None:
                self._on_tier_event("demote", n=len(keys))
        now = timex.now_ms()
        if now - self._last_scan_ms >= self.layout.scan_interval_ms \
                and "touch" in (state or {}):
            self._last_scan_ms = now
            import jax.numpy as jnp

            # a FRESH buffer, not the live state leaf: the next fold
            # donates the state pytree (donate_argnums), which would
            # delete the leaf out from under the worker's fetch — the
            # same class as bench.py's _block_marker slice
            touch_dev = state["touch"] + jnp.uint32(0)
            try:
                touch_dev.copy_to_host_async()
            except AttributeError:
                pass
            self._dispatch(("scan", touch_dev, self.kt.n_keys,
                            list(self.kt.free_slots()), now))
        return state

    @staticmethod
    def _retirable(key) -> bool:
        """Keys whose normalized form aliases a raw form ("" from a nil
        key, tuples holding "") stay resident: retiring them would leave
        a dangling alias entry in the table. They are rare and bounded."""
        if key == "":
            return False
        if isinstance(key, tuple) and any(v == "" for v in key):
            return False
        return True

    def _dispatch(self, payload: tuple) -> None:
        if self._submit is not None:
            self._submit(payload)
        else:
            self.worker_task(payload)

    # ------------------------------------------------------ worker thread
    def worker_task(self, payload: tuple) -> None:
        """Prefinalize/emit-worker half: harvest landed demote blocks and
        run the placement policy. Never touches the KeyTable."""
        kind = payload[0]
        if kind == "harvest":
            self._harvest(payload[1], payload[2], payload[3])
        elif kind == "scan":
            self._scan(payload[1], payload[2], payload[3], payload[4])

    def _harvest(self, packed_dev, keys: List[Any],
                 epochs: np.ndarray) -> None:
        # kuiperlint: ignore[host-sync]: worker thread — the demote fetch IS the intended sync point, the fold thread dispatched and moved on
        arr = np.asarray(packed_dev)
        with self._mu:
            for i, key in enumerate(keys):
                entry = self._inflight.get(key)
                if entry is None or entry[0] is not packed_dev:
                    # admit() already consumed this key off the pending
                    # block (promote-before-harvest), or a NEWER demote
                    # of the same key superseded this one
                    continue
                del self._inflight[key]
                row = arr[i]
                if self.ts.row_is_idle(row):
                    self.recycled_total += 1  # pure slot recycle
                    continue
                if self.quiescent_only:
                    # the policy only demotes quiescent keys here; a racy
                    # touch between scan and apply can still spill live
                    # data — requeue it for immediate re-promotion
                    self._requeue.append((key, row.copy(), epochs.copy()))
                    continue
                self.store.put(key, row, epochs)

    def _scan(self, touch_dev, n_slots: int, free: List[int],
              now_ms: int) -> None:
        # kuiperlint: ignore[host-sync]: worker thread — scheduled touch-column fetch off the fold path
        counts = np.asarray(touch_dev)[:n_slots].astype(np.int64)
        with self._mu:
            if len(self._mirror) < len(counts):
                pad = len(counts) - len(self._mirror)
                self._mirror = np.concatenate(
                    [self._mirror, np.zeros(pad, np.int64)])
                self._idle = np.concatenate(
                    [self._idle, np.zeros(pad, np.int32)])
            mirror = self._mirror[:len(counts)]
            delta = counts - mirror
            idle = self._idle[:len(counts)]
            idle[delta != 0] = 0
            idle[delta == 0] += 1
            self._mirror[:len(counts)] = counts
            live = n_slots - len(free)
            overflow = live - self.layout.hot_slots
            plan: List[int] = []
            if overflow > 0:
                min_idle = self.layout.min_idle_scans
                if self.min_idle_ms:
                    min_idle = max(min_idle, -(-self.min_idle_ms
                                               // max(self.layout.
                                                      scan_interval_ms, 1)))
                free_set = set(free)
                cand = np.nonzero(idle >= min_idle)[0]
                if len(cand):
                    order = np.argsort(-idle[cand], kind="stable")
                    want = min(overflow,
                               self.layout.demote_batch
                               * MAX_DEMOTE_BATCHES)
                    for slot in cand[order].tolist():
                        if slot in free_set:
                            continue
                        plan.append(int(slot))
                        if len(plan) >= want:
                            break
            self._plan = plan
            # prune: resident rows whose every pane went stale carry no
            # information — a reappearance is just a fresh key
            self._prune_locked()

    def _prune_locked(self) -> None:
        keys, rows, epochs = self.store.items_arrays()
        if rows is None:
            return
        comp, off, _ = self.ts.blocks[-1]  # act block
        act = rows[:, off:off + self.ts.n_panes]
        valid = epochs == self._pane_epoch[None, :]
        dead = ~np.any((act > 0) & valid, axis=1)
        for i in np.nonzero(dead)[0].tolist():
            self.store.drop(keys[i])

    # ------------------------------------------------------ ingest prep
    def prefetch(self, batch) -> None:
        """Ingest-prep hook (decode-pool drainer): spot returning keys in
        a decoding batch and start their packed rows' H2D copy early, so
        `admit` finds the block already resident. Best-effort — a miss
        just means admit builds and uploads the block itself."""
        if self.key_name is None:
            return
        col = getattr(batch, "columns", {}).get(self.key_name)
        if col is None or not len(self.store):
            return
        try:
            distinct = list(dict.fromkeys(col.tolist()))
        except Exception:
            return
        # membership probes OUTSIDE the lock (GIL-atomic dict reads; a
        # stale hit just re-verifies below): a 64k-distinct batch must
        # not hold _mu — the fold thread's admit()/on_boundary() take it
        # every batch — for the whole scan. Bounded at D hits.
        key_map = self.store._key_row
        cand = []
        for k in distinct:
            if k in key_map:
                cand.append(k)
                if len(cand) >= self.ts.demote_batch:
                    break
        if not cand:
            return
        with self._mu:
            epoch = self._pane_epoch.copy()
            hits = []
            rows = []
            for k in cand:
                peeked = self.store.peek(k)  # re-verify under the lock
                if peeked is None:
                    continue
                row = peeked[0].copy()
                self.ts.mask_stale_panes(row, peeked[1] != epoch)
                hits.append(k)
                rows.append(row)
            if not hits:
                return
        D = self.ts.demote_batch
        block = np.tile(self.ts.init_row(), (D, 1))
        block[:len(rows)] = np.stack(rows)
        import jax.numpy as jnp

        dev = jnp.asarray(block)
        with self._mu:
            # the epoch snapshot rides along: a pane reset between this
            # prefetch and admit() invalidates the staged block (its
            # stale-masking was done against THESE epochs)
            self._prefetch_q.append((tuple(hits), dev, epoch))
            if len(self._prefetch_q) > 4:
                self._prefetch_q.pop(0)

    def _settle_inflight_locked(self) -> None:
        """Land any un-harvested demote blocks into the store NOW —
        boundary emission and checkpoints need the complete cold tier.
        Caller holds _mu. Rare: the worker normally harvests well inside
        one window period."""
        if not self._inflight:
            return
        items = list(self._inflight.items())
        self._inflight.clear()
        for key, (packed_dev, idx, epochs) in items:
            # kuiperlint: ignore[host-sync]: boundary/checkpoint settlement of an already-in-flight copy
            row = np.asarray(packed_dev)[idx]
            if self.ts.row_is_idle(row):
                self.recycled_total += 1
                continue
            if self.quiescent_only:
                # same contract as _harvest: a racy live spill in
                # quiescent mode re-promotes instead of parking in a
                # store the sliding emission path never reads
                self._requeue.append((key, row.copy(), epochs.copy()))
                continue
            self.store.put(key, row, epochs)

    # -------------------------------------------------------- emissions
    def window_groups(self, plan, panes: Optional[List[int]] = None):
        """Spilled keys' contribution to a closing window: merge each
        resident row's still-valid panes (subset `panes`, default all)
        and compute final values with the prefinalize numpy tail.
        Returns (keys, outs, act) like DeviceGroupBy.finalize, or None
        when no spilled key has live data for the window."""
        from .prefinalize import final_value_np

        with self._mu:
            self._settle_inflight_locked()
            keys, rows, epochs = self.store.items_arrays()
            if rows is None:
                return None
            rows = rows.copy()
            valid = epochs == self._pane_epoch[None, :]
        if panes is not None:
            pane_mask = np.zeros(self.ts.n_panes, dtype=np.bool_)
            pane_mask[list(panes)] = True
            valid = valid & pane_mask[None, :]
        comb: Dict[str, np.ndarray] = {}
        for comp, off, tail in self.ts.blocks:
            w = int(np.prod(tail)) if tail else 1
            seg = rows[:, off:off + self.ts.n_panes * w].reshape(
                len(keys), self.ts.n_panes, *(tail or ()))
            vm = valid.reshape(len(keys), self.ts.n_panes,
                               *([1] * len(tail)))
            if comp == "mn":
                m = np.min(np.where(vm, seg, np.inf), axis=1)
            elif comp in ("mx", "hll"):
                m = np.max(np.where(vm, seg, -np.inf), axis=1)
            else:
                m = np.sum(np.where(vm, seg, 0.0), axis=1)
            comb[comp] = m
        act = comb.pop("act")
        alive = np.nonzero(act > 0)[0]
        if not len(alive):
            return None
        comp_specs = self.gb.comp_specs
        outs: List[np.ndarray] = []
        for i, spec in enumerate(plan.specs):
            c = {comp: comb[comp][alive][:, comp_specs[comp].index(i)]
                 for comp in spec.components}
            outs.append(np.asarray(final_value_np(spec, c)))
        outs = apply_int_semantics(plan.specs, outs)
        return [keys[j] for j in alive.tolist()], outs, act[alive]

    # ------------------------------------------------------- checkpoint
    def snapshot(self) -> Dict[str, Any]:
        import base64

        with self._mu:
            self._settle_inflight_locked()
            keys, rows, epochs = self.store.items_arrays()
            return {
                "keys": [list(k) if isinstance(k, tuple) else k
                         for k in keys],
                "rows": base64.b64encode(
                    np.ascontiguousarray(
                        rows if rows is not None
                        else np.zeros((0, self.ts.packed_w), np.float32)
                    ).tobytes()).decode("ascii"),
                "epochs": base64.b64encode(
                    np.ascontiguousarray(
                        epochs if epochs is not None
                        else np.zeros((0, self.ts.n_panes), np.int64)
                    ).tobytes()).decode("ascii"),
                "pane_epoch": self._pane_epoch.tolist(),
                # racy live spills awaiting re-promotion (quiescent
                # mode): the first post-restore admit re-promotes them,
                # matching the uninterrupted behavior
                "requeue": [
                    [list(k) if isinstance(k, tuple) else k,
                     base64.b64encode(r.tobytes()).decode("ascii"),
                     base64.b64encode(e.tobytes()).decode("ascii")]
                    for (k, r, e) in self._requeue
                ],
                "counters": {
                    "demoted": self.demoted_total,
                    "promoted": self.promoted_total,
                    "recycled": self.recycled_total,
                },
            }

    def restore(self, snap: Dict[str, Any]) -> None:
        import base64

        keys = [tuple(k) if isinstance(k, list) else k
                for k in snap.get("keys", [])]
        rows = np.frombuffer(
            base64.b64decode(snap.get("rows", "")),
            dtype=np.float32).reshape(-1, self.ts.packed_w).copy()
        epochs = np.frombuffer(
            base64.b64decode(snap.get("epochs", "")),
            dtype=np.int64).reshape(-1, self.ts.n_panes).copy()
        with self._mu:
            self._pane_epoch = np.asarray(
                snap.get("pane_epoch", [0] * self.ts.n_panes),
                dtype=np.int64)
            counters = snap.get("counters", {})
            self.demoted_total = int(counters.get("demoted", 0))
            self.promoted_total = int(counters.get("promoted", 0))
            self.recycled_total = int(counters.get("recycled", 0))
            for i, key in enumerate(keys):
                self.store.put(key, rows[i], epochs[i])
            self._requeue = [
                (tuple(k) if isinstance(k, list) else k,
                 np.frombuffer(base64.b64decode(r),
                               dtype=np.float32).copy(),
                 np.frombuffer(base64.b64decode(e),
                               dtype=np.int64).copy())
                for (k, r, e) in snap.get("requeue", [])
            ]
