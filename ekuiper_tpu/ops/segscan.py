"""Segmented-scan analytic kernels — ROW_NUMBER / RANK / DENSE_RANK /
LAG / LEAD and running aggregates on device.

The reference computes window/analytic functions one row at a time
against host-side state caches (runtime/nodes_ops.py AnalyticNode /
WindowFuncNode; internal/topo/operator/*_operator.go). Here a micro-batch
key-sorts once inside the kernel (jnp.lexsort — stable, original index as
tiebreak) and every function becomes a segmented `jax.lax.associative_scan`
over the sorted order:

  * segmented cumsum   -> ROW_NUMBER, running sum/count
  * propagate-last     -> RANK (first position of each value group)
  * new-value flags    -> DENSE_RANK
  * in-segment shift   -> LAG / LEAD

Partitions larger than one micro-batch follow the tierstore spill
discipline (arxiv 2007.10385): the cross-batch state is O(partitions)
scalar partials — count, last value, running sum per key slot — never
buffered rows. The `segscan.shift` site carries those partials in donated
device arrays on the key-capacity growth ladder; `segscan.sort` is the
stateless per-collection variant (window functions see one complete
collection at a time, so no partial ever crosses calls).

NULL semantics match the host evaluator: a NULL value ranks as NULL
(rank/dense_rank skip it and it never counts as "smaller"), LAG records
NULL rows in history (NaN-encoded), running sums are NULL-transparent.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: pow-2 pad floor per micro-batch — one executable serves every
#: collection up to the floor, doublings cover the rest
SEG_PAD_FLOOR = 256

#: certified top of the micro-batch pad ladder
SEG_PAD_CAP = 1 << 17

#: pad-row segment id: sorts after every real slot, so pads form their
#: own segment and can never pollute a real partition's scan
_SEG_PAD = 1 << 30


def _pad_pow2(n: int) -> int:
    b = SEG_PAD_FLOOR
    while b < n:
        b <<= 1
    return b


def _seg_cumsum(head, x):
    """Inclusive segmented sum: resets at every True in `head`."""
    import jax

    def comb(a, b):
        import jax.numpy as jnp

        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, va + vb)

    _, v = jax.lax.associative_scan(comb, (head, x))
    return v


def _seg_propagate(flag, x):
    """Propagate the most recent flagged value forward (copy scan);
    `flag` must be True at every segment head so propagation never
    crosses a segment boundary."""
    import jax

    def comb(a, b):
        import jax.numpy as jnp

        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, va)

    _, v = jax.lax.associative_scan(comb, (flag, x))
    return v


class SegScan:
    """Owner of the two certified segmented-scan sites plus their host
    shadow twins. One instance per lifted node; the cross-batch partials
    (`segscan.shift`) live in donated device arrays sized to the key
    capacity and grow on the same doubling ladder as every other kernel."""

    #: jitcert/devwatch site family for this kernel's jit sites
    watch_prefix = "segscan"

    def __init__(self, capacity: int = 4096) -> None:
        import jax.numpy as jnp

        self.capacity = int(capacity)
        self._carry = (
            jnp.zeros(self.capacity, dtype=jnp.int32),    # row count
            jnp.zeros(self.capacity, dtype=jnp.float32),  # last value
            jnp.zeros(self.capacity, dtype=bool),         # has last
            jnp.zeros(self.capacity, dtype=jnp.float32),  # running sum
        )
        self.rows_total = 0
        self.spills_total = 0  # partitions continued across micro-batches
        from ..observability import jitcert, memwatch
        from ..runtime.aotcache import aot_jit

        self._shift = aot_jit(self._shift_impl, op="segscan.shift",
                              donate_argnums=(0,))
        self._sort = aot_jit(self._sort_impl, op="segscan.sort",
                             kind="boundary")
        memwatch.register("segscan", self,
                          lambda ss: sum(int(c.nbytes)
                                         for c in ss._carry))
        jitcert.register_kernel(self)
        _registry.register(self)

    def _watch_op(self, site: str) -> str:
        return f"{self.watch_prefix}.{site}"

    # ----------------------------------------------------------- kernels
    def _shift_impl(self, carry, slots, vals, valid):
        import jax.numpy as jnp

        cnt, last, has, acc = carry
        mb = slots.shape[0]
        s = jnp.where(valid, slots, jnp.int32(_SEG_PAD))
        idx = jnp.arange(mb, dtype=jnp.int32)
        order = jnp.lexsort((idx, s))
        ss, vv, mm = s[order], vals[order], valid[order]
        head = jnp.concatenate([jnp.ones(1, bool), ss[1:] != ss[:-1]])
        tail = jnp.concatenate([ss[:-1] != ss[1:], jnp.ones(1, bool)])
        sc = jnp.clip(ss, 0, cnt.shape[0] - 1)  # pad-safe gather index
        pos = _seg_cumsum(head, jnp.ones(mb, jnp.int32))
        rn_s = cnt[sc] + pos
        pv = jnp.concatenate([vv[:1], vv[:-1]])
        lag_s = jnp.where(head,
                          jnp.where(has[sc], last[sc], jnp.nan), pv)
        lhas_s = jnp.where(head, has[sc], True)
        vz = jnp.where(jnp.isnan(vv), 0.0, vv)
        cum = _seg_cumsum(head, vz)
        run_s = acc[sc] + cum
        continued = jnp.sum((head & mm & has[sc]).astype(jnp.int32))
        # partial spill: segment tails scatter O(partitions) scalars back
        # into the carry; pad rows dump into a ghost row sliced off below
        dump = jnp.int32(cnt.shape[0])
        tidx = jnp.where(tail & mm, ss, dump)

        def ext(a):
            return jnp.concatenate([a, a[:1]])

        cnt2 = ext(cnt).at[tidx].add(jnp.where(tail & mm, pos, 0))[:-1]
        last2 = ext(last).at[tidx].set(vv)[:-1]
        has2 = ext(has).at[tidx].set(True)[:-1]
        acc2 = ext(acc).at[tidx].add(jnp.where(tail & mm, cum, 0.0))[:-1]

        def unsort(x):
            return jnp.zeros(mb, x.dtype).at[order].set(x)

        return ((cnt2, last2, has2, acc2), unsort(rn_s), unsort(lag_s),
                unsort(lhas_s), unsort(run_s), continued)

    def _sort_impl(self, seg, vals, valid):
        import jax.numpy as jnp

        mb = seg.shape[0]
        s = jnp.where(valid, seg, jnp.int32(_SEG_PAD))
        idx = jnp.arange(mb, dtype=jnp.int32)
        # arrival order within segment: ROW_NUMBER / LEAD
        o1 = jnp.lexsort((idx, s))
        s1, v1 = s[o1], vals[o1]
        head1 = jnp.concatenate([jnp.ones(1, bool), s1[1:] != s1[:-1]])
        rn_s = _seg_cumsum(head1, jnp.ones(mb, jnp.int32))
        same = jnp.concatenate([s1[:-1] == s1[1:], jnp.zeros(1, bool)])
        nxt = jnp.where(same, jnp.concatenate([v1[1:], v1[:1]]), jnp.nan)
        # value order within segment: RANK / DENSE_RANK (NULLs sort last
        # and rank as NULL; they never count as "smaller")
        vkey = jnp.where(jnp.isnan(vals), jnp.inf, vals)
        o2 = jnp.lexsort((idx, vkey, s))
        s2, k2 = s[o2], vkey[o2]
        vval2 = ~jnp.isnan(vals[o2])
        head2 = jnp.concatenate([jnp.ones(1, bool), s2[1:] != s2[:-1]])
        newv = head2 | jnp.concatenate(
            [jnp.ones(1, bool), k2[1:] != k2[:-1]])
        pos2 = _seg_cumsum(head2, jnp.ones(mb, jnp.int32))
        rank_s = jnp.where(vval2, _seg_propagate(newv, pos2), 0)
        dense_s = jnp.where(vval2,
                            _seg_cumsum(head2, newv.astype(jnp.int32)), 0)

        def unsort(order, x):
            return jnp.zeros(mb, x.dtype).at[order].set(x)

        return (unsort(o1, rn_s), unsort(o1, nxt), unsort(o1, same),
                unsort(o2, rank_s), unsort(o2, dense_s),
                unsort(o2, vval2))

    # -------------------------------------------------------- host entry
    def shift(self, slots: np.ndarray, vals: np.ndarray, n: int
              ) -> Dict[str, np.ndarray]:
        """Streaming analytics for one micro-batch (arrival order):
        per-partition ROW_NUMBER, LAG(1), running sum. Donated carry,
        so cross-batch state never leaves the device."""
        import jax.numpy as jnp

        while int(np.max(slots, initial=0)) >= self.capacity:
            self.grow(self.capacity * 2)
        b = _pad_pow2(n)
        sl = np.zeros(b, dtype=np.int32)
        sl[:n] = slots[:n]
        va = np.full(b, np.nan, dtype=np.float32)
        va[:n] = vals[:n]
        valid = np.zeros(b, dtype=bool)
        valid[:n] = True
        self._carry, rn, lag, lhas, run, cont = self._shift(
            self._carry, jnp.asarray(sl), jnp.asarray(va),
            jnp.asarray(valid))
        self.rows_total += n
        self.spills_total += int(cont)
        return {"row_number": np.asarray(rn)[:n],
                "lag": np.asarray(lag)[:n],
                "lag_has": np.asarray(lhas)[:n],
                "run_sum": np.asarray(run)[:n]}

    def ranks(self, seg: np.ndarray, vals: np.ndarray, n: int
              ) -> Dict[str, np.ndarray]:
        """Whole-collection window functions: ROW_NUMBER / RANK /
        DENSE_RANK / LEAD(1) over one complete (padded) collection."""
        import jax.numpy as jnp

        b = _pad_pow2(n)
        sg = np.zeros(b, dtype=np.int32)
        sg[:n] = seg[:n]
        va = np.full(b, np.nan, dtype=np.float32)
        va[:n] = vals[:n]
        valid = np.zeros(b, dtype=bool)
        valid[:n] = True
        rn, lead, lead_has, rank, dense, rhas = self._sort(
            jnp.asarray(sg), jnp.asarray(va), jnp.asarray(valid))
        self.rows_total += n
        return {"row_number": np.asarray(rn)[:n],
                "lead": np.asarray(lead)[:n],
                "lead_has": np.asarray(lead_has)[:n],
                "rank": np.asarray(rank)[:n],
                "dense_rank": np.asarray(dense)[:n],
                "rank_has": np.asarray(rhas)[:n]}

    # ------------------------------------------------------------- state
    def grow(self, new_capacity: int) -> None:
        """Capacity doubling: carries pad with fold identities (count 0,
        no last value, sum 0) — the same ladder jitcert certifies."""
        import jax.numpy as jnp

        if new_capacity <= self.capacity:
            return
        pad = new_capacity - self.capacity
        cnt, last, has, acc = self._carry
        self._carry = (
            jnp.concatenate([cnt, jnp.zeros(pad, dtype=jnp.int32)]),
            jnp.concatenate([last, jnp.zeros(pad, dtype=jnp.float32)]),
            jnp.concatenate([has, jnp.zeros(pad, dtype=bool)]),
            jnp.concatenate([acc, jnp.zeros(pad, dtype=jnp.float32)]),
        )
        self.capacity = new_capacity

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable carry state (checkpoint seam). NaN floats
        survive the json round-trip (allow_nan default)."""
        cnt, last, has, acc = self._carry
        return {"capacity": self.capacity,
                "cnt": [int(x) for x in np.asarray(cnt)],
                "last": [float(x) for x in np.asarray(last)],
                "has": [bool(x) for x in np.asarray(has)],
                "acc": [float(x) for x in np.asarray(acc)]}

    def restore(self, state: Dict[str, Any]) -> None:
        import jax.numpy as jnp

        self.capacity = int(state["capacity"])
        self._carry = (
            jnp.asarray(np.asarray(state["cnt"], dtype=np.int32)),
            jnp.asarray(np.asarray(state["last"], dtype=np.float32)),
            jnp.asarray(np.asarray(state["has"], dtype=bool)),
            jnp.asarray(np.asarray(state["acc"], dtype=np.float32)),
        )

    def peek_carry(self) -> Dict[str, np.ndarray]:
        """Host view of the carry partials (lag-state migration and the
        parity/battery drivers read this; never on the hot path)."""
        cnt, last, has, acc = self._carry
        return {"cnt": np.asarray(cnt), "last": np.asarray(last),
                "has": np.asarray(has), "acc": np.asarray(acc)}


# ------------------------------------------------------------- host twins
def sort_host(seg: np.ndarray, vals: np.ndarray, n: int
              ) -> Dict[str, np.ndarray]:
    """Numpy shadow twin of `segscan.sort` — the host window-function
    path computes rank/dense_rank/lead with exactly this, so host and
    device emissions are definitionally comparable bit-for-bit."""
    seg = np.asarray(seg[:n], dtype=np.int64)
    vals = np.asarray(vals[:n], dtype=np.float32)
    rn = np.zeros(n, dtype=np.int32)
    rank = np.zeros(n, dtype=np.int32)
    dense = np.zeros(n, dtype=np.int32)
    rhas = np.zeros(n, dtype=bool)
    lead = np.full(n, np.nan, dtype=np.float32)
    lead_has = np.zeros(n, dtype=bool)
    for s in np.unique(seg):
        sel = np.nonzero(seg == s)[0]
        rn[sel] = np.arange(1, len(sel) + 1, dtype=np.int32)
        lead[sel[:-1]] = vals[sel[1:]]
        lead_has[sel[:-1]] = True
        sv = vals[sel]
        ok = ~np.isnan(sv)
        rhas[sel] = ok
        vv = sv[ok]
        if len(vv):
            uniq = np.unique(vv)
            rank[sel[ok]] = 1 + np.searchsorted(np.sort(vv), vv,
                                                side="left").astype(np.int32)
            dense[sel[ok]] = 1 + np.searchsorted(uniq, vv).astype(np.int32)
    return {"row_number": rn, "rank": rank, "dense_rank": dense,
            "rank_has": rhas, "lead": lead, "lead_has": lead_has}


def shift_host(carry: Dict[str, np.ndarray], slots: np.ndarray,
               vals: np.ndarray, n: int) -> Dict[str, np.ndarray]:
    """Numpy shadow twin of `segscan.shift` (mutates `carry` in place —
    dict of cnt/last/has/acc arrays)."""
    rn = np.zeros(n, dtype=np.int32)
    lag = np.full(n, np.nan, dtype=np.float32)
    lhas = np.zeros(n, dtype=bool)
    run = np.zeros(n, dtype=np.float32)
    for i in range(n):
        s = int(slots[i])
        carry["cnt"][s] += 1
        rn[i] = carry["cnt"][s]
        lag[i] = carry["last"][s] if carry["has"][s] else np.nan
        lhas[i] = bool(carry["has"][s])
        v = float(vals[i])
        if not np.isnan(v):
            carry["acc"][s] += np.float32(v)
        run[i] = carry["acc"][s]
        carry["last"][s] = v
        carry["has"][s] = True
    return {"row_number": rn, "lag": lag, "lag_has": lhas, "run_sum": run}


# ----------------------------------------------------------- observability
class _Registry:
    """Weakref index of live segscan kernels for /metrics."""

    def __init__(self) -> None:
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        self._entries: List[Tuple[Any, Optional[str]]] = []

    def register(self, ss, rule: Optional[str] = None) -> None:
        from ..utils.rulelog import current_rule

        with self._lock:
            self._entries = [(r, ru) for (r, ru) in self._entries
                             if r() is not None]
            self._entries.append((self._weakref.ref(ss),
                                  rule or current_rule()))

    def kernels(self) -> List[Tuple[Any, Optional[str]]]:
        with self._lock:
            refs = list(self._entries)
        return [(k, rule) for (r, rule) in refs if (k := r()) is not None]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_registry = _Registry()


def registry() -> _Registry:
    return _registry


def reset() -> None:
    """Test hook."""
    _registry.clear()


def render_prometheus(out: List[str], esc) -> None:
    """Append the kuiper_segscan_* families to a /metrics scrape."""
    fams = (
        ("kuiper_segscan_rows_total", "counter",
         "rows computed through the segmented-scan analytic kernels",
         lambda ss: ss.rows_total),
        ("kuiper_segscan_spills_total", "counter",
         "partition partials carried across micro-batch boundaries "
         "(spilled partials, never rows)",
         lambda ss: ss.spills_total),
    )
    kernels = _registry.kernels()
    for name, mtype, help_txt, fn in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        agg: Dict[str, int] = {}
        for ss, rule in kernels:
            try:
                v = int(fn(ss))
            except Exception:
                continue
            label = rule or "__engine__"
            agg[label] = agg.get(label, 0) + v
        for rule, v in sorted(agg.items()):
            out.append(f'{name}{{rule="{esc(rule)}"}} {v}')
