"""Latency-hiding window emit — pre-issued device finalize + host tail shadow.

Why: on a tunneled TPU one dispatch→result round trip costs 50-90ms, so any
emit path that *starts* a device round trip at the window boundary can never
hit the <50ms p99 emit-latency target (BASELINE.md north-star row 2). The
reference never faces this (its aggregation state lives in process memory,
internal/topo/node/window_inc_agg_op.go); a TPU-resident design needs an
explicit latency plan.

The plan, exploiting that tumbling/hopping boundaries are known in advance
(timex.align_to_window) and that jax arrays are immutable (a dispatched
program sees a snapshot — no double buffering needed):

  1. One RTT before the boundary, dispatch `components()` on the current
     state and start an async device→host copy (PendingFinalize). The fold
     stream continues uninterrupted.
  2. Rows arriving in the tail window keep folding into the device state
     (so hopping panes / checkpoints stay complete) AND into a HostShadow —
     a numpy mirror of the fold kernel over just those rows (~1-2ms per
     64k-row batch; the tail is a few batches at most).
  3. At the boundary, merge: device components (already on host or in
     flight) ⊕ shadow components, then compute final values in numpy.
     Emit latency = merge + message build, no device round trip.

The shadow folds through the SAME compiled expressions as the device kernel
(host-mode twins from sql/compiler.py) and mirrors its masking rules
(ops/groupby.py _fold_impl), so sync and pre-finalized emits agree to float32
accumulation order.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .aggspec import AggSpec, KernelPlan, WIDE_COMPONENTS
# identity values / wide register sizes are THE kernel's tables — shared so
# the host shadow can never drift from the device state layout
from .groupby import _INIT, _wide_size
from .sketches import HIST_BINS, HLL_M, _HIST_HALF, _HIST_HI, _HIST_LO, _LOG_GAMMA, _GAMMA


def _comp_shape(comp: str, spec_idxs: List[int]):
    shape = (len(spec_idxs),)
    if comp in WIDE_COMPONENTS:
        shape = shape + (_wide_size(comp),)
    return shape


# ------------------------------------------------------- numpy sketch mirrors
def _splitmix32_np(x: np.ndarray, c1: int, c2: int) -> np.ndarray:
    x = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(c1)
        x = x ^ (x >> np.uint32(15))
        x = x * np.uint32(c2)
        x = x ^ (x >> np.uint32(16))
    return x


def hash_f32_np(v: np.ndarray, salt: int = 0) -> np.ndarray:
    bits = np.ascontiguousarray(np.asarray(v, np.float32)).view(np.uint32)
    bits = bits ^ np.uint32((0x9E3779B9 * (salt + 1)) & 0xFFFFFFFF)
    return _splitmix32_np(bits, 0x7FEB352D, 0x846CA68B)


def hll_parts_np(values: np.ndarray):
    """Numpy twin of sketches.hll_parts (same float32 rho derivation)."""
    h1 = hash_f32_np(values, salt=0)
    h2 = hash_f32_np(values, salt=1)
    reg = (h1 & np.uint32(HLL_M - 1)).astype(np.int32)
    hv = np.maximum(h2, np.uint32(1)).astype(np.float32)
    nbits = np.floor(np.log2(hv)) + np.float32(1.0)
    rho = (np.float32(33.0) - nbits).astype(np.float32)
    return reg, rho


def hll_estimate_np(registers: np.ndarray) -> np.ndarray:
    m = registers.shape[-1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    z = np.sum(2.0 ** (-registers), axis=-1)
    raw = alpha * m * m / z
    zeros = np.sum(registers == 0.0, axis=-1)
    small = m * np.log(m / np.maximum(zeros, 1).astype(np.float32))
    return np.where((raw < 2.5 * m) & (zeros > 0), small, raw)


def hist_bin_np(values: np.ndarray) -> np.ndarray:
    v = np.asarray(values, np.float32)
    clamped = np.clip(np.abs(v), _HIST_LO, _HIST_HI * 0.999)
    with np.errstate(divide="ignore", invalid="ignore"):
        mag = np.floor(np.log(clamped / _HIST_LO) / _LOG_GAMMA).astype(np.int32)
    mag = np.clip(mag, 0, _HIST_HALF - 1)
    pos = _HIST_HALF + 1 + mag
    neg = _HIST_HALF - 1 - mag
    return np.where(v > 0, pos, np.where(v < 0, neg, _HIST_HALF)).astype(np.int32)


def _hh_slot_np(code: np.ndarray, d: int) -> np.ndarray:
    """Numpy twin of the per-depth slot hash in sketches.hh_update_parts."""
    from .sketches import HH_WIDTH, _hh_salt

    h = _splitmix32_np(
        code.astype(np.uint32) ^ np.uint32(_hh_salt(d)), 0x7FEB352D, 0x846CA68B
    )
    return (h % np.uint32(HH_WIDTH)).astype(np.int32)


def hh_update_parts_np(codes: np.ndarray, mf: np.ndarray):
    """Numpy twin of sketches.hh_update_parts (shadow fold)."""
    from .sketches import HH_BITS, HH_DEPTH, HH_WIDTH

    code = np.nan_to_num(codes, nan=0.0).astype(np.uint32)
    bits = [
        ((code >> np.uint32(b)) & np.uint32(1)).astype(np.float32)
        for b in range(HH_BITS)
    ]
    idx_parts, w_parts = [], []
    for d in range(HH_DEPTH):
        slot = _hh_slot_np(code, d)
        base = (d * HH_WIDTH + slot) * (1 + HH_BITS)
        idx_parts.append(base)
        w_parts.append(mf)
        for b in range(HH_BITS):
            idx_parts.append(base + 1 + b)
            w_parts.append(mf * bits[b])
    return np.stack(idx_parts, axis=1), np.stack(w_parts, axis=1)


def hh_dedupe_topk(codes_row, est_row, k: int):
    """Dedupe estimate-descending candidates (a code can appear once per
    depth) and trim to top-k (code, count) pairs. Shared by the device
    finalize route (groupby._host_finalize) and the numpy components route
    (hh_topk_np) so both produce identical top lists."""
    seen = set()
    row = []
    for c, e in zip(codes_row, est_row):
        if e <= 0:
            break
        c = int(c)
        if c in seen:
            continue
        seen.add(c)
        row.append((c, int(round(e))))
        if len(row) >= k:
            break
    return row


def hh_topk_np(hh: np.ndarray, k: int) -> np.ndarray:
    """Recover per-key top-k (code, count) pairs from the linear
    heavy-hitters sketch. hh: (capacity, HH_SIZE). Returns an object array
    of [(code, est_count), ...] lists, count-descending.

    Recovery: per (depth, slot), bit-majority vote reconstructs the code
    that dominates the slot; a candidate must hash back to its own slot
    (garbage codes from mixed slots almost never do), and its count is the
    count-min estimate (min over depth totals at the code's slots)."""
    from .sketches import HH_BITS, HH_DEPTH, HH_WIDTH

    cap = hh.shape[0]
    a = hh.reshape(cap, HH_DEPTH, HH_WIDTH, 1 + HH_BITS)
    tot = a[..., 0]  # (cap, D, W)
    bits = (a[..., 1:] * 2.0) > tot[..., None]
    codes = np.zeros((cap, HH_DEPTH, HH_WIDTH), dtype=np.uint32)
    for b in range(HH_BITS):
        codes |= bits[..., b].astype(np.uint32) << np.uint32(b)
    ok = tot > 0
    wslots = np.arange(HH_WIDTH, dtype=np.int32)[None, :]
    for d in range(HH_DEPTH):
        ok[:, d, :] &= _hh_slot_np(codes[:, d, :], d) == wslots
    est = np.full(codes.shape, np.inf, dtype=np.float32)
    rows = np.arange(cap)[:, None]
    flat_codes = codes.reshape(cap, -1)
    for d2 in range(HH_DEPTH):
        s = _hh_slot_np(flat_codes, d2)  # (cap, D*W)
        est = np.minimum(est, tot[rows, d2, s].reshape(codes.shape))
    est = np.where(ok, est, 0.0)
    out = np.empty(cap, dtype=np.object_)
    out[:] = [[] for _ in range(cap)]
    flat_est = est.reshape(cap, -1)
    live = np.nonzero(flat_est.max(axis=1) > 0)[0]
    if len(live):
        order = np.argsort(-flat_est[live], axis=1)
        for li, i in enumerate(live.tolist()):
            out[i] = hh_dedupe_topk(
                flat_codes[i, order[li]], flat_est[i, order[li]], k)
    return out


def hist_quantile_np(hist: np.ndarray, frac: float) -> np.ndarray:
    total = np.sum(hist, axis=-1)
    cum = np.cumsum(hist, axis=-1)
    target = frac * total[..., None]
    ge = cum >= np.maximum(target, 1e-9)
    idx = np.argmax(ge, axis=-1)
    mag_idx = np.where(
        idx > _HIST_HALF, idx - _HIST_HALF - 1, _HIST_HALF - 1 - idx
    ).astype(np.float32)
    center = _HIST_LO * np.exp(mag_idx * _LOG_GAMMA) * float(np.sqrt(_GAMMA))
    val = np.where(
        idx == _HIST_HALF, 0.0, np.where(idx > _HIST_HALF, center, -center)
    )
    return np.where(total > 0, val, np.nan)


# -------------------------------------------------------- numpy final values
def final_value_np(spec: AggSpec, c: Dict[str, np.ndarray]) -> np.ndarray:
    """Numpy twin of DeviceGroupBy._final_value."""
    kind = spec.kind
    if kind == "count":
        return c["n"]
    n = c.get("n")
    with np.errstate(invalid="ignore", divide="ignore"):
        if kind == "sum":
            return np.where(n > 0, c["s1"], np.nan)
        if kind == "avg":
            return np.where(n > 0, c["s1"] / np.maximum(n, 1.0), np.nan)
        if kind == "min":
            return np.where(n > 0, c["mn"], np.nan)
        if kind == "max":
            return np.where(n > 0, c["mx"], np.nan)
        if kind in ("stddev", "var"):
            mean = c["s1"] / np.maximum(n, 1.0)
            v = np.maximum(c["s2"] / np.maximum(n, 1.0) - mean * mean, 0.0)
            out = np.sqrt(v) if kind == "stddev" else v
            return np.where(n > 0, out, np.nan)
        if kind in ("stddevs", "vars"):
            mean = c["s1"] / np.maximum(n, 1.0)
            v = np.maximum(
                (c["s2"] - c["s1"] * mean) / np.maximum(n - 1.0, 1.0), 0.0
            )
            out = np.sqrt(v) if kind == "stddevs" else v
            return np.where(n >= 2, out, np.nan)
        if kind == "hll":
            regs = np.maximum(c["hll"], 0.0)
            return np.round(hll_estimate_np(regs))
        if kind == "percentile_approx":
            return hist_quantile_np(c["hist"], spec.frac)
        if kind == "heavy_hitters":
            # (code, count) pairs — the fused node decodes codes back to the
            # original values through its per-column ValueDict
            return hh_topk_np(c["hh"], spec.topk)
    raise ValueError(f"unknown device agg kind {kind}")


# ------------------------------------------------------------- host shadow
class HostShadow:
    """Numpy mirror of the device fold over the tail rows of a closing
    window. Accumulates the same (n, s1, s2, mn, mx, hll, hist, act)
    components the device kernel keeps, merged into the pre-issued device
    result at emit time."""

    def __init__(self, plan: KernelPlan, comp_specs: Dict[str, List[int]],
                 capacity: int) -> None:
        self.plan = plan
        self.comp_specs = comp_specs
        self.capacity = capacity
        self.data: Dict[str, np.ndarray] = {}
        self.n_rows = 0
        for comp, spec_idxs in comp_specs.items():
            shape = (capacity,) + _comp_shape(comp, spec_idxs)
            self.data[comp] = np.full(shape, _INIT[comp], dtype=np.float32)
        self.data["act"] = np.zeros(capacity, dtype=np.float32)

    def _ensure(self, max_slot: int) -> None:
        while max_slot >= self.capacity:
            for comp, arr in self.data.items():
                pad_shape = (self.capacity,) + arr.shape[1:]
                pad = np.full(pad_shape, _INIT[comp], dtype=np.float32)
                self.data[comp] = np.concatenate([arr, pad], axis=0)
            self.capacity *= 2

    def fold(self, cols: Dict[str, np.ndarray], slots: np.ndarray,
             valid: Optional[Dict[str, np.ndarray]] = None) -> None:
        n = len(slots)
        if n == 0:
            return
        self.n_rows += n
        self._ensure(int(slots.max()) if n else 0)
        valid = valid or {}
        cap = self.capacity
        base = np.ones(n, dtype=np.bool_)
        if self.plan.filter_host is not None:
            base &= np.broadcast_to(
                # kuiperlint: ignore[host-sync]: host-shadow fold — `cols` are host numpy columns by contract, no device value in reach
                np.asarray(self.plan.filter_host(cols), dtype=np.bool_), (n,)
            )
        self.data["act"] += np.bincount(
            slots, weights=base.astype(np.float32), minlength=cap
        )[:cap].astype(np.float32)
        for i, spec in enumerate(self.plan.specs):
            if spec.arg is None:
                v = np.ones(n, dtype=np.float32)
                m = base
            else:
                v = np.broadcast_to(
                    # kuiperlint: ignore[host-sync]: host-shadow fold on host columns (see filter_host above)
                    np.asarray(spec.arg_host(cols), dtype=np.float32), (n,)
                )
                m = base
                for col in spec.arg.columns:
                    vm = valid.get(col)
                    if vm is not None:
                        m = np.logical_and(m, vm)
                m = np.logical_and(m, ~np.isnan(v))
            if spec.filter_host is not None:
                m = np.logical_and(m, np.broadcast_to(
                    # kuiperlint: ignore[host-sync]: host-shadow fold on host columns (see filter_host above)
                    np.asarray(spec.filter_host(cols), dtype=np.bool_), (n,)
                ))
            mf = m.astype(np.float32)
            for comp in spec.components:
                k = self.comp_specs[comp].index(i)
                arr = self.data[comp]
                if comp == "n":
                    arr[:, k] += np.bincount(slots, weights=mf, minlength=cap)[:cap]
                elif comp == "s1":
                    arr[:, k] += np.bincount(
                        slots, weights=np.where(m, v, 0.0), minlength=cap
                    )[:cap]
                elif comp == "s2":
                    arr[:, k] += np.bincount(
                        slots, weights=np.where(m, v * v, 0.0), minlength=cap
                    )[:cap]
                elif comp == "mn":
                    if m.any():
                        np.minimum.at(arr[:, k], slots[m], v[m])
                elif comp == "mx":
                    if m.any():
                        np.maximum.at(arr[:, k], slots[m], v[m])
                elif comp == "hll":
                    if m.any():
                        reg, rho = hll_parts_np(v)
                        kk = np.full(int(m.sum()), k)
                        np.maximum.at(arr, (slots[m], kk, reg[m]), rho[m])
                elif comp == "hist":
                    if m.any():
                        b = hist_bin_np(v)
                        kk = np.full(int(m.sum()), k)
                        np.add.at(arr, (slots[m], kk, b[m]), 1.0)
                elif comp == "hh":
                    if m.any():
                        idx, wts = hh_update_parts_np(v[m], mf[m])
                        sl = slots[m][:, None]
                        kk = np.full((int(m.sum()), 1), k)
                        np.add.at(arr, (sl, kk, idx), wts)


def unpack_components(arr: np.ndarray, layout) -> Dict[str, np.ndarray]:
    """Split the stacked (capacity, W) components array back into the
    per-component dict, per the kernel's _components_layout()."""
    cap = arr.shape[0]
    return {
        comp: arr[:, col] if shape == () else
        arr[:, col:col + w].reshape((cap,) + shape)
        for comp, col, w, shape in layout
    }


_MERGE_MAX = {"mn": False, "mx": True, "hll": True}


def merge_components(
    dev: Dict[str, np.ndarray], shadow: Optional[HostShadow], capacity: int,
) -> Dict[str, np.ndarray]:
    """Device components ⊕ shadow components. Pads the device result when
    the key table grew during the tail (new keys exist only in the shadow)."""
    out: Dict[str, np.ndarray] = {}
    if shadow is not None and shadow.n_rows:
        shadow._ensure(capacity - 1)
    for comp, d in dev.items():
        if d.shape[0] < capacity:
            pad_shape = (capacity - d.shape[0],) + d.shape[1:]
            d = np.concatenate(
                [d, np.full(pad_shape, _INIT[comp], dtype=d.dtype)], axis=0
            )
        if shadow is not None and shadow.n_rows:
            s = shadow.data[comp][: d.shape[0]]
            if comp == "mn":
                d = np.minimum(d, s)
            elif comp in ("mx", "hll"):
                d = np.maximum(d, s)
            else:
                d = d + s
        out[comp] = d
    return out


class IdentityFinalize:
    """Always-ready stand-in for a device components fetch whose state
    snapshot is EMPTY (identity values). Used by storm mode
    (runtime/nodes_fused.py): when the device link is stalling, a window
    runs fully host-shadowed and merges against this identity — emit
    latency stays bounded while real fetches probe for recovery."""

    def __init__(self, comp_specs: Dict[str, List[int]], capacity: int) -> None:
        self.capacity = capacity
        self._comps: Dict[str, np.ndarray] = {}
        for comp, spec_idxs in comp_specs.items():
            shape = (capacity,) + _comp_shape(comp, spec_idxs)
            self._comps[comp] = np.full(shape, _INIT[comp], dtype=np.float32)
        self._comps["act"] = np.zeros(capacity, dtype=np.float32)

    def ready(self) -> bool:
        return True

    def get(self) -> Dict[str, np.ndarray]:
        return self._comps


def begin_pending(stacked, capacity: int, layout) -> "PendingFinalize":
    """Start the async device→host copy of a dispatched components array
    and wrap it — the ONE async-fetch protocol shared by the prefinalize,
    components_dyn, and sliding-ring dispatch sites."""
    try:
        stacked.copy_to_host_async()
    except AttributeError:
        pass
    return PendingFinalize(stacked, capacity, layout)


class PendingFinalize:
    """Handle for an in-flight device components fetch, created one RTT
    before the window boundary.

    The fetch runs on its own thread from the moment of creation: on a
    tunneled device the wait-until-ready control call queues FIFO behind
    subsequently dispatched work, so registering the wait EARLY (before the
    tail's fold dispatches flood the link) is what makes the result be on
    host by the time the boundary fires. .get() then just joins the thread.
    """

    def __init__(self, stacked: Any, capacity: int, layout) -> None:
        import threading

        from ..utils import timex

        self.stacked = stacked  # one (capacity, W) device array = one leaf
        self.capacity = capacity
        self.layout = layout  # [(comp, col, width, per-key shape)]
        self._result: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()
        # telemetry for the emit path: when the fetch was issued / landed,
        # in ENGINE-clock ms — mock-clock runs see deterministic timings
        self.t_created = timex.now_ms()
        self.t_done: Optional[int] = None
        threading.Thread(
            target=self._fetch, name="prefinalize-fetch", daemon=True
        ).start()

    def _fetch(self) -> None:
        from ..utils import timex

        try:
            self._result = unpack_components(
                np.asarray(self.stacked), self.layout)
        except BaseException as exc:  # surfaced to the emit thread
            self._exc = exc
        finally:
            self.t_done = timex.now_ms()
            self._done.set()

    def ready(self) -> bool:
        return self._done.is_set()

    def fetch_ms(self) -> float:
        """Issue→landed latency (telemetry); -1 while still in flight."""
        if self.t_done is None:
            return -1.0
        return float(self.t_done - self.t_created)

    def get(self) -> Dict[str, np.ndarray]:
        self._done.wait()
        if self._exc is not None:
            raise self._exc
        return self._result
