"""Shared pane store — the device half of cross-rule window-aggregate
sharing (planner/sharing.py + runtime/nodes_sharedfold.py).

"Factor Windows" (arxiv 2008.12379) observes that correlated window
aggregates over one stream can be rewritten to share factored partials;
the pane/slice merge the group-by kernel already uses for hopping windows
(ops/groupby.py, the constant-time merge structure of arxiv 2009.13768)
is exactly that factorization. Here the panes become a FIRST-CLASS shared
resource: one device-resident ring of panes at the GCD granularity of the
member rules' windows, folded once per batch, from which each rule's
window is a pane-subset finalize (tumbling = pane-sum over its span,
hopping = its live pane set).

This module owns the device state and the union-plan algebra; the node
driving it (attach/detach, boundary timers, watermarks, per-rule emit)
lives in runtime/nodes_sharedfold.py.
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .aggspec import (
    HLL_COL_PREFIX,
    KernelPlan,
    _call_key,
    _hll_encode_numeric,
    hash_column_for_hll,
)
from .groupby import DeviceGroupBy
from .keytable import KeyTable


def pane_gcd(values_ms: Iterable[int]) -> int:
    """Common pane width for a set of window lengths/intervals (ms)."""
    g = 0
    for v in values_ms:
        if v:
            g = math.gcd(g, int(v))
    return max(g, 1)


def union_plan(plans: Sequence[KernelPlan]) -> Tuple[KernelPlan, List[List[int]]]:
    """Union N rules' kernel plans into one foldable plan, deduplicating
    aggregate specs by call key (avg(x) wanted by 5 rules folds once; a
    predicate-lifted spec's key includes its FILTER, so identical-WHERE
    peers still dedup while different-WHERE peers coexist as distinct
    masked specs over ONE fold). Returns (union, maps) where maps[r][i]
    is the union spec index of rule r's spec i. Statement-level WHERE
    filters must be identical across members — predicate lifting
    (ops/aggspec.py lift_predicate) rewrites them into per-spec FILTER
    masks and leaves the plan filter None, so in the sharing path the
    first plan's filter (None) speaks for all."""
    specs: List = []
    index: Dict[str, int] = {}
    columns: set = set()
    col_dtypes: Dict[str, str] = {}
    derived: Dict[str, object] = {}
    maps: List[List[int]] = []
    tags: List[str] = []
    for plan in plans:
        m: List[int] = []
        for spec in plan.specs:
            key = _call_key(spec.call)
            at = index.get(key)
            if at is None:
                at = index[key] = len(specs)
                specs.append(spec)
            m.append(at)
        columns |= plan.columns
        col_dtypes.update(getattr(plan, "col_dtypes", {}))
        for d in getattr(plan, "derived", ()):
            derived[d.name] = d
        if getattr(plan, "expr_tag", ""):
            tags.append(plan.expr_tag)
        maps.append(m)
    first = plans[0]
    from ..sql.expr_ir import ir_hash

    return (
        KernelPlan(specs=specs, filter=first.filter, columns=columns,
                   filter_host=first.filter_host, col_dtypes=col_dtypes,
                   derived=tuple(sorted(derived.values(),
                                        key=lambda d: d.name)),
                   expr_tag=ir_hash(tags) if tags else ""),
        maps,
    )


def spec_map_into(union: KernelPlan, plan: KernelPlan) -> List[int]:
    """Map a member rule's spec indices into a live union plan; raises
    KeyError when the union does not cover the rule (the planner declines
    such joins — hitting this means a plan/open race)."""
    index = {_call_key(s.call): i for i, s in enumerate(union.specs)}
    return [index[_call_key(s.call)] for s in plan.specs]


def build_value_columns(
    plan: KernelPlan, sub,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Materialize the union plan's numeric columns + validity masks for a
    ColumnBatch — the dims-free subset of the fused node's kernel-input
    build (runtime/nodes_fused.py _build_kernel_inputs): hll derived
    columns, object-column coercion, NaN fill for missing columns.
    heavy_hitters never reaches a shared fold (node-local dictionaries),
    so there is no __hhc__ branch here."""
    cols: Dict[str, np.ndarray] = {}
    valid: Dict[str, np.ndarray] = {}
    if getattr(plan, "derived", ()):
        # expression-IR derived columns (__sd_*/__ts32_* — predicate-
        # lifted WHERE masks ride these): host prep with self-describing
        # null sentinels, sql/expr_ir.py
        from ..sql.expr_ir import materialize_derived

        materialize_derived(plan.derived, cols, sub,
                            expr_tag=getattr(plan, "expr_tag", ""))
    for name in plan.columns:
        if name in cols:
            continue  # derived expr column, just materialized
        if name.startswith(HLL_COL_PREFIX):
            raw_name = name[len(HLL_COL_PREFIX):]
            col = sub.columns.get(raw_name)
            if col is None:
                cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
            elif col.dtype == np.object_:
                cols[name] = hash_column_for_hll(col)
            else:
                cols[name] = _hll_encode_numeric(col)
            v = sub.valid.get(raw_name)
            if v is not None:
                valid[name] = v
            continue
        col = sub.columns.get(name)
        if col is None:
            cols[name] = np.full(sub.n, np.nan, dtype=np.float32)
            continue
        if col.dtype == np.object_:
            coerced = np.full(sub.n, np.nan, dtype=np.float32)
            for i, v in enumerate(col):
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    coerced[i] = v
            cols[name] = coerced
        else:
            cols[name] = col
        v = sub.valid.get(name)
        if v is not None:
            valid[name] = v
    return cols, valid


class PaneStore:
    """Device pane ring + key table for one shared fold.

    State shape is the group-by kernel's (n_panes, capacity, k) partials;
    pane p holds the rows of wall/event bucket b where b % n_panes == p.
    One fold per batch serves every member rule; a rule's window is a
    finalize over the pane subset spanning it (ops/groupby.py
    _finalize_dyn — a traced pane mask, one compiled executable no matter
    which subset)."""

    def __init__(self, plan: KernelPlan, pane_ms: int, n_panes: int,
                 capacity: int = 16384, micro_batch: int = 4096,
                 tier_budget_mb: Optional[float] = None,
                 mesh=None) -> None:
        self.plan = plan
        self.pane_ms = int(pane_ms)
        self.n_panes = int(n_panes)
        self.mesh = mesh
        # tiered key state (ops/tierstore.py): the shared store recycles
        # slots of QUIESCENT keys only (a cold key's pane data expires
        # with the ring, so no member window ever misses it); budget
        # defaults to the engine-wide KUIPER_HBM_BUDGET_MB the QoS
        # ledger prices against. Slot recycling breaks the neutral
        # table's dense-order contract — SharedFoldNode self-encodes
        # when the tier is live.
        if tier_budget_mb is None:
            from .tierstore import env_hbm_budget_mb

            tier_budget_mb = env_hbm_budget_mb()
        layout = None
        if tier_budget_mb and mesh is None and not any(
                s.kind == "heavy_hitters" for s in plan.specs):
            # mesh-sharded stores keep the untiered path (the cold tier
            # is single-chip machinery; ROADMAP names a peer-chip tier
            # as the follow-up)
            from .tierstore import plan_tier_layout

            layout = plan_tier_layout(plan, self.n_panes, capacity,
                                      float(tier_budget_mb),
                                      window_ms=self.pane_ms)
        if mesh is not None:
            # key-range-partitioned shared store: the pane ring shards
            # over the mesh's "keys" axis exactly like a private sharded
            # rule's state; folds/combines run through the SPMD kernel
            # (parallel/sharded.py), one pooled fold per batch serving
            # every member — now across every chip
            from ..parallel.sharded import ShardedGroupBy

            self.gb = ShardedGroupBy(plan, mesh, capacity=capacity,
                                     n_panes=self.n_panes,
                                     micro_batch=micro_batch)
        else:
            self.gb = DeviceGroupBy(plan, capacity=capacity,
                                    n_panes=self.n_panes,
                                    micro_batch=micro_batch,
                                    track_touch=layout is not None)
        self.kt = KeyTable(self.gb.capacity)
        self.tier = None
        if layout is not None:
            from .tierstore import TierManager

            self.tier = TierManager(
                self.gb, self.kt, layout, rule_id="__shared__",
                quiescent_only=True,
                # quiescent must mean EXPIRED: idle across the whole
                # pane ring, so no member's open window still holds the
                # key's data (a shorter idle gate would demote keys a
                # hopping member is about to emit)
                min_idle_ms=self.pane_ms * self.n_panes)
        self.state = self.gb.init_state()
        self._dtypes_seen = False
        # HBM accounting: the shared pane ring serves N rules but is ONE
        # allocation — reported once, under the shared-rule label
        from ..observability import memwatch

        memwatch.register(
            "pane_store", self,
            lambda st: sum(int(getattr(a, "nbytes", 0) or 0)
                           for a in st.state.values())
            + st.kt.approx_bytes(),
            rule="__shared__")

    # ------------------------------------------------------------------ fold
    def fold(self, cols: Dict[str, np.ndarray], valid, slots, pane_arg,
             n_rows: Optional[int] = None) -> None:
        """Fold one batch's kernel inputs into `pane_arg` (scalar pane or
        per-row pane vector). Grows the device state when the key table
        outran it (new keys this batch)."""
        if not self._dtypes_seen:
            self.gb.observe_dtypes(cols)
            self._dtypes_seen = True
        if self.gb.capacity < self.kt.capacity:
            self.state = self.gb.grow(self.state, self.kt.capacity)
        if self.tier is not None:
            # admission point: returning demoted keys promote before the
            # batch folds (quiescent-only demotion → promoted rows are
            # identity, this re-seats the key's slot bookkeeping)
            self.state = self.tier.admit(self.state)
        self.state = self.gb.fold(self.state, cols, slots, valid, pane_arg,
                                  n_rows=n_rows)

    # --------------------------------------------------------------- combine
    def combine(self, panes: Sequence[int],
                n_keys: int) -> Tuple[List[np.ndarray], np.ndarray]:
        """Finalize the union plan over a pane subset: one device launch,
        one transfer; integer semantics already applied (groupby.py)."""
        return self.gb.finalize(self.state, n_keys,
                                panes=sorted(set(int(p) for p in panes)))

    def reset_pane(self, pane: int) -> None:
        self.state = self.gb.reset_pane(self.state, int(pane))
        if self.tier is not None:
            # pane boundary: epoch bump + demote-plan apply + touch scan
            # (inline — the shared store has no dedicated worker; the
            # scan cadence keeps it off the per-batch path)
            self.tier.note_pane_reset(int(pane))
            self.state = self.tier.on_boundary(self.state)

    # ---------------------------------------------------------------- warmup
    def warmup(self) -> None:
        """Compile fold (scalar + vector pane) and the dyn finalize on a
        throwaway state so the first live batch/boundary doesn't pay the
        jit latency. Never touches self.state (it may hold restored
        partials)."""
        try:
            from .groupby import warmup_cols

            cols = warmup_cols(self.plan)
            slots = np.zeros(1, dtype=np.int32)
            dummy = self.gb.init_state()
            dummy = self.gb.fold(dummy, dict(cols), slots, pane_idx=0)
            dummy = self.gb.fold(dummy, dict(cols), slots,
                                 pane_idx=np.zeros(1, dtype=np.int64))
            self.gb.finalize(dummy, 1, panes=[0])
        except Exception:
            pass  # non-fatal: first live use compiles instead

    # ------------------------------------------------------------ checkpoint
    def snapshot(self) -> Dict:
        host = self.gb.state_to_host(self.state)
        snap = {
            "keys": self.kt.decode_all(),
            "partials": {k: v.tolist() for k, v in host.items()},
            "pane_ms": self.pane_ms,
            "n_panes": self.n_panes,
        }
        if self.tier is not None:
            snap["tier"] = self.tier.snapshot()
        return snap

    def restore(self, snap: Dict) -> None:
        if int(snap.get("pane_ms", self.pane_ms)) != self.pane_ms or \
                int(snap.get("n_panes", self.n_panes)) != self.n_panes:
            raise ValueError(
                "pane store snapshot does not match this store's pane "
                f"geometry ({snap.get('pane_ms')}ms x {snap.get('n_panes')} "
                f"vs {self.pane_ms}ms x {self.n_panes})")
        keys = snap.get("keys", [])
        self.kt.restore([tuple(k) if isinstance(k, list) else k for k in keys])
        partials = snap.get("partials")
        if partials:
            host, cap = self.gb.host_from_partials(partials)
            self.gb.capacity = cap
            # sharded stores may round the capacity up for even shard
            # division (mesh-size-change tolerance) — kt follows
            self.state = self.gb.state_from_host(host)
            self.kt.capacity = max(self.kt.capacity, self.gb.capacity)
        if self.tier is not None and snap.get("tier"):
            self.tier.restore(snap["tier"])
