"""Fused GROUP BY aggregation kernel — the TPU replacement for the reference's
hot loop (WindowIncAggOperator + AggregateOp + per-group ValuerEval,
reference: internal/topo/node/window_inc_agg_op.go,
internal/topo/operator/aggregate_operator.go:34-74).

Design: per-key partial state lives in dense device arrays of shape
(n_panes, capacity, k) — one column per aggregate spec, one pane per
window sub-interval:

- TUMBLING/COUNT windows: 1 pane, reset after emit.
- HOPPING windows: P = length/interval panes (the "pane/slice" technique from
  sliding-window aggregation literature); each pane is a tumbling sub-window,
  emit merges the live panes, expiry resets one pane.

One jitted `fold` per rule processes a fixed-size micro-batch: WHERE filter,
per-agg argument expressions (compiled device closures), null/validity
masking, and scatter-add/min/max into the partials — all fused by XLA into a
single device program. Micro-batches are padded to a static shape so the
kernel compiles once.

State components per spec: n (count), s1 (sum), s2 (sum of squares),
mn (min), mx (max) — matching funcs_inc_agg.py's accumulators, so shard
merges (parallel/) are elementwise add/min/max.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .aggspec import AggSpec, KernelPlan

_INIT = {
    "n": 0.0, "s1": 0.0, "s2": 0.0, "mn": np.inf, "mx": -np.inf, "act": 0.0,
    # wide (register-axis) components: HLL registers, log-histogram bins,
    # heavy-hitters group-testing counters
    "hll": 0.0, "hist": 0.0, "hh": 0.0,
    # per-slot touch counter (tiered key state, ops/tierstore.py):
    # uint32, shape (capacity,) — NOT pane-scoped, survives pane resets
    "touch": 0,
}

_WIDE_SIZE = {}  # filled lazily from sketches to avoid import cycle


def _wide_size(comp: str) -> int:
    if not _WIDE_SIZE:
        from . import sketches

        _WIDE_SIZE["hll"] = sketches.HLL_M
        _WIDE_SIZE["hist"] = sketches.HIST_BINS
        _WIDE_SIZE["hh"] = sketches.HH_SIZE
    return _WIDE_SIZE[comp]


def col_np_dtype(plan: KernelPlan, name: str):
    """Upload dtype for one kernel column: float32 unless the plan's
    expression IR declared otherwise (int32 string-dict codes / rebased
    ts32 — KernelPlan.col_dtypes). THE one mapping shared by the fold
    upload, the ingest prep pre-upload, warmups, and the jitcert fold
    derivations."""
    return np.dtype(getattr(plan, "col_dtypes", {}).get(name, "float32"))


def warmup_cols(plan: KernelPlan, n: int = 1) -> Dict[str, np.ndarray]:
    """Dtype-correct zero columns for a warmup fold — the throwaway
    batch must present the same column dtypes real batches will, or the
    warmup compiles an executable no real fold ever hits."""
    return {name: np.zeros(n, dtype=col_np_dtype(plan, name))
            for name in plan.columns}


def slot_dtype(capacity: int):
    """Slot-vector wire dtype for a key capacity — the ONE place holding
    the uint16/int32 boundary. Slots ship as uint16 while every assignable
    slot id (0..capacity-1) fits; past 65,535 they ship int32. Callers that
    cache pre-padded slot arrays (sliding _dev_ring, the ingest prep's
    share cache) key or re-derive on this, so a capacity doubling past the
    boundary switches new uploads to int32 while already-cached uint16
    arrays stay valid — their values predate the grow and still index the
    same dense slots (_fold_core casts to int32 on device)."""
    return np.uint16 if capacity <= 65535 else np.int32


def apply_int_semantics(specs, host: List[np.ndarray]) -> List[np.ndarray]:
    """Reference-exact integer semantics on finalize output: counts are
    int64; integer-typed inputs get truncating avg / integral sum/min/max.
    Shared by the single-chip and sharded paths so results are identical
    regardless of placement."""
    for i, spec in enumerate(specs):
        if spec.kind in ("count", "hll"):
            host[i] = host[i].astype(np.int64)
        elif spec.int_input and spec.kind in ("sum", "avg", "min", "max"):
            with np.errstate(invalid="ignore"):
                trunc = np.trunc(host[i])
            host[i] = np.where(np.isnan(host[i]), np.nan, trunc)
    return host


def observe_int_inputs(specs, columns: Dict[str, np.ndarray]) -> None:
    """Record integer-typed agg inputs (drives apply_int_semantics)."""
    for spec in specs:
        if spec.arg is not None and len(spec.arg.columns) == 1:
            (col_name,) = spec.arg.columns
            col = columns.get(col_name)
            if col is not None and np.issubdtype(col.dtype, np.integer):
                spec.int_input = True


class DeviceGroupBy:
    """Device-resident group-by aggregation state + jitted fold/finalize."""

    def __init__(
        self,
        plan: KernelPlan,
        capacity: int = 16384,
        n_panes: int = 1,
        micro_batch: int = 4096,
        track_touch: bool = False,
    ) -> None:
        import jax

        self.plan = plan
        self.capacity = int(capacity)
        self.n_panes = int(n_panes)
        self.micro_batch = int(micro_batch)
        # tiered key state (ops/tierstore.py): a per-slot uint32 touch
        # counter rides the state pytree and is bumped inside the fold —
        # the placement policy's recency/frequency signal, no host sync
        self.track_touch = bool(track_touch)
        # component -> ordered spec indices holding a column in that array
        self.comp_specs: Dict[str, List[int]] = {}
        for i, spec in enumerate(plan.specs):
            for comp in spec.components:
                self.comp_specs.setdefault(comp, []).append(i)
        from ..runtime.aotcache import aot_jit

        self._fold = aot_jit(self._fold_impl, op=self._watch_op("fold"),
                                 donate_argnums=(0,))
        # row-masked fold: the sliding edge refold re-folds CACHED device
        # batches under an arbitrary (mb,) bool row mask (window time cut),
        # so trigger emission uploads one 65KB mask instead of the rows
        self._fold_m = aot_jit(self._fold_masked_impl,
                                   op=self._watch_op("fold_masked"),
                                   kind="boundary",
                                   donate_argnums=(0,))
        # pane mask is static: no device upload per emit, one cached
        # executable per live-pane combination (few), and the output is ONE
        # stacked array -> a single device->host transfer per window emit
        # (sync round trips cost 10-90ms on tunneled TPU; see bench notes)
        self._finalize = aot_jit(self._finalize_impl,
                                     op=self._watch_op("finalize"),
                                     kind="boundary",
                                     static_argnums=(1,))
        # dynamic-mask variant: event-time windows rotate through per-window
        # pane subsets; a static mask would compile one executable per
        # subset (up to n_panes compiles), a traced mask compiles once
        self._finalize_dyn = aot_jit(self._finalize_dyn_impl,
                                         op=self._watch_op("finalize_dyn"),
                                         kind="boundary")
        self._components = aot_jit(self._components_impl,
                                       op=self._watch_op("components"),
                                       kind="boundary",
                                       static_argnums=(1,))
        # traced-pane-mask components twin: the sliding ring's exact
        # fallback (delayed emissions, recycled panes) merges an arbitrary
        # live-pane subset into the SAME stacked components layout with
        # one compiled executable per capacity
        self._components_dyn = aot_jit(self._components_dyn_impl,
                                           op=self._watch_op("components_dyn"),
                                           kind="boundary")
        self._reset_pane = aot_jit(self._reset_pane_impl,
                                       op=self._watch_op("reset_pane"),
                                       kind="boundary",
                                       donate_argnums=(0,))
        # heavy_hitters finalize: candidate recovery + top-k run ON DEVICE
        # (sketches.hh_candidates) so the emit transfer is 2*k2 floats/key,
        # not the HH_SIZE-wide raw sketch; dedupe + value decode finish on
        # host. finalize() routes through _host_finalize for such plans.
        self._host_finalize_only = any(
            s.kind == "heavy_hitters" for s in plan.specs
        )
        if self._host_finalize_only:
            self._hh_fin = aot_jit(self._hh_finalize_impl,
                                       op=self._watch_op("hh_finalize"),
                                       kind="boundary")
        # bind this kernel to its compile contract: jitcert derives the
        # closed signature set every site above may be traced with, and
        # the runtime diff (bench rounds, /diagnostics/xla) holds the
        # observed devwatch signatures to it
        from ..observability import jitcert

        jitcert.register_kernel(self)

    #: kuiper_xla_* metric prefix for this kernel's jit sites; subclasses
    #: override (multirule / sharded) so recompiles attribute to the
    #: kernel variant that paid them
    watch_prefix = "groupby"

    def _watch_op(self, site: str) -> str:
        return f"{self.watch_prefix}.{site}"

    #: the latency-hiding emit pipeline (ops/prefinalize.py) works here;
    #: the sharded subclass opts out (its finalize runs collective gathers)
    supports_prefinalize = True
    #: fold() accepts pre-padded device arrays (shared-source fan-out
    #: uploads); the sharded subclass opts out — its fold shards HOST
    #: arrays across the mesh itself
    accepts_device_inputs = True

    # ------------------------------------------------------------------ state
    def init_state(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        from .aggspec import WIDE_COMPONENTS

        state: Dict[str, Any] = {}
        for comp, spec_idxs in self.comp_specs.items():
            shape = (self.n_panes, self.capacity, len(spec_idxs))
            if comp in WIDE_COMPONENTS:
                shape = shape + (_wide_size(comp),)
            state[comp] = jnp.full(shape, _INIT[comp], dtype=jnp.float32)
        # activity: rows per key per pane (post-WHERE), for group existence
        state["act"] = jnp.zeros((self.n_panes, self.capacity), dtype=jnp.float32)
        if self.track_touch:
            state["touch"] = jnp.zeros((self.capacity,), dtype=jnp.uint32)
        return state

    def grow(self, state: Dict[str, Any], new_capacity: int) -> Dict[str, Any]:
        """Double the key capacity, preserving partials. Runs ON DEVICE
        (jnp.pad) — at 1M-key cardinality a host roundtrip would move GBs
        through the host↔device link per doubling."""
        import jax.numpy as jnp

        out: Dict[str, Any] = {}
        for comp, arr in state.items():
            # the touch column is (capacity,), not pane-scoped — the key
            # axis is axis 0 there, axis 1 everywhere else
            key_axis = 0 if comp == "touch" else 1
            if isinstance(arr, np.ndarray):  # host-restored state
                pad_shape = list(arr.shape)
                pad_shape[key_axis] = new_capacity - arr.shape[key_axis]
                pad = np.full(pad_shape, _INIT[comp], dtype=arr.dtype)
                out[comp] = jnp.asarray(
                    np.concatenate([arr, pad], axis=key_axis))
                continue
            pad_width = [(0, 0)] * arr.ndim
            pad_width[key_axis] = (0, new_capacity - arr.shape[key_axis])
            out[comp] = jnp.pad(arr, pad_width,
                                constant_values=_INIT[comp])
        self.capacity = new_capacity
        return out

    # ------------------------------------------------------------------- fold
    def fold(
        self,
        state: Dict[str, Any],
        cols: Dict[str, np.ndarray],
        slots: np.ndarray,
        valid: Optional[Dict[str, np.ndarray]] = None,
        pane_idx=0,
        n_rows: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Fold a host micro-batch into the device partials.

        cols: numeric columns referenced by the kernel plan (numpy).
        slots: int32 key slot per row. valid: optional per-column masks.
        pane_idx: the destination pane — a scalar (processing-time windows)
        or a per-row array (event-time windows route each row to its
        bucket's pane). Rows are chunked/padded to the static micro_batch
        size.
        """
        import jax
        import jax.numpy as jnp

        from .aggspec import materialize_hll_columns

        # pre-padded device slots are length mb regardless of real rows, so
        # the true count must come from the caller in that case
        n = n_rows if n_rows is not None else len(slots)
        mb = self.micro_batch
        valid = valid or {}
        cols = materialize_hll_columns(self.plan.columns, cols, n)
        # shared-source fan-out hands PRE-PADDED device arrays (length mb,
        # one upload serving many consumers — nodes_fused.py
        # _shared_device_inputs). Those are single-chunk by contract.
        has_dev = isinstance(slots, jax.Array) or any(
            isinstance(cols[name], jax.Array) for name in self.plan.columns)
        if has_dev:
            assert n <= mb, "pre-uploaded device inputs must be one chunk"
        for start in range(0, max(n, 1), mb):
            end = min(start + mb, n)
            cnt = end - start
            if cnt <= 0:
                break
            pad = mb - cnt
            dev_cols = {}
            for name in self.plan.columns:
                c = cols[name]
                if isinstance(c, jax.Array):  # pre-padded shared upload
                    dev_cols[name] = c
                    dev_cols["__valid_" + name] = valid.get(name)
                    continue
                # kuiperlint: ignore[host-sync]: `c` is a HOST column here (device arrays took the pre-padded branch above) — this is H2D staging, not a sync
                arr = np.asarray(c[start:end],
                                 dtype=col_np_dtype(self.plan, name))
                if pad:
                    arr = np.pad(arr, (0, pad))
                dev_cols[name] = jnp.asarray(arr)
                vmask = valid.get(name)
                if vmask is not None:
                    vm = vmask[start:end]
                    if pad:
                        vm = np.pad(vm, (0, pad))
                else:
                    vm = None
                dev_cols["__valid_" + name] = (
                    jnp.asarray(vm) if vm is not None else None
                )
            if isinstance(slots, jax.Array):
                s_dev = slots  # pre-padded + dtype-chosen by the sharer
            else:
                s = slots[start:end]
                if pad:
                    s = np.pad(s, (0, pad))
                # tunnel-byte diet: slots ship as uint16 when capacity
                # allows (halves the largest upload), and row validity
                # ships as ONE scalar count compared against an iota on
                # device instead of an mb-byte bool mask — HBM/link
                # bandwidth is the bottleneck, not device compute
                s_dev = jnp.asarray(
                    s.astype(slot_dtype(self.capacity), copy=False))
            if isinstance(pane_idx, np.ndarray):
                pv = pane_idx[start:end]
                if pad:
                    pv = np.pad(pv, (0, pad))
                pane_arg = jnp.asarray(pv.astype(np.uint8))  # n_panes <= 255
            else:
                pane_arg = jnp.asarray(pane_idx, dtype=jnp.int32)
            state = self._fold(
                state, dev_cols, s_dev,
                jnp.asarray(cnt, dtype=jnp.int32),
                pane_arg,
            )
        return state

    def _fold_impl(self, state, cols, slots, n_valid, pane_idx):
        import jax.numpy as jnp

        base = jnp.arange(self.micro_batch, dtype=jnp.int32) < n_valid
        return self._fold_core(state, cols, slots, base, pane_idx)

    def _fold_masked_impl(self, state, cols, slots, mask, pane_idx):
        return self._fold_core(state, cols, slots, mask, pane_idx)

    def fold_masked(self, state, dev_cols, slots_dev, mask: np.ndarray,
                    pane_idx: int):
        """Re-fold a cached pre-padded device batch under a host row mask
        (False rows contribute nothing — the mask already ANDs the real-row
        count). Used by the sliding edge refold; see nodes_fused.py."""
        import jax.numpy as jnp

        return self._fold_m(state, dev_cols, slots_dev,
                            jnp.asarray(mask, dtype=jnp.bool_),
                            jnp.asarray(pane_idx, dtype=jnp.int32))

    def _fold_core(self, state, cols, slots, base, pane_idx):
        import jax.numpy as jnp

        slots = slots.astype(jnp.int32)
        pane_idx = pane_idx.astype(jnp.int32)  # scalar or per-row vector
        if self.plan.filter is not None:
            base = jnp.logical_and(base, self.plan.filter(cols))
        # per-column validity composes into per-spec masks below
        state["act"] = state["act"].at[pane_idx, slots].add(
            base.astype(jnp.float32)
        )
        if "touch" in state:
            # tier placement signal (ops/tierstore.py): per-slot touched-
            # row count, cumulative — the policy worker diffs successive
            # async fetches for recency/frequency, so the fold itself
            # never syncs
            state["touch"] = state["touch"].at[slots].add(
                base.astype(jnp.uint32))
        per_spec: List[Tuple[Any, Any]] = []
        for spec in self.plan.specs:
            if spec.arg is None:
                v = jnp.ones_like(base, dtype=jnp.float32)
                m = base
            else:
                v = spec.arg(cols).astype(jnp.float32)
                m = base
                for col in spec.arg.columns:
                    vm = cols.get("__valid_" + col)
                    if vm is not None:
                        m = jnp.logical_and(m, vm)
                m = jnp.logical_and(m, jnp.logical_not(jnp.isnan(v)))
            if spec.filter is not None:
                m = jnp.logical_and(m, spec.filter(cols))
            per_spec.append((v, m))
        for comp, spec_idxs in self.comp_specs.items():
            arr = state[comp]
            for k, si in enumerate(spec_idxs):
                v, m = per_spec[si]
                mf = m.astype(jnp.float32)
                if comp == "n":
                    arr = arr.at[pane_idx, slots, k].add(mf)
                elif comp == "s1":
                    arr = arr.at[pane_idx, slots, k].add(jnp.where(m, v, 0.0))
                elif comp == "s2":
                    arr = arr.at[pane_idx, slots, k].add(jnp.where(m, v * v, 0.0))
                elif comp == "mn":
                    arr = arr.at[pane_idx, slots, k].min(
                        jnp.where(m, v, jnp.inf)
                    )
                elif comp == "mx":
                    arr = arr.at[pane_idx, slots, k].max(
                        jnp.where(m, v, -jnp.inf)
                    )
                elif comp == "hll":
                    from .sketches import hll_parts

                    reg, rho = hll_parts(v)
                    arr = arr.at[pane_idx, slots, k, reg].max(
                        jnp.where(m, rho, 0.0)
                    )
                elif comp == "hist":
                    from .sketches import hist_bin

                    b = hist_bin(v)
                    arr = arr.at[pane_idx, slots, k, b].add(mf)
                elif comp == "hh":
                    from .sketches import hh_update_parts

                    idx, wts = hh_update_parts(v, mf)  # (mb, J)
                    p = (pane_idx[:, None]
                         if getattr(pane_idx, "ndim", 0) == 1 else pane_idx)
                    arr = arr.at[p, slots[:, None], k, idx].add(wts)
            state[comp] = arr
        return state

    # --------------------------------------------------------------- finalize
    def _merged(self, state, comp: str, pane_mask):
        """Merge panes under a (n_panes,) bool mask."""
        import jax.numpy as jnp

        arr = state[comp]
        pm = pane_mask.reshape(-1, *([1] * (arr.ndim - 1)))
        if comp == "mn":
            return jnp.min(jnp.where(pm, arr, jnp.inf), axis=0)
        if comp in ("mx", "hll"):  # hll registers merge by max
            return jnp.max(jnp.where(pm, arr, -jnp.inf), axis=0)
        return jnp.sum(jnp.where(pm, arr, 0.0), axis=0)

    def _finalize_dyn_impl(self, state, pane_mask):
        return self._finalize_body(state, pane_mask)

    def _finalize_impl(self, state, pane_mask_tuple):
        import jax.numpy as jnp

        pane_mask = jnp.asarray(np.array(pane_mask_tuple, dtype=np.bool_))
        return self._finalize_body(state, pane_mask)

    def _finalize_body(self, state, pane_mask):
        import jax.numpy as jnp

        merged = {
            comp: self._merged(state, comp, pane_mask) for comp in self.comp_specs
        }
        act = self._merged(state, "act", pane_mask)
        outs = []
        for i, spec in enumerate(self.plan.specs):
            col = {
                comp: merged[comp][:, self.comp_specs[comp].index(i)]
                for comp in spec.components
            }
            outs.append(self._final_value(spec, col))
        # one stacked array -> one transfer
        return jnp.stack(outs + [act], axis=0)

    @staticmethod
    def _final_value(spec: AggSpec, c):
        import jax.numpy as jnp

        kind = spec.kind
        if kind == "count":
            return c["n"]
        n = c.get("n")
        if kind == "sum":
            return jnp.where(n > 0, c["s1"], jnp.nan)
        if kind == "avg":
            return jnp.where(n > 0, c["s1"] / jnp.maximum(n, 1.0), jnp.nan)
        if kind == "min":
            return jnp.where(n > 0, c["mn"], jnp.nan)
        if kind == "max":
            return jnp.where(n > 0, c["mx"], jnp.nan)
        if kind in ("stddev", "var"):
            mean = c["s1"] / jnp.maximum(n, 1.0)
            v = jnp.maximum(c["s2"] / jnp.maximum(n, 1.0) - mean * mean, 0.0)
            out = jnp.sqrt(v) if kind == "stddev" else v
            return jnp.where(n > 0, out, jnp.nan)
        if kind in ("stddevs", "vars"):
            mean = c["s1"] / jnp.maximum(n, 1.0)
            v = jnp.maximum(
                (c["s2"] - c["s1"] * mean) / jnp.maximum(n - 1.0, 1.0), 0.0
            )
            out = jnp.sqrt(v) if kind == "stddevs" else v
            return jnp.where(n >= 2, out, jnp.nan)
        if kind == "hll":
            from .sketches import hll_estimate

            # pane merge used -inf for masked panes; clamp back to 0
            regs = jnp.maximum(c["hll"], 0.0)
            return jnp.round(hll_estimate(regs))
        if kind == "percentile_approx":
            from .sketches import hist_quantile

            return hist_quantile(c["hist"], spec.frac)
        raise ValueError(f"unknown device agg kind {kind}")

    def _components_layout(self):
        """(comp, col_start, width, per-key shape) for the stacked
        components array; one flat (capacity, W) f32 array means ONE device
        leaf -> one transfer/wait round trip on a tunneled chip (per-leaf
        waits cost ~an RTT each)."""
        from .aggspec import WIDE_COMPONENTS

        layout = []
        col = 0
        for comp in sorted(self.comp_specs):
            shape: Tuple[int, ...] = (len(self.comp_specs[comp]),)
            if comp in WIDE_COMPONENTS:
                shape = shape + (_wide_size(comp),)
            w = int(np.prod(shape))
            layout.append((comp, col, w, shape))
            col += w
        layout.append(("act", col, 1, ()))
        return layout

    def _components_impl(self, state, pane_mask_tuple):
        """Pane-merged raw components (not final values), stacked into one
        (capacity, W) array — the device half of the latency-hiding emit
        (ops/prefinalize.py). Final values are computed on host after the
        tail shadow is merged in."""
        return self._components_body(
            state, np.array(pane_mask_tuple, dtype=np.bool_))

    def _components_dyn_impl(self, state, pane_mask):
        return self._components_body(state, pane_mask)

    def components_begin_dyn(self, state: Dict[str, Any],
                             pane_mask: np.ndarray):
        """Dispatch the traced-mask components merge over an arbitrary
        live-pane subset and start the async copy; returns a
        PendingFinalize sharing prefinalize_merge's host tail. The
        sliding ring's exact fallback route (runtime/nodes_fused.py)."""
        import jax.numpy as jnp

        from .prefinalize import begin_pending

        out = self._components_dyn(
            state, jnp.asarray(pane_mask, dtype=jnp.bool_))
        return begin_pending(out, self.capacity, self._components_layout())

    def _components_body(self, state, pane_mask):
        import jax.numpy as jnp

        parts = []
        for comp in sorted(self.comp_specs):
            m = self._merged(state, comp, pane_mask)
            parts.append(m.reshape(m.shape[0], -1))
        act = self._merged(state, "act", pane_mask)
        parts.append(act.reshape(-1, 1))
        return jnp.concatenate(parts, axis=1)

    def _pane_mask(self, panes: Optional[List[int]]) -> Tuple[bool, ...]:
        pane_mask = np.zeros(self.n_panes, dtype=np.bool_)
        if panes is None:
            pane_mask[:] = True
        else:
            pane_mask[panes] = True
        return tuple(pane_mask.tolist())

    def prefinalize_begin(self, state: Dict[str, Any],
                          panes: Optional[List[int]] = None):
        """Dispatch the components computation and start the async
        device→host copy; returns a PendingFinalize. Non-blocking: the jax
        program sees an immutable snapshot of `state`, so subsequent folds
        don't disturb it."""
        from .prefinalize import begin_pending

        out = self._components(state, self._pane_mask(panes))
        return begin_pending(out, self.capacity, self._components_layout())

    def _final_from_components(
        self, comb: Dict[str, np.ndarray], n_keys: int,
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Numpy final values from pane-merged host components."""
        from .prefinalize import final_value_np

        act = comb["act"]
        outs: List[np.ndarray] = []
        for i, spec in enumerate(self.plan.specs):
            c = {
                comp: comb[comp][:, self.comp_specs[comp].index(i)]
                for comp in spec.components
            }
            outs.append(np.asarray(final_value_np(spec, c))[:n_keys])
        outs = apply_int_semantics(self.plan.specs, outs)
        return outs, np.asarray(act[:n_keys])

    def prefinalize_merge(
        self, pending, shadow, n_keys: int,
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Complete a pre-issued finalize: fetch device components (usually
        already on host), merge the tail shadow, compute final values in
        numpy. Same (outs, act) contract as finalize()."""
        from .prefinalize import merge_components

        # capacity may have grown during a frozen tail (new keys live only in
        # the shadow) — merge at the widest extent so no slot is truncated
        cap = max(self.capacity,
                  shadow.capacity if shadow is not None else 0)
        comb = merge_components(pending.get(), shadow, cap)
        return self._final_from_components(comb, n_keys)

    def _hh_finalize_impl(self, state, pane_mask):
        """Device finalize for plans containing heavy_hitters: non-hh specs
        produce their final-value row; hh specs produce 2*k2 rows of
        device-recovered candidate (codes, estimates). One small
        (R, capacity) transfer regardless of sketch width."""
        import jax.numpy as jnp

        from .sketches import hh_candidates

        merged = {
            comp: self._merged(state, comp, pane_mask)
            for comp in self.comp_specs
        }
        act = self._merged(state, "act", pane_mask)
        rows = []
        for i, spec in enumerate(self.plan.specs):
            if spec.kind == "heavy_hitters":
                hhm = merged["hh"][:, self.comp_specs["hh"].index(i)]
                codes, est = hh_candidates(hhm, 2 * spec.topk)
                rows.append(codes.T)  # (k2, cap)
                rows.append(est.T)
            else:
                col = {
                    comp: merged[comp][:, self.comp_specs[comp].index(i)]
                    for comp in spec.components
                }
                rows.append(self._final_value(spec, col)[None, :])
        rows.append(act[None, :])
        return jnp.concatenate(rows, axis=0)

    def hh_assemble(
        self, stacked: np.ndarray, n_keys: int,
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Host tail of the heavy-hitters finalize: dedupe candidates (a
        code can appear once per depth) and trim to top-k; plain specs read
        their final-value row. Shared by the sync finalize route and the
        async emit worker."""
        from .prefinalize import hh_dedupe_topk

        outs: List[np.ndarray] = []
        r = 0
        for spec in self.plan.specs:
            if spec.kind == "heavy_hitters":
                k2 = 2 * spec.topk
                codes = stacked[r:r + k2, :n_keys]
                est = stacked[r + k2:r + 2 * k2, :n_keys]
                r += 2 * k2
                col = np.empty(n_keys, dtype=np.object_)
                for j in range(n_keys):
                    col[j] = hh_dedupe_topk(codes[:, j], est[:, j],
                                            spec.topk)
                outs.append(col)
            else:
                outs.append(stacked[r, :n_keys].copy())
                r += 1
        act = stacked[-1]
        outs = apply_int_semantics(self.plan.specs, outs)
        return outs, np.asarray(act[:n_keys])

    def _host_finalize(
        self, state: Dict[str, Any], n_keys: int,
        panes: Optional[List[int]],
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Finalize route for heavy_hitters plans: fetch the compact device
        result, then assemble the top-k lists on host."""
        pm = np.zeros(self.n_panes, dtype=np.bool_)
        if panes is None:
            pm[:] = True
        else:
            pm[panes] = True
        stacked = np.asarray(self._hh_fin(state, pm))
        return self.hh_assemble(stacked, n_keys)

    def finalize(
        self, state: Dict[str, Any], n_keys: int,
        panes: Optional[List[int]] = None,
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Emit final aggregate values for slots [0, n_keys).

        Returns (per-spec value arrays, active-row-count array); keys with
        active == 0 did not appear in this window and must not emit a group.
        NaN encodes NULL for empty-group sum/avg/min/max.
        """
        if self._host_finalize_only:
            return self._host_finalize(state, n_keys, panes)
        pane_mask = np.zeros(self.n_panes, dtype=np.bool_)
        if panes is None:
            pane_mask[:] = True
            stacked = np.asarray(
                self._finalize(state, tuple(pane_mask.tolist())))
        else:
            # subset masks rotate per window (event time): traced mask,
            # single compiled executable
            pane_mask[panes] = True
            stacked = np.asarray(self._finalize_dyn(state, pane_mask))
        host = [stacked[i][:n_keys] for i in range(len(self.plan.specs))]
        act = stacked[-1]
        host = apply_int_semantics(self.plan.specs, host)
        return host, np.asarray(act[:n_keys])

    # ----------------------------------------------------------------- absorb
    def _absorb_impl(self, state, sh, pane_idx):
        for comp in list(state.keys()):
            if comp not in sh:
                continue  # touch column: shadows carry no policy state
            arr = state[comp]
            u = sh[comp]
            if comp == "mn":
                state[comp] = arr.at[pane_idx].min(u)
            elif comp in ("mx", "hll"):
                state[comp] = arr.at[pane_idx].max(u)
            else:
                state[comp] = arr.at[pane_idx].add(u)
        return state

    def absorb(self, state: Dict[str, Any], shadow_data: Dict[str, np.ndarray],
               pane_idx: int) -> Dict[str, Any]:
        """Merge host-shadow components into one pane of the device state.
        Used when a checkpoint barrier lands during a host-only window tail
        (runtime/nodes_fused.py): the shadowed rows are flushed to the device
        so the snapshot stays complete."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_absorb"):
            from ..runtime.aotcache import aot_jit

            self._absorb = aot_jit(self._absorb_impl,
                                       op=self._watch_op("absorb"),
                                       kind="boundary",
                                       donate_argnums=(0,))
        sh = {k: jnp.asarray(v) for k, v in shadow_data.items()}
        return self._absorb(state, sh, jnp.asarray(pane_idx, dtype=jnp.int32))

    # ------------------------------------------------------------------ reset
    def _reset_pane_impl(self, state, pane_idx):
        import jax.numpy as jnp

        for comp in list(state.keys()):
            if comp == "touch":
                continue  # per-slot recency survives pane expiry
            init = _INIT[comp]
            arr = state[comp]
            state[comp] = arr.at[pane_idx].set(jnp.full(arr.shape[1:], init, dtype=arr.dtype))
        return state

    def reset_pane(self, state: Dict[str, Any], pane_idx: int) -> Dict[str, Any]:
        import jax.numpy as jnp

        return self._reset_pane(state, jnp.asarray(pane_idx, dtype=jnp.int32))

    def reset_all(self, state: Dict[str, Any]) -> Dict[str, Any]:
        return self.init_state()

    # ------------------------------------------------------------- dtype note
    def observe_dtypes(self, columns: Dict[str, np.ndarray]) -> None:
        """Record integer-typed agg inputs for reference-exact finalize."""
        observe_int_inputs(self.plan.specs, columns)

    # ---------------------------------------------------------- checkpointing
    def state_to_host(self, state: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in state.items()}

    def state_from_host(self, host: Dict[str, np.ndarray]) -> Dict[str, Any]:
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in host.items()}

    def host_from_partials(
        self, partials: Dict[str, Any],
    ) -> Tuple[Dict[str, np.ndarray], int]:
        """Checkpoint partials -> (typed host arrays, capacity): THE one
        place knowing the per-component restore dtypes (float32 except
        the uint32 touch column) and reconciling the touch leaf against
        this kernel's track_touch (zero-fill a pre-tier checkpoint,
        drop the column for an untiered kernel — the certs here carry
        no touch leaf). Shared by the fused node and the pane store."""
        host = {k: np.asarray(v, dtype=(np.uint32 if k == "touch"
                                        else np.float32))
                for k, v in partials.items()}
        cap = host["act"].shape[1] if "act" in host else \
            next(iter(host.values())).shape[1]
        if self.track_touch:
            if "touch" not in host:
                host["touch"] = np.zeros(cap, dtype=np.uint32)
        else:
            host.pop("touch", None)
        return host, cap
