"""Sketch primitives on device — the north-star UDFs (BASELINE.json):
HyperLogLog distinct count, DDSketch-style log-histogram percentiles, and a
count-min sketch for heavy hitters. All are built from scatter-add/max into
dense per-key register arrays, so they fold into the same fused group-by
kernel as sum/avg (ops/groupby.py wide components) and merge across panes
and shards with elementwise max/add — exactly the property that makes them
streaming- and ICI-friendly.

Accuracy notes:
- HLL with m=256 registers: ~6.5% standard error on distinct counts.
- signed log-histogram percentiles: B bins split into negative/zero/positive
  ranges over magnitude [1e-9, 1e12); relative error set by
  gamma = (1e21)^(2/(B-3)); B=1024 → ~4.9% (sqrt(gamma)-1).
- count-min (d=4): overestimates by at most eps*N with eps = e/w.
"""
from __future__ import annotations

from typing import Any, Tuple

import numpy as np

HLL_M = 256  # registers per key (power of two)
HIST_BINS = 1024
_HIST_LO = 1e-9
_HIST_HI = 1e12


# ------------------------------------------------------------------ hashing
def _splitmix32(x, c1: int, c2: int):
    import jax.numpy as jnp

    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(c1)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(c2)
    x = x ^ (x >> 16)
    return x


def hash_f32(v, salt: int = 0):
    """Hash float32 values (bit pattern) to uint32 on device."""
    import jax.numpy as jnp

    bits = jnp.asarray(v, jnp.float32).view(jnp.uint32)
    bits = bits ^ jnp.uint32((0x9E3779B9 * (salt + 1)) & 0xFFFFFFFF)
    return _splitmix32(bits, 0x7FEB352D, 0x846CA68B)


def hll_parts(values):
    """(register_index, rho) per value for HLL update."""
    import jax.numpy as jnp

    h1 = hash_f32(values, salt=0)
    h2 = hash_f32(values, salt=1)
    reg = (h1 & jnp.uint32(HLL_M - 1)).astype(jnp.int32)
    # rho = leading zeros of h2 + 1, via float exponent (fine for sketches)
    hv = jnp.maximum(h2, jnp.uint32(1)).astype(jnp.float32)
    nbits = jnp.floor(jnp.log2(hv)) + 1.0  # position of highest set bit
    rho = (33.0 - nbits).astype(jnp.float32)
    return reg, rho


def hll_estimate(registers):
    """Vectorized HLL cardinality estimate; registers (..., m) float32."""
    import jax.numpy as jnp

    m = registers.shape[-1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    z = jnp.sum(2.0 ** (-registers), axis=-1)
    raw = alpha * m * m / z
    zeros = jnp.sum(registers == 0.0, axis=-1)
    small = m * jnp.log(m / jnp.maximum(zeros, 1).astype(jnp.float32))
    return jnp.where(
        (raw < 2.5 * m) & (zeros > 0), small, raw
    )


# --------------------------------------------------------------- log histogram
# Signed layout (ascending value order, so cumsum quantiles work directly):
#   bins [0 .. HALF-1]        negative values, most negative first
#   bin  [HALF]               zeros
#   bins [HALF+1 .. 2*HALF]   positive values, ascending
_HIST_HALF = (HIST_BINS - 1) // 2
_GAMMA = (_HIST_HI / _HIST_LO) ** (1.0 / (_HIST_HALF - 1))
_LOG_GAMMA = float(np.log(_GAMMA))


def _mag_bin(mag):
    """Log bin of a magnitude in [0, HALF-1]."""
    import jax.numpy as jnp

    clamped = jnp.clip(mag, _HIST_LO, _HIST_HI * 0.999)
    idx = jnp.floor(jnp.log(clamped / _HIST_LO) / _LOG_GAMMA).astype(jnp.int32)
    return jnp.clip(idx, 0, _HIST_HALF - 1)


def hist_bin(values):
    """Map float values (any sign) to the signed log-bin layout above."""
    import jax.numpy as jnp

    v = jnp.asarray(values, jnp.float32)
    mag = _mag_bin(jnp.abs(v))
    pos = _HIST_HALF + 1 + mag
    neg = _HIST_HALF - 1 - mag
    return jnp.where(v > 0, pos, jnp.where(v < 0, neg, _HIST_HALF))


def hist_quantile(hist, frac: float):
    """Vectorized quantile from per-key signed histograms (..., B)."""
    import jax.numpy as jnp

    total = jnp.sum(hist, axis=-1)
    cum = jnp.cumsum(hist, axis=-1)
    target = frac * total[..., None]
    # first bin where cum >= target
    ge = cum >= jnp.maximum(target, 1e-9)
    idx = jnp.argmax(ge, axis=-1)
    # bin center (geometric mean of bin edges), sign by layout position
    mag_idx = jnp.where(
        idx > _HIST_HALF, idx - _HIST_HALF - 1, _HIST_HALF - 1 - idx
    ).astype(jnp.float32)
    center = _HIST_LO * jnp.exp(mag_idx * _LOG_GAMMA) * float(np.sqrt(_GAMMA))
    val = jnp.where(
        idx == _HIST_HALF, 0.0, jnp.where(idx > _HIST_HALF, center, -center)
    )
    return jnp.where(total > 0, val, jnp.nan)


# ------------------------------------------------- heavy hitters (linear)
# Device-native heavy hitters = count-min totals + group-testing bit
# recovery (a "deltoid" sketch): values dictionary-encode to integer codes
# < 2^HH_BITS; each code updates, per depth row d, the slot h_d(code) with
#   counters[0]     += 1          (count-min total — the estimate table)
#   counters[1 + b] += bit_b(code)  for every bit b of the code
# A code that holds the MAJORITY of a slot's traffic is recovered exactly by
# per-bit majority vote (bit_b = counters[1+b] > counters[0]/2), then
# validated by hashing back to its slot and estimated by the count-min rule
# (min of totals across depths). Every counter update is a scatter-add, so
# the sketch is LINEAR: panes merge by +, shards merge by psum — the same
# property that makes hll/hist fold into the fused kernel.
HH_DEPTH = 2
HH_WIDTH = 64
HH_BITS = 20  # dictionary codes < 2^20 (~1M distinct values per column)
HH_SIZE = HH_DEPTH * HH_WIDTH * (1 + HH_BITS)
HH_MAX_CODES = 1 << HH_BITS


def _hh_salt(d: int) -> int:
    return (0x9E3779B9 * (d + 7)) & 0xFFFFFFFF


def hh_update_parts(codes, mf):
    """Scatter indices + weights for one micro-batch of dictionary codes.

    codes: (mb,) float32 integer codes (NaN rows carry weight 0 via mf).
    mf: (mb,) float32 row mask. Returns (idx, wts) of shape
    (mb, HH_DEPTH*(1+HH_BITS)) addressing the flat per-key hh component.
    """
    import jax.numpy as jnp

    code = jnp.nan_to_num(codes, nan=0.0).astype(jnp.uint32)
    bits = [
        ((code >> jnp.uint32(b)) & jnp.uint32(1)).astype(jnp.float32)
        for b in range(HH_BITS)
    ]
    idx_parts, w_parts = [], []
    for d in range(HH_DEPTH):
        h = _splitmix32(code ^ jnp.uint32(_hh_salt(d)), 0x7FEB352D, 0x846CA68B)
        slot = (h % jnp.uint32(HH_WIDTH)).astype(jnp.int32)
        base = (jnp.int32(d * HH_WIDTH) + slot) * jnp.int32(1 + HH_BITS)
        idx_parts.append(base)
        w_parts.append(mf)
        for b in range(HH_BITS):
            idx_parts.append(base + 1 + b)
            w_parts.append(mf * bits[b])
    return jnp.stack(idx_parts, axis=1), jnp.stack(w_parts, axis=1)


def hh_candidates(hh, k2: int):
    """Device-side heavy-hitter recovery from the pane-merged sketch.

    hh: (capacity, HH_SIZE) float32. Returns (codes, est) each (cap, k2):
    the top-k2 bit-majority candidates per key by count-min estimate
    (pre-dedupe — a code can appear once per depth, so k2 = 2*topk
    guarantees topk uniques). Keeping recovery on device shrinks the emit
    transfer from HH_SIZE floats/key (~10.7KB) to 2*k2 floats/key."""
    import jax
    import jax.numpy as jnp

    cap = hh.shape[0]
    a = hh.reshape(cap, HH_DEPTH, HH_WIDTH, 1 + HH_BITS)
    tot = a[..., 0]  # (cap, D, W)
    bits = (a[..., 1:] * 2.0) > tot[..., None]
    shifts = jnp.arange(HH_BITS, dtype=jnp.uint32)
    codes = jnp.sum(
        bits.astype(jnp.uint32) << shifts, axis=-1
    )  # (cap, D, W) uint32
    # a recovered code must hash back to its own slot (garbage codes from
    # mixed slots almost never do) and the slot must have traffic
    wslots = jnp.arange(HH_WIDTH, dtype=jnp.uint32)[None, :]
    ok = tot > 0
    ok_parts = []
    for d in range(HH_DEPTH):
        h = _splitmix32(
            codes[:, d, :] ^ jnp.uint32(_hh_salt(d)), 0x7FEB352D, 0x846CA68B
        ) % jnp.uint32(HH_WIDTH)
        ok_parts.append(ok[:, d, :] & (h == wslots))
    ok = jnp.stack(ok_parts, axis=1)
    # count-min estimate: min over depths of the total at the code's slot
    flat = codes.reshape(cap, -1)  # (cap, D*W)
    est = jnp.full(flat.shape, jnp.inf, dtype=jnp.float32)
    for d2 in range(HH_DEPTH):
        s = (_splitmix32(
            flat ^ jnp.uint32(_hh_salt(d2)), 0x7FEB352D, 0x846CA68B
        ) % jnp.uint32(HH_WIDTH)).astype(jnp.int32)
        est = jnp.minimum(est, jnp.take_along_axis(tot[:, d2, :], s, axis=1))
    est = jnp.where(ok.reshape(cap, -1), est, 0.0)
    top_est, top_idx = jax.lax.top_k(est, k2)
    top_codes = jnp.take_along_axis(flat, top_idx, axis=1)
    return top_codes.astype(jnp.float32), top_est


# ----------------------------------------------------------------- count-min
#: pow-2 pad floor for count-min value batches — one executable serves
#: every batch up to the floor, doublings cover the rest (jitcert
#: certifies the ladder as this site's closed signature set)
SKETCH_PAD_FLOOR = 256


def _pad_pow2(n: int) -> int:
    b = SKETCH_PAD_FLOOR
    while b < n:
        b <<= 1
    return b


class CountMinSketch:
    """Window-level device count-min sketch with host candidate tracking for
    heavy hitters (top-k most frequent values).

    Device: (d, w) float32 counts updated by scatter-add of d row hashes.
    Host: candidate set of distinct values seen (bounded), whose estimated
    counts are read from the sketch at emit time.
    """

    #: jitcert/devwatch site family for this kernel's jit sites
    watch_prefix = "sketch"

    def __init__(self, depth: int = 4, width: int = 8192, max_candidates: int = 4096) -> None:
        import jax
        import jax.numpy as jnp

        self.depth = depth
        self.width = width
        self.max_candidates = max_candidates
        self.counts = jnp.zeros((depth, width), dtype=jnp.float32)
        self.candidates: dict = {}
        from ..runtime.aotcache import aot_jit
        from ..observability import jitcert, memwatch

        self._update = aot_jit(self._update_impl, op="sketch.update",
                                   donate_argnums=(0,))
        self._query = aot_jit(self._query_impl, op="sketch.query",
                                  kind="boundary")
        # HBM accounting: the (d, w) device counts plus the bounded host
        # candidate map (~96B/entry of dict + key machinery)
        memwatch.register(
            "sketch", self,
            lambda sk: int(sk.counts.nbytes) + 96 * len(sk.candidates))
        jitcert.register_kernel(self)

    def _hashes(self, values):
        import jax.numpy as jnp

        rows = []
        for d in range(self.depth):
            h = hash_f32(values, salt=d + 2)
            rows.append((h % jnp.uint32(self.width)).astype(jnp.int32))
        return jnp.stack(rows, axis=0)  # (d, n)

    def _update_impl(self, counts, values, weight):
        idx = self._hashes(values)
        for d in range(self.depth):
            counts = counts.at[d, idx[d]].add(weight)
        return counts

    def _query_impl(self, counts, values):
        import jax.numpy as jnp

        idx = self._hashes(values)
        ests = jnp.stack(
            [counts[d, idx[d]] for d in range(self.depth)], axis=0
        )
        return jnp.min(ests, axis=0)

    def update(self, values: np.ndarray) -> None:
        import jax.numpy as jnp

        arr = np.asarray(values, dtype=np.float32)
        n = len(arr)
        # value batches pad to the next power of two with weight-0 rows
        # (scatter-add of 0 is the identity), so this site's signature
        # set is the closed pad ladder jitcert certifies — raw lengths
        # would compile one executable per distinct batch size, the
        # exact storm class devwatch exists to flag. Candidate tracking
        # below reads arr[:n]: the 0.0 pad rows are device-only filler
        # and must never become a phantom candidate value.
        b = _pad_pow2(n)
        padded = np.pad(arr, (0, b - n)) if b > n else arr
        w = np.zeros(b, dtype=np.float32)
        w[:n] = 1.0
        self.counts = self._update(self.counts, jnp.asarray(padded),
                                   jnp.asarray(w))
        new = [
            float(x) for x in np.unique(arr) if float(x) not in self.candidates
        ]
        if not new:
            return
        if len(self.candidates) + len(new) <= self.max_candidates:
            for x in new:
                self.candidates[x] = True
            return
        # saturated: keep the max_candidates values with the highest sketch
        # estimates, so a late-arriving frequent value can displace a rare
        # incumbent instead of being silently untrackable forever
        cand = np.concatenate([
            np.fromiter(self.candidates.keys(), dtype=np.float32,
                        count=len(self.candidates)),
            np.asarray(new, dtype=np.float32),
        ])
        ests = self._query_padded(cand)
        keep = np.argsort(-ests)[: self.max_candidates]
        self.candidates = {float(cand[i]): True for i in keep}

    def _query_padded(self, cand: np.ndarray) -> np.ndarray:
        """Point-query estimates for `cand`, padded to the certified
        pow-2 ladder (pad rows are sliced off the result)."""
        import jax.numpy as jnp

        n = len(cand)
        b = _pad_pow2(n)
        if b > n:
            cand = np.pad(cand, (0, b - n))
        return np.asarray(self._query(self.counts,
                                      jnp.asarray(cand)))[:n]

    def heavy_hitters(self, k: int):
        if not self.candidates:
            return []
        cand = np.fromiter(self.candidates.keys(), dtype=np.float32)
        ests = self._query_padded(cand)
        order = np.argsort(-ests)[:k]
        return [(float(cand[i]), float(ests[i])) for i in order]

    def reset(self) -> None:
        import jax.numpy as jnp

        self.counts = jnp.zeros((self.depth, self.width), dtype=jnp.float32)
        self.candidates.clear()
