"""Direct (vectorized) window emission — compiles the post-aggregation tail
of a rule (HAVING → ORDER BY → LIMIT → SELECT projection) into numpy
operations over the kernel's finalize arrays, replacing the per-group
object/interpreter chain.

For the common fused rule shape
    SELECT dims..., agg(...) AS x FROM s GROUP BY dims, WINDOW(...)
    HAVING f(aggs) ORDER BY g(dims, aggs) LIMIT n
the emit path becomes: finalize (device, one transfer) → vectorized HAVING
mask → vectorized sort keys + argsort → vectorized field expressions → one
zip loop building the final output dicts. ~10x faster than constructing
GroupedTuples + running the evaluator per group, which matters at 10k+
groups per window (the p99 emit-latency target).

Aggregate calls inside expressions are rewritten to column references on the
finalize output (keyed by aggspec call key), so any host-compilable scalar
expression over dims+aggs vectorizes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..functions import registry
from ..sql import ast
from ..sql.compiler import CompiledExpr, try_compile
from .aggspec import KernelPlan, _call_key


def _substitute_aggs(expr: ast.Expr, spec_keys: Dict[str, int]) -> ast.Expr:
    """Replace aggregate Call nodes with FieldRefs on the finalize output
    columns (__agg_{i}), recursing through composite expressions."""
    sub = lambda e: _substitute_aggs(e, spec_keys)  # noqa: E731
    if isinstance(expr, ast.Call) and registry.is_aggregate(expr.name):
        key = _call_key(expr)
        idx = spec_keys.get(key)
        if idx is None:
            # not in the kernel plan — marker ref that fails the allowed-
            # columns check in compile_tail, forcing row-path fallback
            return ast.FieldRef(name=f"__missing_{key}")
        return ast.FieldRef(name=f"__agg_{idx}")
    if isinstance(expr, ast.BinaryExpr):
        return ast.BinaryExpr(expr.op, sub(expr.lhs), sub(expr.rhs))
    if isinstance(expr, ast.UnaryExpr):
        return ast.UnaryExpr(expr.op, sub(expr.expr))
    if isinstance(expr, ast.BetweenExpr):
        return ast.BetweenExpr(sub(expr.value), sub(expr.lo), sub(expr.hi),
                               expr.negate)
    if isinstance(expr, ast.InExpr):
        return ast.InExpr(sub(expr.value), [sub(v) for v in expr.values],
                          expr.negate)
    if isinstance(expr, ast.CaseExpr):
        return ast.CaseExpr(
            sub(expr.value) if expr.value is not None else None,
            [ast.WhenClause(sub(w.cond), sub(w.result)) for w in expr.whens],
            sub(expr.else_expr) if expr.else_expr is not None else None,
        )
    if isinstance(expr, ast.Call):
        return ast.Call(name=expr.name, args=[sub(a) for a in expr.args],
                        func_id=expr.func_id, filter=expr.filter,
                        partition=expr.partition, when=expr.when)
    return expr


@dataclass
class DirectField:
    out_name: str
    kind: str  # dim | agg | window_start | window_end | expr
    dim_name: str = ""
    spec_idx: int = -1
    compiled: Optional[CompiledExpr] = None


@dataclass
class DirectEmitPlan:
    fields: List[DirectField]
    having: Optional[CompiledExpr]
    sorts: List[Tuple[CompiledExpr, bool]]  # (key expr, ascending)
    limit: Optional[int]

    def _prepare(
        self,
        dim_cols: Dict[str, np.ndarray],
        agg_cols: List[np.ndarray],
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        """Shared HAVING→ORDER tail; returns (env, n) or (None, 0)."""
        n = len(next(iter(dim_cols.values()))) if dim_cols else (
            len(agg_cols[0]) if agg_cols else 0
        )
        if n == 0:
            return None, 0
        env: Dict[str, np.ndarray] = dict(dim_cols)
        for i, col in enumerate(agg_cols):
            env[f"__agg_{i}"] = col
        sel = None
        if self.having is not None:
            mask = np.asarray(self.having(env), dtype=bool)
            # NaN agg results (NULL) fail the condition
            sel = np.nonzero(mask)[0]
            if len(sel) == 0:
                return None, 0
            env = {k: v[sel] for k, v in env.items()}
            n = len(sel)
        if self.sorts:
            keys = []
            for ce, asc in reversed(self.sorts):
                col = np.asarray(ce(env))
                if col.dtype == np.object_:
                    # incomparable Nones sort as empty string (row path treats
                    # incomparables as equal; this is the stable analogue);
                    # mixed types stringify so lexsort never sees incomparables
                    vals = ["" if v is None else v for v in col.tolist()]
                    if not all(isinstance(v, str) for v in vals):
                        vals = [v if isinstance(v, str) else str(v) for v in vals]
                    col = np.array(vals)
                if not asc:
                    if np.issubdtype(col.dtype, np.number) or col.dtype == np.bool_:
                        col = -col.astype(np.float64)
                    else:
                        # descending non-numeric: negate the sort ranks
                        _, inv = np.unique(col, return_inverse=True)
                        col = -inv
                keys.append(col)
            order = np.lexsort(keys)
            env = {k: v[order] for k, v in env.items()}
        return env, n

    def run(
        self,
        dim_cols: Dict[str, np.ndarray],
        agg_cols: List[np.ndarray],
        window_start: int,
        window_end: int,
    ) -> List[Dict[str, Any]]:
        """Produce the final output messages for one window."""
        env, n = self._prepare(dim_cols, agg_cols)
        if env is None:
            return []
        out_cols: List[Tuple[str, List[Any]]] = []
        limit = self.limit if self.limit is not None else n
        for f in self.fields:
            if f.kind == "dim":
                col = env[f.dim_name][:limit]
                out_cols.append((f.out_name, col.tolist()))
            elif f.kind == "agg":
                col = env[f"__agg_{f.spec_idx}"][:limit]
                out_cols.append((f.out_name, _nan_to_none(col)))
            elif f.kind == "window_start":
                out_cols.append((f.out_name, [window_start] * min(limit, n)))
            elif f.kind == "window_end":
                out_cols.append((f.out_name, [window_end] * min(limit, n)))
            else:
                col = np.asarray(f.compiled(env))[:limit]
                out_cols.append((f.out_name, _nan_to_none(col)))
        names = [name for name, _ in out_cols]
        cols = [vals for _, vals in out_cols]
        return [dict(zip(names, vals)) for vals in zip(*cols)]

    def run_columnar(
        self,
        dim_cols: Dict[str, np.ndarray],
        agg_cols: List[np.ndarray],
        window_start: int,
        window_end: int,
    ):
        """Columnar variant of run(): the window result stays a ColumnBatch
        (NaN→valid-mask for NULLs) instead of exploding into per-group dicts.
        Downstream nodes/sinks consume ColumnBatch natively; sinks that need
        per-message dicts convert at the edge (to_messages). At 10k+ groups
        this removes ~20ms of dict building from the emit path."""
        from ..data.batch import ColumnBatch

        env, n = self._prepare(dim_cols, agg_cols)
        if env is None:
            return None
        limit = min(self.limit if self.limit is not None else n, n)
        columns: Dict[str, np.ndarray] = {}
        valid: Dict[str, np.ndarray] = {}
        for f in self.fields:
            if f.kind == "dim":
                columns[f.out_name] = env[f.dim_name][:limit]
            elif f.kind == "agg":
                columns[f.out_name] = _null_preserving(
                    env[f"__agg_{f.spec_idx}"][:limit])
            elif f.kind == "window_start":
                columns[f.out_name] = np.full(limit, window_start, dtype=np.int64)
            elif f.kind == "window_end":
                columns[f.out_name] = np.full(limit, window_end, dtype=np.int64)
            else:
                columns[f.out_name] = _null_preserving(
                    np.asarray(f.compiled(env))[:limit])
        return ColumnBatch(
            n=limit, columns=columns, valid=valid,
            timestamps=np.full(limit, window_end, dtype=np.int64),
        )


def _null_preserving(col: np.ndarray) -> np.ndarray:
    """NaN aggregates are NULLs and must stay as explicit None in the sink
    payload (a valid-mask would make to_tuples OMIT the key — a different
    message shape than the row path emits). NaN-free columns (the common
    case) stay numeric; NULL-bearing ones go object with None holes."""
    if np.issubdtype(col.dtype, np.floating):
        nan = np.isnan(col)
        if nan.any():
            out = col.astype(object)
            out[nan] = None
            return out
    return col


def _nan_to_none(col: np.ndarray) -> List[Any]:
    if np.issubdtype(col.dtype, np.floating):
        return [None if v != v else v for v in col.tolist()]
    return col.tolist() if isinstance(col, np.ndarray) else list(col)


def build_direct_emit(
    stmt: ast.SelectStatement, plan: KernelPlan, dim_names: List[str]
) -> Optional[DirectEmitPlan]:
    """Try to compile the rule's post-agg tail into a DirectEmitPlan.
    Returns None if any part needs the row-path evaluator."""
    spec_keys = {_call_key(s.call): i for i, s in enumerate(plan.specs)}

    def compile_tail(expr: ast.Expr) -> Optional[CompiledExpr]:
        sub = _substitute_aggs(expr, spec_keys)
        ce = try_compile(sub, mode="host")
        if ce is None:
            return None
        allowed = set(dim_names) | {f"__agg_{i}" for i in range(len(plan.specs))}
        if not ce.columns <= allowed:
            return None
        return ce

    fields: List[DirectField] = []
    for f in stmt.fields:
        if f.invisible:
            continue
        name = f.output_name or f.name
        e = f.expr
        if isinstance(e, ast.FieldRef) and e.name in dim_names:
            fields.append(DirectField(name, "dim", dim_name=e.name))
            continue
        if isinstance(e, ast.Call) and registry.is_aggregate(e.name):
            key = _call_key(e)
            if key in spec_keys:
                fields.append(DirectField(name, "agg", spec_idx=spec_keys[key]))
                continue
            return None
        if isinstance(e, ast.Call) and e.name in ("window_start", "window_end"):
            fields.append(DirectField(name, e.name))
            continue
        ce = compile_tail(e)
        if ce is None:
            return None
        fields.append(DirectField(name, "expr", compiled=ce))

    having: Optional[CompiledExpr] = None
    if stmt.having is not None:
        having = compile_tail(stmt.having)
        if having is None:
            return None

    sorts: List[Tuple[CompiledExpr, bool]] = []
    for sf in stmt.sorts:
        expr = sf.expr if sf.expr is not None else ast.FieldRef(sf.name, sf.stream)
        ce = compile_tail(expr)
        if ce is None:
            return None
        sorts.append((ce, sf.ascending))

    return DirectEmitPlan(fields=fields, having=having, sorts=sorts,
                          limit=stmt.limit)
