"""Constant-time sliding aggregation rings — the DABA replacement for the
refold-on-trigger sliding path (ROADMAP item 2, per "In-Order
Sliding-Window Aggregation in Worst-Case Constant Time" / the two-stacks
discipline, PAPERS.md).

The refold path answers a trigger by merging EVERY pane inside the window
(`finalize_dyn` over a ~window-span pane mask) plus device refolds of the
two partial edge buckets from the cached `_dev_ring` batch history — work
proportional to the window length, per trigger, and exactly the owner of
the 400-900ms sliding emit stalls (BENCH_r04, kernwatch attribution).

This module keeps the same pane ring the fold path already maintains
(`ops/groupby.py` state, one pane per time bucket) and adds per-key
running partials over the CLOSED panes so a trigger is a single combine
of two running partials instead of a window-length fold:

- **subtract-on-evict totals** for components whose combine is addition
  (`n`, `s1`, `s2`, `hist`, `hh`, `act` — sum/count/avg, stddev via
  sum-of-squares, log-histogram percentiles, heavy-hitter counters):
  one `tot_<comp>` array of shape ``[keys, agg_width]``; closing a
  bucket adds its pane slice, evicting the expired bucket subtracts it.
  O(1) per bucket advance, O(1) per query.
- **two-stack front/back partials** for non-invertible combines
  (`mn`, `mx`, `hll` — min/max-merge cannot subtract): `back_<comp>`
  ``[keys, agg_width]`` accumulates panes closed since the last flip;
  `front_<comp>` ``[keys, ring_slots, agg_width]`` (stored slot-major as
  ``[ring_slots, keys, ...]``) holds SUFFIX combines over the older
  panes, rebuilt by one reverse cumulative scan per ring rotation
  (amortized O(1) per pane — the DABA flip). A query is
  ``combine(front[j], back)``.

All three operations — ``advance`` (insert+evict), ``flip`` (rebuild),
``query`` — are single jitted device programs over dense
``[keys, ...]``/``[ring_slots, keys, ...]`` arrays, vectorized across
every GROUP BY key, with statically bounded shapes (capacity ladder ×
plan-time ring geometry) so jitcert can certify the closed signature
set (`observability/jitcert.py _derive_ring`).

The ring caches are pure functions of the pane state: a checkpoint
restore or any host-side confusion (late rows into closed buckets, time
gaps) simply marks the cache dirty and the next trigger rebuilds it with
one flip. Exactness never depends on the cache being fresh.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from .groupby import _INIT, DeviceGroupBy

#: components whose pane combine is elementwise addition — these take the
#: subtract-on-evict fast path (one running total, no suffix stack)
#: "touch" never materializes ring partials (it rides the pane state
#: pytree, not the ring — comp_specs never contains it); it is listed so
#: the combine classification stays TOTAL over groupby._INIT, which the
#: guardrail test (test_sliding_ring.py combine-classes-are-total)
#: enforces for every state component
ADD_COMBINE = frozenset({"n", "s1", "s2", "hist", "hh", "act", "touch"})
#: min-merge components (two-stack discipline; subtraction undefined)
MIN_COMBINE = frozenset({"mn"})
#: max-merge components (two-stack discipline; hll registers merge by max)
MAX_COMBINE = frozenset({"mx", "hll"})

#: pane-slice adjustment slots a query carries: up to two low-edge
#: subtractions (the running total trails the window start by at most the
#: eviction hysteresis) plus the live head pane, with one slot spare
QUERY_ADJ = 4


@dataclass(frozen=True)
class RingLayout:
    """Plan-time sliding ring geometry — chosen by the planner from the
    window/hop/pane declarations (planner/planner.py) and shared with the
    fused node so both agree on bucket routing and certificate shapes."""

    bucket_ms: int      # fine time-pane width rows route into
    n_ring_panes: int   # pane ring slots (window span + slack)
    n_panes: int        # n_ring_panes + 1 (scratch pane, refold impl only)
    span_buckets: int   # buckets a full window spans (ceil((L+delay)/B))
    scratch_pane: int   # scratch slot index (refold edge folds)


def plan_ring_layout(length_ms: int, delay_ms: int, wide: bool,
                     budget_bytes: Optional[int] = None,
                     mm_slot_bytes: int = 0,
                     fixed_bytes: int = 0) -> RingLayout:
    """Ring geometry for a sliding window: finer buckets shrink the edge
    corrections (≤1 bucket of rows host-folded per trigger edge); bounded
    by the uint8 pane budget AND by HBM. Wide sketch components
    (hist=512, hll=64 registers) pay panes×capacity×width×4B of
    front-stack state, so they start coarser — and when `budget_bytes`
    is given (the slidingDevRingMb budget), the bucket target walks DOWN
    a ladder until the ring's static footprint fits: a wide-hll sliding
    rule coarsens its ring instead of silently refolding (ROADMAP item-2
    remnant). `mm_slot_bytes` is the per-ring-slot front-stack cost at
    the plan's key capacity; `fixed_bytes` the slot-count-independent
    part (running totals + back stacks)."""
    targets = (48,) if wide else (128,)
    if budget_bytes is not None:
        targets = (48, 32, 24, 16, 12, 8) if wide \
            else (128, 64, 48, 32, 24, 16, 12, 8)
    layout = None
    for target in targets:
        bucket_ms = max(length_ms // target, 25,
                        -(-(length_ms + delay_ms) // 250))
        span = -(-(length_ms + delay_ms) // bucket_ms)
        n_ring = span + 3
        n_panes = n_ring + 1  # +1 scratch pane (refold impl edge folds)
        if n_panes > 255:
            raise ValueError(
                f"sliding window needs {n_panes} panes (max 255)")
        layout = RingLayout(
            bucket_ms=int(bucket_ms), n_ring_panes=int(n_ring),
            n_panes=int(n_panes), span_buckets=int(span),
            scratch_pane=int(n_ring))
        if budget_bytes is None:
            return layout
        est = fixed_bytes + (1 + n_ring) * mm_slot_bytes
        if est <= budget_bytes:
            return layout
    return layout  # coarsest rung; the node's own budget check decides


def _plan_ring_bytes(plan, capacity: int):
    """(mm_slot_bytes, fixed_bytes) of a plan's ring state at `capacity`
    — the same component arithmetic SlidingRing.estimate_bytes uses,
    computed WITHOUT constructing the kernel (plan-time layout choice)."""
    from .aggspec import WIDE_COMPONENTS
    from .groupby import _wide_size

    comp_specs: dict = {}
    for i, spec in enumerate(plan.specs):
        for comp in spec.components:
            comp_specs.setdefault(comp, []).append(i)
    mm_slot = 0
    fixed = 0
    for comp in sorted(list(comp_specs) + ["act"]):
        k = len(comp_specs.get(comp, ()))
        dims = 1 if comp == "act" else (
            k * (_wide_size(comp) if comp in WIDE_COMPONENTS else 1))
        per = capacity * dims * 4
        if comp in ADD_COMBINE:
            fixed += per              # tot_<comp>
        else:
            # back_<comp> + front_<comp>: one per-slot unit covers the
            # back stack too, matching SlidingRing.estimate_bytes's
            # per×(1+n_ring) exactly (the regression test pins parity)
            mm_slot += per
    return mm_slot, fixed


def ring_layout_for(window, plan, capacity: Optional[int] = None,
                    budget_mb: Optional[int] = None) -> RingLayout:
    """Layout from the parsed window + kernel plan (the planner's entry).
    With `capacity` + `budget_mb` the layout is budget-aware: the ring
    coarsens until its static HBM estimate fits slidingDevRingMb."""
    from .aggspec import WIDE_COMPONENTS

    wide = any(set(s.components) & WIDE_COMPONENTS for s in plan.specs)
    if capacity is None or budget_mb is None:
        return plan_ring_layout(window.length_ms(), window.delay_ms(),
                                wide)
    mm_slot, fixed = _plan_ring_bytes(plan, int(capacity))
    return plan_ring_layout(window.length_ms(), window.delay_ms(), wide,
                            budget_bytes=int(budget_mb) << 20,
                            mm_slot_bytes=mm_slot, fixed_bytes=fixed)


class SlidingRing:
    """Device-resident DABA ring over a DeviceGroupBy's pane state.

    Owns three jit sites (`slidingring.advance/flip/query`), each
    certified by jitcert (`_derive_ring`); the host-side bucket
    bookkeeping (which bucket is closed/evicted/queried) lives in the
    fused node — this class is the pure device kernel."""

    watch_prefix = "slidingring"

    def __init__(self, gb: DeviceGroupBy, layout: RingLayout) -> None:
        self.gb = gb
        self.layout = layout
        self.capacity = int(gb.capacity)
        self.n_ring_panes = int(layout.n_ring_panes)
        comps = sorted(list(gb.comp_specs) + ["act"])
        self.add_comps = [c for c in comps if c in ADD_COMBINE]
        self.mm_comps = [c for c in comps
                         if c in MIN_COMBINE or c in MAX_COMBINE]
        unknown = [c for c in comps
                   if c not in ADD_COMBINE
                   and c not in MIN_COMBINE and c not in MAX_COMBINE]
        if unknown:
            raise ValueError(
                f"no sliding-ring combine class for components {unknown}")
        from ..runtime.aotcache import aot_jit

        self._advance = aot_jit(self._advance_impl,
                                    op=self._watch_op("advance"),
                                    kind="boundary", donate_argnums=(0,))
        self._flip = aot_jit(self._flip_impl,
                                 op=self._watch_op("flip"),
                                 kind="boundary", donate_argnums=(0,))
        self._query = aot_jit(self._query_impl,
                                  op=self._watch_op("query"),
                                  kind="boundary")
        from ..observability import jitcert

        jitcert.register_kernel(self)

    def _watch_op(self, site: str) -> str:
        return f"{self.watch_prefix}.{site}"

    # ------------------------------------------------------------ layout
    def _comp_dims(self, comp: str):
        """Per-key trailing dims of one component (matches the pane state
        minus its (n_panes, capacity) lead)."""
        if comp == "act":
            return ()
        from .aggspec import WIDE_COMPONENTS
        from .groupby import _wide_size

        k = len(self.gb.comp_specs[comp])
        if comp in WIDE_COMPONENTS:
            return (k, _wide_size(comp))
        return (k,)

    def init_state(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        out: Dict[str, Any] = {}
        for c in self.add_comps:
            out[f"tot_{c}"] = jnp.zeros(
                (self.capacity,) + self._comp_dims(c), dtype=jnp.float32)
        for c in self.mm_comps:
            shape = (self.capacity,) + self._comp_dims(c)
            out[f"back_{c}"] = jnp.full(shape, _INIT[c], dtype=jnp.float32)
            out[f"front_{c}"] = jnp.full(
                (self.n_ring_panes,) + shape, _INIT[c], dtype=jnp.float32)
        return out

    def grow(self, ring: Dict[str, Any], new_capacity: int) -> Dict[str, Any]:
        """Pad the key axis to a grown capacity, preserving partials (the
        add identity is 0, mn/mx/hll pad with their combine identities)."""
        import jax.numpy as jnp

        out: Dict[str, Any] = {}
        for key, arr in ring.items():
            comp = key.split("_", 1)[1]
            axis = 1 if key.startswith("front_") else 0
            pad = [(0, 0)] * arr.ndim
            pad[axis] = (0, int(new_capacity) - arr.shape[axis])
            out[key] = jnp.pad(arr, pad, constant_values=_INIT[comp])
        self.capacity = int(new_capacity)
        return out

    @staticmethod
    def state_nbytes(ring: Dict[str, Any]) -> int:
        return sum(int(getattr(a, "nbytes", 0) or 0) for a in ring.values())

    def estimate_bytes(self, capacity: int) -> int:
        """Static HBM footprint at a given key capacity — checked against
        the sliding_dev_ring_mb budget before the ring is allocated."""
        total = 0
        for c in self.add_comps:
            total += int(np.prod((capacity,) + self._comp_dims(c),
                                 dtype=np.int64)) * 4
        for c in self.mm_comps:
            per = int(np.prod((capacity,) + self._comp_dims(c),
                              dtype=np.int64)) * 4
            total += per * (1 + self.n_ring_panes)
        return total

    # ----------------------------------------------------------- kernels
    @staticmethod
    def _combine(comp: str, a, b):
        import jax.numpy as jnp

        if comp in MIN_COMBINE:
            return jnp.minimum(a, b)
        return jnp.maximum(a, b)

    def _advance_impl(self, ring, pane_state, closed_slot, closed_on,
                      evict_slot, evict_on):
        """O(1) ring step: absorb the just-closed pane into the running
        partials, subtract the evicted pane from the additive totals."""
        import jax.numpy as jnp

        out = dict(ring)
        for c in self.add_comps:
            p_new = pane_state[c][closed_slot]
            p_old = pane_state[c][evict_slot]
            zero = jnp.zeros_like(p_new)
            out[f"tot_{c}"] = (ring[f"tot_{c}"]
                               + jnp.where(closed_on, p_new, zero)
                               - jnp.where(evict_on, p_old, zero))
        for c in self.mm_comps:
            p_new = jnp.where(closed_on, pane_state[c][closed_slot],
                              jnp.float32(_INIT[c]))
            out[f"back_{c}"] = self._combine(c, ring[f"back_{c}"], p_new)
        return out

    def _flip_impl(self, ring, pane_state, order, valid):
        """The DABA flip: rebuild every running partial from the live
        panes in one pass. `order` is an age-ordered rotation of the ring
        slots (a permutation — the scatter back to slot-major rows is
        collision-free); `valid` masks slots to their combine identity.
        The front stack becomes the reverse cumulative combine (suffix
        aggregates); the back stack resets to identity; additive totals
        become the masked sum."""
        import jax
        import jax.numpy as jnp

        out = dict(ring)
        for c in self.add_comps:
            g = pane_state[c][order]
            vm = valid.reshape((-1,) + (1,) * (g.ndim - 1))
            out[f"tot_{c}"] = jnp.sum(jnp.where(vm, g, 0.0), axis=0)
        for c in self.mm_comps:
            ident = jnp.float32(_INIT[c])
            g = pane_state[c][order]
            vm = valid.reshape((-1,) + (1,) * (g.ndim - 1))
            g = jnp.where(vm, g, ident)
            if c in MIN_COMBINE:
                suffix = jax.lax.cummin(g, axis=0, reverse=True)
            else:
                suffix = jax.lax.cummax(g, axis=0, reverse=True)
            out[f"front_{c}"] = ring[f"front_{c}"].at[order].set(suffix)
            out[f"back_{c}"] = jnp.full_like(ring[f"back_{c}"], _INIT[c])
        return out

    def _query_impl(self, ring, pane_state, body_on, f_on, f_idx,
                    adj_slots, adj_w, adj_mm):
        """Trigger-time window body: one combine of the two running
        partials plus at most QUERY_ADJ pane-slice adjustments, stacked
        into the SAME (capacity, W) components array _components_body
        produces — the host merge/final-value tail is shared with the
        prefinalize emit path."""
        import jax.numpy as jnp

        cap = self.capacity
        parts = []
        for c in sorted(self.gb.comp_specs) + ["act"]:
            if c in ADD_COMBINE:
                v = jnp.where(body_on, ring[f"tot_{c}"], 0.0)
                for i in range(QUERY_ADJ):
                    v = v + adj_w[i] * pane_state[c][adj_slots[i]]
            else:
                ident = jnp.float32(_INIT[c])
                v = jnp.where(jnp.logical_and(body_on, f_on),
                              ring[f"front_{c}"][f_idx], ident)
                v = self._combine(
                    c, v, jnp.where(body_on, ring[f"back_{c}"], ident))
                for i in range(QUERY_ADJ):
                    v = self._combine(
                        c, v, jnp.where(adj_mm[i],
                                        pane_state[c][adj_slots[i]],
                                        ident))
            parts.append(v.reshape(cap, -1))
        return jnp.concatenate(parts, axis=1)

    # ---------------------------------------------------------- wrappers
    def advance(self, ring, pane_state, closed_slot: int, closed_on: bool,
                evict_slot: int, evict_on: bool):
        import jax.numpy as jnp

        return self._advance(
            ring, pane_state,
            jnp.asarray(int(closed_slot), dtype=jnp.int32),
            jnp.asarray(bool(closed_on)),
            jnp.asarray(int(evict_slot), dtype=jnp.int32),
            jnp.asarray(bool(evict_on)))

    def flip(self, ring, pane_state, base_slot: int, valid: np.ndarray):
        """Rebuild partials over the age-ordered rotation starting at
        `base_slot`; `valid[i]` says whether slot (base+i) % R holds live
        data for the flip span."""
        import jax.numpy as jnp

        order = ((int(base_slot)
                  + np.arange(self.n_ring_panes, dtype=np.int64))
                 % self.n_ring_panes).astype(np.int32)
        return self._flip(ring, pane_state, jnp.asarray(order),
                          jnp.asarray(np.asarray(valid, dtype=np.bool_)))

    def query_begin(self, ring, pane_state, *, body_on: bool, f_on: bool,
                    f_slot: int, adj_slots: np.ndarray,
                    adj_weights: np.ndarray, adj_mm: np.ndarray):
        """Dispatch the O(1) window-body combine and start the async
        device→host copy; returns a PendingFinalize the emit worker
        merges with the host edge shadow (ops/prefinalize.py)."""
        import jax.numpy as jnp

        from .prefinalize import begin_pending

        out = self._query(
            ring, pane_state,
            jnp.asarray(bool(body_on)), jnp.asarray(bool(f_on)),
            jnp.asarray(int(f_slot), dtype=jnp.int32),
            jnp.asarray(np.asarray(adj_slots, dtype=np.int32)),
            jnp.asarray(np.asarray(adj_weights, dtype=np.float32)),
            jnp.asarray(np.asarray(adj_mm, dtype=np.bool_)))
        return begin_pending(out, self.capacity,
                             self.gb._components_layout())
