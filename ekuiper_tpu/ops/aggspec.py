"""Aggregate kernel specs — which aggregates of a SELECT can fuse into the
device group-by kernel, and what partial-state components each needs.

The planner extracts AggSpecs from the statement (the incremental-agg rewrite,
reference: planner.go:910-999 rewriteIfIncAggStmt); device-eligible aggregates
fold into (n, s1, s2, mn, mx) partials — the same (count, sum, sum-of-squares,
min, max) triple-plus layout funcs_inc_agg.py uses, so cross-shard merges are
plain adds/mins/maxes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..sql import ast
from ..sql.compiler import CompiledExpr, try_compile

# aggregate name -> components needed by finalize
DEVICE_AGGS: Dict[str, Set[str]] = {
    "count": {"n"},
    "sum": {"n", "s1"},
    "avg": {"n", "s1"},
    "min": {"mn", "n"},
    "max": {"mx", "n"},
    "stddev": {"n", "s1", "s2"},
    "stddevs": {"n", "s1", "s2"},
    "var": {"n", "s1", "s2"},
    "vars": {"n", "s1", "s2"},
    # inc_ forms share the same partials
    "inc_count": {"n"},
    "inc_sum": {"n", "s1"},
    "inc_avg": {"n", "s1"},
    "inc_min": {"mn", "n"},
    "inc_max": {"mx", "n"},
    "inc_stddev": {"n", "s1", "s2"},
    "inc_stddevs": {"n", "s1", "s2"},
    # sketch aggregates (north-star UDFs) — wide device components
    "hll": {"hll"},
    "distinct_count_approx": {"hll"},
    "percentile_approx": {"hist"},
}

ALL_COMPONENTS = ("n", "s1", "s2", "mn", "mx")
# components with a trailing register axis (capacity, k, R)
WIDE_COMPONENTS = {"hll", "hist"}


@dataclass
class AggSpec:
    """One device-foldable aggregate call."""

    call: ast.Call
    kind: str  # count/sum/avg/min/max/stddev/.../hll/percentile_approx
    components: Set[str]
    arg: Optional[CompiledExpr]  # device closure for the argument (None = count(*))
    filter: Optional[CompiledExpr]  # FILTER(WHERE ...) device closure
    int_input: bool = False  # observed integer input → integer avg/sum results
    frac: float = 0.5  # percentile_approx quantile (2nd literal arg)

    @property
    def is_star(self) -> bool:
        return self.arg is None


@dataclass
class KernelPlan:
    """Everything the fused window→aggregate device kernel needs."""

    specs: List[AggSpec]
    filter: Optional[CompiledExpr]  # WHERE clause (device)
    columns: Set[str] = field(default_factory=set)  # numeric columns to upload


def extract_kernel_plan(
    stmt: ast.SelectStatement, where_on_device: bool = True
) -> Optional[KernelPlan]:
    """Try to build a fully-fused device plan for the statement's aggregates.

    Returns None if any aggregate (or its argument expression) is not
    device-eligible — the planner then uses the host window path.
    """
    calls = _collect_agg_calls(stmt)
    if not calls:
        return None
    specs: List[AggSpec] = []
    columns: Set[str] = set()
    for call in calls:
        kind = call.name[4:] if call.name.startswith("inc_") else call.name
        if call.name not in DEVICE_AGGS:
            return None
        if call.partition or call.when is not None:
            return None
        frac = 0.5
        arg_ce: Optional[CompiledExpr] = None
        if call.args and not isinstance(call.args[0], ast.Wildcard):
            if call.name == "percentile_approx":
                if len(call.args) != 2 or not isinstance(
                    call.args[1], (ast.NumberLiteral, ast.IntegerLiteral)
                ):
                    return None
                frac = float(call.args[1].val)
                if not 0.0 <= frac <= 1.0:
                    # invalid fraction: host path raises the clear error
                    return None
            elif len(call.args) != 1:
                return None
            arg_ce = try_compile(call.args[0], mode="device")
            if arg_ce is None:
                return None
            columns |= arg_ce.columns
        filter_ce: Optional[CompiledExpr] = None
        if call.filter is not None:
            filter_ce = try_compile(call.filter, mode="device")
            if filter_ce is None:
                return None
            columns |= filter_ce.columns
        specs.append(
            AggSpec(
                call=call,
                kind="hll" if kind == "distinct_count_approx" else kind,
                components=set(DEVICE_AGGS[call.name]),
                arg=arg_ce,
                filter=filter_ce,
                frac=frac,
            )
        )
    where_ce: Optional[CompiledExpr] = None
    if stmt.condition is not None and where_on_device:
        where_ce = try_compile(stmt.condition, mode="device")
        if where_ce is None:
            return None  # caller may retry with host-side where
        columns |= where_ce.columns
    return KernelPlan(specs=specs, filter=where_ce, columns=columns)


def _collect_agg_calls(stmt: ast.SelectStatement) -> List[ast.Call]:
    """All aggregate calls in SELECT fields + HAVING, deduplicated by
    (name, arg-tree repr) so avg(x) in both places folds once."""
    from ..functions import registry

    seen: Dict[str, ast.Call] = {}
    roots = [f.expr for f in stmt.fields]
    if stmt.having is not None:
        roots.append(stmt.having)
    for sf in stmt.sorts:
        if sf.expr is not None:
            roots.append(sf.expr)
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and registry.is_aggregate(node.name):
                seen.setdefault(_call_key(node), node)
    return list(seen.values())


def _call_key(call: ast.Call) -> str:
    return f"{call.name}({','.join(map(_expr_key, call.args))})" + (
        f"|f:{_expr_key(call.filter)}" if call.filter is not None else ""
    )


def _expr_key(e: Optional[ast.Expr]) -> str:
    if e is None:
        return ""
    if isinstance(e, ast.FieldRef):
        return f"{e.stream}.{e.name}"
    if isinstance(e, ast.Call):
        return _call_key(e)
    if isinstance(e, (ast.IntegerLiteral, ast.NumberLiteral, ast.StringLiteral, ast.BooleanLiteral)):
        return repr(e.val)
    if isinstance(e, ast.BinaryExpr):
        return f"({_expr_key(e.lhs)}{e.op}{_expr_key(e.rhs)})"
    if isinstance(e, ast.UnaryExpr):
        return f"({e.op}{_expr_key(e.expr)})"
    if isinstance(e, ast.Wildcard):
        return "*"
    return repr(e)
