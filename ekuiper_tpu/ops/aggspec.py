"""Aggregate kernel specs — which aggregates of a SELECT can fuse into the
device group-by kernel, and what partial-state components each needs.

The planner extracts AggSpecs from the statement (the incremental-agg rewrite,
reference: planner.go:910-999 rewriteIfIncAggStmt); device-eligible aggregates
fold into (n, s1, s2, mn, mx) partials — the same (count, sum, sum-of-squares,
min, max) triple-plus layout funcs_inc_agg.py uses, so cross-shard merges are
plain adds/mins/maxes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import numpy as np

from ..sql import ast
from ..sql.compiler import CompiledExpr, try_compile

# aggregate name -> components needed by finalize
DEVICE_AGGS: Dict[str, Set[str]] = {
    "count": {"n"},
    "sum": {"n", "s1"},
    "avg": {"n", "s1"},
    "min": {"mn", "n"},
    "max": {"mx", "n"},
    "stddev": {"n", "s1", "s2"},
    "stddevs": {"n", "s1", "s2"},
    "var": {"n", "s1", "s2"},
    "vars": {"n", "s1", "s2"},
    # inc_ forms share the same partials
    "inc_count": {"n"},
    "inc_sum": {"n", "s1"},
    "inc_avg": {"n", "s1"},
    "inc_min": {"mn", "n"},
    "inc_max": {"mx", "n"},
    "inc_stddev": {"n", "s1", "s2"},
    "inc_stddevs": {"n", "s1", "s2"},
    # sketch aggregates (north-star UDFs) — wide device components
    "hll": {"hll"},
    "distinct_count_approx": {"hll"},
    "percentile_approx": {"hist"},
    "heavy_hitters": {"hh"},
}

ALL_COMPONENTS = ("n", "s1", "s2", "mn", "mx")
# components with a trailing register axis (capacity, k, R)
WIDE_COMPONENTS = {"hll", "hist", "hh"}

# Derived-column prefix: hll over a bare column reads a dedicated hashed
# copy (strings crc32-hashed, numerics passed through) so the raw column
# stays numeric for every other spec / WHERE / FILTER sharing it.
HLL_COL_PREFIX = "__hll__"

# Derived-column prefix for heavy_hitters: the raw column dictionary-encodes
# to dense integer codes (< sketches.HH_MAX_CODES) that the bit-recovery
# sketch can reconstruct; codes decode back to the original values at emit.
HH_COL_PREFIX = "__hhc__"


# values below this are exactly representable in float32 and pass through;
# larger integral values hash their decimal repr so the float32 cast cannot
# collapse distinct IDs (e.g. ~1e9-range device ids differing in low bits)
_HLL_SMALL = 2 ** 24


def _hll_encode_value(v) -> float:
    """Distinct-preserving float32 encoding of one value for hll. The SAME
    rule applies whether the value arrives in an object, integer, or float
    batch, so a logical value always folds to the same register."""
    import zlib

    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, np.integer)):
        iv = int(v)
        if abs(iv) < _HLL_SMALL:
            return float(iv)
        return float(zlib.crc32(str(iv).encode()))
    if isinstance(v, (float, np.floating)):
        fv = float(v)
        if np.isfinite(fv) and fv.is_integer() and abs(fv) >= _HLL_SMALL:
            return float(zlib.crc32(str(int(fv)).encode()))
        return fv
    return float(zlib.crc32(str(v).encode()))


def hash_column_for_hll(col) -> "np.ndarray":
    """Distinct-preserving stable encoding of a mixed/object column into
    float32 for hll (see _hll_encode_value). crc32 is stable across
    processes so checkpointed registers stay consistent after restore.
    None -> NaN (masked, matching SQL null-skipping aggregates)."""
    out = np.empty(len(col), dtype=np.float32)
    memo: dict = {}
    for i, v in enumerate(col):
        if v is None:
            out[i] = np.nan
            continue
        try:
            h = memo.get(v)
        except TypeError:  # unhashable (dict/list)
            out[i] = _hll_encode_value(v)
            continue
        if h is None:
            h = _hll_encode_value(v)
            memo[v] = h
        out[i] = h
    return out


def _hll_encode_numeric(raw: "np.ndarray") -> "np.ndarray":
    """Vectorized hll encoding of a numeric-dtype column: float32 passthrough
    with the (rare) large integral values deferred to _hll_encode_value so
    the result matches the object-column path exactly."""
    if np.issubdtype(raw.dtype, np.integer):
        arr = raw.astype(np.int64)
        out = arr.astype(np.float32)
        big = np.abs(arr) >= _HLL_SMALL
        for i in np.nonzero(big)[0]:
            out[i] = _hll_encode_value(int(arr[i]))
        return out
    f = np.asarray(raw, dtype=np.float64)
    out = f.astype(np.float32)
    with np.errstate(invalid="ignore"):
        big = np.isfinite(f) & (np.abs(f) >= _HLL_SMALL) & (f == np.floor(f))
    for i in np.nonzero(big)[0]:
        out[i] = _hll_encode_value(float(f[i]))
    return out


class ValueDict:
    """Reversible dictionary encoding for a heavy_hitters column: values map
    to dense integer codes (< sketches.HH_MAX_CODES) that fit the sketch's
    bit recovery; codes decode back to the ORIGINAL values (any type,
    strings included) at emit. Codes only grow, so they stay stable across
    the window, across panes, and across checkpoint restore (the fused node
    persists the value list). Values past the code budget encode as NaN
    (masked — invisible to the sketch); heavy hitters by definition appear
    early and often, so they claim low codes long before overflow."""

    def __init__(self) -> None:
        self._ids: Dict[Any, int] = {}
        self._values: List[Any] = []
        self.overflowed = False

    def _code(self, v) -> float:
        from .sketches import HH_MAX_CODES

        ids = self._ids
        c = ids.get(v)
        if c is None:
            if len(self._values) >= HH_MAX_CODES:
                self.overflowed = True
                return np.nan
            c = len(self._values)
            ids[v] = c
            self._values.append(v)
        return float(c)

    def encode(self, col: "np.ndarray") -> "np.ndarray":
        """Column -> float32 codes (NaN for None/overflow)."""
        n = len(col)
        out = np.empty(n, dtype=np.float32)
        if col.dtype == np.object_:
            for i, v in enumerate(col.tolist()):
                if v is None:
                    out[i] = np.nan
                    continue
                try:
                    out[i] = self._code(v)
                except TypeError:  # unhashable (list/dict): stringify
                    out[i] = self._code(repr(v))
            return out
        arr = np.asarray(col)
        if np.issubdtype(arr.dtype, np.floating):
            nan = np.isnan(arr)
        else:
            nan = np.zeros(n, dtype=bool)
        out = np.full(n, np.nan, dtype=np.float32)
        clean = arr[~nan] if nan.any() else arr
        if len(clean):
            uniq, inverse = np.unique(clean, return_inverse=True)
            ucodes = np.array(
                [self._code(u.item()) for u in uniq], dtype=np.float32
            )
            out[~nan] = ucodes[inverse]
        return out

    def decode(self, code: int):
        return self._values[code] if 0 <= code < len(self._values) else None

    def snapshot(self) -> List[Any]:
        return list(self._values)

    def restore(self, values: List[Any]) -> None:
        self._values = list(values)
        self._ids = {}
        for i, v in enumerate(self._values):
            try:
                self._ids[v] = i
            except TypeError:
                pass  # unhashable snapshot value (encode stored repr anyway)


def materialize_hll_columns(plan_columns, cols: Dict[str, "np.ndarray"], n: int):
    """Fill in any missing __hll__<col> derived columns from the raw column.
    Returns a new dict when a derivation was needed; callers that already
    materialized them (nodes_fused, with validity masks) pass through."""
    out = None
    for name in plan_columns:
        if not name.startswith(HLL_COL_PREFIX) or name in cols:
            continue
        if out is None:
            out = dict(cols)
        raw = cols.get(name[len(HLL_COL_PREFIX):])
        if raw is None:
            out[name] = np.full(n, np.nan, dtype=np.float32)
        elif getattr(raw, "dtype", None) == np.object_:
            out[name] = hash_column_for_hll(raw)
        else:
            out[name] = _hll_encode_numeric(np.asarray(raw))
    return out if out is not None else cols


@dataclass
class AggSpec:
    """One device-foldable aggregate call."""

    call: ast.Call
    kind: str  # count/sum/avg/min/max/stddev/.../hll/percentile_approx
    components: Set[str]
    arg: Optional[CompiledExpr]  # device closure for the argument (None = count(*))
    filter: Optional[CompiledExpr]  # FILTER(WHERE ...) device closure
    int_input: bool = False  # observed integer input → integer avg/sum results
    frac: float = 0.5  # percentile_approx quantile (2nd literal arg)
    topk: int = 3  # heavy_hitters k (2nd literal arg)
    # numpy twins of arg/filter, used by the latency-hiding tail shadow
    # (ops/prefinalize.py); None when the expr only compiles for device
    arg_host: Optional[CompiledExpr] = None
    filter_host: Optional[CompiledExpr] = None

    @property
    def is_star(self) -> bool:
        return self.arg is None


@dataclass
class KernelPlan:
    """Everything the fused window→aggregate device kernel needs."""

    specs: List[AggSpec]
    filter: Optional[CompiledExpr]  # WHERE clause (device)
    columns: Set[str] = field(default_factory=set)  # numeric columns to upload
    filter_host: Optional[CompiledExpr] = None  # numpy twin of `filter`

    @property
    def host_foldable(self) -> bool:
        """True when every closure has a numpy twin, so a tail of rows can be
        folded on host by the pre-finalize emit pipeline."""
        if self.filter is not None and self.filter_host is None:
            return False
        for s in self.specs:
            if s.arg is not None and s.arg_host is None:
                return False
            if s.filter is not None and s.filter_host is None:
                return False
        return True


def extract_kernel_plan(
    stmt: ast.SelectStatement, where_on_device: bool = True
) -> Optional[KernelPlan]:
    """Try to build a fully-fused device plan for the statement's aggregates.

    Returns None if any aggregate (or its argument expression) is not
    device-eligible — the planner then uses the host window path.
    """
    calls = _collect_agg_calls(stmt)
    if not calls:
        return None
    specs: List[AggSpec] = []
    columns: Set[str] = set()
    for call in calls:
        kind = call.name[4:] if call.name.startswith("inc_") else call.name
        if call.name not in DEVICE_AGGS:
            return None
        if call.partition or call.when is not None:
            return None
        frac = 0.5
        topk = 3
        arg_ce: Optional[CompiledExpr] = None
        if call.args and not isinstance(call.args[0], ast.Wildcard):
            if call.name == "heavy_hitters":
                # heavy_hitters(col, k): bare column + literal k only — the
                # column dictionary-encodes through a per-node ValueDict.
                # k is bounded by half the candidate pool (top_k fetches 2k
                # of HH_DEPTH*HH_WIDTH candidates); larger k → exact host path
                from .sketches import HH_DEPTH, HH_WIDTH

                if (
                    len(call.args) != 2
                    or not isinstance(call.args[0], ast.FieldRef)
                    or not isinstance(call.args[1], ast.IntegerLiteral)
                    or not 0 < call.args[1].val <= HH_DEPTH * HH_WIDTH // 2
                ):
                    return None
                topk = int(call.args[1].val)
            elif call.name == "percentile_approx":
                if len(call.args) != 2 or not isinstance(
                    call.args[1], (ast.NumberLiteral, ast.IntegerLiteral)
                ):
                    return None
                frac = float(call.args[1].val)
                if not 0.0 <= frac <= 1.0:
                    # invalid fraction: host path raises the clear error
                    return None
            elif len(call.args) != 1:
                return None
            arg_host: Optional[CompiledExpr] = None
            if kind == "heavy_hitters":
                hcol = HH_COL_PREFIX + call.args[0].name
                arg_ce = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "device"
                )
                arg_host = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "host"
                )
            elif kind in ("hll", "distinct_count_approx") and isinstance(
                call.args[0], ast.FieldRef
            ):
                hcol = HLL_COL_PREFIX + call.args[0].name
                arg_ce = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "device"
                )
                arg_host = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "host"
                )
            else:
                arg_ce = try_compile(call.args[0], mode="device")
                if arg_ce is None:
                    return None
                arg_host = try_compile(call.args[0], mode="host")
            columns |= arg_ce.columns
        else:
            arg_host = None
        filter_ce: Optional[CompiledExpr] = None
        filter_host: Optional[CompiledExpr] = None
        if call.filter is not None:
            filter_ce = try_compile(call.filter, mode="device")
            if filter_ce is None:
                return None
            filter_host = try_compile(call.filter, mode="host")
            columns |= filter_ce.columns
        specs.append(
            AggSpec(
                call=call,
                kind="hll" if kind == "distinct_count_approx" else kind,
                components=set(DEVICE_AGGS[call.name]),
                arg=arg_ce,
                filter=filter_ce,
                frac=frac,
                topk=topk,
                arg_host=arg_host,
                filter_host=filter_host,
            )
        )
    where_ce: Optional[CompiledExpr] = None
    where_host: Optional[CompiledExpr] = None
    if stmt.condition is not None and where_on_device:
        where_ce = try_compile(stmt.condition, mode="device")
        if where_ce is None:
            return None  # caller may retry with host-side where
        where_host = try_compile(stmt.condition, mode="host")
        columns |= where_ce.columns
    return KernelPlan(specs=specs, filter=where_ce, columns=columns,
                      filter_host=where_host)


def _collect_agg_calls(stmt: ast.SelectStatement) -> List[ast.Call]:
    """All aggregate calls in SELECT fields + HAVING, deduplicated by
    (name, arg-tree repr) so avg(x) in both places folds once."""
    from ..functions import registry

    seen: Dict[str, ast.Call] = {}
    roots = [f.expr for f in stmt.fields]
    if stmt.having is not None:
        roots.append(stmt.having)
    for sf in stmt.sorts:
        if sf.expr is not None:
            roots.append(sf.expr)
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and registry.is_aggregate(node.name):
                seen.setdefault(_call_key(node), node)
    return list(seen.values())


def _call_key(call: ast.Call) -> str:
    return f"{call.name}({','.join(map(_expr_key, call.args))})" + (
        f"|f:{_expr_key(call.filter)}" if call.filter is not None else ""
    )


def _expr_key(e: Optional[ast.Expr]) -> str:
    if e is None:
        return ""
    if isinstance(e, ast.FieldRef):
        return f"{e.stream}.{e.name}"
    if isinstance(e, ast.Call):
        return _call_key(e)
    if isinstance(e, (ast.IntegerLiteral, ast.NumberLiteral, ast.StringLiteral, ast.BooleanLiteral)):
        return repr(e.val)
    if isinstance(e, ast.BinaryExpr):
        return f"({_expr_key(e.lhs)}{e.op}{_expr_key(e.rhs)})"
    if isinstance(e, ast.UnaryExpr):
        return f"({e.op}{_expr_key(e.expr)})"
    if isinstance(e, ast.Wildcard):
        return "*"
    return repr(e)
