"""Aggregate kernel specs — which aggregates of a SELECT can fuse into the
device group-by kernel, and what partial-state components each needs.

The planner extracts AggSpecs from the statement (the incremental-agg rewrite,
reference: planner.go:910-999 rewriteIfIncAggStmt); device-eligible aggregates
fold into (n, s1, s2, mn, mx) partials — the same (count, sum, sum-of-squares,
min, max) triple-plus layout funcs_inc_agg.py uses, so cross-shard merges are
plain adds/mins/maxes.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ..sql import ast, expr_ir
from ..sql.compiler import CompiledExpr
from ..sql.expr_ir import NotVectorizable

# aggregate name -> components needed by finalize
DEVICE_AGGS: Dict[str, Set[str]] = {
    "count": {"n"},
    "sum": {"n", "s1"},
    "avg": {"n", "s1"},
    "min": {"mn", "n"},
    "max": {"mx", "n"},
    "stddev": {"n", "s1", "s2"},
    "stddevs": {"n", "s1", "s2"},
    "var": {"n", "s1", "s2"},
    "vars": {"n", "s1", "s2"},
    # inc_ forms share the same partials
    "inc_count": {"n"},
    "inc_sum": {"n", "s1"},
    "inc_avg": {"n", "s1"},
    "inc_min": {"mn", "n"},
    "inc_max": {"mx", "n"},
    "inc_stddev": {"n", "s1", "s2"},
    "inc_stddevs": {"n", "s1", "s2"},
    # sketch aggregates (north-star UDFs) — wide device components
    "hll": {"hll"},
    "distinct_count_approx": {"hll"},
    "percentile_approx": {"hist"},
    "heavy_hitters": {"hh"},
}

ALL_COMPONENTS = ("n", "s1", "s2", "mn", "mx")
# components with a trailing register axis (capacity, k, R)
WIDE_COMPONENTS = {"hll", "hist", "hh"}

# Derived-column prefix: hll over a bare column reads a dedicated hashed
# copy (strings crc32-hashed, numerics passed through) so the raw column
# stays numeric for every other spec / WHERE / FILTER sharing it.
HLL_COL_PREFIX = "__hll__"

# Derived-column prefix for heavy_hitters: the raw column dictionary-encodes
# to dense integer codes (< sketches.HH_MAX_CODES) that the bit-recovery
# sketch can reconstruct; codes decode back to the original values at emit.
HH_COL_PREFIX = "__hhc__"


# values below this are exactly representable in float32 and pass through;
# larger integral values hash their decimal repr so the float32 cast cannot
# collapse distinct IDs (e.g. ~1e9-range device ids differing in low bits)
_HLL_SMALL = 2 ** 24


def _hll_encode_value(v) -> float:
    """Distinct-preserving float32 encoding of one value for hll. The SAME
    rule applies whether the value arrives in an object, integer, or float
    batch, so a logical value always folds to the same register."""
    import zlib

    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, np.integer)):
        iv = int(v)
        if abs(iv) < _HLL_SMALL:
            return float(iv)
        return float(zlib.crc32(str(iv).encode()))
    if isinstance(v, (float, np.floating)):
        fv = float(v)
        if np.isfinite(fv) and fv.is_integer() and abs(fv) >= _HLL_SMALL:
            return float(zlib.crc32(str(int(fv)).encode()))
        return fv
    return float(zlib.crc32(str(v).encode()))


def hash_column_for_hll(col) -> "np.ndarray":
    """Distinct-preserving stable encoding of a mixed/object column into
    float32 for hll (see _hll_encode_value). crc32 is stable across
    processes so checkpointed registers stay consistent after restore.
    None -> NaN (masked, matching SQL null-skipping aggregates)."""
    out = np.empty(len(col), dtype=np.float32)
    memo: dict = {}
    for i, v in enumerate(col):
        if v is None:
            out[i] = np.nan
            continue
        try:
            h = memo.get(v)
        except TypeError:  # unhashable (dict/list)
            out[i] = _hll_encode_value(v)
            continue
        if h is None:
            h = _hll_encode_value(v)
            memo[v] = h
        out[i] = h
    return out


def _hll_encode_numeric(raw: "np.ndarray") -> "np.ndarray":
    """Vectorized hll encoding of a numeric-dtype column: float32 passthrough
    with the (rare) large integral values deferred to _hll_encode_value so
    the result matches the object-column path exactly."""
    if np.issubdtype(raw.dtype, np.integer):
        arr = raw.astype(np.int64)
        out = arr.astype(np.float32)
        big = np.abs(arr) >= _HLL_SMALL
        for i in np.nonzero(big)[0]:
            out[i] = _hll_encode_value(int(arr[i]))
        return out
    f = np.asarray(raw, dtype=np.float64)
    out = f.astype(np.float32)
    with np.errstate(invalid="ignore"):
        big = np.isfinite(f) & (np.abs(f) >= _HLL_SMALL) & (f == np.floor(f))
    for i in np.nonzero(big)[0]:
        out[i] = _hll_encode_value(float(f[i]))
    return out


class ValueDict:
    """Reversible dictionary encoding for a heavy_hitters column: values map
    to dense integer codes (< sketches.HH_MAX_CODES) that fit the sketch's
    bit recovery; codes decode back to the ORIGINAL values (any type,
    strings included) at emit. Codes only grow, so they stay stable across
    the window, across panes, and across checkpoint restore (the fused node
    persists the value list). Values past the code budget encode as NaN
    (masked — invisible to the sketch); heavy hitters by definition appear
    early and often, so they claim low codes long before overflow."""

    def __init__(self) -> None:
        self._ids: Dict[Any, int] = {}
        self._values: List[Any] = []
        self.overflowed = False

    def _code(self, v) -> float:
        from .sketches import HH_MAX_CODES

        ids = self._ids
        c = ids.get(v)
        if c is None:
            if len(self._values) >= HH_MAX_CODES:
                self.overflowed = True
                return np.nan
            c = len(self._values)
            ids[v] = c
            self._values.append(v)
        return float(c)

    def encode(self, col: "np.ndarray") -> "np.ndarray":
        """Column -> float32 codes (NaN for None/overflow)."""
        n = len(col)
        out = np.empty(n, dtype=np.float32)
        if col.dtype == np.object_:
            for i, v in enumerate(col.tolist()):
                if v is None:
                    out[i] = np.nan
                    continue
                try:
                    out[i] = self._code(v)
                except TypeError:  # unhashable (list/dict): stringify
                    out[i] = self._code(repr(v))
            return out
        arr = np.asarray(col)
        if np.issubdtype(arr.dtype, np.floating):
            nan = np.isnan(arr)
        else:
            nan = np.zeros(n, dtype=bool)
        out = np.full(n, np.nan, dtype=np.float32)
        clean = arr[~nan] if nan.any() else arr
        if len(clean):
            uniq, inverse = np.unique(clean, return_inverse=True)
            ucodes = np.array(
                [self._code(u.item()) for u in uniq], dtype=np.float32
            )
            out[~nan] = ucodes[inverse]
        return out

    def decode(self, code: int):
        return self._values[code] if 0 <= code < len(self._values) else None

    def snapshot(self) -> List[Any]:
        return list(self._values)

    def restore(self, values: List[Any]) -> None:
        self._values = list(values)
        self._ids = {}
        for i, v in enumerate(self._values):
            try:
                self._ids[v] = i
            except TypeError:
                pass  # unhashable snapshot value (encode stored repr anyway)


def materialize_hll_columns(plan_columns, cols: Dict[str, "np.ndarray"], n: int):
    """Fill in any missing __hll__<col> derived columns from the raw column.
    Returns a new dict when a derivation was needed; callers that already
    materialized them (nodes_fused, with validity masks) pass through."""
    out = None
    for name in plan_columns:
        if not name.startswith(HLL_COL_PREFIX) or name in cols:
            continue
        if out is None:
            out = dict(cols)
        raw = cols.get(name[len(HLL_COL_PREFIX):])
        if raw is None:
            out[name] = np.full(n, np.nan, dtype=np.float32)
        elif getattr(raw, "dtype", None) == np.object_:
            out[name] = hash_column_for_hll(raw)
        else:
            out[name] = _hll_encode_numeric(np.asarray(raw))
    return out if out is not None else cols


@dataclass
class AggSpec:
    """One device-foldable aggregate call."""

    call: ast.Call
    kind: str  # count/sum/avg/min/max/stddev/.../hll/percentile_approx
    components: Set[str]
    arg: Optional[CompiledExpr]  # device closure for the argument (None = count(*))
    filter: Optional[CompiledExpr]  # FILTER(WHERE ...) device closure
    int_input: bool = False  # observed integer input → integer avg/sum results
    frac: float = 0.5  # percentile_approx quantile (2nd literal arg)
    topk: int = 3  # heavy_hitters k (2nd literal arg)
    # numpy twins of arg/filter, used by the latency-hiding tail shadow
    # (ops/prefinalize.py); None when the expr only compiles for device
    arg_host: Optional[CompiledExpr] = None
    filter_host: Optional[CompiledExpr] = None

    @property
    def is_star(self) -> bool:
        return self.arg is None


@dataclass
class KernelPlan:
    """Everything the fused window→aggregate device kernel needs."""

    specs: List[AggSpec]
    filter: Optional[CompiledExpr]  # WHERE clause (device)
    columns: Set[str] = field(default_factory=set)  # numeric columns to upload
    filter_host: Optional[CompiledExpr] = None  # numpy twin of `filter`
    #: per-kernel-column upload dtype ("float32" default; "int32" for the
    #: expression IR's dictionary-code / rebased-ts32 derived columns) —
    #: consumed by the fold upload (ops/groupby.py) and the jitcert fold
    #: derivations (bounded signature families include the dtype)
    col_dtypes: Dict[str, str] = field(default_factory=dict)
    #: expression-IR derived columns (sql/expr_ir.py DerivedCol): host
    #: prep producing the __sd_*/__ts32_* device columns
    derived: Tuple[Any, ...] = ()
    #: stable hash of every compiled expression's IR — part of the
    #: ingest-prep upload share keys (runtime/ingest.py), so two plans
    #: whose expressions differ can never alias a pre-uploaded column
    expr_tag: str = ""
    #: predicate lifting (planner/sharing.py): index of the synthetic
    #: `count(*) FILTER(WHERE <rule predicate>)` activity spec a lifted
    #: member reads its group-existence from (None = the global `act`)
    act_idx: Optional[int] = None

    @property
    def host_foldable(self) -> bool:
        """True when every closure has a numpy twin, so a tail of rows can be
        folded on host by the pre-finalize emit pipeline."""
        if self.filter is not None and self.filter_host is None:
            return False
        for s in self.specs:
            if s.arg is not None and s.arg_host is None:
                return False
            if s.filter is not None and s.filter_host is None:
                return False
        return True


_tl = threading.local()


def take_expr_fallbacks() -> List[Dict[str, str]]:
    """Structured NotVectorizable reasons recorded by the LAST
    extract_kernel_plan call on this thread (cleared on read) — the
    planner turns them into `kuiper_expr_host_fallback_total` samples
    and the explain "expressions" section."""
    out = getattr(_tl, "expr_fallbacks", [])
    _tl.expr_fallbacks = []
    return out


def _note_fallback(kind: str, expr: Optional[ast.Expr],
                   exc: NotVectorizable) -> None:
    notes = getattr(_tl, "expr_fallbacks", None)
    if notes is None:
        notes = _tl.expr_fallbacks = []
    notes.append({"kind": kind,
                  "expr": _expr_key(expr) if expr is not None else "",
                  "reason": getattr(exc, "reason", "other"),
                  "detail": str(exc)})


def _compile_device(expr: ast.Expr, want: str, kind: str,
                    anchor_ms: int, str_seed=None
                    ) -> Optional[expr_ir.CompiledIR]:
    """Device-compile one expression via the IR; a failure records the
    structured reason (the whole rule then takes the host path)."""
    try:
        return expr_ir.compile_expr_ir(expr, mode="device", want=want,
                                       anchor_ms=anchor_ms,
                                       str_seed=str_seed)
    except NotVectorizable as exc:
        _note_fallback(kind, expr, exc)
        return None


def extract_kernel_plan(
    stmt: ast.SelectStatement, where_on_device: bool = True
) -> Optional[KernelPlan]:
    """Try to build a fully-fused device plan for the statement's aggregates.

    Returns None if any aggregate (or its argument expression) is not
    device-eligible — the planner then uses the host window path.
    """
    _tl.expr_fallbacks = []
    calls = _collect_agg_calls(stmt)
    if not calls:
        return None
    # one temporal anchor per plan: every ts32 derivation and rebased
    # literal of this rule shares it (and the IR hashes reflect it)
    anchor_ms = expr_ir.plan_anchor_ms()
    # plan-level string-dictionary seed: union the string constants of
    # every compilable piece, so WHERE + agg args + FILTERs derive ONE
    # __sd_* column per raw column (one host encode, one upload)
    str_seed: Dict[str, Set[str]] = {}
    seed_roots: List[ast.Expr] = []
    if stmt.condition is not None and where_on_device:
        seed_roots.append(stmt.condition)
    for c in calls:
        if c.args and not isinstance(c.args[0], ast.Wildcard):
            seed_roots.append(c.args[0])
        if c.filter is not None:
            seed_roots.append(c.filter)
    for root in seed_roots:
        for col, vals in expr_ir.collect_str_consts(root).items():
            str_seed.setdefault(col, set()).update(vals)
    col_dtypes: Dict[str, str] = {}
    derived: Dict[str, Any] = {}
    ir_keys: List[str] = []

    def absorb(ce: expr_ir.CompiledIR) -> None:
        col_dtypes.update(ce.col_dtypes)
        for d in ce.derived:
            derived[d.name] = d
        ir_keys.append(ce.ir_key)

    specs: List[AggSpec] = []
    columns: Set[str] = set()
    for call in calls:
        kind = call.name[4:] if call.name.startswith("inc_") else call.name
        if call.name not in DEVICE_AGGS:
            return None
        if call.partition or call.when is not None:
            return None
        frac = 0.5
        topk = 3
        arg_ce: Optional[CompiledExpr] = None
        if call.args and not isinstance(call.args[0], ast.Wildcard):
            if call.name == "heavy_hitters":
                # heavy_hitters(col, k): bare column + literal k only — the
                # column dictionary-encodes through a per-node ValueDict.
                # k is bounded by half the candidate pool (top_k fetches 2k
                # of HH_DEPTH*HH_WIDTH candidates); larger k → exact host path
                from .sketches import HH_DEPTH, HH_WIDTH

                if (
                    len(call.args) != 2
                    or not isinstance(call.args[0], ast.FieldRef)
                    or not isinstance(call.args[1], ast.IntegerLiteral)
                    or not 0 < call.args[1].val <= HH_DEPTH * HH_WIDTH // 2
                ):
                    return None
                topk = int(call.args[1].val)
            elif call.name == "percentile_approx":
                if len(call.args) != 2 or not isinstance(
                    call.args[1], (ast.NumberLiteral, ast.IntegerLiteral)
                ):
                    return None
                frac = float(call.args[1].val)
                if not 0.0 <= frac <= 1.0:
                    # invalid fraction: host path raises the clear error
                    return None
            elif len(call.args) != 1:
                return None
            arg_host: Optional[CompiledExpr] = None
            if kind == "heavy_hitters":
                hcol = HH_COL_PREFIX + call.args[0].name
                arg_ce = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "device"
                )
                arg_host = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "host"
                )
            elif kind in ("hll", "distinct_count_approx") and isinstance(
                call.args[0], ast.FieldRef
            ):
                hcol = HLL_COL_PREFIX + call.args[0].name
                arg_ce = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "device"
                )
                arg_host = CompiledExpr(
                    lambda cols, _h=hcol: cols[_h], {hcol}, "host"
                )
            else:
                arg_ce = _compile_device(call.args[0], "number",
                                         f"agg-arg:{call.name}", anchor_ms,
                                         str_seed=str_seed)
                if arg_ce is None:
                    return None
                absorb(arg_ce)
                arg_host = expr_ir.try_compile_ir(
                    call.args[0], mode="host", want="number",
                    anchor_ms=anchor_ms, str_seed=str_seed)
            columns |= arg_ce.columns
        else:
            arg_host = None
        filter_ce: Optional[CompiledExpr] = None
        filter_host: Optional[CompiledExpr] = None
        if call.filter is not None:
            filter_ce = _compile_device(call.filter, "bool",
                                        f"agg-filter:{call.name}",
                                        anchor_ms, str_seed=str_seed)
            if filter_ce is None:
                return None
            absorb(filter_ce)
            filter_host = expr_ir.try_compile_ir(
                call.filter, mode="host", want="bool", anchor_ms=anchor_ms,
                str_seed=str_seed)
            columns |= filter_ce.columns
        specs.append(
            AggSpec(
                call=call,
                kind="hll" if kind == "distinct_count_approx" else kind,
                components=set(DEVICE_AGGS[call.name]),
                arg=arg_ce,
                filter=filter_ce,
                frac=frac,
                topk=topk,
                arg_host=arg_host,
                filter_host=filter_host,
            )
        )
    where_ce: Optional[CompiledExpr] = None
    where_host: Optional[CompiledExpr] = None
    if stmt.condition is not None and where_on_device:
        where_ce = _compile_device(stmt.condition, "bool", "where",
                                   anchor_ms, str_seed=str_seed)
        if where_ce is None:
            return None  # caller may retry with host-side where
        absorb(where_ce)
        where_host = expr_ir.try_compile_ir(
            stmt.condition, mode="host", want="bool", anchor_ms=anchor_ms,
            str_seed=str_seed)
        columns |= where_ce.columns
    return KernelPlan(
        specs=specs, filter=where_ce, columns=columns,
        filter_host=where_host, col_dtypes=col_dtypes,
        derived=tuple(sorted(derived.values(), key=lambda d: d.name)),
        expr_tag=expr_ir.ir_hash(ir_keys) if ir_keys else "")


def conj(a: Optional[ast.Expr], b: Optional[ast.Expr]) -> Optional[ast.Expr]:
    """AND-conjunction of two optional predicates."""
    if a is None:
        return b
    if b is None:
        return a
    return ast.BinaryExpr("AND", a, b)


def lift_predicate(plan: KernelPlan,
                   condition: Optional[ast.Expr]
                   ) -> Optional[KernelPlan]:
    """Predicate lifting for the shared pane fold (planner/sharing.py,
    per "On the Semantic Overlap of Operators in Stream Processing
    Engines"): the rule-level WHERE moves out of the plan's base filter
    and into every spec's FILTER mask, plus a synthetic
    `count(*) FILTER(WHERE <predicate>)` activity spec the member's emit
    reads its group existence from. Fold output for the original specs
    is byte-identical to the private plan's (the same base∧filter mask
    composition in ops/groupby.py _fold_core), but the plan no longer
    gates the SHARED fold — rules that differ only in predicate can
    union into one pooled fold.

    Spec order is preserved (direct-emit indices stay valid); the
    activity spec appends at the end, its index in `act_idx`.

    Returns None when the conjunction does not device-compile (the
    pieces compiled separately but conflict when conjoined — e.g. a
    column typed temporal by the WHERE and numeric by a FILTER): the
    caller must then keep the fold PRIVATE. An unlifted filtered plan
    must never enter a pooled union — its base filter would gate every
    peer's rows.
    """
    if condition is None:
        # nothing to lift: the plan folds every row, the global `act`
        # is this rule's own activity — share as-is
        return plan
    anchor_ms = expr_ir.plan_anchor_ms()
    # plan-level dictionary seed across WHERE + every FILTER, so the
    # lifted plan derives ONE __sd_* column per raw column (the same
    # one-encode/one-upload invariant extract_kernel_plan keeps)
    str_seed: Dict[str, Set[str]] = {}
    for d in plan.derived:
        # the plan's existing dictionaries (agg args / CASE constants)
        # seed the lift, so the lifted filters resolve to the SAME
        # __sd_* columns the arg closures already reference
        if d.kind == "strdict":
            str_seed.setdefault(d.raw, set()).update(d.values)
    for root in [condition] + [s.call.filter for s in plan.specs
                               if s.call.filter is not None]:
        for col, vals in expr_ir.collect_str_consts(root).items():
            str_seed.setdefault(col, set()).update(vals)
    try:
        new_specs: List[AggSpec] = []
        for spec in plan.specs:
            f_ast = conj(condition, spec.call.filter)
            filter_ce = expr_ir.compile_expr_ir(
                f_ast, mode="device", want="bool", anchor_ms=anchor_ms,
                str_seed=str_seed)
            filter_host = expr_ir.try_compile_ir(
                f_ast, mode="host", want="bool", anchor_ms=anchor_ms,
                str_seed=str_seed)
            new_specs.append(_dc_replace(
                spec, call=_dc_replace(spec.call, filter=f_ast),
                filter=filter_ce, filter_host=filter_host))
        act_filter = expr_ir.compile_expr_ir(
            condition, mode="device", want="bool", anchor_ms=anchor_ms,
            str_seed=str_seed)
        act_host = expr_ir.try_compile_ir(
            condition, mode="host", want="bool", anchor_ms=anchor_ms,
            str_seed=str_seed)
    except NotVectorizable:
        return None
    act_call = ast.Call(name="count", args=[ast.Wildcard()],
                        filter=condition)
    new_specs.append(AggSpec(
        call=act_call, kind="count", components={"n"}, arg=None,
        filter=act_filter, filter_host=act_host))
    col_dtypes = dict(plan.col_dtypes)
    derived = {d.name: d for d in plan.derived}
    columns = set(plan.columns)
    ir_keys = []
    for ce in [s.filter for s in new_specs if s.filter is not None]:
        col_dtypes.update(ce.col_dtypes)
        for d in ce.derived:
            derived[d.name] = d
        columns |= ce.columns
        ir_keys.append(ce.ir_key)
    return KernelPlan(
        specs=new_specs, filter=None, columns=columns, filter_host=None,
        col_dtypes=col_dtypes,
        derived=tuple(sorted(derived.values(), key=lambda d: d.name)),
        expr_tag=expr_ir.ir_hash([plan.expr_tag] + ir_keys),
        act_idx=len(new_specs) - 1)


def explain_expressions(stmt: ast.SelectStatement) -> Dict[str, Any]:
    """The "expressions" section of GET /rules/{id}/explain: per-piece
    device-compilation status with structured NotVectorizable reasons —
    names host expression eval instead of an opaque host-path verdict."""
    anchor_ms = expr_ir.plan_anchor_ms()
    pieces: List[Tuple[str, Optional[ast.Expr], str]] = []
    if stmt.condition is not None:
        pieces.append(("where", stmt.condition, "bool"))
    for call in _collect_agg_calls(stmt):
        if call.args and not isinstance(call.args[0], ast.Wildcard):
            pieces.append((f"agg-arg:{call.name}", call.args[0], "number"))
        if call.filter is not None:
            pieces.append((f"agg-filter:{call.name}", call.filter, "bool"))
    out: List[Dict[str, Any]] = []
    n_host = 0
    for kind, expr, want in pieces:
        entry: Dict[str, Any] = {"kind": kind, "expr": _expr_key(expr)}
        try:
            ce = expr_ir.compile_expr_ir(expr, mode="device", want=want,
                                         anchor_ms=anchor_ms)
            entry["path"] = "device"
            if ce.derived:
                entry["derived"] = [d.name for d in ce.derived]
        except NotVectorizable as exc:
            entry["path"] = "host"
            entry["reason"] = getattr(exc, "reason", "other")
            entry["detail"] = str(exc)
            n_host += 1
        out.append(entry)
    return {"pieces": out, "host_fallbacks": n_host,
            "path": "host" if n_host else "device"}


def _collect_agg_calls(stmt: ast.SelectStatement) -> List[ast.Call]:
    """All aggregate calls in SELECT fields + HAVING, deduplicated by
    (name, arg-tree repr) so avg(x) in both places folds once."""
    from ..functions import registry

    seen: Dict[str, ast.Call] = {}
    roots = [f.expr for f in stmt.fields]
    if stmt.having is not None:
        roots.append(stmt.having)
    for sf in stmt.sorts:
        if sf.expr is not None:
            roots.append(sf.expr)
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and registry.is_aggregate(node.name):
                seen.setdefault(_call_key(node), node)
    return list(seen.values())


def _call_key(call: ast.Call) -> str:
    return f"{call.name}({','.join(map(_expr_key, call.args))})" + (
        f"|f:{_expr_key(call.filter)}" if call.filter is not None else ""
    )


def _expr_key(e: Optional[ast.Expr]) -> str:
    if e is None:
        return ""
    if isinstance(e, ast.FieldRef):
        return f"{e.stream}.{e.name}"
    if isinstance(e, ast.Call):
        return _call_key(e)
    if isinstance(e, (ast.IntegerLiteral, ast.NumberLiteral, ast.StringLiteral, ast.BooleanLiteral)):
        return repr(e.val)
    if isinstance(e, ast.BinaryExpr):
        return f"({_expr_key(e.lhs)}{e.op}{_expr_key(e.rhs)})"
    if isinstance(e, ast.UnaryExpr):
        return f"({e.op}{_expr_key(e.expr)})"
    if isinstance(e, ast.BetweenExpr):
        neg = "!" if e.negate else ""
        return (f"({_expr_key(e.value)} {neg}BETWEEN "
                f"{_expr_key(e.lo)},{_expr_key(e.hi)})")
    if isinstance(e, ast.InExpr):
        neg = "!" if e.negate else ""
        return (f"({_expr_key(e.value)} {neg}IN "
                f"[{','.join(_expr_key(v) for v in e.values)}])")
    if isinstance(e, ast.LikeExpr):
        neg = "!" if e.negate else ""
        return f"({_expr_key(e.value)} {neg}LIKE {_expr_key(e.pattern)})"
    if isinstance(e, ast.CaseExpr):
        base = _expr_key(e.value) if e.value is not None else ""
        whens = ";".join(f"{_expr_key(w.cond)}->{_expr_key(w.result)}"
                         for w in e.whens)
        els = _expr_key(e.else_expr) if e.else_expr is not None else ""
        return f"CASE({base};{whens};{els})"
    if isinstance(e, ast.Wildcard):
        return "*"
    return repr(e)
