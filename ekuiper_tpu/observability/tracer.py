"""Per-rule span tracing with a local, queryable span store (analogue of
pkg/tracer/manager.go:36-171 and the /trace REST routes).

Tracing is enabled per rule (with an optional strategy: "always" records
every dispatch, "head" samples the first N spans per second). When a traced
rule's node dispatches an item, the fabric records a span: rule, op, start,
duration, item kind, row count. Spans group into traces by ingest batch: a
trace id is stamped at the source and follows the item chain via thread
context — the dispatching node annotates its spans with the trace current
on its worker (one item processed at a time per node, so the context is
exact for the linear chains the engine builds).

The store is a bounded in-memory ring per rule (the reference's local span
storage with remote-collector export gated out — zero egress here)."""
from __future__ import annotations

import itertools
import threading
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from ..utils import timex

_local = threading.local()


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "rule_id", "op",
                 "start_ms", "duration_us", "kind", "rows", "attrs")

    def __init__(self, trace_id, span_id, parent_id, rule_id, op, start_ms,
                 duration_us, kind, rows, attrs=None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.rule_id = rule_id
        self.op = op
        self.start_ms = start_ms
        self.duration_us = duration_us
        self.kind = kind
        self.rows = rows
        # extra key→value span attributes (e.g. the sink's e2e_ms latency);
        # None for the common attribute-less span
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentSpanId": self.parent_id, "rule": self.rule_id,
            "op": self.op, "startTimeMs": self.start_ms,
            "durationUs": self.duration_us, "kind": self.kind,
            "rows": self.rows,
        }
        if self.attrs:
            out["attributes"] = dict(self.attrs)
        return out


class Tracer:
    _instance: Optional["Tracer"] = None

    def __init__(self, max_spans_per_rule: int = 2048) -> None:
        self._enabled: Dict[str, str] = {}  # rule_id -> strategy
        self._spans: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.max_spans = max_spans_per_rule
        self.any_enabled = False  # hot-path fast check, no lock
        self._head_window: Dict[str, tuple] = {}  # head sampling buckets
        # trace propagation across queue hops: emitted items are tagged with
        # the emitting dispatch's trace id, keyed by id() with a weakref
        # cleanup (many item types — dataclasses with eq — are unhashable,
        # so WeakKeyDictionary can't hold them)
        self._item_traces: Dict[int, tuple] = {}
        # non-weakref-able items (plain lists/dicts — e.g. multi-row project
        # output) can't register a cleanup callback, so they live in a
        # BOUNDED insertion-ordered map with explicit oldest-first eviction.
        # id() reuse after gc can mis-associate a stale entry with a new
        # object; the map is small and short-lived, and a wrong trace id on
        # one span is a telemetry blemish, not a correctness issue.
        self._fallback_traces: "OrderedDict[int, str]" = OrderedDict()
        # optional remote tee (observability/otlp.py) — every span the local
        # store admits is also handed to the exporter, mirroring the
        # reference's dual local+OTLP export (pkg/tracer/manager.go:62-76)
        self.exporter = None

    @classmethod
    def global_instance(cls) -> "Tracer":
        if cls._instance is None:
            cls._instance = Tracer()
        return cls._instance

    # ------------------------------------------------------------- management
    #: "head" sampling records at most this many spans per rule per second
    HEAD_SPANS_PER_SEC = 32

    def enable(self, rule_id: str, strategy: str = "always") -> None:
        if strategy not in ("always", "head"):
            from ..utils.infra import EngineError

            raise EngineError(
                f"unknown trace strategy {strategy!r} (want always|head)")
        with self._lock:
            self._enabled[rule_id] = strategy
            self._spans.setdefault(rule_id, deque(maxlen=self.max_spans))
            self.any_enabled = True

    def disable(self, rule_id: str) -> None:
        with self._lock:
            self._enabled.pop(rule_id, None)
            self.any_enabled = bool(self._enabled)

    def is_enabled(self, rule_id: str) -> bool:
        return rule_id in self._enabled

    def set_exporter(self, exporter) -> None:
        """Install (or clear, with None) the remote OTLP tee."""
        old, self.exporter = self.exporter, exporter
        if old is not None:
            old.close()

    # ------------------------------------------------------------- recording
    def new_trace(self) -> str:
        tid = f"t{next(self._ids):08x}"
        _local.trace_id = tid
        return tid

    @staticmethod
    def current_trace() -> Optional[str]:
        return getattr(_local, "trace_id", None)

    @staticmethod
    def set_current(trace_id: Optional[str]) -> None:
        _local.trace_id = trace_id

    #: bounded size of the non-weakref-able item→trace fallback map
    FALLBACK_CAP = 4096

    def tag(self, item: Any) -> None:
        tid = self.current_trace()
        if tid is None:
            return
        key = id(item)
        try:
            ref = weakref.ref(
                item, lambda _r, k=key: self._item_traces.pop(k, None))
        except TypeError:
            # not weakref-able (plain list/dict): bounded fallback map so
            # the trace still survives the queue hop to the next node
            with self._lock:
                self._fallback_traces[key] = tid
                self._fallback_traces.move_to_end(key)
                while len(self._fallback_traces) > self.FALLBACK_CAP:
                    self._fallback_traces.popitem(last=False)
            return
        self._item_traces[key] = (ref, tid)

    def lookup(self, item: Any) -> Optional[str]:
        got = self._item_traces.get(id(item))
        if got is not None and got[0]() is item:
            return got[1]
        tid = self._fallback_traces.get(id(item))
        if tid is not None:
            return tid
        return None

    def record(self, rule_id: str, op: str, start_ms: int, duration_us: int,
               kind: str, rows: int, attrs: Optional[dict] = None) -> None:
        trace_id = self.current_trace() or self.new_trace()
        span = Span(trace_id, f"s{next(self._ids):08x}", "", rule_id, op,
                    start_ms, duration_us, kind, rows, attrs=attrs)
        # ENGINE-clock seconds for head sampling: mock-clock tests see
        # deterministic sampling windows (advance() moves the bucket
        # boundary). Read BEFORE self._lock — a mock advance fires timer
        # callbacks holding the clock lock, and those can reach tag()
        # (which takes self._lock), so reading the clock under our lock
        # would invert the clock-first order utils/lockcheck.py polices
        sec = timex.now_ms() // 1000
        with self._lock:
            if self._enabled.get(rule_id) == "head":
                wsec, n = getattr(self, "_head_window", {}).get(
                    rule_id, (sec, 0))
                if wsec != sec:
                    wsec, n = sec, 0
                if n >= self.HEAD_SPANS_PER_SEC:
                    self._head_window[rule_id] = (wsec, n)
                    return
                self._head_window[rule_id] = (wsec, n + 1)
            ring = self._spans.get(rule_id)
            if ring is not None:
                ring.append(span)
        if ring is not None and self.exporter is not None:
            self.exporter.on_span(span)

    # --------------------------------------------------------------- queries
    def rule_traces(self, rule_id: str, limit: int = 50) -> List[str]:
        """Most recent trace ids of a rule (reference /trace/rule/{id})."""
        with self._lock:
            ring = self._spans.get(rule_id)
            if not ring:
                return []
            seen: List[str] = []
            for span in reversed(ring):
                if span.trace_id not in seen:
                    seen.append(span.trace_id)
                if len(seen) >= limit:
                    break
            return seen

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """All spans of one trace (reference /trace/{id})."""
        with self._lock:
            out = []
            for ring in self._spans.values():
                out.extend(s.to_dict() for s in ring if s.trace_id == trace_id)
            out.sort(key=lambda s: s["startTimeMs"])
            return out

    def rule_spans(self, rule_id: str, limit: int = 200) -> List[Dict[str, Any]]:
        with self._lock:
            ring = self._spans.get(rule_id)
            if not ring:
                return []
            return [s.to_dict() for s in list(ring)[-limit:]]


def item_stats(item: Any) -> tuple:
    """(kind, row count) of a dispatched item for span annotation."""
    kind = type(item).__name__
    n = getattr(item, "n", None)
    if n is None:
        if isinstance(item, list):
            n = len(item)
        else:
            n = 1
    return kind, int(n)
