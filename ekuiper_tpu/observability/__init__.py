"""Observability plane: Prometheus exposition, per-rule span tracing with a
queryable local span store, and metrics dumps (analogue of the reference's
metrics/metrics.go Prometheus registry, pkg/tracer span manager, and
metrics/metrics_dump.go)."""
from .tracer import Tracer  # noqa: F401
