"""OTLP/HTTP span export — the remote-collector tee of the tracer
(reference pkg/tracer/manager.go:28-45: otlptracehttp.New + WithInsecure;
every exported span ALSO stays in the local store, manager.go:62-76).

Spans are serialized as an OTLP `ExportTraceServiceRequest` protobuf and
POSTed to `http://<endpoint>/v1/traces` with content-type
application/x-protobuf. The message is hand-encoded against the official
opentelemetry-proto field numbers (trace/v1/trace.proto, common/v1/
common.proto, resource/v1/resource.proto) — protobuf wire bytes carry only
field numbers and wire types, so no schema compilation is needed at
runtime; tests/test_otlp.py cross-validates the bytes by decoding them
with protoc + google.protobuf against a spec-derived schema.

Export is config-gated OFF (utils/config.py OpenTelemetryConfig): zero
egress unless the operator points the engine at a collector.
"""
from __future__ import annotations

import hashlib
import struct
import threading
import urllib.request
from typing import Any, Dict, List, Optional

from ..utils.infra import logger

# ------------------------------------------------------ protobuf wire encode
_LEN = 2  # wire types
_VARINT = 0
_I64 = 1


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field (submessage / string / bytes)."""
    return _tag(field, _LEN) + _varint(len(payload)) + payload


def _str(field: int, s: str) -> bytes:
    return _ld(field, s.encode())


def _u64(field: int, v: int) -> bytes:
    """fixed64 (OTLP timestamps)."""
    return _tag(field, _I64) + struct.pack("<Q", v)


def _vint(field: int, v: int) -> bytes:
    return _tag(field, _VARINT) + _varint(v)


def _any_value(v: Any) -> bytes:
    # AnyValue: string_value=1 | bool_value=2 | int_value=3 | double_value=4
    if isinstance(v, bool):
        return _vint(2, 1 if v else 0)
    if isinstance(v, int):
        return _vint(3, v & 0xFFFFFFFFFFFFFFFF)
    if isinstance(v, float):
        return _tag(4, _I64) + struct.pack("<d", v)
    return _str(1, str(v))


def _kv(key: str, v: Any) -> bytes:
    # KeyValue: key=1, value=2
    return _str(1, key) + _ld(2, _any_value(v))


def _trace_id_bytes(tid: str) -> bytes:
    """Engine trace ids are short strings ("t0000002a"); OTLP requires 16
    opaque bytes — a deterministic digest keeps one engine trace one OTLP
    trace across batches and restarts."""
    return hashlib.md5(tid.encode()).digest()


def _span_id_bytes(sid: str) -> bytes:
    return hashlib.md5(sid.encode()).digest()[:8]


#: OTLP SpanKind: the engine's operator spans are INTERNAL(1)
_KIND_INTERNAL = 1


def encode_span(span) -> bytes:
    """observability.tracer.Span -> opentelemetry.proto.trace.v1.Span bytes.
    Field numbers: trace_id=1, span_id=2, parent_span_id=4, name=5, kind=6,
    start_time_unix_nano=7, end_time_unix_nano=8, attributes=9."""
    start_ns = span.start_ms * 1_000_000
    end_ns = start_ns + span.duration_us * 1_000
    out = _ld(1, _trace_id_bytes(span.trace_id))
    out += _ld(2, _span_id_bytes(span.span_id))
    if span.parent_id:
        out += _ld(4, _span_id_bytes(span.parent_id))
    out += _str(5, f"{span.rule_id}/{span.op}")
    out += _vint(6, _KIND_INTERNAL)
    out += _u64(7, start_ns)
    out += _u64(8, end_ns)
    for k, v in (("rule", span.rule_id), ("op", span.op),
                 ("item.kind", span.kind), ("item.rows", span.rows)):
        out += _ld(9, _kv(k, v))
    # extra span attributes (e.g. the sink's end-to-end e2e_ms latency) —
    # absent on the common span, so legacy encodings are byte-identical
    for k, v in (getattr(span, "attrs", None) or {}).items():
        out += _ld(9, _kv(str(k), v))
    return out


def encode_export_request(spans: List[Any],
                          service_name: str = "ekuiper_tpu") -> bytes:
    """-> ExportTraceServiceRequest{resource_spans=1} bytes.
    ResourceSpans: resource=1, scope_spans=2; Resource: attributes=1;
    ScopeSpans: scope=1, spans=2; InstrumentationScope: name=1."""
    resource = _ld(1, _kv("service.name", service_name))
    scope = _str(1, "ekuiper_tpu.tracer")
    scope_spans = _ld(1, scope) + b"".join(_ld(2, encode_span(s))
                                           for s in spans)
    resource_spans = _ld(1, resource) + _ld(2, scope_spans)
    return _ld(1, resource_spans)


# ------------------------------------------------------------------ exporter
class OtlpExporter:
    """Batching background exporter. on_span() is called from dispatch hot
    paths — it only appends under a lock; serialization + HTTP happen on
    the flusher thread."""

    def __init__(self, endpoint: str, batch_max_spans: int = 512,
                 batch_interval_ms: int = 2000,
                 service_name: str = "ekuiper_tpu") -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint  # WithInsecure analogue
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.batch_max = batch_max_spans
        self.interval = batch_interval_ms / 1000.0
        self.service_name = service_name
        self._buf: List[Any] = []
        self._mu = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.dropped = 0
        self.exported = 0
        self.errors = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-export")
        self._thread.start()

    def on_span(self, span) -> None:
        with self._mu:
            if len(self._buf) >= 4 * self.batch_max:
                self.dropped += 1  # collector down — bound memory, not block
                return
            self._buf.append(span)
            full = len(self._buf) >= self.batch_max
        if full:
            self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        with self._mu:
            batch, self._buf = self._buf, []
        if not batch:
            return
        body = encode_export_request(batch, self.service_name)
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/x-protobuf"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
            self.exported += len(batch)
        except Exception as e:
            self.errors += 1
            if self.errors in (1, 10) or self.errors % 100 == 0:
                logger.warning("otlp export to %s failed (%d so far): %s",
                               self.url, self.errors, e)

    def stats(self) -> Dict[str, int]:
        return {"exported": self.exported, "dropped": self.dropped,
                "errors": self.errors}

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)
        self.flush()


def from_config(cfg) -> Optional[OtlpExporter]:
    """Build the exporter the boot sequence installs on the tracer when
    open_telemetry.enable_remote_collector is on (server/main.py)."""
    ot = cfg.open_telemetry
    if not ot.enable_remote_collector:
        return None
    return OtlpExporter(ot.remote_endpoint,
                        batch_max_spans=ot.batch_max_spans,
                        batch_interval_ms=ot.batch_interval_ms,
                        service_name=ot.service_name)
