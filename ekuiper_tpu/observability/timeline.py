"""Durable telemetry timeline — a bounded on-disk ring of metric
snapshots and flight events.

The in-memory surfaces (the 1024-event flight recorder, the last health
verdicts, a point-in-time /metrics scrape) all die with the process or
age out within minutes. This module gives a post-mortem a time axis: on
a `KUIPER_TIMELINE_INTERVAL_MS` cadence it scrapes the full Prometheus
render (every family — kernel timings, shard rows, burn rates, shed
totals — plus the health verdict states), delta-encodes the sample
against the previous one, and appends a JSON line to a segment file
under `<store.path>/timeline/`. Flight-recorder events mirror in as
they happen (runtime/events.py `record()` calls `note_event`), so the
incident trail outlives the ring.

Segment format (`seg-<seq>-<t0 ms>.jsonl`, one JSON object per line):

- `{"t": ms, "k": "snap", "full": true, "d": {series: value, ...}}` —
  the first snapshot record of every segment carries the complete
  sample, so any single segment replays standalone;
- `{"t": ms, "k": "snap", "d": {changed...}, "x": [removed...]}` —
  later records carry only series whose value changed (`x` lists series
  that disappeared);
- `{"t": ms, "k": "ev", "ev": {...}}` — a mirrored flight event,
  verbatim.

Series keys are the Prometheus sample identity (`name{labels}`), so
`query(family=, rule=)` filters are plain string tests. Segments rotate
at `KUIPER_TIMELINE_SEG_KB` and the directory is capped by
`KUIPER_TIMELINE_MAX_MB` / `KUIPER_TIMELINE_MAX_AGE_MS` (oldest
segments deleted first — a ring, on disk). Every append flushes, so a
hard kill (chaos-harness `hard_kill`) loses at most the line being
written; `dying_gasp()` (wired to atexit and the fatal paths) forces one
last full snapshot out. `tools/kuiperdiag.py --timeline` packs recent
segments into the support bundle; `GET /diagnostics/timeline` serves
the replay over REST.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import timex
from ..utils.infra import logger

DEFAULT_INTERVAL_MS = 5_000   # KUIPER_TIMELINE_INTERVAL_MS (0 = no timer)
DEFAULT_SEG_KB = 256          # KUIPER_TIMELINE_SEG_KB — rotate threshold
DEFAULT_MAX_MB = 8            # KUIPER_TIMELINE_MAX_MB — directory byte cap
DEFAULT_MAX_AGE_MS = 6 * 3600 * 1000  # KUIPER_TIMELINE_MAX_AGE_MS


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except (TypeError, ValueError):
        return default


def parse_scrape(text: str) -> Dict[str, float]:
    """Prometheus text exposition -> {series identity: value}. The series
    identity is the sample line minus its value (`name{labels}`), which
    keeps delta keys stable across scrapes."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        if not key:
            continue
        try:
            v = float(val)
        except ValueError:
            continue
        out[key] = int(v) if v == int(v) else v
    return out


class Timeline:
    """One on-disk telemetry ring. `scrape_fn()` returns the Prometheus
    text to snapshot; `verdicts_fn()` (optional) returns the health
    verdict map folded in as pseudo-series `health|<rule> = state`."""

    def __init__(self, scrape_fn: Callable[[], str],
                 base_dir: Optional[str] = None,
                 verdicts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 interval_ms: Optional[int] = None) -> None:
        if base_dir is None:
            from ..utils.config import get_config

            base_dir = os.path.join(get_config().store.path, "timeline")
        self.dir = base_dir
        self._scrape_fn = scrape_fn
        self._verdicts_fn = verdicts_fn
        self.interval_ms = (
            _env_int("KUIPER_TIMELINE_INTERVAL_MS", DEFAULT_INTERVAL_MS)
            if interval_ms is None else int(interval_ms))
        self.seg_bytes = _env_int(
            "KUIPER_TIMELINE_SEG_KB", DEFAULT_SEG_KB) * 1024
        self.max_bytes = _env_int(
            "KUIPER_TIMELINE_MAX_MB", DEFAULT_MAX_MB) * 1024 * 1024
        self.max_age_ms = _env_int(
            "KUIPER_TIMELINE_MAX_AGE_MS", DEFAULT_MAX_AGE_MS)
        self._lock = threading.Lock()
        self._fh = None
        self._fh_path: Optional[str] = None
        self._fh_bytes = 0
        self._last: Optional[Dict[str, float]] = None
        self._timer = None
        self._running = False
        self._gasped = False
        self.snapshots = 0
        self.events_mirrored = 0
        os.makedirs(self.dir, exist_ok=True)
        # resume the seq past any segments a previous life left behind —
        # recovery IS the point, never clobber them
        self._seq = max(
            [self._parse_name(n)[0] for n in self._list_names()] or [0])

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self.interval_ms <= 0:
            return
        with self._lock:
            if self._running:
                return
            self._running = True
        self._arm()

    def stop(self) -> None:
        with self._lock:
            self._running = False
            if self._timer is not None:
                self._timer.stop()
                self._timer = None
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None

    def _arm(self) -> None:
        self._timer = timex.after(self.interval_ms, self._fire)

    def _fire(self, ts: int) -> None:
        if not self._running:
            return
        try:
            self.snapshot(now=ts)
        except Exception as exc:
            logger.warning("timeline snapshot failed: %s", exc)
        finally:
            if self._running:
                self._arm()

    # ----------------------------------------------------------- segments
    @staticmethod
    def _parse_name(name: str) -> Tuple[int, int]:
        """seg-<seq>-<t0>.jsonl -> (seq, t0); (0, 0) for foreign files."""
        try:
            stem = name[:-len(".jsonl")]
            _, seq, t0 = stem.split("-", 2)
            return int(seq), int(t0)
        except (ValueError, IndexError):
            return (0, 0)

    def _list_names(self) -> List[str]:
        try:
            return sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("seg-") and n.endswith(".jsonl"))
        except OSError:
            return []

    def _open_segment(self, now: int) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass
        self._seq += 1
        name = f"seg-{self._seq:08d}-{now}.jsonl"
        self._fh_path = os.path.join(self.dir, name)
        self._fh = open(self._fh_path, "a", encoding="utf-8")
        self._fh_bytes = 0
        self._last = None  # force the segment-opening record to be full

    def _roll(self, now: int) -> None:
        """Rotate when the active segment is missing or over the size
        threshold. Caller holds self._lock."""
        if self._fh is None or self._fh_bytes >= self.seg_bytes:
            self._open_segment(now)

    def _write(self, rec: Dict[str, Any], now: int) -> None:
        """Serialize + append + flush one record, then retire segments to
        the caps. Caller holds self._lock and has called _roll()."""
        line = json.dumps(rec, separators=(",", ":"), default=str)
        self._fh.write(line + "\n")
        self._fh.flush()
        self._fh_bytes += len(line) + 1
        self._retire(now)

    def _retire(self, now: int) -> None:
        """Oldest-first segment deletion to the byte/age caps. Caller
        holds self._lock; the active segment is never deleted. Segment
        start times ride the filename — no file reads here."""
        names = self._list_names()
        sizes = {}
        for n in names:
            try:
                sizes[n] = os.path.getsize(os.path.join(self.dir, n))
            except OSError:
                sizes[n] = 0
        total = sum(sizes.values())
        for n in names[:-1]:  # keep the active (newest) segment
            _, t0 = self._parse_name(n)
            too_big = total > self.max_bytes
            too_old = bool(self.max_age_ms > 0 and t0
                           and (now - t0) > self.max_age_ms)
            if not (too_big or too_old):
                break  # t0 rises with the name sort; newer can't be older
            try:
                os.remove(os.path.join(self.dir, n))
                total -= sizes[n]
            except OSError:
                pass

    # ---------------------------------------------------------- recording
    def snapshot(self, now: Optional[int] = None) -> Dict[str, Any]:
        """Scrape, delta against the previous sample, append. The scrape
        runs OUTSIDE the timeline lock (it takes every registry's lock);
        clock reads happen before the lock (timer callbacks hold the
        clock lock — utils/lockcheck.py ABBA discipline)."""
        if now is None:
            now = timex.now_ms()
        sample = parse_scrape(self._scrape_fn() or "")
        if self._verdicts_fn is not None:
            try:
                for rid, v in (self._verdicts_fn() or {}).items():
                    state = v.get("state") if isinstance(v, dict) else v
                    sample[f"health|{rid}"] = str(state)
            except Exception:
                pass
        with self._lock:
            # rotate BEFORE building the record: _open_segment clears
            # self._last, so a fresh segment always opens with a full
            # sample and replays standalone
            self._roll(now)
            prev = self._last
            if prev is None:
                rec: Dict[str, Any] = {"t": now, "k": "snap", "full": True,
                                       "d": sample}
            else:
                changed = {k: v for k, v in sample.items()
                           if prev.get(k) != v}
                removed = [k for k in prev if k not in sample]
                rec = {"t": now, "k": "snap", "d": changed}
                if removed:
                    rec["x"] = removed
            self._write(rec, now)
            self._last = sample
            self.snapshots += 1
        return rec

    def note_event(self, ev: Dict[str, Any]) -> None:
        """Mirror one flight-recorder event (already stamped with ts_ms
        and seq by the ring)."""
        now = int(ev.get("ts_ms", 0))
        with self._lock:
            self._roll(now)
            self._write({"t": now, "k": "ev", "ev": ev}, now)
            self.events_mirrored += 1

    def dying_gasp(self) -> None:
        """One last full snapshot on the way down — fatal handlers and
        atexit call this; re-entry and double-gasp are no-ops."""
        if self._gasped:
            return
        self._gasped = True
        try:
            with self._lock:
                self._last = None  # force a full, standalone record
            self.snapshot()
        except Exception as exc:
            logger.warning("timeline dying gasp failed: %s", exc)

    # ------------------------------------------------------------- replay
    def query(self, family: Optional[str] = None,
              rule: Optional[str] = None,
              since: Optional[int] = None,
              limit: int = 200) -> Dict[str, Any]:
        """Replay the segments oldest→newest into filtered records:
        `family` matches the series name (exact) or prefix with a
        trailing `*`; `rule` matches the `rule="..."` label (and event
        rules); `since` drops records at/before that engine ms; `limit`
        keeps the NEWEST n after filtering."""
        def keep_series(key: str) -> bool:
            if family:
                name = key.split("{", 1)[0]
                if family.endswith("*"):
                    if not name.startswith(family[:-1]):
                        return False
                elif name != family and key != family:
                    return False
            if rule and f'rule="{rule}"' not in key \
                    and not key.endswith(f"|{rule}"):
                return False
            return True

        records: List[Dict[str, Any]] = []
        for name in self._list_names():
            try:
                with open(os.path.join(self.dir, name),
                          encoding="utf-8") as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue  # torn tail line after a hard kill
                        t = int(rec.get("t", 0))
                        if since is not None and t <= since:
                            continue
                        if rec.get("k") == "ev":
                            ev = rec.get("ev") or {}
                            if rule and ev.get("rule") != rule:
                                continue
                            if family and family not in ("ev", "events"):
                                continue
                            records.append(
                                {"t": t, "kind": "event", "event": ev})
                        else:
                            d = {k: v for k, v in
                                 (rec.get("d") or {}).items()
                                 if keep_series(k)}
                            if not d and not rec.get("full"):
                                continue
                            out_rec = {"t": t, "kind": "snapshot",
                                       "series": d}
                            if rec.get("full"):
                                out_rec["full"] = True
                            records.append(out_rec)
            except OSError:
                continue
        if limit is not None and limit >= 0:
            records = records[len(records) - min(limit, len(records)):]
        return {"records": records, "returned": len(records),
                **self.stats()}

    def segment_dump(self, max_segments: int = 8,
                     max_bytes: int = 1 << 20) -> Dict[str, List[str]]:
        """Newest segments as raw lines for the kuiperdiag bundle,
        bounded by count and total bytes (newest win)."""
        out: Dict[str, List[str]] = {}
        budget = max_bytes
        for name in reversed(self._list_names()[-max_segments:]):
            try:
                with open(os.path.join(self.dir, name),
                          encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
            except OSError:
                continue
            size = sum(len(ln) + 1 for ln in lines)
            if size > budget:
                break
            budget -= size
            out[name] = lines
        return out

    def stats(self) -> Dict[str, Any]:
        names = self._list_names()
        total = 0
        for n in names:
            try:
                total += os.path.getsize(os.path.join(self.dir, n))
            except OSError:
                pass
        return {"dir": self.dir, "segments": len(names),
                "bytes": total, "snapshots": self.snapshots,
                "events_mirrored": self.events_mirrored,
                "interval_ms": self.interval_ms,
                "seg_bytes": self.seg_bytes,
                "max_bytes": self.max_bytes,
                "max_age_ms": self.max_age_ms}


# -------------------------------------------------------------- singleton
_timeline: Optional[Timeline] = None
_install_lock = threading.Lock()


def install(scrape_fn: Callable[[], str],
            base_dir: Optional[str] = None,
            verdicts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
            interval_ms: Optional[int] = None,
            start: bool = True) -> Timeline:
    """Install (replacing any prior) the engine-wide timeline. The REST
    server installs one over its own /metrics render at boot."""
    global _timeline
    with _install_lock:
        if _timeline is not None:
            _timeline.stop()
        _timeline = Timeline(scrape_fn, base_dir=base_dir,
                             verdicts_fn=verdicts_fn,
                             interval_ms=interval_ms)
        tl = _timeline
    if start:
        tl.start()
    return tl


def timeline() -> Optional[Timeline]:
    return _timeline


def note_event(ev: Dict[str, Any]) -> None:
    """Flight-recorder mirror hook — a no-op until install()."""
    tl = _timeline
    if tl is None:
        return
    try:
        tl.note_event(ev)
    except Exception:
        pass  # telemetry must never take down a producer


def dying_gasp() -> None:
    tl = _timeline
    if tl is not None:
        tl.dying_gasp()


def render_prometheus(out: List[str], esc) -> None:
    tl = _timeline
    if tl is None:
        return
    st = tl.stats()
    out.append("# TYPE kuiper_timeline_segments gauge")
    out.append("# HELP kuiper_timeline_segments on-disk telemetry "
               "segments in the timeline ring")
    out.append(f"kuiper_timeline_segments {st['segments']}")
    out.append("# TYPE kuiper_timeline_bytes gauge")
    out.append("# HELP kuiper_timeline_bytes total bytes of the on-disk "
               "timeline ring")
    out.append(f"kuiper_timeline_bytes {st['bytes']}")
    out.append("# TYPE kuiper_timeline_snapshots_total counter")
    out.append("# HELP kuiper_timeline_snapshots_total snapshots appended "
               "since install")
    out.append(f"kuiper_timeline_snapshots_total {st['snapshots']}")


def reset() -> None:
    """Test hook: stop and drop the installed timeline."""
    global _timeline
    with _install_lock:
        if _timeline is not None:
            _timeline.stop()
        _timeline = None


atexit.register(dying_gasp)
