"""Kernel observatory — device-time & roofline attribution, the
device-side twin of devwatch.

Every timing the engine exported before this module was HOST wall clock:
a "fold" stage number conflates Python dispatch, XLA queueing, H2D
transfer, and the actual device compute. That makes the two questions
behind the sliding-latency and headroom roadmap items unanswerable:
*where do the 400-900ms sliding trigger stalls actually go*, and *how
close is the fused fold to the HBM-bandwidth roof*. TiLT (arxiv
2301.12030) argues stream-query optimization needs per-operator hardware
cost as a first-class signal; this module supplies it with two
low-overhead capture paths hooked into `devwatch.watched_jit` (every jit
site in the engine already routes through it):

- **Cost capture at lowering time.** When a site compiles, the lowered
  HLO's `cost_analysis()` is read (FLOPs, bytes accessed) and stored per
  compile signature. Backends that return no estimates (some CPU builds,
  remote plugins) degrade to `cost: None` — the timing plane keeps
  working without the roofline.
- **Sampled device timing.** Every Nth call (cadence per site *kind*:
  hot-path folds default 1/64, rare boundary ops 1/4 — a window boundary
  sync per ~40s of windows is noise, a per-batch sync is not) the wrapper
  times dispatch→`block_until_ready` and splits the call into
  host-dispatch vs device+transfer time by subtracting the site's
  host-dispatch floor (the running minimum dispatch time — pure host
  work, no device wait). Transfer is estimated from the host-resident
  argument bytes at the device's H2D bandwidth spec.

From the per-device peak table (`PEAK_SPECS`, read off
`jax.devices()[0].device_kind`) each sampled kernel gets a roofline
utilization: achieved FLOP/s against the compute roof and achieved
bytes/s against the HBM roof — the max of the two is how close the
kernel runs to *its* binding roof, and which one binds classifies it
compute- vs memory-bound.

Surfaces: `kuiper_kernel_{device_ms,dispatch_ms,flops,bytes,
roofline_util}` Prometheus families, `GET /diagnostics/kernels`, a
`device_time` section in `/rules/{id}/status`, the `kernels` section of
kuiperdiag bundles, the health plane's device/host bottleneck axis, and
the bench artifact's per-kernel summaries (`docs/OBSERVABILITY.md`
"Device time & roofline").
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

def _default_sampling() -> Dict[str, int]:
    return {
        "hot": int(os.environ.get("KUIPER_KERNWATCH_EVERY", "64") or 0),
        "boundary": int(os.environ.get("KUIPER_KERNWATCH_BOUNDARY_EVERY",
                                       "4") or 0),
    }


#: default sampling cadence per site kind (1/N calls pay a device sync);
#: 0 disables sampling for that kind (cost capture still runs)
DEFAULT_SAMPLING = _default_sampling()

#: per-device peak specs for the roofline: f32-class peak FLOP/s (the
#: engine's folds are f32 elementwise/scatter — for TPUs the bf16 MXU
#: number is listed because XLA's flop estimate counts MXU-eligible ops
#: against it), HBM/memory bandwidth, and host→device link bandwidth.
#: Keyed by a lowercase substring of `jax.devices()[0].device_kind`;
#: first match wins, unknown kinds report utilization as None. CPU
#: numbers are order-of-magnitude (CI realism, not marketing).
PEAK_SPECS: Tuple[Tuple[str, Dict[str, float]], ...] = (
    ("v5 lite", {"name": "TPU v5e", "peak_flops": 197e12,
                 "hbm_gbs": 819.0, "h2d_gbs": 32.0}),
    ("v5e", {"name": "TPU v5e", "peak_flops": 197e12,
             "hbm_gbs": 819.0, "h2d_gbs": 32.0}),
    ("v5p", {"name": "TPU v5p", "peak_flops": 459e12,
             "hbm_gbs": 2765.0, "h2d_gbs": 32.0}),
    ("v4", {"name": "TPU v4", "peak_flops": 275e12,
            "hbm_gbs": 1228.0, "h2d_gbs": 32.0}),
    ("v3", {"name": "TPU v3", "peak_flops": 123e12,
            "hbm_gbs": 900.0, "h2d_gbs": 16.0}),
    ("cpu", {"name": "host CPU", "peak_flops": 200e9,
             "hbm_gbs": 20.0, "h2d_gbs": 10.0}),
)

_device_spec_cache: List[Optional[Dict[str, Any]]] = []  # [(kind, spec)]
_spec_lock = threading.Lock()


def device_spec() -> Dict[str, Any]:
    """{kind, spec|None} for the default jax device, cached after first
    successful read (a failed backend probe is NOT cached — the backend
    may simply not be initialized yet)."""
    with _spec_lock:
        if _device_spec_cache:
            return _device_spec_cache[0]  # type: ignore[return-value]
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        return {"kind": "unavailable", "spec": None}
    low = str(kind).lower()
    spec = None
    for key, s in PEAK_SPECS:
        if key in low:
            spec = dict(s)
            break
    out = {"kind": str(kind), "spec": spec}
    with _spec_lock:
        if not _device_spec_cache:
            _device_spec_cache.append(out)
    return out


def roofline(flops: Optional[float], bytes_: Optional[float],
             compute_us: float,
             spec: Optional[Dict[str, float]]) -> Dict[str, Any]:
    """Utilization of the binding roof for one kernel execution:
    util = max(achieved FLOP/s / peak, achieved bytes/s / HBM peak); the
    larger ratio names the bound. Returns {} when cost or spec is
    missing, or the measured compute time is zero (nothing to divide)."""
    if spec is None or compute_us <= 0.0:
        return {}
    secs = compute_us / 1e6
    util_f = util_b = None
    if flops is not None and flops > 0 and spec.get("peak_flops"):
        util_f = (flops / secs) / spec["peak_flops"]
    if bytes_ is not None and bytes_ > 0 and spec.get("hbm_gbs"):
        util_b = (bytes_ / secs) / (spec["hbm_gbs"] * 1e9)
    if util_f is None and util_b is None:
        return {}
    if (util_b or 0.0) >= (util_f or 0.0):
        return {"util": round(util_b, 4), "bound": "memory"}
    return {"util": round(util_f, 4), "bound": "compute"}


class KernelRecord:
    """Per-jit-site device-time record, owned by its devwatch OpWatch
    (same lifetime: dies with the kernel object, retires into the
    module rollup so exported counters stay monotonic)."""

    __slots__ = ("op", "kind", "sample_every", "_n", "samples",
                 "device_us", "dispatch_us", "transfer_us",
                 "dispatch_floor_us", "cost", "cost_error",
                 "last_sample", "_util_sum", "_util_n", "_bound",
                 "_lock")

    def __init__(self, op: str, kind: str = "hot") -> None:
        self.op = op
        self.kind = kind if kind in DEFAULT_SAMPLING else "hot"
        self.sample_every = DEFAULT_SAMPLING[self.kind]
        self._n = 0
        self.samples = 0
        self.device_us = 0.0   # post-floor device+transfer wait, summed
        self.dispatch_us = 0.0  # host dispatch time, summed over samples
        self.transfer_us = 0.0  # H2D estimate from host-arg bytes, summed
        self.dispatch_floor_us: Optional[float] = None
        self.cost: Optional[Dict[str, float]] = None  # latest signature
        self.cost_error: Optional[str] = None
        self.last_sample: Optional[Dict[str, float]] = None
        self._util_sum = 0.0
        self._util_n = 0
        self._bound: Optional[str] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ hot path
    def tick(self) -> bool:
        """Called once per wrapped call; True = this call is sampled.
        Unlocked counter — a lost increment under racing dispatch skews
        the cadence by one call, which is fine for telemetry."""
        n = self._n + 1
        self._n = n
        e = self.sample_every
        return e > 0 and n % e == 0

    # ------------------------------------------------------- compile path
    def on_compile(self, jitted: Any, args: tuple, kwargs: dict) -> None:
        """Capture XLA cost_analysis at lowering time (compiles only —
        `jit.lower` re-traces, which is noise against a real XLA compile
        but far too slow for the call path). Degrades gracefully when the
        backend returns no estimates."""
        try:
            ca = jitted.lower(*args, **kwargs).cost_analysis()
        except Exception as exc:
            self.cost_error = f"{type(exc).__name__}: {exc}"[:160]
            return
        if isinstance(ca, (list, tuple)):  # some backends: one per device
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            self.cost_error = "no estimates from backend"
            return
        flops = _non_negative(ca.get("flops"))
        bytes_ = _non_negative(ca.get("bytes accessed"))
        if flops is None and bytes_ is None:
            self.cost_error = "no flops/bytes estimates from backend"
            return
        cost: Dict[str, float] = {}
        if flops is not None:
            cost["flops"] = flops
        if bytes_ is not None:
            cost["bytes"] = bytes_
        if flops and bytes_:
            cost["intensity"] = round(flops / bytes_, 4)
        self.cost = cost
        self.cost_error = None

    # ------------------------------------------------------- sampled path
    def sample(self, out: Any, t0: float, t1: float, args: tuple,
               kwargs: dict) -> None:
        """One sampled call: block on the outputs, then split the wall
        time into host-dispatch vs device(+transfer) components."""
        import time as _time

        try:
            import jax

            jax.block_until_ready(out)
        except Exception:
            return  # a sample must never break the call path
        t2 = _time.perf_counter()
        h2d = 0
        try:
            import numpy as np

            for leaf in jax.tree_util.tree_leaves((args, kwargs)):
                if isinstance(leaf, np.ndarray):
                    h2d += leaf.nbytes
        except Exception:
            pass
        self.record_sample((t1 - t0) * 1e6, (t2 - t0) * 1e6, h2d_bytes=h2d)

    def record_sample(self, dispatch_us: float, total_us: float,
                      h2d_bytes: int = 0) -> None:
        """Fold one measured (dispatch, total-blocked) pair into the
        record — the unit-testable core of `sample()`."""
        ds = device_spec()
        spec = ds.get("spec")
        with self._lock:
            floor = self.dispatch_floor_us
            if floor is None or dispatch_us < floor:
                floor = self.dispatch_floor_us = dispatch_us
            device_us = max(total_us - floor, 0.0)
            transfer_us = 0.0
            if h2d_bytes > 0 and spec is not None and spec.get("h2d_gbs"):
                # bytes / (GB/s * 1e9) seconds -> µs
                transfer_us = min(h2d_bytes / (spec["h2d_gbs"] * 1e3),
                                  device_us)
            compute_us = max(device_us - transfer_us, 0.0)
            self.samples += 1
            self.dispatch_us += dispatch_us
            self.device_us += device_us
            self.transfer_us += transfer_us
            cost = self.cost or {}
            rl = roofline(cost.get("flops"), cost.get("bytes"),
                          compute_us, spec)
            if rl:
                self._util_sum += rl["util"]
                self._util_n += 1
                self._bound = rl["bound"]
            self.last_sample = {
                "dispatch_us": round(dispatch_us, 1),
                "device_us": round(device_us, 1),
                "transfer_est_us": round(transfer_us, 1),
                **({"roofline_util": rl["util"]} if rl else {}),
            }

    def set_cost(self, flops: Optional[float],
                 bytes_: Optional[float]) -> None:
        """Synthetic-cost hook (check_metrics, tests)."""
        cost: Dict[str, float] = {}
        if flops is not None:
            cost["flops"] = float(flops)
        if bytes_ is not None:
            cost["bytes"] = float(bytes_)
        if flops and bytes_:
            cost["intensity"] = round(flops / bytes_, 4)
        self.cost = cost or None

    # ------------------------------------------------------------- queries
    def roofline_util(self) -> Optional[float]:
        with self._lock:
            if not self._util_n:
                return None
            return round(self._util_sum / self._util_n, 4)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n = max(self.samples, 1)
            out: Dict[str, Any] = {
                "kind": self.kind,
                "sample_every": self.sample_every,
                "samples": self.samples,
                "device_us_total": round(self.device_us, 1),
                "dispatch_us_total": round(self.dispatch_us, 1),
                "transfer_est_us_total": round(self.transfer_us, 1),
                "device_us_mean": round(self.device_us / n, 1),
                "dispatch_us_mean": round(self.dispatch_us / n, 1),
                "dispatch_floor_us": (
                    round(self.dispatch_floor_us, 1)
                    if self.dispatch_floor_us is not None else None),
                "cost": dict(self.cost) if self.cost else None,
                "last_sample": (dict(self.last_sample)
                                if self.last_sample else None),
            }
            if self.cost_error:
                out["cost_error"] = self.cost_error
            if self._util_n:
                out["roofline_util"] = round(self._util_sum / self._util_n,
                                             4)
                out["bound"] = self._bound
        return out


def _non_negative(v: Any) -> Optional[float]:
    """Cost-analysis values can be absent, NaN, or -1 sentinels."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if f != f or f < 0.0:
        return None
    return f


# ----------------------------------------------------------- module state
_lock = threading.Lock()
#: (op, rule) -> retired counter rollup, fed by devwatch when an OpWatch
#: owner is collected — exported counters stay monotonic across restarts
_retired: Dict[Tuple[str, str], Dict[str, float]] = {}
RETIRED_CAP = 4096


def retire(op: str, rule: str, kern: KernelRecord) -> None:
    """Fold a dying record's counters into the rollup (called from
    devwatch._Registry.retire_dead; kern is mid-collection — plain
    counter reads only)."""
    if kern.samples == 0:
        return
    with _lock:
        acc = _retired.setdefault((op, rule), {
            "samples": 0, "device_us": 0.0, "dispatch_us": 0.0,
            "transfer_us": 0.0})
        acc["samples"] += kern.samples
        acc["device_us"] += kern.device_us
        acc["dispatch_us"] += kern.dispatch_us
        acc["transfer_us"] += kern.transfer_us
        while len(_retired) > RETIRED_CAP:
            del _retired[next(iter(_retired))]


def _live() -> List[Tuple[str, str, KernelRecord]]:
    """[(op, rule, kern)] for every live watched site."""
    from . import devwatch

    return [(w.op, w.rule or "", w.kern)
            for w in devwatch.registry().watches()
            if getattr(w, "kern", None) is not None]


def set_sampling(hot: Optional[int] = None,
                 boundary: Optional[int] = None) -> Dict[str, int]:
    """Adjust sampling cadence live (module default + every live record
    of that kind). Returns the PRIOR defaults so a caller (the bench's
    instrumented segments) can restore them."""
    prior = dict(DEFAULT_SAMPLING)
    for kind, val in (("hot", hot), ("boundary", boundary)):
        if val is None:
            continue
        DEFAULT_SAMPLING[kind] = int(val)
        for _op, _rule, kern in _live():
            if kern.kind == kind:
                kern.sample_every = int(val)
    return prior


def aggregate() -> Dict[Tuple[str, str], Dict[str, Any]]:
    """Rollup by (op, rule) for the Prometheus exposition: counters
    include retired instances; gauges (cost, utilization) ride the live
    records."""
    with _lock:
        out: Dict[Tuple[str, str], Dict[str, Any]] = {
            k: dict(v) for k, v in _retired.items()}
    for op, rule, kern in _live():
        snap = kern.snapshot()
        acc = out.setdefault((op, rule), {
            "samples": 0, "device_us": 0.0, "dispatch_us": 0.0,
            "transfer_us": 0.0})
        acc["samples"] += snap["samples"]
        acc["device_us"] += snap["device_us_total"]
        acc["dispatch_us"] += snap["dispatch_us_total"]
        acc["transfer_us"] += snap["transfer_est_us_total"]
        if snap.get("cost"):
            acc["cost"] = snap["cost"]
        if snap.get("roofline_util") is not None:
            acc["roofline_util"] = snap["roofline_util"]
            acc["bound"] = snap.get("bound")
    return out


def rule_ops_all() -> Dict[str, Dict[str, Dict[str, Any]]]:
    """{rule: {op: cumulative device-time counters}} for EVERY rule
    (live + retired) in ONE registry pass — the health evaluator fetches
    this once per tick and diffs per rule for the device/host bottleneck
    axis (a per-rule scan would make the tick O(rules x watches))."""
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    with _lock:
        for (op, rule), v in _retired.items():
            out.setdefault(rule, {})[op] = {
                "samples": v["samples"], "device_us": v["device_us"],
                "dispatch_us": v["dispatch_us"]}
    for op, rule, kern in _live():
        acc = out.setdefault(rule, {}).setdefault(
            op, {"samples": 0, "device_us": 0.0, "dispatch_us": 0.0})
        acc["samples"] += kern.samples
        acc["device_us"] += kern.device_us
        acc["dispatch_us"] += kern.dispatch_us
        util = kern.roofline_util()
        if util is not None:
            acc["roofline_util"] = util
            acc["bound"] = kern._bound
    return out


def rule_ops(rule_id: str) -> Dict[str, Dict[str, Any]]:
    """Cumulative per-op device-time counters for ONE rule."""
    return rule_ops_all().get(rule_id, {})


def rule_status(rule_id: str) -> Dict[str, Any]:
    """The `device_time` section of one rule's /status JSON: the rule's
    sampled host/device time split plus a per-op breakdown."""
    ops: Dict[str, Any] = {}
    device_us = dispatch_us = transfer_us = 0.0
    samples = 0
    for op, rule, kern in _live():
        if rule != rule_id:
            continue
        snap = kern.snapshot()
        ops[op] = {k: snap[k] for k in (
            "samples", "device_us_mean", "dispatch_us_mean", "cost")}
        for key in ("roofline_util", "bound", "cost_error"):
            if snap.get(key) is not None:
                ops[op][key] = snap[key]
        device_us += snap["device_us_total"]
        dispatch_us += snap["dispatch_us_total"]
        transfer_us += snap["transfer_est_us_total"]
        samples += snap["samples"]
    if not ops:
        return {}
    total = device_us + dispatch_us
    return {
        "samples": samples,
        "device_ms": round(device_us / 1e3, 3),
        "dispatch_ms": round(dispatch_us / 1e3, 3),
        "transfer_est_ms": round(transfer_us / 1e3, 3),
        "device_share": round(device_us / total, 4) if total else None,
        "ops": ops,
    }


def diagnostics() -> Dict[str, Any]:
    """The GET /diagnostics/kernels payload."""
    sites = []
    for op, rule, kern in _live():
        sites.append({"op": op, "rule": rule or None, **kern.snapshot()})
    sites.sort(key=lambda s: -s["device_us_total"])
    agg = aggregate()
    return {
        "device": device_spec(),
        "sampling": dict(DEFAULT_SAMPLING),
        "sites": sites,
        "totals": {
            "samples": int(sum(v["samples"] for v in agg.values())),
            "device_ms": round(
                sum(v["device_us"] for v in agg.values()) / 1e3, 3),
            "dispatch_ms": round(
                sum(v["dispatch_us"] for v in agg.values()) / 1e3, 3),
        },
    }


def totals_by_op(prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """Live per-op rollup across rules (bench phase deltas)."""
    out: Dict[str, Dict[str, Any]] = {}
    for op, _rule, kern in _live():
        if prefix and not op.startswith(prefix):
            continue
        snap = kern.snapshot()
        acc = out.setdefault(op, {"samples": 0, "device_us": 0.0,
                                  "dispatch_us": 0.0, "transfer_us": 0.0})
        acc["samples"] += snap["samples"]
        acc["device_us"] += snap["device_us_total"]
        acc["dispatch_us"] += snap["dispatch_us_total"]
        acc["transfer_us"] += snap["transfer_est_us_total"]
        if snap.get("roofline_util") is not None:
            acc["roofline_util"] = snap["roofline_util"]
            acc["bound"] = snap.get("bound")
    return out


def bench_summary(top: int = 6) -> Dict[str, Any]:
    """Compact per-kernel summary for the bench artifact: the top-N sites
    by sampled device time."""
    rows = []
    for op, rule, kern in _live():
        snap = kern.snapshot()
        if not snap["samples"] and not snap.get("cost"):
            continue
        row = {"op": op, "samples": snap["samples"],
               "device_ms": round(snap["device_us_total"] / 1e3, 2),
               "dispatch_ms": round(snap["dispatch_us_total"] / 1e3, 2),
               "device_us_mean": snap["device_us_mean"]}
        cost = snap.get("cost") or {}
        if cost.get("flops"):
            row["flops"] = cost["flops"]
        if cost.get("bytes"):
            row["bytes"] = cost["bytes"]
        for key in ("roofline_util", "bound"):
            if snap.get(key) is not None:
                row[key] = snap[key]
        rows.append(row)
    rows.sort(key=lambda r: -r["device_ms"])
    return {"device": device_spec().get("kind"),
            "top": rows[:top]}


def reset() -> None:
    """Test hook: drop retired rollups, restore default cadences, and
    un-cache the device spec (tests monkeypatch it)."""
    with _lock:
        _retired.clear()
    # in place: set_sampling and callers hold the dict itself
    DEFAULT_SAMPLING.update(_default_sampling())
    with _spec_lock:
        _device_spec_cache.clear()


# -------------------------------------------------------- Prometheus view
def render_prometheus(out: List[str], esc) -> None:
    """Append the kuiper_kernel_* families to a /metrics scrape. `esc` is
    the exposition label escaper (observability/prometheus.py _esc)."""
    rows = sorted(aggregate().items())

    def label(op: str, rule: str) -> str:
        return f'op="{esc(op)}",rule="{esc(rule or "__engine__")}"'

    fams = (
        ("kuiper_kernel_device_ms", "counter",
         "sampled device-side time per jit site (ms; post-dispatch-floor"
         " wait incl. transfer)",
         lambda v: round(v["device_us"] / 1e3, 3), lambda v: True),
        ("kuiper_kernel_dispatch_ms", "counter",
         "sampled host-dispatch time per jit site (ms)",
         lambda v: round(v["dispatch_us"] / 1e3, 3), lambda v: True),
        ("kuiper_kernel_flops", "gauge",
         "XLA cost-analysis FLOPs per call, latest compiled signature",
         lambda v: v["cost"]["flops"],
         # per-key gate: a bytes-only estimate must not fabricate a 0
         # FLOPs "measurement" (and vice versa) — absence means absence
         lambda v: bool((v.get("cost") or {}).get("flops"))),
        ("kuiper_kernel_bytes", "gauge",
         "XLA cost-analysis bytes accessed per call, latest signature",
         lambda v: v["cost"]["bytes"],
         lambda v: bool((v.get("cost") or {}).get("bytes"))),
        ("kuiper_kernel_roofline_util", "gauge",
         "sampled utilization of the binding device roof (compute or "
         "HBM), 1.0 = at the roof",
         lambda v: v["roofline_util"],
         lambda v: v.get("roofline_util") is not None),
    )
    for name, mtype, help_txt, value, want in fams:
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"# HELP {name} {help_txt}")
        for (op, rule), v in rows:
            if want(v):
                out.append(f"{name}{{{label(op, rule)}}} {value(v)}")
