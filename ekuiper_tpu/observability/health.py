"""Streaming health plane — the first CONSUMER of the engine's telemetry.

Everything below already existed as raw signal: per-stage process/queue
histograms and stage timings (utils/metrics.py StatManager), the per-rule
ingest→emit distribution (runtime/topo.py e2e_hist), the drop taxonomy,
the XLA compile watcher (devwatch), the HBM byte probes (memwatch). What
was missing — ROADMAP item 5's "the engine has rich telemetry but nothing
consumes it" — is a component that reads those surfaces periodically and
renders a VERDICT per rule: *this rule is breaching its SLO, the
bottleneck is the upload stage, and event time is falling behind*.

The `HealthEvaluator` ticks on the engine clock (mock-clock friendly:
tests drive `tick()` directly or advance the clock) — the burn windows
are sample-count-aware (observation-indexed decay bounded by
IDLE_HOLD_TICKS, evidence-weighted burns via `_weighted_burn`), so
sub-second cadences judge slow-emitting rules without verdict flap —
and computes, per running rule:

- **SLO burn rate** — multi-window (fast/slow) burn against a per-rule
  latency + drop SLO. Each tick the delta of the rule's cumulative e2e
  histogram is folded into two evaluator-owned window histograms that
  are decayed geometrically via `LatencyHistogram.snapshot_and_decay`
  (fast ≈ 2-tick memory, slow ≈ 8-tick); burn = violating fraction /
  error budget, the standard SRE multi-window multi-burn shape (both
  windows must burn before the verdict escalates, so a single spike
  cannot flap it).
- **Bottleneck attribution** — per-tick deltas of every node's stage
  timings and busy time, mapped onto the canonical pipeline taxonomy
  (decode / upload / fold / emit_combine / sink — the time-centric
  decomposition argument of TiLT, arxiv 2301.12030), plus enqueue-time
  queue-depth high-water marks split upstream/downstream of the
  attributed node so backpressure direction is visible.
- **Event-time progress** — watermark lag (engine clock vs the rule's
  watermark), pane-ring occupancy (fused/shared event paths), buffered
  rows (host window path), and the per-member emit cursor for rules
  riding a shared pane fold (lag is reported PER RULE, not per store).
- **HBM headroom trend** — memwatch byte totals per tick, with a
  bytes/minute slope over the sample window.

Verdicts move healthy→degraded→breaching (and back) through a hysteresis
FSM: escalation needs `up_ticks` consecutive ticks above threshold,
recovery steps down one level per `down_ticks` quiet ticks. Every
transition emits a `rule_health` flight-recorder event and the current
verdicts export as the `kuiper_rule_health` / `kuiper_slo_burn_rate` /
`kuiper_watermark_lag_ms` / `kuiper_bottleneck_stage` Prometheus
families and the `GET /rules/{id}/health` + `GET /diagnostics/health`
REST views. This layer is what the later control-plane PRs (admission
control, QoS shedding, auto-sizing) will read.

On-demand deep capture lives here too: `capture_profile` runs a bounded
`jax.profiler.trace` plus a devwatch signature/memwatch dump into a
bundle directory (`POST /diagnostics/profile`, collected by
`tools/kuiperdiag.py --profile`).
"""
from __future__ import annotations

import json
import os
import threading
import time as _time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import timex
from ..utils.infra import logger
from .histogram import LatencyHistogram

# ----------------------------------------------------------------- states
HEALTHY = "healthy"
DEGRADED = "degraded"
BREACHING = "breaching"
STATE_LEVEL = {HEALTHY: 0, DEGRADED: 1, BREACHING: 2}
_LEVEL_STATE = {v: k for k, v in STATE_LEVEL.items()}

#: canonical bottleneck taxonomy (TiLT-style stage decomposition of the
#: ingest→emit path); "host_expr" is host-side expression evaluation
#: (FilterNode vectorized/row WHERE, the row-interpreter fallback seam —
#: sql/expr_ir.py compiles these onto the device for fused rules);
#: "other" absorbs host-op busy time that belongs to none of the named
#: stages (projections, joins); "shard_skew" is mesh-level — a sharded
#: rule whose hottest shard absorbs ≥ KUIPER_MESH_SKEW_THRESHOLD times
#: the mean fold rows (observability/meshwatch.py) is bound by one
#: chip's key range, not by any pipeline stage
STAGES = ("decode", "upload", "fold", "emit_combine", "sink",
          "host_expr", "shard_skew", "other")

#: node-local stage labels → canonical taxonomy
_STAGE_CANON = {"decode": "decode", "ring": "decode",
                "upload": "upload", "prep": "upload",
                "fold": "fold", "host_expr": "host_expr"}

#: classes whose UNSTAGED busy time is boundary work (finalize + window
#: combine + emission) rather than row processing
_EMIT_CLASSES = {"FusedWindowAggNode", "SharedFoldNode", "WindowNode",
                 "SharedEmitNode"}

# -------------------------------------------------------------- SLO config
#: engine-default SLO, overridable per rule via options.slo (camelCase or
#: snake_case keys accepted — docs/OBSERVABILITY.md "Health plane")
DEFAULT_SLO = {
    "latency_p99_ms": 1000,        # e2e emit latency bound
    "target": 0.99,                # fraction of emits that must beat it
    "max_drop_ratio": 0.01,        # tolerated dropped/offered ratio
    "max_watermark_lag_ms": None,  # event-time lag bound (None = off)
}

_SLO_ALIASES = {
    "latencyP99Ms": "latency_p99_ms",
    "latency_p99_ms": "latency_p99_ms",
    "target": "target",
    "maxDropRatio": "max_drop_ratio",
    "max_drop_ratio": "max_drop_ratio",
    "maxWatermarkLagMs": "max_watermark_lag_ms",
    "max_watermark_lag_ms": "max_watermark_lag_ms",
}


def parse_slo(options: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Resolve a rule's SLO config from its options (`"slo": {...}`),
    falling back to engine defaults; malformed values keep the default
    (a bad SLO must not stop a rule from being evaluated at all)."""
    out = dict(DEFAULT_SLO)
    raw = (options or {}).get("slo") or {}
    if not isinstance(raw, dict):
        return out
    for key, val in raw.items():
        norm = _SLO_ALIASES.get(key)
        if norm is None:
            continue
        try:
            if norm == "target":
                v = float(val)
                if 0.0 < v < 1.0:
                    out[norm] = v
            elif norm == "max_drop_ratio":
                v = float(val)
                if v > 0:
                    out[norm] = v
            else:
                v = int(val)
                if v > 0:
                    out[norm] = v
        except (TypeError, ValueError):
            continue
    return out


#: burn-rate multiple at/above which BOTH windows flag a breach; [1,
#: BREACH_BURN) is the degraded band — budget is being consumed faster
#: than sustainable but not catastrophically
BREACH_BURN = 6.0
#: geometric window decay per tick: fast ≈ 2-tick memory, slow ≈ 8-tick
FAST_DECAY = 0.5
SLOW_DECAY = 0.875
#: evidence-hold bound: zero-sample ticks HOLD the burn windows (a
#: sub-second evaluator must not flush a slow emitter's evidence
#: between window emissions), but only this many in a row — past it
#: the decay resumes so a rule whose traffic STOPS entirely (dead
#: broker, disconnected source) ages back to healthy instead of
#: freezing at its last verdict forever (which would also permanently
#: trip KUIPER_ADMISSION_DEFER_BREACHING)
IDLE_HOLD_TICKS = 16
#: default evaluator cadence (engine clock)
DEFAULT_INTERVAL_MS = int(os.environ.get("KUIPER_HEALTH_INTERVAL_MS",
                                         "5000") or 5000)
#: HBM trend window (ticks)
_HBM_SAMPLES = 12


class _RuleTrack:
    """Per-rule evaluator state across ticks."""

    __slots__ = ("fast_hist", "slow_hist", "prev_e2e", "prev_nodes",
                 "prev_queue", "prev_kern", "fast_drops", "slow_drops",
                 "fast_in", "slow_in", "state", "state_since_ms",
                 "ticks_in_state", "up_pend", "up_level", "down_pend",
                 "verdict", "peak_burn", "lat_idle", "drop_idle")

    def __init__(self, now_ms: int) -> None:
        self.fast_hist = LatencyHistogram()
        self.slow_hist = LatencyHistogram()
        self.prev_e2e: Optional[List[int]] = None
        self.prev_nodes: Dict[str, Dict[str, Any]] = {}
        self.prev_queue: Dict[str, int] = {}
        self.prev_kern: Dict[str, Dict[str, Any]] = {}
        self.fast_drops = 0.0
        self.slow_drops = 0.0
        self.fast_in = 0.0
        self.slow_in = 0.0
        self.state = HEALTHY
        self.state_since_ms = now_ms
        self.ticks_in_state = 0
        self.up_pend = 0
        self.up_level = 0
        self.down_pend = 0
        self.verdict: Optional[Dict[str, Any]] = None
        self.peak_burn = 0.0
        self.lat_idle = 0   # consecutive zero-sample ticks (latency)
        self.drop_idle = 0  # consecutive zero-traffic ticks (drops)


def _viol_fraction(hist: LatencyHistogram, bound_ms: int) -> Tuple[float, int]:
    """(fraction of window samples above `bound_ms`, window count). The
    bucket→bound mapping is conservative (histogram.py cumulative), so
    the fraction can only over-report violations — burn rate never
    flatters the SLO."""
    cum, count, _ = hist.export((int(bound_ms),))
    if count <= 0:
        return 0.0, 0
    return (count - cum[0]) / count, count


def _weighted_burn(violations: float, mass: float, budget: float) -> float:
    """Sample-count-aware burn: `violations` bad samples out of `mass`
    observed, against an error budget. The violating fraction is taken
    over at least the budget's own resolution (1/budget samples): a
    window too sparse to statistically resolve the budget cannot claim
    a full-rate burn off one or two samples — the exact flap churn_soak
    had to pin KUIPER_HEALTH_INTERVAL_MS=1500 to dodge (a sub-second
    evaluator tick between two window emissions saw a 1-sample window
    and swung the verdict on it). Unseen samples are presumed good —
    burn under-claims on thin evidence, never over-claims."""
    budget = max(budget, 1e-6)
    n_min = 1.0 / budget + 1.0
    return (violations / max(mass, n_min)) / budget


class HealthEvaluator:
    """Periodic per-rule health verdicts off the existing telemetry
    surfaces. `rules_fn()` yields `(rule_id, topo, options)` triples for
    every rule worth evaluating; everything else is read through public
    accessors on the topo's nodes. All sampling is read-only — a tick
    never blocks the data path beyond the StatManagers' short locks."""

    def __init__(self, rules_fn: Callable[[], List[tuple]],
                 interval_ms: int = DEFAULT_INTERVAL_MS,
                 up_ticks: int = 2, down_ticks: int = 3,
                 breach_burn: float = BREACH_BURN,
                 fast_decay: float = FAST_DECAY,
                 slow_decay: float = SLOW_DECAY) -> None:
        self._rules_fn = rules_fn
        self.interval_ms = int(interval_ms)
        self.up_ticks = max(int(up_ticks), 1)
        self.down_ticks = max(int(down_ticks), 1)
        self.breach_burn = float(breach_burn)
        self.fast_decay = float(fast_decay)
        self.slow_decay = float(slow_decay)
        self._tracks: Dict[str, _RuleTrack] = {}
        self._lock = threading.RLock()
        # single-flight guard for REST-triggered seeding ticks; ordered
        # BEFORE the clock/evaluator locks (held across tick()), never
        # taken from timer callbacks — see rule_health
        self._seed_mu = threading.Lock()
        self._timer = None
        self._running = False
        self.ticks = 0
        self.last_tick_us = 0.0
        self._hbm: deque = deque(maxlen=_HBM_SAMPLES)
        #: per-tick queue-peak memo (node identity → peak) — shared
        #: nodes are walked once per member rule, but the underlying
        #: high-water mark is read-and-reset
        self._tick_qpeaks: Dict[int, int] = {}

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._arm()

    def stop(self) -> None:
        with self._lock:
            self._running = False
            if self._timer is not None:
                self._timer.stop()
                self._timer = None

    def _arm(self) -> None:
        self._timer = timex.after(self.interval_ms, self._fire)

    def _fire(self, ts: int) -> None:
        if not self._running:
            return
        try:
            self.tick()
        except Exception as exc:  # the evaluator must never kill a timer
            logger.warning("health evaluator tick failed: %s", exc)
        if self._running:
            self._arm()

    # ------------------------------------------------------------------- tick
    def tick(self) -> Dict[str, Dict[str, Any]]:
        """Evaluate every rule once. Returns {rule_id: verdict}."""
        # clock read BEFORE the evaluator lock: a mock-clock advance fires
        # _fire -> tick() while HOLDING the clock lock, so taking the
        # clock inside our lock would be the clock/evaluator ABBA square
        # (utils/lockcheck.py flags it — same class as the PR 6
        # clock/stats inversion)
        now = timex.now_ms()
        with self._lock:
            t0 = _time.perf_counter()
            sweep = True
            try:
                rules = list(self._rules_fn() or [])
            except Exception as exc:
                # transient registry failure: evaluate nothing this tick
                # but KEEP every track — deleting them would silently
                # reset breaching rules to healthy and make the next
                # tick re-seed the full cumulative e2e history as one
                # tick's delta
                logger.warning("health rules_fn failed: %s", exc)
                rules = []
                sweep = False
            self._tick_qpeaks: Dict[int, int] = {}
            # kernel-observatory counters for ALL rules in one registry
            # pass (observability/kernwatch.py) — _device_axis diffs per
            # rule against this tick-shared map
            from . import kernwatch

            try:
                self._tick_kern = kernwatch.rule_ops_all()
            except Exception:
                self._tick_kern = {}
            # mesh skew observed once per tick, shared by every rule's
            # attribution below (observability/meshwatch.py); ts passed
            # explicitly — we hold self._lock, the clock lock is off
            # limits (same ABBA discipline as the recorder calls)
            from . import meshwatch

            try:
                self._tick_mesh = meshwatch.observe(now)
            except Exception:
                self._tick_mesh = {}
            seen = set()
            for entry in rules:
                try:
                    rid, topo, options = entry
                except (TypeError, ValueError):
                    continue
                if topo is None:
                    continue
                seen.add(rid)
                try:
                    self._eval_rule(rid, topo, options or {}, now)
                except Exception as exc:
                    logger.warning("health eval of rule %s failed: %s",
                                   rid, exc)
            if sweep:
                for rid in [r for r in self._tracks if r not in seen]:
                    del self._tracks[rid]
            # engine-level HBM sample (memwatch probes; pull-model, cheap)
            from . import memwatch

            try:
                self._hbm.append((now, memwatch.registry().total_bytes()))
            except Exception:
                pass
            self.ticks += 1
            self.last_tick_us = (_time.perf_counter() - t0) * 1e6
            return {rid: tr.verdict for rid, tr in self._tracks.items()
                    if tr.verdict is not None}

    # ------------------------------------------------------------ per rule
    def _eval_rule(self, rid: str, topo: Any, options: Dict[str, Any],
                   now: int) -> None:
        tr = self._tracks.get(rid)
        if tr is None:
            tr = self._tracks[rid] = _RuleTrack(now)
        slo = parse_slo(options)

        # ---- latency window delta → fast/slow burn
        hist = getattr(topo, "e2e_hist", None)
        delta_n = 0
        if hist is not None:
            cur = hist.bucket_counts()
            prev = tr.prev_e2e
            if prev is None or sum(cur) < sum(prev):
                # first tick, or the source histogram was decayed/reset
                # (bench segments do): re-seed from the full cumulative
                delta = cur
            else:
                delta = [max(c - p, 0) for c, p in zip(cur, prev)]
            tr.prev_e2e = cur
            delta_n = sum(delta)
            tr.fast_hist.record_bucket_counts(delta)
            tr.slow_hist.record_bucket_counts(delta)
        budget = max(1.0 - slo["target"], 1e-6)
        bound = slo["latency_p99_ms"]
        frac_f, n_f = _viol_fraction(tr.fast_hist, bound)
        frac_s, n_s = _viol_fraction(tr.slow_hist, bound)
        # burn is weighted by the samples each window actually observed
        # (sparse windows cannot resolve the budget — see _weighted_burn)
        lat_burn_f = _weighted_burn(frac_f * n_f, n_f, budget)
        lat_burn_s = _weighted_burn(frac_s * n_s, n_s, budget)
        # snapshot the window percentiles, then decay toward next tick —
        # ONLY on ticks that observed samples: the windows index the last
        # N observations, not wall ticks, so an evaluator outpacing a
        # slow-emitting rule holds its evidence instead of flushing it
        # to zero between emissions (the verdict-flap class). The hold
        # is BOUNDED (IDLE_HOLD_TICKS): a rule whose traffic stops
        # entirely resumes decaying and ages back to healthy
        tr.lat_idle = 0 if delta_n else tr.lat_idle + 1
        hold_lat = 0 < tr.lat_idle <= IDLE_HOLD_TICKS
        lat_decay_f = 1.0 if hold_lat else self.fast_decay
        lat_decay_s = 1.0 if hold_lat else self.slow_decay
        fast_snap = tr.fast_hist.snapshot_and_decay(lat_decay_f)
        slow_snap = tr.slow_hist.snapshot_and_decay(lat_decay_s)

        # ---- node walk: stage deltas, drops, queue peaks
        nodes = list(getattr(topo, "all_nodes", lambda: [])())
        shared_nodes: List[Any] = []
        for st, _entry in getattr(topo, "live_shared", lambda: [])():
            shared_nodes.extend(getattr(st, "nodes", []))
        # data flows shared-source pipeline → own nodes; keep that order
        # for the upstream/downstream backpressure split
        ordered, seen_ids = [], set()
        for n in shared_nodes + nodes:
            if id(n) not in seen_ids:
                seen_ids.add(id(n))
                ordered.append(n)
        stage_us: Dict[str, float] = {s: 0.0 for s in STAGES}
        node_top: Dict[str, Tuple[str, float]] = {}  # node -> (stage, us)
        drops_d = ins_d = 0
        queue_peaks: Dict[str, int] = {}
        new_prev: Dict[str, Dict[str, Any]] = {}
        for node in ordered:
            stats = getattr(node, "stats", None)
            if stats is None or not hasattr(stats, "health_sample"):
                continue
            cur_s = stats.health_sample()
            prev_s = tr.prev_nodes.get(node.name, {})
            if cur_s.get("partial"):
                # lock-free sample lost the race repeatedly: keep the
                # old baseline and skip this node for the tick — using
                # the degraded sample as prev would attribute the node's
                # full cumulative history to the next delta
                new_prev[node.name] = prev_s
                continue
            new_prev[node.name] = cur_s
            covered = 0.0
            best_stage, best_us = None, 0.0
            for stage, us in cur_s["stages"].items():
                d = us - prev_s.get("stages", {}).get(stage, 0)
                if d <= 0:
                    continue
                covered += d
                if stage.startswith("emit[") and stage.endswith("]"):
                    # shared-fold per-member emit stages
                    # (nodes_sharedfold stage="emit[<rule>]"): another
                    # member's emit work is COVERED busy time (keep it
                    # out of the unstaged remainder below) but must not
                    # be attributed to THIS rule's bottleneck
                    if stage[5:-1] != rid:
                        continue
                    canon = "emit_combine"
                else:
                    canon = _STAGE_CANON.get(
                        stage, "emit_combine" if stage.startswith("emit")
                        else "other")
                stage_us[canon] += d
                if d > best_us:
                    best_stage, best_us = canon, d
            rem = (cur_s["busy_us"] - prev_s.get("busy_us", 0)) - covered
            if rem > 0:
                op_type = getattr(node, "op_type", "op")
                if op_type == "source":
                    canon = "decode"
                elif op_type == "sink":
                    canon = "sink"
                elif type(node).__name__ in _EMIT_CLASSES:
                    canon = "emit_combine"
                else:
                    canon = "other"
                stage_us[canon] += rem
                if rem > best_us:
                    best_stage, best_us = canon, rem
            if best_stage is not None:
                node_top[node.name] = (best_stage, best_us)
            drops_d += cur_s["dropped"] - prev_s.get("dropped", 0)
            if getattr(node, "op_type", "") == "source":
                ins_d += cur_s["in"] - prev_s.get("in", 0)
            # queue spikes: enqueue-time high-water since last tick, plus
            # the live depth (covers sustained levels with no enqueues).
            # take_queue_peak_tick is read-and-reset, and shared-subtopo /
            # shared-fold nodes are walked once PER MEMBER RULE in a tick
            # — memoize per node so every member sees the same peak
            # instead of only the first-evaluated one
            peak = self._tick_qpeaks.get(id(node))
            if peak is None:
                peak = 0
                take = getattr(stats, "take_queue_peak_tick", None)
                if take is not None:
                    peak = take()
                q = getattr(node, "inq", None)
                if q is not None:
                    try:
                        peak = max(peak, q.qsize())
                    except Exception:
                        pass
                self._tick_qpeaks[id(node)] = peak
            queue_peaks[node.name] = peak
        tr.prev_nodes = new_prev

        # ---- drop burn (same fast/slow decayed windows, scalar form,
        # same sample-count weighting and observation-indexed decay)
        drops_d = max(drops_d, 0)
        ins_d = max(ins_d, 0)
        tr.fast_drops += drops_d
        tr.slow_drops += drops_d
        tr.fast_in += ins_d
        tr.slow_in += ins_d
        drop_budget = max(slo["max_drop_ratio"], 1e-6)
        drop_ratio_f = tr.fast_drops / max(tr.fast_in, tr.fast_drops, 1.0)
        drop_ratio_s = tr.slow_drops / max(tr.slow_in, tr.slow_drops, 1.0)
        drop_burn_f = _weighted_burn(
            tr.fast_drops, max(tr.fast_in, tr.fast_drops, 1.0), drop_budget)
        drop_burn_s = _weighted_burn(
            tr.slow_drops, max(tr.slow_in, tr.slow_drops, 1.0), drop_budget)
        tr.drop_idle = 0 if (drops_d or ins_d) else tr.drop_idle + 1
        if not 0 < tr.drop_idle <= IDLE_HOLD_TICKS:
            tr.fast_drops *= self.fast_decay
            tr.fast_in *= self.fast_decay
            tr.slow_drops *= self.slow_decay
            tr.slow_in *= self.slow_decay

        # ---- bottleneck attribution + backpressure direction
        total_us = sum(stage_us.values())
        bottleneck: Dict[str, Any] = {"stage": None, "share": 0.0}
        if total_us > 0:
            dom = max(stage_us, key=lambda s: stage_us[s])
            bn_node = None
            bn_us = -1.0
            for name, (stage, us) in node_top.items():
                if stage == dom and us > bn_us:
                    bn_node, bn_us = name, us
            up_names, down_names, split = [], [], False
            for node in ordered:
                if node.name == bn_node:
                    split = True
                    continue
                (down_names if split else up_names).append(node.name)
            up_peak = max([queue_peaks.get(n, 0) for n in up_names],
                          default=0)
            down_peak = max([queue_peaks.get(n, 0) for n in down_names],
                            default=0)
            up_trend = up_peak - max(
                [tr.prev_queue.get(n, 0) for n in up_names], default=0)
            down_trend = down_peak - max(
                [tr.prev_queue.get(n, 0) for n in down_names], default=0)
            if up_peak > max(down_peak, 0) and up_trend >= 0:
                forming = "upstream"
            elif down_peak > 0 and down_trend >= 0:
                forming = "downstream"
            else:
                forming = "none"
            bottleneck = {
                "stage": dom,
                "node": bn_node,
                "share": round(stage_us[dom] / total_us, 4),
                "stage_us": {s: int(v) for s, v in stage_us.items() if v},
                "backpressure": {
                    "forming": forming,
                    "upstream": {"peak": up_peak, "trend": up_trend},
                    "downstream": {"peak": down_peak, "trend": down_trend},
                },
            }
        tr.prev_queue = queue_peaks

        # ---- device/host axis (observability/kernwatch.py): per-tick
        # deltas of the rule's sampled kernel timings split the dominant
        # stage's wall time into device-side compute/transfer vs
        # host-side dispatch, and carry the hottest kernel's roofline
        # utilization — "fold is dominant" becomes "fold is
        # device-compute-bound at 71% of the HBM roof"
        device_time = self._device_axis(rid, tr,
                                        getattr(self, "_tick_kern", None))
        if device_time is not None and bottleneck.get("stage"):
            bottleneck["axis"] = device_time["axis"]
            bottleneck["device_time"] = device_time

        # ---- mesh attribution (observability/meshwatch.py): a sharded
        # rule whose hottest shard absorbs a super-threshold multiple of
        # the mean fold rows is bound by one chip's key range — that
        # outranks stage attribution (the skewed chip IS the dominant
        # stage's critical path). Attribution only: burn math and the
        # health FSM are untouched, so a skewed-but-meeting-SLO rule
        # stays HEALTHY with a shard_skew verdict attached.
        mesh = (getattr(self, "_tick_mesh", None) or {}).get(rid)
        if mesh is not None:
            bottleneck["mesh"] = {
                "skew_ratio": mesh.get("skew_ratio"),
                "hot_shard": mesh.get("hot_shard"),
                "mesh": mesh.get("mesh"),
                "skewed": bool(mesh.get("skewed")),
            }
            if mesh.get("skewed"):
                hot = next(
                    (s for s in mesh.get("shards", [])
                     if s["shard"] == mesh.get("hot_shard")), None)
                total = sum(s["rows"] for s in mesh.get("shards", [])) or 1
                bottleneck["stage"] = "shard_skew"
                bottleneck["node"] = f"shard:{mesh.get('hot_shard')}"
                bottleneck["share"] = round(
                    (hot["rows"] / total) if hot else 0.0, 4)

        # ---- event-time progress (watermark lag, pane occupancy)
        wm_info = self._watermark_probe(rid, ordered, now)

        # ---- verdict: burn thresholds + watermark bound, with hysteresis
        # burn_f/burn_s (per-window max across signals) are the REPORTED
        # fast/slow gauges; the THRESHOLD test is per signal — a signal
        # must burn in BOTH its windows before it escalates, so a fast
        # latency spike coinciding with residual slow-window drop burn
        # cannot degrade a rule neither signal would degrade alone (it
        # would also emit a reason-less transition: the reasons guards
        # below are per signal too)
        burn_f = max(lat_burn_f, drop_burn_f)
        burn_s = max(lat_burn_s, drop_burn_s)
        tr.peak_burn = max(tr.peak_burn, burn_f, burn_s)
        worst = max(min(lat_burn_f, lat_burn_s),
                    min(drop_burn_f, drop_burn_s))
        reasons: List[str] = []
        breach = worst >= self.breach_burn
        degrade = worst >= 1.0
        if min(lat_burn_f, lat_burn_s) >= 1.0:
            reasons.append(
                f"latency burn fast={lat_burn_f:.1f}x slow="
                f"{lat_burn_s:.1f}x (p99 bound {bound}ms)")
        if min(drop_burn_f, drop_burn_s) >= 1.0:
            reasons.append(
                f"drop burn fast={drop_burn_f:.1f}x slow="
                f"{drop_burn_s:.1f}x (budget {slo['max_drop_ratio']})")
        mwl = slo["max_watermark_lag_ms"]
        lag = wm_info.get("lag_ms")
        if mwl and lag is not None:
            if lag > 3 * mwl:
                breach = True
                reasons.append(
                    f"watermark lag {lag}ms > 3x bound {mwl}ms")
            elif lag > mwl:
                degrade = True
                reasons.append(f"watermark lag {lag}ms > bound {mwl}ms")
        target = (BREACHING if breach
                  else DEGRADED if degrade else HEALTHY)
        prev_state = tr.state
        lvl_t, lvl_c = STATE_LEVEL[target], STATE_LEVEL[tr.state]
        if lvl_t > lvl_c:
            tr.up_pend += 1
            # escalate to the MINIMUM level sustained across the whole
            # pending run — a single breach-level spike inside an
            # otherwise-degraded run must not page as breaching (the
            # "up_ticks consecutive ticks above threshold" promise is
            # per level, not per direction)
            tr.up_level = (lvl_t if tr.up_pend == 1
                           else min(tr.up_level, lvl_t))
            tr.down_pend = 0
            if tr.up_pend >= self.up_ticks:
                tr.state = _LEVEL_STATE[tr.up_level]
                tr.up_pend = 0
        elif lvl_t < lvl_c:
            tr.down_pend += 1
            tr.up_pend = 0
            if tr.down_pend >= self.down_ticks:
                tr.state = _LEVEL_STATE[lvl_c - 1]  # step down one level
                tr.down_pend = 0
        else:
            tr.up_pend = 0
            tr.down_pend = 0
        if tr.state != prev_state:
            tr.state_since_ms = now
            tr.ticks_in_state = 0
            from ..runtime.events import recorder

            severity = ("error" if tr.state == BREACHING
                        else "warn" if tr.state == DEGRADED else "info")
            recorder().record(
                "rule_health", rule=rid, severity=severity,
                # ts_ms: we hold self._lock, which mock-clock callbacks
                # also take (_fire -> tick) — record() must not read the
                # clock on our behalf (see FlightRecorder.record)
                ts_ms=now,
                state=tr.state, previous=prev_state,
                burn_fast=round(burn_f, 2), burn_slow=round(burn_s, 2),
                bottleneck=bottleneck.get("stage"),
                watermark_lag_ms=lag,
                **({"reasons": reasons[:3]} if reasons else {}))
        tr.ticks_in_state += 1

        tr.verdict = {
            "rule": rid,
            "state": tr.state,
            "since_ms": tr.state_since_ms,
            "ticks_in_state": tr.ticks_in_state,
            "slo": slo,
            "burn_rate": {
                "fast": round(burn_f, 3), "slow": round(burn_s, 3),
                "latency_fast": round(lat_burn_f, 3),
                "latency_slow": round(lat_burn_s, 3),
                "drop_fast": round(drop_burn_f, 3),
                "drop_slow": round(drop_burn_s, 3),
                "breach_threshold": self.breach_burn,
            },
            "latency": {
                "window_fast": fast_snap, "window_slow": slow_snap,
                "violating_fast": round(frac_f, 4) if n_f else 0.0,
                "violating_slow": round(frac_s, 4) if n_s else 0.0,
                # window evidence mass — what the burns were weighted by
                "samples_fast": n_f, "samples_slow": n_s,
                "tick_samples": delta_n,
            },
            "drops": {
                "tick_dropped": drops_d, "tick_offered": ins_d,
                "ratio_fast": round(drop_ratio_f, 5),
                "ratio_slow": round(drop_ratio_s, 5),
            },
            "bottleneck": bottleneck,
            "watermark": wm_info,
            "hbm": self._rule_hbm(rid),
            **({"reasons": reasons} if reasons else {}),
        }

    @staticmethod
    def _device_axis(rid: str, tr: "_RuleTrack",
                     ops: Optional[Dict[str, Any]] = None
                     ) -> Optional[Dict[str, Any]]:
        """Tick delta of the rule's kernwatch counters → device vs host
        attribution. None when no kernel was sampled this tick (the axis
        is only asserted on evidence, never inferred). `ops` is the
        tick-shared kernwatch.rule_ops_all() map; None falls back to a
        single-rule fetch (direct callers, tests)."""
        if ops is not None:
            cur = {op: dict(v) for op, v in (ops.get(rid) or {}).items()}
        else:
            from . import kernwatch

            try:
                cur = kernwatch.rule_ops(rid)
            except Exception:
                return None
        prev = tr.prev_kern
        tr.prev_kern = cur
        dev_d = disp_d = 0.0
        samp_d = 0
        top_op: Optional[str] = None
        top_dev = -1.0
        for op, c in cur.items():
            p = prev.get(op, {})
            sd = c["samples"] - p.get("samples", 0)
            if sd <= 0:
                continue
            dd = max(c["device_us"] - p.get("device_us", 0.0), 0.0)
            pd = max(c["dispatch_us"] - p.get("dispatch_us", 0.0), 0.0)
            samp_d += sd
            dev_d += dd
            disp_d += pd
            if dd > top_dev:
                top_dev, top_op = dd, op
        if samp_d <= 0:
            return None
        total = dev_d + disp_d
        share = dev_d / total if total > 0 else 0.0
        out: Dict[str, Any] = {
            "axis": "device" if share >= 0.5 else "host",
            "device_share": round(share, 4),
            "device_us": int(dev_d),
            "dispatch_us": int(disp_d),
            "samples": int(samp_d),
            "op": top_op,
        }
        top = cur.get(top_op) or {}
        if top.get("roofline_util") is not None:
            out["roofline_util"] = top["roofline_util"]
            out["bound"] = top.get("bound")
        return out

    @staticmethod
    def _watermark_probe(rid: str, nodes: List[Any],
                         now: int) -> Dict[str, Any]:
        """Event-time progress read off the rule's live nodes. Lazy class
        imports — observability must not import the runtime at module
        load. Shared-fold members report THEIR OWN emit cursor (lag is a
        per-rule fact even when the pane store is shared)."""
        from ..runtime.nodes_fused import FusedWindowAggNode
        from ..runtime.nodes_sharedfold import SharedFoldNode
        from ..runtime.nodes_window import WatermarkNode, WindowNode

        wm_ts: Optional[int] = None
        occupancy: Optional[float] = None
        buffered = 0
        cursor: Optional[int] = None
        event_time = False
        for node in nodes:
            if isinstance(node, WatermarkNode):
                ts = node.watermark_ts()
                if ts is not None and (wm_ts is None or ts > wm_ts):
                    wm_ts = ts
                event_time = True
            elif isinstance(node, SharedFoldNode):
                occ = node.pane_occupancy()
                occupancy = occ if occupancy is None else max(occupancy,
                                                              occ)
                cur = node.member_cursor_ms(rid)
                if cur is not None:
                    cursor = cur
                event_time = event_time or node.is_event_time
            elif isinstance(node, FusedWindowAggNode):
                occ = node.pane_occupancy()
                if occ is not None:
                    occupancy = (occ if occupancy is None
                                 else max(occupancy, occ))
                event_time = event_time or node.is_event_time
            elif isinstance(node, WindowNode):
                buffered += node.occupancy_rows()
                event_time = event_time or node.is_event_time
        lag = max(now - wm_ts, 0) if wm_ts is not None else None
        out: Dict[str, Any] = {"event_time": event_time, "lag_ms": lag,
                               "watermark_ts": wm_ts,
                               "buffered_rows": buffered}
        if occupancy is not None:
            out["pane_occupancy"] = round(occupancy, 4)
        if cursor is not None:
            out["emit_cursor_ms"] = cursor
        return out

    @staticmethod
    def _rule_hbm(rid: str) -> Dict[str, Any]:
        from . import memwatch

        total = 0
        for (component, rule), n in memwatch.registry().aggregate().items():
            if rule == rid:
                total += n
        return {"bytes": total}

    # ---------------------------------------------------------------- queries
    def verdicts(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {rid: tr.verdict for rid, tr in self._tracks.items()
                    if tr.verdict is not None}

    def has_track(self, rule_id: str) -> bool:
        """True once the evaluator has attempted this rule at least once
        (a track exists even when evaluation raised — REST callers use
        this to avoid forcing a tick per request for a rule that will
        never produce a verdict)."""
        with self._lock:
            return rule_id in self._tracks

    def rule_health(self, rule_id: str,
                    refresh_if_missing: bool = True) -> Optional[Dict[str, Any]]:
        """Last verdict for one rule; when the evaluator has never seen
        the rule (installed after it, or never ticked) one synchronous
        tick seeds it. A rule with a track but no verdict (its eval
        raises) does NOT re-tick — off-cadence ticks decay the burn
        windows and collapse the FSM hysteresis for every other rule, so
        a polled endpoint must not be able to trigger them repeatedly."""
        with self._lock:
            tr = self._tracks.get(rule_id)
        if tr is None and refresh_if_missing:
            # tick() OUTSIDE our lock: it reads the engine clock first,
            # and a mock advance fires _fire -> tick while holding the
            # clock lock — ticking reentrantly under self._lock was the
            # evaluator half of the clock/health ABBA utils/lockcheck.py
            # caught on day one (clock orders before the evaluator lock).
            # _seed_mu keeps the seeding single-flight: concurrent polls
            # for an untracked rule must produce ONE off-cadence tick,
            # not one each (off-cadence ticks decay every rule's burn
            # windows — see the docstring above)
            with self._seed_mu:
                with self._lock:
                    tr = self._tracks.get(rule_id)
                if tr is None:
                    self.tick()
                    with self._lock:
                        tr = self._tracks.get(rule_id)
        with self._lock:
            return tr.verdict if tr is not None else None

    def peak_burn(self, rule_id: str) -> float:
        with self._lock:
            tr = self._tracks.get(rule_id)
            return round(tr.peak_burn, 3) if tr is not None else 0.0

    def hbm_trend(self) -> Dict[str, Any]:
        """Engine HBM headroom trend off the per-tick memwatch samples."""
        with self._lock:
            samples = list(self._hbm)
        if not samples:
            return {"bytes": 0, "trend_bytes_per_min": 0.0, "samples": 0}
        cur = samples[-1][1]
        trend = 0.0
        if len(samples) >= 2:
            dt_ms = samples[-1][0] - samples[0][0]
            if dt_ms > 0:
                trend = (cur - samples[0][1]) * 60_000.0 / dt_ms
        return {"bytes": cur, "trend_bytes_per_min": round(trend, 1),
                "samples": len(samples)}

    def diagnostics(self) -> Dict[str, Any]:
        """The GET /diagnostics/health payload."""
        return {
            "evaluator": {
                "interval_ms": self.interval_ms,
                "ticks": self.ticks,
                "last_tick_us": round(self.last_tick_us, 1),
                "up_ticks": self.up_ticks,
                "down_ticks": self.down_ticks,
                "breach_burn": self.breach_burn,
            },
            "hbm": self.hbm_trend(),
            "rules": self.verdicts(),
        }


# ------------------------------------------------------------- singleton
_evaluator: Optional[HealthEvaluator] = None
_install_lock = threading.Lock()


def install(rules_fn: Callable[[], List[tuple]],
            interval_ms: int = DEFAULT_INTERVAL_MS,
            start: bool = True, **kw) -> HealthEvaluator:
    """Install (replacing any prior) the engine-wide evaluator. The REST
    server installs one over its rule registry at boot."""
    global _evaluator
    with _install_lock:
        if _evaluator is not None:
            _evaluator.stop()
        _evaluator = HealthEvaluator(rules_fn, interval_ms=interval_ms,
                                     **kw)
        ev = _evaluator
    if start:
        ev.start()
    return ev


def evaluator() -> Optional[HealthEvaluator]:
    return _evaluator


def rule_verdict(rule_id: str) -> Optional[Dict[str, Any]]:
    """Last verdict WITHOUT forcing a tick — status JSON enrichment must
    not pay evaluation cost per call."""
    ev = _evaluator
    if ev is None:
        return None
    return ev.rule_health(rule_id, refresh_if_missing=False)


def reset() -> None:
    """Test hook: stop and drop the installed evaluator."""
    global _evaluator
    with _install_lock:
        if _evaluator is not None:
            _evaluator.stop()
        _evaluator = None


# -------------------------------------------------------- Prometheus view
def render_prometheus(out: List[str], esc) -> None:
    """Append the health-plane families to a /metrics scrape."""
    ev = _evaluator
    if ev is None:
        return
    verdicts = sorted(ev.verdicts().items())
    out.append("# TYPE kuiper_rule_health gauge")
    out.append("# HELP kuiper_rule_health verdict per rule "
               "(0 healthy, 1 degraded, 2 breaching)")
    for rid, v in verdicts:
        out.append(f'kuiper_rule_health{{rule="{esc(rid)}"}} '
                   f"{STATE_LEVEL.get(v['state'], 0)}")
    out.append("# TYPE kuiper_slo_burn_rate gauge")
    out.append("# HELP kuiper_slo_burn_rate SLO error-budget burn "
               "multiple per rule and window (>=1 unsustainable)")
    for rid, v in verdicts:
        br = v["burn_rate"]
        for window in ("fast", "slow"):
            out.append(
                f'kuiper_slo_burn_rate{{rule="{esc(rid)}",'
                f'window="{window}"}} {br[window]}')
    out.append("# TYPE kuiper_watermark_lag_ms gauge")
    out.append("# HELP kuiper_watermark_lag_ms event-time watermark lag "
               "behind the engine clock per rule (ms)")
    for rid, v in verdicts:
        lag = v["watermark"].get("lag_ms")
        if lag is not None:
            out.append(
                f'kuiper_watermark_lag_ms{{rule="{esc(rid)}"}} {lag}')
    out.append("# TYPE kuiper_bottleneck_stage gauge")
    out.append("# HELP kuiper_bottleneck_stage dominant pipeline stage "
               "per rule (value = its share of stage time this tick)")
    for rid, v in verdicts:
        bn = v["bottleneck"]
        if bn.get("stage"):
            out.append(
                f'kuiper_bottleneck_stage{{rule="{esc(rid)}",'
                f'stage="{esc(bn["stage"])}"}} {bn["share"]}')


# ------------------------------------------------------- profile capture
#: hard cap on one capture — the endpoint must stay "bounded" even when
#: a caller asks for minutes
PROFILE_MAX_MS = 30_000
_profile_lock = threading.Lock()


def capture_profile(duration_ms: int = 1000,
                    out_dir: Optional[str] = None) -> Dict[str, Any]:
    """On-demand deep capture: a bounded `jax.profiler.trace` plus a
    devwatch signature dump, memwatch snapshot, and current health
    verdicts, written into one bundle directory. Wall-clock bounded (the
    profiler measures real time; the engine clock may be mocked). One
    capture at a time — the profiler is a process-global resource."""
    dur_ms = min(max(int(duration_ms), 50), PROFILE_MAX_MS)
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a profile capture is already running")
    try:
        if out_dir is None:
            from ..utils.config import get_config

            out_dir = os.path.join(
                get_config().store.path, "profiles",
                # kuiperlint: ignore[clock-discipline]: bundle dirs need unique wall timestamps — a frozen mock clock would collide captures
                f"profile_{int(_time.time() * 1000)}")
        os.makedirs(out_dir, exist_ok=True)
        result: Dict[str, Any] = {"dir": out_dir, "duration_ms": dur_ms}
        t0 = _time.perf_counter()
        try:
            import jax

            jax.profiler.start_trace(out_dir)
            try:
                # kuiperlint: ignore[clock-discipline]: jax.profiler.trace records wall time; timex.sleep under a mock clock would end the capture instantly
                _time.sleep(dur_ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
            result["trace"] = "ok"
        except Exception as exc:
            # a capture with no device trace still carries the dumps —
            # degrade, never fail the endpoint
            result["trace"] = f"unavailable: {exc}"
        result["captured_s"] = round(_time.perf_counter() - t0, 3)
        from . import devwatch, memwatch

        dump = {
            # kuiperlint: ignore[clock-discipline]: postmortem bundles are correlated against external logs by wall time, not engine time
            "generated_at_ms": int(_time.time() * 1000),
            "xla": {
                "totals": devwatch.registry().totals(),
                "sites": [{**w.snapshot(),
                           "signatures": w.signature_dump()}
                          for w in devwatch.registry().watches()],
            },
            "memory": memwatch.diagnostics(),
        }
        ev = _evaluator
        if ev is not None:
            dump["health"] = ev.diagnostics()
        dump_path = os.path.join(out_dir, "devwatch_dump.json")
        with open(dump_path, "w") as f:
            json.dump(dump, f, indent=2, default=str)
        files = []
        for root, _dirs, names in os.walk(out_dir):
            for name in names:
                files.append(os.path.relpath(os.path.join(root, name),
                                             out_dir))
        result["files"] = sorted(files)
        return result
    finally:
        _profile_lock.release()
