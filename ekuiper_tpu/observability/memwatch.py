"""Device/host memory accounting — who owns the HBM.

Every device-resident component in the engine — the sliding `_dev_ring`
batch cache (runtime/nodes_fused.py), group-by partial state
(ops/groupby.py), shared pane rings (ops/panestore.py), sketches
(ops/sketches.py) — allocates against one physical HBM pool with only
per-component budgets (`sliding_dev_ring_mb`). Before this module the
ENGINE-WIDE footprint was invisible: a slow leak (unrecycled panes, a
key-table that never stops growing) looked like throughput decay until
the allocator OOM'd. Components now register a byte probe here and the
observability layers read them all at once:

- `kuiper_device_bytes{component,rule}` Prometheus gauges,
- `GET /diagnostics/memory` (per-component rows + a `jax.live_arrays()`
  sample — the allocator's OWN view, which catches anything that forgot
  to register).

Registration is weakref-based: a component registers `(component, rule,
owner, fn)` where `fn(owner) -> bytes`; when the owner is garbage
collected the row disappears on the next snapshot. No unregister calls
on close paths to forget, no leak when one is missed. Probes run only at
scrape/diagnostics time (pull model) — the hot path pays nothing.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, List, Optional


class _Probe:
    __slots__ = ("component", "rule", "owner_ref", "fn")

    def __init__(self, component: str, rule: str, owner: Any,
                 fn: Callable[[Any], int]) -> None:
        self.component = component
        self.rule = rule
        self.owner_ref = weakref.ref(owner)
        self.fn = fn


class MemRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._probes: List[_Probe] = []

    def register(self, component: str, owner: Any,
                 fn: Callable[[Any], int],
                 rule: Optional[str] = None) -> None:
        """Register a live-byte probe. `fn(owner)` must be cheap (an
        attribute read or a small sum) — it runs on every scrape. `rule`
        defaults to the registering thread's rule context."""
        if rule is None:
            from ..utils.rulelog import current_rule

            rule = current_rule() or ""
        with self._lock:
            self._probes.append(_Probe(component, rule, owner, fn))

    # ---------------------------------------------------------------- queries
    def snapshot(self) -> List[Dict[str, Any]]:
        """[{component, rule, bytes}] for every live probe; dead owners are
        dropped in place."""
        with self._lock:
            probes = list(self._probes)
        out: List[Dict[str, Any]] = []
        dead: List[_Probe] = []
        for p in probes:
            owner = p.owner_ref()
            if owner is None:
                dead.append(p)
                continue
            try:
                n = int(p.fn(owner))
            except Exception:
                continue  # a probe must never fail a scrape
            out.append({"component": p.component, "rule": p.rule,
                        "bytes": n})
        if dead:
            with self._lock:
                self._probes = [p for p in self._probes if p not in dead]
        return out

    def aggregate(self) -> Dict[tuple, int]:
        """{(component, rule): bytes} — one gauge line per pair."""
        agg: Dict[tuple, int] = {}
        for row in self.snapshot():
            key = (row["component"], row["rule"])
            agg[key] = agg.get(key, 0) + row["bytes"]
        return agg

    def total_bytes(self) -> int:
        return sum(r["bytes"] for r in self.snapshot())

    def clear(self) -> None:
        """Test hook."""
        with self._lock:
            self._probes.clear()


_registry = MemRegistry()


def registry() -> MemRegistry:
    return _registry


def register(component: str, owner: Any, fn: Callable[[Any], int],
             rule: Optional[str] = None) -> None:
    _registry.register(component, owner, fn, rule=rule)


def jax_sample() -> Dict[str, Any]:
    """The allocator's own view: every live jax.Array's bytes, by backend.
    Ground truth against the registered probes — a large gap means a
    component is allocating device memory without reporting it."""
    try:
        import jax

        arrays = jax.live_arrays()
        total = 0
        for a in arrays:
            try:
                total += int(a.nbytes)
            except Exception:
                pass
        return {
            "backend": jax.default_backend(),
            "live_arrays": len(arrays),
            "live_bytes": total,
        }
    except Exception as exc:  # no jax / backend not initialized
        return {"backend": "unavailable", "live_arrays": 0,
                "live_bytes": 0, "error": str(exc)}


def diagnostics() -> Dict[str, Any]:
    """The GET /diagnostics/memory payload."""
    rows = _registry.snapshot()
    return {
        "components": sorted(
            rows, key=lambda r: (-r["bytes"], r["component"], r["rule"])),
        "registered_bytes_total": sum(r["bytes"] for r in rows),
        "jax": jax_sample(),
    }


def render_prometheus(out: List[str], esc) -> None:
    """Append kuiper_device_bytes gauges to a /metrics scrape: one line
    per (component, rule) plus the jax live-array sample under
    component="jax_live_arrays" (engine-wide, so rule="__engine__")."""
    name = "kuiper_device_bytes"
    out.append(f"# TYPE {name} gauge")
    out.append(f"# HELP {name} device/host bytes held per component "
               "(self-reported; jax_live_arrays = allocator view)")
    for (component, rule), n in sorted(_registry.aggregate().items()):
        out.append(
            f'{name}{{component="{esc(component)}",'
            f'rule="{esc(rule or "__engine__")}"}} {n}')
    js = jax_sample()
    out.append(
        f'{name}{{component="jax_live_arrays",rule="__engine__"}} '
        f"{js['live_bytes']}")
