"""Prometheus text exposition for engine + per-rule/per-op metrics
(analogue of metrics/metrics.go:64-88 + internal/server/prome_init.go).

No client library: the text format is lines of
`name{labels} value` with `# TYPE` headers — rendered directly from the
rules' StatManagers on each scrape, so there is no second bookkeeping
system to keep in sync (the reference wires its StatManager into
promauto gauges the same way)."""
from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

_STATE_VALUES = {"running": 1, "stopped": 0}

_COUNTERS = (
    ("records_in_total", "records_in"),
    ("records_out_total", "records_out"),
    ("exceptions_total", "exceptions"),
)
_GAUGES = (
    ("buffer_length", "buffer_length"),
    ("process_latency_us", "process_latency_us"),
)

_START_TIME = time.time()


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")


def render(rule_registry) -> str:
    """Scrape callback: rule states + every node's StatManager."""
    out: List[str] = []
    out.append("# TYPE kuiper_rule_status gauge")
    out.append("# HELP kuiper_rule_status 1 running, 0 stopped")
    rows: List[Tuple[str, Any]] = []
    for entry in rule_registry.list():
        rule_id = entry["id"]
        out.append(
            f'kuiper_rule_status{{rule="{_esc(rule_id)}"}} '
            f"{_STATE_VALUES.get(str(entry.get('status', '')).lower(), 0)}")
        rs = rule_registry.state(rule_id)
        topo = rs.topo if rs is not None else None
        if topo is not None:
            for node in topo.all_nodes():
                rows.append((rule_id, node))
            for subtopo, _ in topo._live_shared:
                for node in subtopo.nodes:
                    rows.append((rule_id, node))
    for mname, attr in _COUNTERS:
        out.append(f"# TYPE kuiper_op_{mname} counter")
        for rule_id, node in rows:
            out.append(
                f'kuiper_op_{mname}{{rule="{_esc(rule_id)}",'
                f'op="{_esc(node.name)}",type="{_esc(node.op_type)}"}} '
                f"{getattr(node.stats, attr)}")
    for mname, attr in _GAUGES:
        out.append(f"# TYPE kuiper_op_{mname} gauge")
        for rule_id, node in rows:
            out.append(
                f'kuiper_op_{mname}{{rule="{_esc(rule_id)}",'
                f'op="{_esc(node.name)}",type="{_esc(node.op_type)}"}} '
                f"{getattr(node.stats, attr)}")
    # per-stage pipeline timings (decode/upload/fold): the ingest-pipeline
    # balance — which stage a node's wall time goes to — read straight off
    # the StatManagers' stage accounting
    stage_rows = [(rule_id, node, stage, st)
                  for rule_id, node in rows
                  for stage, st in
                  node.stats.snapshot()["stage_timings"].items()]
    for mname, key in (("stage_us_total", "total_us"),
                       ("stage_calls_total", "calls"),
                       ("stage_rows_total", "rows")):
        out.append(f"# TYPE kuiper_op_{mname} counter")
        for rule_id, node, stage, st in stage_rows:
            out.append(
                f'kuiper_op_{mname}{{rule="{_esc(rule_id)}",'
                f'op="{_esc(node.name)}",type="{_esc(node.op_type)}",'
                f'stage="{_esc(stage)}"}} {st[key]}')
    # ingest-pipeline occupancy: ring depth (decoded batches awaiting their
    # emission turn) and decode-queue depth (jobs awaiting a worker) per
    # pooled source — backpressure becomes a visible gauge instead of an
    # inference from throughput dips
    pool_rows = []
    for rule_id, node in rows:
        depths_fn = getattr(node, "pool_depths", None)
        if depths_fn is None:
            continue
        depths = depths_fn()
        if depths is not None:
            pool_rows.append((rule_id, node, depths))
    for mname, idx, help_txt in (
            ("ingest_ring_depth", 0,
             "decoded batches in the ordered ring (submitted, not emitted)"),
            ("decode_pool_queue", 1,
             "decode jobs waiting for a pool worker")):
        out.append(f"# TYPE kuiper_{mname} gauge")
        out.append(f"# HELP kuiper_{mname} {help_txt}")
        for rule_id, node, depths in pool_rows:
            out.append(
                f'kuiper_{mname}{{rule="{_esc(rule_id)}",'
                f'op="{_esc(node.name)}"}} {depths[idx]}')
    out.append("# TYPE kuiper_uptime_seconds gauge")
    out.append(f"kuiper_uptime_seconds {time.time() - _START_TIME:.1f}")
    return "\n".join(out) + "\n"


class TextResponse(str):
    """Marker: REST dispatch replies text/plain instead of json."""

    content_type = "text/plain; version=0.0.4; charset=utf-8"
