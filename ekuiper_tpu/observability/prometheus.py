"""Prometheus text exposition for engine + per-rule/per-op metrics
(analogue of metrics/metrics.go:64-88 + internal/server/prome_init.go).

No client library: the text format is lines of
`name{labels} value` with `# TYPE`/`# HELP` headers — rendered directly
from the rules' StatManagers on each scrape, so there is no second
bookkeeping system to keep in sync (the reference wires its StatManager
into promauto gauges the same way).

Every metric family carries a HELP line and is cataloged in
docs/OBSERVABILITY.md; tools/check_metrics.py lints that invariant from
the tier-1 suite. Nodes owned by a SHARED subtopo (one physical source
serving N rules) are emitted exactly once, under rule="__shared__" —
per-rule emission double-counted their records_*_total in any PromQL sum.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

from .histogram import E2E_BOUNDS_MS, render_prom_histogram

_STATE_VALUES = {"running": 1, "stopped": 0}

#: (metric name == StatManager snapshot key, help) — values come off the
#: per-node snapshot taken once per scrape, so every line of one node is
#: a consistent cut
_COUNTERS = (
    ("records_in_total", "items received by the op"),
    ("records_out_total", "items emitted by the op"),
    ("exceptions_total", "per-item errors swallowed by the op"),
)
_GAUGES = (
    ("buffer_length", "input queue occupancy"),
    ("process_latency_us", "last dispatch latency (engine clock, us)"),
)
_STAGES = (
    ("stage_us_total", "total_us", "cumulative wall time per pipeline stage"),
    ("stage_calls_total", "calls", "invocations per pipeline stage"),
    ("stage_rows_total", "rows", "rows handled per pipeline stage"),
)
#: per-op latency-distribution quantiles exported per scrape — keys into
#: the StatManager snapshot's histogram summaries (computed once per node
#: per scrape, reused here instead of re-scanning the histograms). Label
#: name is `q`, NOT the reserved `quantile` (promtool flags that label on
#: anything but summary-typed metrics).
_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))

#: rule label shared nodes are emitted under (matches the subtopo's
#: rule context, runtime/subtopo.py _FanoutTopoShim)
SHARED_RULE_LABEL = "__shared__"

# kuiperlint: ignore[clock-discipline]: process uptime is wall-clock by definition — mocking it would misreport restarts to operators
_START_TIME = time.time()


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")


def _family(out: List[str], name: str, mtype: str, help_txt: str) -> None:
    out.append(f"# TYPE {name} {mtype}")
    out.append(f"# HELP {name} {help_txt}")


def render(rule_registry) -> str:
    """Scrape callback: rule states + every node's StatManager."""
    out: List[str] = []
    _family(out, "kuiper_rule_status", "gauge", "1 running, 0 stopped")
    rows: List[Tuple[str, Any]] = []
    shared_nodes: Dict[int, Any] = {}  # id(node) -> node, emitted ONCE
    e2e_rows: List[Tuple[str, Any]] = []  # (rule_id, LatencyHistogram)
    for entry in rule_registry.list():
        rule_id = entry["id"]
        out.append(
            f'kuiper_rule_status{{rule="{_esc(rule_id)}"}} '
            f"{_STATE_VALUES.get(str(entry.get('status', '')).lower(), 0)}")
        rs = rule_registry.state(rule_id)
        topo = rs.topo if rs is not None else None
        if topo is not None:
            for node in topo.all_nodes():
                rows.append((rule_id, node))
            for subtopo, _ in topo.live_shared():
                for node in subtopo.nodes:
                    shared_nodes.setdefault(id(node), node)
            e2e_rows.append((rule_id, topo.e2e_hist))
    rows.extend((SHARED_RULE_LABEL, node) for node in shared_nodes.values())
    snaps = [(rule_id, node, node.stats.snapshot()) for rule_id, node in rows]

    def op_labels(rule_id: str, node: Any) -> str:
        return (f'rule="{_esc(rule_id)}",op="{_esc(node.name)}",'
                f'type="{_esc(node.op_type)}"')

    for mname, help_txt in _COUNTERS:
        _family(out, f"kuiper_op_{mname}", "counter", help_txt)
        for rule_id, node, snap in snaps:
            out.append(f"kuiper_op_{mname}{{{op_labels(rule_id, node)}}} "
                       f"{snap[mname]}")
    for mname, help_txt in _GAUGES:
        _family(out, f"kuiper_op_{mname}", "gauge", help_txt)
        for rule_id, node, snap in snaps:
            out.append(f"kuiper_op_{mname}{{{op_labels(rule_id, node)}}} "
                       f"{snap[mname]}")
    # drop taxonomy (utils/metrics.py inc_dropped): data discarded BY
    # DESIGN, labeled by reason — buffer_full (drop-oldest backpressure),
    # pane_recycle, decode_error, stale_watermark, shed_qos (SLO-driven
    # shedding, runtime/control.py). Distinct from exceptions_total,
    # which counts operator ERRORS only.
    _family(out, "kuiper_node_dropped_total", "counter",
            "items discarded by design, labeled by reason (buffer_full/"
            "pane_recycle/decode_error/stale_watermark/shed_qos)")
    for rule_id, node, snap in snaps:
        for reason, n in sorted(snap["dropped_total"].items()):
            out.append(
                f"kuiper_node_dropped_total{{{op_labels(rule_id, node)},"
                f'reason="{_esc(reason)}"}} {n}')
    # per-edge queue depth: the node's input queue IS its fan-in edge
    # set's buffer (one bounded queue per node). Reported as the MAX of
    # the live occupancy and the enqueue-time high-water mark since the
    # last scrape (StatManager.note_queue_depth) — a backpressure spike
    # that fills and drains BETWEEN scrapes (or between health-evaluator
    # ticks) would otherwise be invisible to burn-rate math
    _family(out, "kuiper_node_queue_depth", "gauge",
            "peak input-queue occupancy since last scrape "
            "(enqueue-time high-water mark, floor = live occupancy)")
    for rule_id, node, _snap in snaps:
        q = getattr(node, "inq", None)
        if q is not None:
            take = getattr(node.stats, "take_queue_peak_scrape", None)
            peak = take() if take is not None else 0
            out.append(
                f"kuiper_node_queue_depth{{{op_labels(rule_id, node)}}} "
                f"{max(q.qsize(), peak)}")
    # per-op latency DISTRIBUTIONS (observability/histogram.py): dispatch
    # busy time and input-queue wait as quantile gauges — the per-op view
    # of the tail the e2e histogram aggregates per rule
    for mname, snap_key, help_txt in (
            ("process_latency_quantile_us", "process_latency_us_hist",
             "dispatch busy-time percentile (us, log-bucketed histogram)"),
            ("queue_wait_quantile_us", "queue_wait_us_hist",
             "input-queue wait percentile (us, log-bucketed histogram)")):
        _family(out, f"kuiper_op_{mname}", "gauge", help_txt)
        for rule_id, node, snap in snaps:
            summary = snap[snap_key]
            for key, qlabel in _QUANTILES:
                out.append(
                    f"kuiper_op_{mname}{{{op_labels(rule_id, node)},"
                    f'q="{qlabel}"}} {summary[key]}')
    # per-stage pipeline timings (decode/ring/upload/fold): the ingest-
    # pipeline balance — which stage a node's wall time goes to — read
    # straight off the StatManagers' stage accounting
    stage_rows = [(rule_id, node, stage, st)
                  for rule_id, node, snap in snaps
                  for stage, st in snap["stage_timings"].items()]
    for mname, key, help_txt in _STAGES:
        _family(out, f"kuiper_op_{mname}", "counter", help_txt)
        for rule_id, node, stage, st in stage_rows:
            out.append(
                f"kuiper_op_{mname}{{{op_labels(rule_id, node)},"
                f'stage="{_esc(stage)}"}} {st[key]}')
    # ingest-pipeline occupancy: ring depth (decoded batches awaiting their
    # emission turn) and decode-queue depth (jobs awaiting a worker) per
    # pooled source — backpressure becomes a visible gauge instead of an
    # inference from throughput dips
    pool_rows = []
    for rule_id, node in rows:
        depths_fn = getattr(node, "pool_depths", None)
        if depths_fn is None:
            continue
        depths = depths_fn()
        if depths is not None:
            pool_rows.append((rule_id, node, depths))
    for mname, idx, help_txt in (
            ("ingest_ring_depth", 0,
             "decoded batches in the ordered ring (submitted, not emitted)"),
            ("decode_pool_queue", 1,
             "decode jobs waiting for a pool worker")):
        _family(out, f"kuiper_{mname}", "gauge", help_txt)
        for rule_id, node, depths in pool_rows:
            out.append(
                f'kuiper_{mname}{{rule="{_esc(rule_id)}",'
                f'op="{_esc(node.name)}"}} {depths[idx]}')
    # shared pane folds (runtime/nodes_sharedfold.py): pool-level gauges —
    # members per store and the fold-dedup ratio (1 - folds run / folds N
    # private rules would have run). The store node's own op metrics (incl.
    # the per-rule emit-combine stage timings, stage="emit[<rule>]") ride
    # the rule="__shared__" rows above via each rider's live_shared()
    # nodes, so only the pool-level aggregates are emitted here.
    from ..runtime import nodes_sharedfold as _sharedfold

    fold_stores = _sharedfold.live_stores()
    for mname, mtype, help_txt, value in (
            ("kuiper_shared_fold_rules", "gauge",
             "member rules riding each shared pane fold",
             lambda st: st.member_count()),
            ("kuiper_shared_fold_dedup_ratio", "gauge",
             "1 - device folds run / folds N private rules would have run",
             lambda st: round(st.fold_dedup_ratio(), 4)),
            ("kuiper_shared_fold_windows_total", "counter",
             "per-rule windows emitted from shared pane folds",
             lambda st: st.windows_emitted)):
        _family(out, mname, mtype, help_txt)
        for st in fold_stores:
            out.append(f'{mname}{{op="{_esc(st.name)}"}} {value(st)}')
    # the SLO headline: per-rule ingest→emit latency as a real Prometheus
    # histogram (_bucket/_sum/_count with le labels) — histogram_quantile()
    # over it answers "is p99 emit under 50ms" directly
    _family(out, "kuiper_rule_e2e_latency_ms", "histogram",
            "ingest->emit end-to-end latency per rule (ms)")
    for rule_id, hist in e2e_rows:
        render_prom_histogram(
            out, "kuiper_rule_e2e_latency_ms", f'rule="{_esc(rule_id)}"',
            hist, E2E_BOUNDS_MS)
    # engine-health planes (devwatch: XLA trace-vs-hit accounting;
    # kernwatch: sampled device time + roofline; memwatch: per-component
    # device/host byte probes) — module-global registries, so they render
    # once per scrape, not per rule
    from . import devwatch, health, kernwatch, memwatch

    devwatch.render_prometheus(out, _esc)
    kernwatch.render_prometheus(out, _esc)
    memwatch.render_prometheus(out, _esc)
    # AOT executable cache (runtime/aotcache.py): pre-built-executable
    # hit/miss/build accounting + the warmup-failure counter — the
    # zero-compile-serving plane's scrape surface
    from ..runtime import aotcache as _aotcache

    _aotcache.render_prometheus(out, _esc)
    # tiered key state (ops/tierstore.py): demote/promote counters,
    # cold-tier residency and host arena bytes per tiered rule
    from ..ops import tierstore

    tierstore.render_prometheus(out, _esc)
    # multi-chip sharded serving (parallel/sharded.py): per-shard fold
    # rows and key occupancy for every live mesh kernel
    from ..parallel import sharded as _sharded

    _sharded.render_prometheus(out, _esc)
    # mesh attribution (observability/meshwatch.py): per-rule shard skew
    # ratio + rows/s, collective-vs-compute split of the sharded fold
    # sites — observes the shard registry at scrape time
    from . import meshwatch as _meshwatch

    _meshwatch.render_prometheus(out, _esc)
    # telemetry timeline (observability/timeline.py): on-disk segment
    # count/bytes of the durable snapshot ring (absent when none is
    # installed)
    from . import timeline as _timeline

    _timeline.render_prometheus(out, _esc)
    # relational tier (ops/joinring.py, ops/segscan.py): join-ring rows,
    # matches, per-window host fallbacks and ring bytes; segscan rows
    # and partial spills per rule
    from ..ops import joinring as _joinring
    from ..ops import segscan as _segscan

    _joinring.render_prometheus(out, _esc)
    _segscan.render_prometheus(out, _esc)
    # expression host fallbacks (sql/compiler.py counters): plan-time
    # count of expressions routed to the row interpreter, by structured
    # NotVectorizable reason — the metric the health plane's bottleneck
    # attribution pairs with the "host_expr" stage
    from ..sql.compiler import host_fallback_counts

    _family(out, "kuiper_expr_host_fallback_total", "counter",
            "expressions that fell back to the host row interpreter at "
            "plan time, by NotVectorizable reason")
    for reason, n in sorted((host_fallback_counts()
                             or {"none": 0}).items()):
        out.append(
            f'kuiper_expr_host_fallback_total{{reason="{_esc(reason)}"}} '
            f"{n}")
    # health plane (observability/health.py): per-rule verdict, SLO burn
    # rate, watermark lag, bottleneck stage — computed at evaluator ticks,
    # rendered from the last verdicts (a scrape never forces a tick)
    health.render_prometheus(out, _esc)
    # QoS control plane (runtime/control.py): admission decisions, rows
    # shed per rule/qos class, autosize action count — rendered from the
    # installed controller's counters (absent when none is installed)
    from ..runtime import control as _control

    _control.render_prometheus(out, _esc)
    _family(out, "kuiper_uptime_seconds", "gauge",
            "seconds since engine start")
    # kuiperlint: ignore[clock-discipline]: wall-clock pair of _START_TIME above
    out.append(f"kuiper_uptime_seconds {time.time() - _START_TIME:.1f}")
    return "\n".join(out) + "\n"


class TextResponse(str):
    """Marker: REST dispatch replies text/plain instead of json."""

    content_type = "text/plain; version=0.0.4; charset=utf-8"
