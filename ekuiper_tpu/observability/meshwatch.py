"""Mesh attribution — per-shard skew series and the collective-vs-compute
split for shard_map fold sites.

Two questions the fleet operator asks that no per-rule surface answers:

1. **Which chip is hot?** `observe()` diffs each live sharded kernel's
   `shard_stats()` rows against the previous observation: a per-shard
   rows/s EWMA plus `kuiper_mesh_skew_ratio` = hottest shard / mean over
   the window. A key-skewed workload (one device's key range absorbing
   most rows) shows up as a ratio far above 1.0; the health evaluator
   turns a sustained ratio above `KUIPER_MESH_SKEW_THRESHOLD` into a
   `shard_skew` bottleneck verdict and the QoS controller emits a
   structured `rebalance_hint` flight event (signal only — rebalancing
   itself is ROADMAP item 2's work).

2. **Collective or compute?** kernwatch already samples wall/dispatch
   timing for every `sharded.*` jit site but cannot say how much of the
   device time is the psum merge moving partials across chips.
   `collective_split()` prices that from first principles: the kernel's
   own `collective_bytes_per_fold()` (ring all-reduce bytes of the
   per-shard state slice) divided by the device class's ICI bandwidth,
   clamped to the sampled device time → `kuiper_mesh_collective_ms`.
   kernwatch's sampled-timing semantics are untouched — this module is a
   pure downstream consumer of `kernwatch.aggregate()`, and single-chip
   sites (R == 1 meshes, plain DeviceGroupBy) price to exactly zero.

Registry-driven like every watcher here: sharded kernels self-register in
`parallel/sharded.py`'s weakref registry; a collected kernel simply stops
contributing (its rows live on in the retired rollup).
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import timex

# ICI / interconnect bandwidth class per chip generation, GB/s per link
# direction — order-of-magnitude figures for the attribution estimate,
# matched by lowercase substring against kernwatch.device_spec()["kind"].
# The CPU row prices host-emulated "collectives" (memcpy class) so the
# 8-virtual-device CI meshes produce a nonzero, stable split.
MESH_LINK_GBS: Tuple[Tuple[str, float], ...] = (
    ("v5p", 600.0),
    ("v5e", 200.0),
    ("v4", 300.0),
    ("v3", 140.0),
    ("tpu", 200.0),
    ("cpu", 8.0),
)

DEFAULT_SKEW_THRESHOLD = 2.0   # KUIPER_MESH_SKEW_THRESHOLD
DEFAULT_SKEW_MIN_ROWS = 256    # KUIPER_MESH_SKEW_MIN_ROWS — window floor


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except (TypeError, ValueError):
        return default


class _Track:
    """Per-kernel observation state (keyed weakly off the kernel)."""

    __slots__ = ("prev_rows", "prev_ms", "rate", "skew", "hot_shard",
                 "window_rows")

    def __init__(self) -> None:
        self.prev_rows: Optional[np.ndarray] = None
        self.prev_ms: Optional[int] = None
        self.rate: Optional[np.ndarray] = None  # rows/s EWMA per shard
        self.skew: Optional[float] = None
        self.hot_shard = 0
        self.window_rows = 0


class MeshWatch:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tracks: "weakref.WeakKeyDictionary[Any, _Track]" = (
            weakref.WeakKeyDictionary())
        # last collective_bytes_per_fold per rule label — kept past kernel
        # death so retired kernwatch aggregates still price
        self._bytes_cache: Dict[str, int] = {}
        self._last_report: Dict[str, Dict[str, Any]] = {}
        self.threshold = _env_float(
            "KUIPER_MESH_SKEW_THRESHOLD", DEFAULT_SKEW_THRESHOLD)
        self.min_rows = int(_env_float(
            "KUIPER_MESH_SKEW_MIN_ROWS", DEFAULT_SKEW_MIN_ROWS))

    # ------------------------------------------------------------- skew
    def observe(self, now: Optional[int] = None) -> Dict[str, Dict[str, Any]]:
        """Diff every live sharded kernel against the last observation and
        refresh the per-rule skew report. Callers that hold locks which
        clock callbacks also take must pass `now` (same contract as the
        flight recorder's ts_ms)."""
        from ..parallel import sharded as _sharded

        if now is None:
            now = timex.now_ms()
        kernels = _sharded.registry().items()
        report: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for kernel, rule in kernels:
                label = rule or "__engine__"
                try:
                    stats = kernel.shard_stats()
                    rows = np.array([s["rows"] for s in stats],
                                    dtype=np.int64)
                except Exception:
                    continue
                tr = self._tracks.get(kernel)
                if tr is None:
                    tr = self._tracks[kernel] = _Track()
                if tr.prev_rows is None or len(tr.prev_rows) != len(rows):
                    window = rows  # first sight: cumulative counts
                    dt_ms = None
                else:
                    window = rows - tr.prev_rows
                    if np.any(window < 0):  # counter rebased (restore)
                        window = rows
                    dt_ms = (now - tr.prev_ms
                             if tr.prev_ms is not None else None)
                wsum = int(window.sum())
                if wsum >= max(self.min_rows, 1):
                    mean = float(window.mean())
                    tr.skew = float(window.max() / mean) if mean > 0 else None
                    tr.hot_shard = int(np.argmax(window))
                    tr.window_rows = wsum
                # else: carry the previous skew — a quiet interval is not
                # evidence the imbalance cleared
                if dt_ms and dt_ms > 0:
                    inst = window.astype(np.float64) * 1000.0 / dt_ms
                    tr.rate = (inst if tr.rate is None
                               or len(tr.rate) != len(inst)
                               else 0.5 * inst + 0.5 * tr.rate)
                tr.prev_rows = rows.copy()
                tr.prev_ms = now
                try:
                    self._bytes_cache[label] = int(
                        kernel.collective_bytes_per_fold())
                except Exception:
                    pass
                entry = {
                    "rule": label,
                    "mesh": getattr(kernel, "mesh_tag", ""),
                    "skew_ratio": tr.skew,
                    "hot_shard": tr.hot_shard,
                    "window_rows": tr.window_rows,
                    "skewed": bool(tr.skew is not None
                                   and tr.skew >= self.threshold),
                    "threshold": self.threshold,
                    "shards": [
                        {"shard": int(s["shard"]),
                         "rows": int(s["rows"]),
                         "keys": int(s["keys"]),
                         "rows_per_s": (float(tr.rate[i])
                                        if tr.rate is not None
                                        and i < len(tr.rate) else 0.0)}
                        for i, s in enumerate(stats)
                    ],
                }
                # one entry per rule: keep the widest window (a rule can
                # briefly own two kernels across a restore)
                prev = report.get(label)
                if prev is None or entry["window_rows"] >= prev["window_rows"]:
                    report[label] = entry
            self._last_report = report
        return report

    def skew_report(self) -> Dict[str, Dict[str, Any]]:
        """Last observe()'s per-rule skew entries (no re-observation)."""
        with self._lock:
            return dict(self._last_report)

    def rule_skew(self, rule: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last_report.get(rule or "__engine__")

    # -------------------------------------------------------- collective
    def _link_gbs(self) -> float:
        from . import kernwatch

        kind = str(kernwatch.device_spec().get("kind", "")).lower()
        for sub, gbs in MESH_LINK_GBS:
            if sub in kind:
                return gbs
        return MESH_LINK_GBS[-1][1]

    def collective_split(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Collective-vs-compute estimate for every sampled `sharded.*`
        site, (op, rule) keyed — a pure read of kernwatch.aggregate()."""
        from . import kernwatch

        link = self._link_gbs()
        agg = kernwatch.aggregate()
        with self._lock:
            bytes_cache = dict(self._bytes_cache)
        out: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for (op, rule), v in agg.items():
            if not str(op).startswith("sharded."):
                continue
            label = rule or "__engine__"
            bpf = bytes_cache.get(label)
            if bpf is None and len(bytes_cache) == 1:
                # kernel registered under a different label than the
                # fold's rule context (direct-driven kernels in probes)
                bpf = next(iter(bytes_cache.values()))
            if bpf is None:
                continue
            samples = int(v.get("samples", 0))
            if samples <= 0:
                continue  # never-sampled sites add only zero rows
            device_us = float(v.get("device_us", 0.0))
            coll_us = 0.0
            # the byte model prices the fold psum; finalize's gathers are
            # capacity-axis local (docs/DISTRIBUTED.md) — compute-only
            if "fold" in str(op) and bpf > 0 and link > 0:
                coll_us = min(samples * bpf / (link * 1e3), device_us)
            out[(op, label)] = {
                "samples": samples,
                "device_us": device_us,
                "collective_us": coll_us,
                "compute_us": device_us - coll_us,
                "share": (coll_us / device_us) if device_us > 0 else 0.0,
                "bytes_per_fold": bpf,
                "link_gbs": link,
            }
        return out

    # ------------------------------------------------------------ render
    def render_prometheus(self, out: List[str], esc) -> None:
        report = self.observe()
        out.append("# TYPE kuiper_mesh_skew_ratio gauge")
        out.append("# HELP kuiper_mesh_skew_ratio hottest shard rows over "
                   "the mean across the mesh (per rule, last window)")
        for label in sorted(report):
            skew = report[label]["skew_ratio"]
            if skew is not None:
                out.append(
                    f'kuiper_mesh_skew_ratio{{rule="{esc(label)}"}} '
                    f'{skew:.4f}')
        out.append("# TYPE kuiper_mesh_shard_rows_per_s gauge")
        out.append("# HELP kuiper_mesh_shard_rows_per_s per-shard fold "
                   "rate EWMA (rows/s)")
        for label in sorted(report):
            for s in report[label]["shards"]:
                out.append(
                    f'kuiper_mesh_shard_rows_per_s{{rule="{esc(label)}",'
                    f'shard="{s["shard"]}"}} {s["rows_per_s"]:.1f}')
        split = self.collective_split()
        out.append("# TYPE kuiper_mesh_collective_ms counter")
        out.append("# HELP kuiper_mesh_collective_ms estimated cross-chip "
                   "collective time inside sampled sharded fold sites")
        for (op, label) in sorted(split):
            v = split[(op, label)]
            out.append(
                f'kuiper_mesh_collective_ms{{op="{esc(op)}",'
                f'rule="{esc(label)}"}} {v["collective_us"] / 1000.0:.3f}')
        out.append("# TYPE kuiper_mesh_collective_share gauge")
        out.append("# HELP kuiper_mesh_collective_share collective fraction "
                   "of sampled device time per sharded site (0-1)")
        for (op, label) in sorted(split):
            v = split[(op, label)]
            out.append(
                f'kuiper_mesh_collective_share{{op="{esc(op)}",'
                f'rule="{esc(label)}"}} {v["share"]:.4f}')

    def diagnostics(self) -> Dict[str, Any]:
        """GET /diagnostics/mesh + kuiperdiag "mesh" section."""
        split = self.collective_split()
        return {
            "skew": self.skew_report(),
            "collective": [
                {"op": op, "rule": label, **v}
                for (op, label), v in sorted(split.items())
            ],
            "threshold": self.threshold,
            "min_rows": self.min_rows,
            "link_gbs": self._link_gbs(),
        }


# ----------------------------------------------------------- module facade
_watch = MeshWatch()


def observe(now: Optional[int] = None) -> Dict[str, Dict[str, Any]]:
    return _watch.observe(now)


def skew_report() -> Dict[str, Dict[str, Any]]:
    return _watch.skew_report()


def rule_skew(rule: str) -> Optional[Dict[str, Any]]:
    return _watch.rule_skew(rule)


def collective_split() -> Dict[Tuple[str, str], Dict[str, Any]]:
    return _watch.collective_split()


def skew_threshold() -> float:
    return _watch.threshold


def render_prometheus(out: List[str], esc) -> None:
    _watch.render_prometheus(out, esc)


def diagnostics() -> Dict[str, Any]:
    return _watch.diagnostics()


def reset() -> None:
    """Test hook — drop tracks and re-read the env knobs."""
    global _watch
    _watch = MeshWatch()
