"""Log-bucketed latency histograms — the engine's SLO measurement layer.

The paper's north star is a tail-latency claim (p99 emit < 50ms for the
10k-device tumbling GROUP BY), but a last-value gauge cannot express a
percentile: the engine needs real distributions on the hot path. This is an
HDR-style histogram (Tene's HdrHistogram bucketing, as used by the TiLT and
in-order sliding-window-aggregation evaluations — arxiv 2301.12030 /
2009.13768 both report streaming latency as percentiles): values land in
log₂ buckets subdivided into 2^SUB_BITS linear sub-buckets, giving a fixed
relative error of 2^-SUB_BITS (6.25%) across the whole range with a small,
flat int array — no per-sample allocation, no sorting, O(1) record.

Recording takes one short lock; at the engine's batch granularity (one
record per dispatched item / per window emit, never per row) the cost is
~100ns against multi-microsecond dispatches — the bench records the
measured overhead against the fused fold (BENCH full_pipe
hist_overhead_pct).

Units are the caller's: StatManager records microseconds, the per-rule
end-to-end histogram records milliseconds. Values are clamped to
[0, 2^MAX_BITS).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

#: linear sub-buckets per octave = 2^SUB_BITS → relative error 2^-SUB_BITS
SUB_BITS = 4
_SUB = 1 << SUB_BITS
#: values clamp at 2^MAX_BITS - 1 (≈ 35 minutes in µs, ≈ 24 days in ms)
MAX_BITS = 41
_N_BUCKETS = _SUB + (MAX_BITS - SUB_BITS) * _SUB


def _index(v: int) -> int:
    """Bucket index of non-negative int `v` (clamped to the top bucket)."""
    if v < _SUB:
        return v  # exact linear range
    e = v.bit_length() - 1  # floor(log2 v) >= SUB_BITS
    if e >= MAX_BITS:
        return _N_BUCKETS - 1
    shift = e - SUB_BITS
    # mantissa sub-bucket within the octave [2^e, 2^(e+1))
    return _SUB * (e - SUB_BITS + 1) + ((v >> shift) - _SUB)


def _bucket_max(idx: int) -> int:
    """Largest value that maps to bucket `idx` (its inclusive upper edge)."""
    if idx < _SUB:
        return idx
    octave = idx >> SUB_BITS  # >= 1
    mant = idx & (_SUB - 1)
    return ((_SUB + mant + 1) << (octave - 1)) - 1


class LatencyHistogram:
    """Thread-safe log-bucketed histogram: record / merge / percentile /
    snapshot-and-decay. One flat count array, bounded error (6.25%)."""

    __slots__ = ("_counts", "count", "sum", "min", "max", "_lock")

    def __init__(self) -> None:
        self._counts: List[int] = [0] * _N_BUCKETS
        self.count = 0
        self.sum = 0
        self.min = 0
        self.max = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- recording
    def record(self, value) -> None:
        v = int(value)
        if v < 0:
            v = 0
        with self._lock:
            self._counts[_index(v)] += 1
            if self.count == 0 or v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self.count += 1
            self.sum += v

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold `other`'s distribution into this one (e.g. per-instance
        histograms rolled up to a rule)."""
        with other._lock:
            counts = list(other._counts)
            ocount, osum = other.count, other.sum
            omin, omax = other.min, other.max
        if not ocount:
            return
        with self._lock:
            for i, c in enumerate(counts):
                if c:
                    self._counts[i] += c
            if self.count == 0 or omin < self.min:
                self.min = omin
            if omax > self.max:
                self.max = omax
            self.count += ocount
            self.sum += osum

    # --------------------------------------------------------------- queries
    def _percentiles_locked(self, qs: Sequence[float]) -> List[int]:
        """Values at each percentile of ASCENDING `qs`, ONE bucket walk.
        Caller holds the lock."""
        if self.count == 0:
            return [0] * len(qs)
        targets = [max(1, -(-int(self.count * q) // 100)) for q in qs]  # ceil
        out = [self.max] * len(qs)
        qi = 0
        seen = 0
        for i, c in enumerate(self._counts):
            if not c:
                continue
            seen += c
            while qi < len(targets) and seen >= targets[qi]:
                out[qi] = min(_bucket_max(i), self.max)
                qi += 1
            if qi >= len(targets):
                break
        return out

    def percentile(self, q: float) -> int:
        """Value at percentile q (0-100): the inclusive upper edge of the
        bucket where the cumulative count crosses q — an overestimate by at
        most the bucket's 6.25% relative width. 0 when empty."""
        with self._lock:
            return self._percentiles_locked([q])[0]

    def percentiles(self, qs: Sequence[float]) -> List[int]:
        order = sorted(range(len(qs)), key=lambda i: qs[i])
        with self._lock:
            vals = self._percentiles_locked([qs[i] for i in order])
        out = [0] * len(qs)
        for pos, i in enumerate(order):
            out[i] = vals[pos]
        return out

    def snapshot(self) -> Dict[str, int]:
        """The percentile summary the status/REST layers report — computed
        under ONE lock so a concurrent record burst cannot yield an
        inconsistent summary (p99 below p50, count disagreeing with the
        distribution the percentiles came from)."""
        with self._lock:
            p50, p90, p99 = self._percentiles_locked([50, 90, 99])
            return {
                "count": self.count,
                "p50": p50,
                "p90": p90,
                "p99": p99,
                "max": self.max,
            }

    def snapshot_and_decay(self, factor: float = 0.5) -> Dict[str, int]:
        """Snapshot, then scale every bucket by `factor` (0 clears) — a
        cheap sliding observation window for long-lived rules: old samples
        fade geometrically instead of dominating the distribution forever.
        min/max reset when the decayed histogram is empty. Snapshot and
        decay share ONE lock hold: a sample recorded between them would be
        wiped without ever appearing in any snapshot."""
        with self._lock:
            p50, p90, p99 = self._percentiles_locked([50, 90, 99])
            snap = {"count": self.count, "p50": p50, "p90": p90,
                    "p99": p99, "max": self.max}
            total = s = 0
            for i, c in enumerate(self._counts):
                if c:
                    nc = int(c * factor)
                    self._counts[i] = nc
                    total += nc
                    # bucket-resolution approximation of the decayed sum
                    s += nc * _bucket_max(i)
            self.count = total
            self.sum = min(int(self.sum * factor), s) if total else 0
            if total == 0:
                self.min = self.max = 0
        return snap

    def bucket_counts(self) -> List[int]:
        """Copy of the raw bucket counts — the health plane's delta
        windows subtract two of these to get the distribution of samples
        recorded BETWEEN evaluator ticks (the cumulative histogram itself
        must never be decayed while Prometheus scrapes it)."""
        with self._lock:
            return list(self._counts)

    def record_bucket_counts(self, counts: Sequence[int]) -> None:
        """Fold raw per-bucket count deltas (a `bucket_counts()`
        difference) into this histogram. min/max/sum are maintained at
        bucket resolution (upper edges) — the same ≤6.25% error as every
        other derived quantity."""
        total = s = 0
        lo = hi = -1
        for i, c in enumerate(counts):
            if c > 0:
                total += c
                s += c * _bucket_max(i)
                if lo < 0:
                    lo = i
                hi = i
        if not total:
            return
        with self._lock:
            for i, c in enumerate(counts):
                if c > 0:
                    self._counts[i] += c
            lo_v, hi_v = _bucket_max(lo), _bucket_max(hi)
            if self.count == 0 or lo_v < self.min:
                self.min = lo_v
            if hi_v > self.max:
                self.max = hi_v
            self.count += total
            self.sum += s

    def _cumulative_locked(self, bounds: Sequence[int]) -> List[int]:
        out = [0] * len(bounds)
        bi = 0
        cum = 0
        for i, c in enumerate(self._counts):
            if not c:
                continue
            edge = _bucket_max(i)
            while bi < len(bounds) and bounds[bi] < edge:
                out[bi] = cum
                bi += 1
            if bi >= len(bounds):
                break
            cum += c
        for j in range(bi, len(bounds)):
            out[j] = cum
        return out

    def cumulative(self, bounds: Sequence[int]) -> List[int]:
        """Cumulative counts at each upper bound (`le` semantics) for
        Prometheus histogram exposition. A sample counts toward the first
        bound >= its bucket's upper edge, so the mapping is conservative
        (never under-reports latency). `bounds` must be ascending."""
        with self._lock:
            return self._cumulative_locked(bounds)

    def export(self, bounds: Sequence[int]):
        """(cumulative bucket counts, total count, sum) captured under ONE
        lock — a concurrent record() between separate reads could otherwise
        leave a finite `le` bucket exceeding `+Inf` (non-monotonic series,
        NaN histogram_quantile)."""
        with self._lock:
            return self._cumulative_locked(bounds), self.count, self.sum


#: canonical `le` ladder (ms) for the per-rule ingest→emit histogram — spans
#: sub-SLO (the 50ms north star sits mid-ladder) to window-length dwells
E2E_BOUNDS_MS = (1, 2, 5, 10, 25, 50, 100, 250, 500,
                 1000, 2500, 5000, 10000, 30000, 60000)


def render_prom_histogram(out: List[str], name: str, labels: str,
                          hist: Optional[LatencyHistogram],
                          bounds: Sequence[int] = E2E_BOUNDS_MS) -> None:
    """Append `{name}_bucket/_sum/_count` exposition lines for one labeled
    histogram (labels = pre-escaped `key="value"` pairs, no braces)."""
    if hist is None:
        return
    sep = "," if labels else ""
    cum, count, total = hist.export(bounds)
    for b, c in zip(bounds, cum):
        out.append(f'{name}_bucket{{{labels}{sep}le="{b}"}} {c}')
    out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {count}')
    out.append(f"{name}_sum{{{labels}}} {total}")
    out.append(f"{name}_count{{{labels}}} {count}")
